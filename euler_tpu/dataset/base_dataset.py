"""Dataset framework.

Parity: tf_euler/python/dataset/ (base_dataset.py:37-60 download→json→
binary pipeline + 13 named datasets with get_dataset registry). This
environment has no network egress, so each named dataset resolves in
order (DATA.md documents every layout with download pointers):
  1. a prepared binary graph under $EULER_TPU_DATA_DIR/<name>/ (meta.bin)
  2. a raw .npz under $EULER_TPU_DATA_DIR/<name>.npz — either native
     keys (features [N,D], labels [N]/[N,C], edges [2,E], optional
     train/val/test masks) or the public gnn-benchmark CSR layout
     (adj_*/attr_*/labels); absent masks get the planetoid protocol
     split
  3. an OGB-style directory $EULER_TPU_DATA_DIR/<name>/ with
     edge_index/node_feat/node_label/{train,valid,test}_idx .npy files
  4. a deterministic synthetic stand-in with the same statistical shape
     (class-informative features over an SBM graph) so the full pipeline
     — engine build, sampling, training, eval — exercises identically.

The split convention matches the reference datasets: node type 0=train,
1=val, 2=test; labels in dense feature 'label'; inputs in dense feature
'feature'.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from euler_tpu.graph import GraphBuilder, GraphEngine

DATA_DIR_ENV = "EULER_TPU_DATA_DIR"

FEATURE_FID = 0   # 'feature'
LABEL_FID = 1     # 'label'
TRAIN_TYPE, VAL_TYPE, TEST_TYPE = 0, 1, 2


@dataclass
class GraphData:
    """A loaded node-classification dataset."""

    engine: GraphEngine
    num_classes: int
    feature_dim: int
    max_id: int
    name: str = ""
    multilabel: bool = False
    source: str = "synthetic"


def build_engine(features: np.ndarray, labels: np.ndarray,
                 edges: np.ndarray, train_mask, val_mask, test_mask,
                 directed: bool = False) -> GraphEngine:
    """Arrays → GraphEngine with the split/type/feature conventions above."""
    n, d = features.shape
    if labels.ndim == 1:
        num_classes = int(labels.max()) + 1
        onehot = np.zeros((n, num_classes), np.float32)
        onehot[np.arange(n), labels.astype(int)] = 1.0
        label_mat = onehot
    else:
        label_mat = labels.astype(np.float32)
        num_classes = labels.shape[1]
    types = np.full(n, TEST_TYPE, np.int32)
    types[np.asarray(val_mask, bool)] = VAL_TYPE
    types[np.asarray(train_mask, bool)] = TRAIN_TYPE
    ids = np.arange(n, dtype=np.uint64)
    b = GraphBuilder()
    b.set_num_types(3, 1)
    b.set_feature(FEATURE_FID, 0, d, "feature")
    b.set_feature(LABEL_FID, 0, num_classes, "label")
    b.add_nodes(ids, types=types, weights=np.ones(n, np.float32))
    src = edges[0].astype(np.uint64)
    dst = edges[1].astype(np.uint64)
    if not directed:
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
    b.add_edges(src, dst)
    b.set_node_dense(ids, FEATURE_FID, features.astype(np.float32))
    b.set_node_dense(ids, LABEL_FID, label_mat)
    return b.finalize()


def synthetic_citation(name: str, n: int, d: int, num_classes: int,
                       intra_degree: float = 4.0, inter_degree: float = 1.0,
                       signal: float = 1.6, seed: int = 0,
                       train_per_class: int = 20, val: int = 500,
                       test: int = 1000, informative_dims: int = 0,
                       confuse_frac: float = 0.0) -> GraphData:
    """SBM + class-informative features (a Cora-shaped problem).

    Difficulty is calibrated so a reference-grade 2-layer GNN lands near
    the published BASELINE.md numbers (≈0.82 on cora-shaped data), NOT at
    ~1.0 — see dataset/__init__.py for the per-dataset calibrated knobs
    and tests/test_tools_datasets.py for the regression guard. Two knobs
    create realistic hardness:

    informative_dims — when > 0, only this many dims carry class signal
      (bag-of-words-like); the rest are pure noise. When 0, every dim
      carries `signal` × a Gaussian class center (the easy legacy shape,
      still used by bench.py where only throughput matters).
    confuse_frac — fraction of nodes whose FEATURES are drawn from a
      random other class while the label (and edge homophily) stay true:
      feature-only classifiers cap near 1-ρ+ρ/C, and a GNN recovers part
      of the gap through homophilous neighbors — mirroring why real
      citation graphs reward message passing.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    # feature class: mostly the true label; a ρ-fraction of "confused"
    # nodes draw features from a different class
    feat_class = labels.copy()
    if confuse_frac > 0:
        flip = rng.random(n) < confuse_frac
        shift = rng.integers(1, num_classes, n)
        feat_class = np.where(flip, (labels + shift) % num_classes, labels)
    if informative_dims and informative_dims < d:
        k = int(informative_dims)
        # per-class informative dim sets (drawn independently → overlap)
        class_dims = np.stack(
            [rng.choice(d, size=k, replace=False)
             for _ in range(num_classes)])
        per_dim_gain = rng.uniform(0.5, 1.5, (num_classes, k))
        features = rng.normal(0, 1.0, (n, d))
        bump = signal * per_dim_gain[feat_class]
        np.add.at(features, (np.arange(n)[:, None], class_dims[feat_class]),
                  bump)
        features = features.astype(np.float32)
    else:
        centers = rng.normal(0, 1.0, (num_classes, d))
        features = (signal * centers[feat_class]
                    + rng.normal(0, 1.0, (n, d))).astype(np.float32)
    # sparse SBM edges via sampled pairs
    n_intra = int(n * intra_degree / 2)
    n_inter = int(n * inter_degree / 2)
    # intra: pick random nodes, partner within same class (vectorized —
    # a per-edge Python loop here would dominate products-scale builds)
    by_class = [np.where(labels == c)[0] for c in range(num_classes)]
    class_sizes = np.array([len(b) for b in by_class], np.int64)
    class_offs = np.concatenate([[0], np.cumsum(class_sizes)])
    nodes_by_class = np.concatenate(by_class) if n else np.array([], np.int64)
    intra_src = rng.integers(0, n, n_intra)
    src_cls = labels[intra_src]
    within = rng.integers(0, class_sizes[src_cls])
    intra_dst = nodes_by_class[class_offs[src_cls] + within]
    inter_src = rng.integers(0, n, n_inter)
    inter_dst = rng.integers(0, n, n_inter)
    edges = np.stack([
        np.concatenate([intra_src, inter_src]),
        np.concatenate([intra_dst, inter_dst]),
    ])
    # splits: the planetoid protocol (shared with the real-data loaders)
    train_mask, val_mask, test_mask = _planetoid_split(
        labels, train_per_class=train_per_class, val=val, test=test)
    engine = build_engine(features, labels, edges, train_mask, val_mask,
                          test_mask)
    return GraphData(engine, num_classes, d, n - 1, name=name,
                     source="synthetic")


def _planetoid_split(labels_1d: np.ndarray, train_per_class: int = 20,
                     val: int = 500, test: int = 1000):
    """The planetoid protocol split (20 labeled nodes per class, 500
    val, 1000 test — Yang et al. 2016, the split the reference's
    published cora/pubmed/citeseer numbers use) over nodes in id order.
    Used when a dump carries no masks (e.g. gnn-benchmark CSR files)."""
    n = labels_1d.shape[0]
    train_mask = np.zeros(n, bool)
    for c in np.unique(labels_1d):
        train_mask[np.where(labels_1d == c)[0][:train_per_class]] = True
    rest = np.where(~train_mask)[0]
    val_mask = np.zeros(n, bool)
    val_mask[rest[:val]] = True
    test_mask = np.zeros(n, bool)
    test_mask[rest[val:val + test]] = True
    return train_mask, val_mask, test_mask


def _csr_to_dense(z, prefix: str) -> np.ndarray:
    """Rebuild a dense [N, D] float32 matrix from the CSR triplet keys
    `<prefix>_data/_indices/_indptr/_shape` (the gnn-benchmark layout)
    without scipy."""
    data = z[f"{prefix}_data"]
    indices = z[f"{prefix}_indices"].astype(np.int64)
    indptr = z[f"{prefix}_indptr"].astype(np.int64)
    shape = tuple(int(s) for s in z[f"{prefix}_shape"])
    out = np.zeros(shape, np.float32)
    rows = np.repeat(np.arange(shape[0]), np.diff(indptr))
    out[rows, indices] = data
    return out


def _csr_to_edges(z, prefix: str = "adj") -> np.ndarray:
    indices = z[f"{prefix}_indices"].astype(np.int64)
    indptr = z[f"{prefix}_indptr"].astype(np.int64)
    n = int(z[f"{prefix}_shape"][0])
    src = np.repeat(np.arange(n), np.diff(indptr))
    return np.stack([src, indices])


def _load_npz(path: str, name: str) -> GraphData:
    """A `.npz` drop-in under $EULER_TPU_DATA_DIR/<name>.npz. Two
    accepted layouts (DATA.md documents both with download pointers):

    1. native: features [N,D], labels [N] or [N,C], edges [2,E],
       train_mask/val_mask/test_mask [N] bool.
    2. gnn-benchmark CSR (the public cora/citeseer/pubmed dumps from
       github.com/shchur/gnn-benchmark, also used by many planetoid
       mirrors): adj_data/adj_indices/adj_indptr/adj_shape +
       attr_data/attr_indices/attr_indptr/attr_shape + labels. Masks are
       optional there; absent masks get the planetoid protocol split
       (20 per class / 500 val / 1000 test, in node order).

    Parity: the reference downloads the same public sources in
    tf_euler/python/dataset/base_dataset.py:37-60 (e.g. dataset/cora.py)."""
    z = np.load(path, allow_pickle=False)
    keys = set(z.files)

    def masks_for(labels):
        # all three masks or none: a partial set is a malformed file
        # (an actionable error, not a KeyError from the archive)
        have = {"train_mask", "val_mask", "test_mask"} & keys
        if len(have) == 3:
            return z["train_mask"], z["val_mask"], z["test_mask"]
        if have:
            raise ValueError(
                f"{path}: carries {sorted(have)} but not all of "
                "train_mask/val_mask/test_mask — provide all three or "
                "none (absent masks get the planetoid split; see DATA.md)")
        if labels.ndim > 1:
            raise ValueError(
                f"{path}: multilabel [N, C] labels need explicit "
                "train/val/test masks — the planetoid per-class split "
                "protocol is single-label only (see DATA.md)")
        return _planetoid_split(labels)

    if {"features", "labels", "edges"} <= keys:
        features, labels, edges = z["features"], z["labels"], z["edges"]
        masks = masks_for(labels)
    elif {"adj_data", "adj_indices", "adj_indptr", "adj_shape",
          "labels"} <= keys:
        features = _csr_to_dense(z, "attr")
        labels = z["labels"]
        edges = _csr_to_edges(z, "adj")
        masks = masks_for(labels)
    else:
        raise ValueError(
            f"{path}: unrecognized npz layout (keys: {sorted(keys)}); "
            "expected native keys (features/labels/edges/*_mask) or the "
            "gnn-benchmark CSR keys (adj_*/attr_*/labels) — see DATA.md")
    engine = build_engine(features, labels, edges, *masks)
    num_classes = int(labels.max()) + 1 if labels.ndim == 1 else labels.shape[1]
    return GraphData(engine, num_classes, features.shape[1],
                     int(features.shape[0]) - 1, name=name,
                     multilabel=labels.ndim > 1, source=path)


def _load_ogb_dir(path: str, name: str) -> GraphData:
    """An OGB-style directory drop-in: $EULER_TPU_DATA_DIR/<name>/ with
    edge_index.npy [2,E], node_feat.npy [N,D], node_label.npy [N] or
    [N,1], and train_idx.npy/valid_idx.npy/test_idx.npy (the arrays
    `ogb.nodeproppred.NodePropPredDataset` exposes — np.save each once
    on any machine with egress; see DATA.md)."""
    ld = {k: np.load(os.path.join(path, f"{k}.npy"))
          for k in ("edge_index", "node_feat", "node_label",
                    "train_idx", "valid_idx", "test_idx")}
    labels = ld["node_label"].reshape(-1).astype(np.int64)
    n = ld["node_feat"].shape[0]
    masks = []
    for k in ("train_idx", "valid_idx", "test_idx"):
        m = np.zeros(n, bool)
        m[ld[k].reshape(-1).astype(np.int64)] = True
        masks.append(m)
    engine = build_engine(ld["node_feat"], labels, ld["edge_index"], *masks)
    return GraphData(engine, int(labels.max()) + 1, ld["node_feat"].shape[1],
                     n - 1, name=name, source=path)


def load_named(name: str, synthetic_cfg: Dict) -> GraphData:
    data_dir = os.environ.get(DATA_DIR_ENV, "")
    if data_dir:
        bin_dir = os.path.join(data_dir, name)
        if os.path.exists(os.path.join(bin_dir, "meta.bin")):
            eng = GraphEngine.load(bin_dir)
            d = eng.feature_dim("feature")
            c = eng.feature_dim("label")
            n = eng.node_count
            return GraphData(eng, c, d, n - 1, name=name, source=bin_dir)
        npz = os.path.join(data_dir, f"{name}.npz")
        if os.path.exists(npz):
            return _load_npz(npz, name)
        if os.path.exists(os.path.join(bin_dir, "edge_index.npy")):
            return _load_ogb_dir(bin_dir, name)
    return synthetic_citation(name, **synthetic_cfg)
