"""Dataset registry — parity with tf_euler/python/dataset/__init__.py:20
get_dataset over 13 named datasets. Shapes/sizes mirror the real datasets;
see base_dataset.load_named for the local-file → synthetic fallback."""

from __future__ import annotations

from functools import partial

from euler_tpu.dataset.base_dataset import (  # noqa: F401
    FEATURE_FID,
    LABEL_FID,
    TEST_TYPE,
    TRAIN_TYPE,
    VAL_TYPE,
    GraphData,
    build_engine,
    load_named,
    synthetic_citation,
)
from euler_tpu.dataset.graph_sets import mutag_like  # noqa: F401
from euler_tpu.dataset.kg_sets import load_kg  # noqa: F401

# Statistical shapes of the real datasets (nodes, feature dim, classes)
# plus CALIBRATED difficulty knobs (signal / informative_dims /
# confuse_frac / homophily): tuned so a reference-grade 2-layer GCN lands
# near the published BASELINE.md F1 for each dataset while feature-only
# and structure-only baselines land far below — i.e. the synthetic
# stand-in rewards message passing the way the real data does.
# Measured at seed=0 (the default): cora GCN 0.825 (ref 0.822,
# feat-only 0.746, label-prop 0.651); pubmed 0.866 (ref 0.871);
# citeseer 0.762 (ref 0.752). Guarded by tests/test_tools_datasets.py.
_CITATION_SHAPES = {
    "cora": dict(n=2708, d=1433, num_classes=7, signal=1.2,
                 confuse_frac=0.2, informative_dims=48,
                 intra_degree=3.0, inter_degree=1.5),
    "citeseer": dict(n=3327, d=3703, num_classes=6, signal=1.12,
                     confuse_frac=0.21, informative_dims=48,
                     intra_degree=3.0, inter_degree=1.4),
    # pubmed: homophily ≈ 0.80 (the real graph's level — Zhu et al. 2020
    # measure 0.80); difficulty carried by confuse_frac, picked so the
    # model spread straddles the published table (GCN 0.89 vs ref 0.871,
    # sampled-fanout models ≈0.84 vs ref 0.884) with minimum total error
    "pubmed": dict(n=19717, d=500, num_classes=3, signal=1.1,
                   confuse_frac=0.25, informative_dims=32,
                   intra_degree=3.6, inter_degree=0.9),
    "ppi": dict(n=14755, d=50, num_classes=121, signal=1.0,
                confuse_frac=0.2, informative_dims=24),
    "reddit": dict(n=232965, d=602, num_classes=41, signal=1.2,
                   confuse_frac=0.15, informative_dims=48),
}

_REGISTRY = {}
for _name, _shape in _CITATION_SHAPES.items():
    _REGISTRY[_name] = partial(load_named, _name, dict(_shape))
_REGISTRY["mutag"] = mutag_like
for _kg in ("fb15k", "fb15k237", "wn18"):
    _REGISTRY[_kg] = partial(load_kg, _kg)

from euler_tpu.dataset.ml_1m import ml_1m  # noqa: E402,F401

_REGISTRY["ml_1m"] = ml_1m

# REAL datasets available without egress (see real_sets.py): every node/
# edge/label in karate is observed 1977 data; digits_knn has real
# features+labels with derived kNN edges.
from euler_tpu.dataset.real_sets import digits_knn, karate  # noqa: E402,F401

_REGISTRY["karate"] = karate
_REGISTRY["digits_knn"] = digits_knn


def get_dataset(name: str, **overrides):
    name = name.lower()
    if name not in _REGISTRY:
        raise ValueError(f"unknown dataset {name!r}; options {sorted(_REGISTRY)}")
    fn = _REGISTRY[name]
    if overrides and isinstance(fn, partial) and fn.func is load_named:
        cfg = dict(fn.args[1])
        cfg.update(overrides)
        return load_named(fn.args[0], cfg)
    return fn(**overrides) if overrides else fn()
