"""MovieLens-1M bipartite recommendation dataset.

Parity: tf_euler/python/dataset/ml_1m.py — users and items as two node
types, one 'rated' edge type weighted by rating, driving the unsupervised
/ recommendation solution examples (train embeddings on rated edges, then
knn retrieval over item embeddings).

Resolution order (no network egress here):
  1. $EULER_TPU_DATA_DIR/ml_1m/ratings.dat  ("user::item::rating::ts")
  2. synthetic stand-in with MovieLens-1M statistics: 6040 users ×
     3706 items, ~1M ratings from clustered preferences (users and items
     share latent genres, so embedding models learn a real structure).

Node ids: users are 1..U, items are U+1..U+I (the reference offsets item
ids the same way to keep one id space).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from euler_tpu.dataset.base_dataset import DATA_DIR_ENV
from euler_tpu.graph import GraphBuilder, GraphEngine

USER_TYPE, ITEM_TYPE = 0, 1
RATED_EDGE = 0


@dataclass
class RecData:
    engine: GraphEngine
    num_users: int
    num_items: int
    name: str = "ml_1m"
    source: str = "synthetic"

    @property
    def max_id(self) -> int:
        return self.num_users + self.num_items


def _synthetic_ratings(num_users: int, num_items: int, num_ratings: int,
                       n_genres: int = 18, seed: int = 0) -> np.ndarray:
    """(user, item, rating) rows; users prefer items of their favored
    genres with higher ratings."""
    rng = np.random.default_rng(seed)
    user_genre = rng.integers(0, n_genres, num_users)
    item_genre = rng.integers(0, n_genres, num_items)
    # popularity skew (zipf-ish) like real MovieLens
    item_pop = 1.0 / (1.0 + np.arange(num_items)) ** 0.7
    item_pop /= item_pop.sum()
    # real ratings are unique (user, item) pairs; oversample then dedupe
    users = rng.integers(0, num_users, int(num_ratings * 1.3))
    items = rng.choice(num_items, size=users.size, p=item_pop)
    _, keep = np.unique(users.astype(np.int64) * num_items + items,
                        return_index=True)
    keep = np.sort(keep)[:num_ratings]
    users, items = users[keep], items[keep]
    num_ratings = users.size
    match = user_genre[users] == item_genre[items]
    rating = np.where(match,
                      rng.integers(4, 6, num_ratings),
                      rng.integers(1, 4, num_ratings)).astype(np.float32)
    return np.stack([users + 1,
                     items + 1 + num_users,
                     rating], axis=1)


def ml_1m(num_users: int = 6040, num_items: int = 3706,
          num_ratings: int = 1_000_209, seed: int = 0) -> RecData:
    source = "synthetic"
    rows = None
    data_dir = os.environ.get(DATA_DIR_ENV, "")
    path = os.path.join(data_dir, "ml_1m", "ratings.dat") if data_dir else ""
    if path and os.path.exists(path):
        raw = []
        with open(path, encoding="latin-1") as f:
            for line in f:
                parts = line.strip().split("::")
                if len(parts) >= 3:
                    raw.append((int(parts[0]), int(parts[1]),
                                float(parts[2])))
        arr = np.array(raw, dtype=np.float64)
        # raw MovieLens ids are sparse (movie ids run past the movie
        # count); size the id space from the FILE, not the defaults, so
        # every item node is pre-typed and max_id covers the table
        num_users = int(arr[:, 0].max())
        num_items = int(arr[:, 1].max())
        rows = np.stack([arr[:, 0], arr[:, 1] + num_users, arr[:, 2]],
                        axis=1)
        source = "local"
    if rows is None:
        rows = _synthetic_ratings(num_users, num_items, num_ratings,
                                  seed=seed)

    b = GraphBuilder()
    b.set_num_types(2, 1)
    user_ids = np.arange(1, num_users + 1, dtype=np.uint64)
    item_ids = np.arange(num_users + 1, num_users + num_items + 1,
                         dtype=np.uint64)
    b.add_nodes(user_ids, types=np.full(num_users, USER_TYPE, np.int32))
    b.add_nodes(item_ids, types=np.full(num_items, ITEM_TYPE, np.int32))
    src = rows[:, 0].astype(np.uint64)
    dst = rows[:, 1].astype(np.uint64)
    w = rows[:, 2].astype(np.float32)
    b.add_edges(src, dst, weights=w)
    b.add_edges(dst, src, weights=w)  # reverse edges for item-side hops
    return RecData(b.finalize(), num_users, num_items, source=source)
