"""Graph-classification datasets (mutag family).

Parity: tf_euler/python/dataset/mutag.py — here a deterministic synthetic
stand-in: two structural classes of small molecules-like graphs (cycles
vs trees with decorations) that GIN-class models separate at ≈0.9+.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np


@dataclass
class GraphSetData:
    graphs: List[dict]          # each {x [n,D], edge_index [2,e]}
    labels: np.ndarray
    num_classes: int
    feature_dim: int
    train_indices: np.ndarray
    eval_indices: np.ndarray
    name: str = "mutag"


def _cycle_graph(n, rng, d):
    idx = np.arange(n)
    ei = np.stack([idx, np.roll(idx, -1)])
    ei = np.concatenate([ei, ei[::-1]], axis=1)
    x = rng.normal(0, 1, (n, d)).astype(np.float32) + 0.5
    return {"x": x, "edge_index": ei.astype(np.int32)}


def _tree_graph(n, rng, d):
    parents = np.array([rng.integers(0, max(i, 1)) for i in range(1, n)])
    child = np.arange(1, n)
    ei = np.stack([parents, child])
    ei = np.concatenate([ei, ei[::-1]], axis=1)
    x = rng.normal(0, 1, (n, d)).astype(np.float32) - 0.5
    return {"x": x, "edge_index": ei.astype(np.int32)}


def mutag_like(num_graphs: int = 188, feature_dim: int = 7,
               seed: int = 0) -> GraphSetData:
    rng = np.random.default_rng(seed)
    graphs, labels = [], []
    for i in range(num_graphs):
        n = int(rng.integers(10, 28))
        if rng.random() < 0.5:
            graphs.append(_cycle_graph(n, rng, feature_dim))
            labels.append(0)
        else:
            graphs.append(_tree_graph(n, rng, feature_dim))
            labels.append(1)
    labels = np.asarray(labels)
    order = rng.permutation(num_graphs)
    split = int(num_graphs * 0.8)
    return GraphSetData(graphs, labels, 2, feature_dim,
                        train_indices=order[:split],
                        eval_indices=order[split:])
