"""Graph-classification datasets (mutag family).

Parity: tf_euler/python/dataset/mutag.py — here a deterministic synthetic
stand-in: two structural classes of small molecules-like graphs (cycles
vs trees with decorations) that GIN-class models separate at ≈0.9+.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np


@dataclass
class GraphSetData:
    graphs: List[dict]          # each {x [n,D], edge_index [2,e]}
    labels: np.ndarray
    num_classes: int
    feature_dim: int
    train_indices: np.ndarray
    eval_indices: np.ndarray
    name: str = "mutag"


def _atom_features(n, rng, d):
    """Class-independent one-hot "atom types" (like MUTAG's 7 atom
    one-hots). Regular atoms draw from types 2..d-1; types 0/1 are the
    "aromatic" types assigned explicitly by _graph — identically many in
    both classes, so features alone carry ZERO label signal."""
    types = rng.integers(2, d, n)
    x = np.zeros((n, d), dtype=np.float32)
    x[np.arange(n), types] = 1.0
    return x


def _tree_edges(n, rng):
    parents = np.array([rng.integers(0, max(i, 1)) for i in range(1, n)])
    child = np.arange(1, n)
    return np.stack([parents, child])


def _graph(n, rng, d, with_ring: bool, ring_len: int, num_rings: int = 1):
    """Molecule-like graphs. Ring class: an explicit ring of `ring_len`
    aromatic atoms with tree decorations hanging off it. Tree class: pure
    random tree with the same number of aromatic atoms scattered
    non-adjacent. Detecting the aromatic RING (adjacent aromatic atoms on
    a cycle) is what message passing must learn."""
    x = _atom_features(n, rng, d)
    if with_ring:
        # nodes 0..ring_len-1 form the ring; the rest attach as random
        # tree decorations to any earlier node
        ring = np.arange(ring_len)
        ring_ei = np.stack([ring, np.roll(ring, -1)])
        deco_parents = np.array(
            [rng.integers(0, i) for i in range(ring_len, n)])
        deco = np.stack([deco_parents, np.arange(ring_len, n)])
        ei = np.concatenate([ring_ei, deco], axis=1)
        # aromatic-carbon-like skew: every ring atom becomes type 0/1
        # — ADJACENT aromatic atoms on a cycle
        aromatic = list(ring)
    else:
        ei = _tree_edges(n, rng)
        # SAME expected number of aromatic atoms, but placed as an
        # independent set (greedy, non-adjacent): the global atom
        # histogram matches the ring class, so a feature-only readout is
        # ≈ chance; only message passing sees the adjacency co-occurrence
        # (real MUTAG's aromatic-ring signal)
        k = min(n, max(1, int(rng.normal(num_rings * ring_len, 1.0))))
        nbrs = {}
        for a, b in ei.T:
            nbrs.setdefault(int(a), set()).add(int(b))
            nbrs.setdefault(int(b), set()).add(int(a))
        aromatic = []
        blocked = set()
        for v in rng.permutation(n):
            if len(aromatic) >= k:
                break
            v = int(v)
            if v in blocked:
                continue
            aromatic.append(v)
            blocked.add(v)
            blocked.update(nbrs.get(v, ()))
    for v in aromatic:
        x[v] = 0.0
        x[v, int(rng.integers(0, 2))] = 1.0
    ei = np.concatenate([ei, ei[::-1]], axis=1)
    return {"x": x, "edge_index": ei.astype(np.int32)}


def mutag_like(num_graphs: int = 188, feature_dim: int = 7,
               seed: int = 0, label_noise: float = 0.07) -> GraphSetData:
    """Calibrated difficulty (BASELINE.md: GIN 0.923, GatedGraph 0.920,
    Set2Set 0.901, GraphGCN 0.891 on real mutag): label = ring motif
    present, features are class-independent atom one-hots, and
    `label_noise` caps the Bayes accuracy near the published numbers —
    a feature-only readout scores ≈ chance (guarded by
    tests/test_tools_datasets.py)."""
    rng = np.random.default_rng(seed)
    graphs, labels = [], []
    for i in range(num_graphs):
        n = int(rng.integers(10, 28))
        has_ring = rng.random() < 0.5
        ring_len = int(rng.integers(4, 6))
        graphs.append(_graph(n, rng, feature_dim, has_ring, ring_len,
                             num_rings=1))
        y = int(has_ring)
        if rng.random() < label_noise:
            y = 1 - y
        labels.append(y)
    labels = np.asarray(labels)
    order = rng.permutation(num_graphs)
    split = int(num_graphs * 0.8)
    return GraphSetData(graphs, labels, 2, feature_dim,
                        train_indices=order[:split],
                        eval_indices=order[split:])
