from euler_tpu.contrib.spmm import spmm  # noqa: F401
