"""Sparse(adj) × dense matmul via segment-sum.

Parity: tf_euler/python/contrib/spmm.py — the segment-sum formulation of
A @ X over an edge list, which XLA lowers to an efficient sorted-segment
reduction on TPU (the reference used it as the faster alternative to
tf.sparse ops; here it IS the canonical path, shared with mp_ops
scatter_add).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def spmm(edge_index: Array, x: Array, num_rows: int,
         edge_weight: Optional[Array] = None,
         normalize: bool = False) -> Array:
    """out[dst] += w · x[src] over the edge list.

    edge_index: [2, E] (src, dst) rows — the same convention as mp_ops
    and the conv zoo; x: [N, D]; normalize divides each output row by its
    incoming weight sum (mean aggregation).
    """
    src, dst = edge_index[0], edge_index[1]
    msgs = x[src]
    if edge_weight is not None:
        msgs = msgs * edge_weight[:, None].astype(msgs.dtype)
    out = jax.ops.segment_sum(msgs, dst, num_segments=num_rows)
    if normalize:
        ones = jnp.ones(dst.shape[0], msgs.dtype) if edge_weight is None \
            else edge_weight.astype(msgs.dtype)
        deg = jax.ops.segment_sum(ones, dst, num_segments=num_rows)
        out = out / jnp.maximum(deg, 1e-12)[:, None]
    return out
