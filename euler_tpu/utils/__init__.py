from euler_tpu.utils import aggregators, encoders, layers, metrics, optimizers  # noqa: F401


def hash64(s) -> int:
    """Stable 64-bit string hash for id mapping in data prep (parity:
    euler/util/python_api.cc py_hash64 exported to the json tools)."""
    from euler_tpu.core import lib as _libmod

    data = s.encode() if isinstance(s, str) else bytes(s)
    return int(_libmod.load().etg_hash64(data, len(data)))
