from euler_tpu.utils import aggregators, encoders, layers, metrics, optimizers  # noqa: F401
