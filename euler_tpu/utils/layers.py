"""Basic NN layers: Dense, Embedding, SparseEmbedding, AttLayer, LSTMLayer.

Parity: tf_euler/python/utils/layers.py:35-245 (a mini-Keras). Here the
layer system is flax.linen; this module provides the pieces the reference
defines that flax lacks — id-keyed embeddings (uint64 node ids → bucketed
rows), sparse-id embedding with mean/sum combiner, and the small attention
/ LSTM wrappers the encoders use.

The PS-sharded embedding of the reference (layers.py:119-171,
embedding.py) has its TPU-native counterpart in
euler_tpu.parallel.sharded_embedding (HBM-sharded table + ICI all-gather).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["Dense", "Embedding", "SparseEmbedding", "AttLayer", "LSTMLayer",
           "bucketize_ids"]

Dense = nn.Dense  # re-export: flax Dense is the reference's Dense


def bucketize_ids(ids: Array, num_buckets: int) -> Array:
    """node ids → int32 table rows, wrapping by modulo (preserves
    contiguous datasets' 1:1 mapping, matching reference behavior where
    ids index directly). Host feeders pre-convert uint64 ids to int32
    (estimator._to_device_tree) since device x64 is disabled; this handles
    any integer dtype that reaches the device."""
    ids = jnp.asarray(ids)
    if ids.dtype != jnp.int32:
        ids = ids.astype(jnp.int32)
    return ids % jnp.int32(num_buckets)


class Embedding(nn.Module):
    """Node-id embedding table: [max_id+1, dim], uint64-id-keyed."""

    num_embeddings: int
    dim: int
    init_scale: float = 0.05

    @nn.compact
    def __call__(self, ids: Array) -> Array:
        table = self.param(
            "table",
            nn.initializers.uniform(scale=self.init_scale),
            (self.num_embeddings, self.dim),
        )
        rows = bucketize_ids(ids, self.num_embeddings)
        return jnp.take(table, rows, axis=0)


class SparseEmbedding(nn.Module):
    """Embedding over variable-length sparse-id features, combined.

    Input is the padded dense form [B, L] with `pad_id` marking empties
    (the feeder pads CSR sparse features to a static L — see
    euler_tpu.dataflow.padding). combiner ∈ {mean, sum, max}.
    """

    num_embeddings: int
    dim: int
    combiner: str = "mean"
    pad_id: int = 0
    init_scale: float = 0.05

    @nn.compact
    def __call__(self, ids: Array) -> Array:
        table = self.param(
            "table",
            nn.initializers.uniform(scale=self.init_scale),
            (self.num_embeddings, self.dim),
        )
        rows = bucketize_ids(ids, self.num_embeddings)
        emb = jnp.take(table, rows, axis=0)            # [B, L, D]
        mask = (jnp.asarray(ids).astype(jnp.int32)
                != jnp.int32(self.pad_id)).astype(emb.dtype)[..., None]
        emb = emb * mask
        if self.combiner == "sum":
            return emb.sum(axis=1)
        if self.combiner == "max":
            return emb.max(axis=1)
        return emb.sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1.0)


class AttLayer(nn.Module):
    """Single-query soft attention pooling over a set [B, L, D] → [B, D].
    Parity: reference AttLayer (layers.py:~200)."""

    dim: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        q = self.param("query", nn.initializers.normal(stddev=0.1),
                       (self.dim,))
        keys = nn.Dense(self.dim, name="key")(x)            # [B, L, dim]
        logits = jnp.einsum("bld,d->bl", jnp.tanh(keys), q)
        att = nn.softmax(logits, axis=-1)
        return jnp.einsum("bl,bld->bd", att, x)


class LSTMLayer(nn.Module):
    """Runs an LSTM over [B, L, D], returns the full sequence of hiddens.
    Parity: reference LSTMLayer (layers.py:~230, used by SageEncoder's lstm
    aggregation and GeniePath)."""

    dim: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        return nn.RNN(nn.OptimizedLSTMCell(features=self.dim))(x)
