"""Dense-batch conversion utilities.

Parity: tf_euler/python/utils/to_dense_adj.py / to_dense_batch.py — turn
edge_index/node batches into fixed-shape dense adjacency / node tensors
for models that want [G, N_max, ...] layouts (DNA, set2set-style readouts).
Pure jnp, jit-safe with static max_nodes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def to_dense_batch(x: Array, graph_idx: Array, num_graphs: int,
                   max_nodes: int) -> Tuple[Array, Array]:
    """Scatter per-node rows into [num_graphs, max_nodes, D] + bool mask.

    x: [N, D]; graph_idx: [N] int graph assignment (rows beyond max_nodes
    per graph are dropped).
    """
    n = x.shape[0]
    # position of each node within its graph: rank among same-graph rows
    order = jnp.argsort(graph_idx, stable=True)
    sorted_gi = graph_idx[order]
    start_of_graph = jnp.searchsorted(sorted_gi, jnp.arange(num_graphs))
    pos_sorted = jnp.arange(n) - start_of_graph[sorted_gi]
    pos = jnp.zeros(n, dtype=pos_sorted.dtype).at[order].set(pos_sorted)

    keep = pos < max_nodes
    flat = jnp.where(keep, graph_idx * max_nodes + pos, num_graphs * max_nodes)
    out = jnp.zeros((num_graphs * max_nodes + 1, x.shape[-1]), x.dtype)
    out = out.at[flat].set(x)
    dense = out[:-1].reshape(num_graphs, max_nodes, x.shape[-1])
    mask = jnp.zeros(num_graphs * max_nodes + 1, bool).at[flat].set(keep)
    return dense, mask[:-1].reshape(num_graphs, max_nodes)


def to_dense_adj(edge_index: Array, graph_idx: Array, num_graphs: int,
                 max_nodes: int,
                 edge_weight: Optional[Array] = None) -> Array:
    """Edge list → dense [num_graphs, max_nodes, max_nodes] adjacency.

    edge_index: [2, E] rows into the node table; graph_idx: [N] graph of
    each node. Edges whose endpoint position exceeds max_nodes drop.
    """
    n = graph_idx.shape[0]
    order = jnp.argsort(graph_idx, stable=True)
    sorted_gi = graph_idx[order]
    start_of_graph = jnp.searchsorted(sorted_gi, jnp.arange(num_graphs))
    pos_sorted = jnp.arange(n) - start_of_graph[sorted_gi]
    pos = jnp.zeros(n, dtype=pos_sorted.dtype).at[order].set(pos_sorted)

    src, dst = edge_index[0], edge_index[1]
    g = graph_idx[src]
    ps, pd = pos[src], pos[dst]
    keep = (ps < max_nodes) & (pd < max_nodes) & (graph_idx[dst] == g)
    w = jnp.ones(src.shape[0], jnp.float32) if edge_weight is None \
        else edge_weight.astype(jnp.float32)
    flat = jnp.where(keep, (g * max_nodes + ps) * max_nodes + pd,
                     num_graphs * max_nodes * max_nodes)
    adj = jnp.zeros(num_graphs * max_nodes * max_nodes + 1, jnp.float32)
    adj = adj.at[flat].add(jnp.where(keep, w, 0.0))
    return adj[:-1].reshape(num_graphs, max_nodes, max_nodes)
