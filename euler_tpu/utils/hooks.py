"""Distributed worker coordination hooks.

Parity: tf_euler/python/utils/hooks.py:25 SyncExitHook — the reference's
between-graph workers block at end-of-training until every worker arrives,
so no PS connection drops while stragglers still need variables.

TPU equivalents:
  * under jax.distributed (multi-host), sync_exit() barriers all
    processes via the coordination service;
  * otherwise (or additionally, for host-side graph-service workers) a
    file barrier over a shared directory — the same mechanism as the
    server registry — lets heterogeneous workers rendezvous.
"""

from __future__ import annotations

import os
import time


def sync_exit(name: str = "exit") -> None:
    """Block until all jax processes reach this point (no-op single-host)."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"euler_tpu_sync_{name}")


class FileBarrier:
    """N-party rendezvous over a shared filesystem directory.

    Each worker calls wait(worker_id); returns once all num_workers have
    arrived. Reusable across rounds via the round counter.

    Marker files are namespaced by run_id — every worker of one job must
    pass the SAME run_id (e.g. the coordinator-assigned job id), and a
    restarted job must use a fresh one (or a fresh directory): stale
    markers from a crashed run would otherwise satisfy the count
    immediately. Files from two rounds back are garbage-collected (by
    then every worker has provably passed them).
    """

    def __init__(self, directory: str, num_workers: int,
                 run_id: str = "0", poll_ms: int = 100,
                 timeout_s: float = 600.0):
        self.dir = directory
        self.num_workers = num_workers
        self.run_id = run_id
        self.poll_ms = poll_ms
        self.timeout_s = timeout_s
        self._round = 0
        os.makedirs(directory, exist_ok=True)

    def _tag(self, rnd: int) -> str:
        return f"barrier_{self.run_id}_{rnd}_"

    def wait(self, worker_id: int) -> None:
        tag = self._tag(self._round)
        mine = os.path.join(self.dir, f"{tag}{worker_id}")
        with open(mine, "w"):
            pass
        # monotonic, not wall-clock: an NTP step during the wait must not
        # spuriously expire (or indefinitely extend) an exit barrier
        deadline = time.monotonic() + self.timeout_s
        while True:
            n = sum(1 for f in os.listdir(self.dir) if f.startswith(tag))
            if n >= self.num_workers:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"barrier timed out: {n}/{self.num_workers} arrived")
            time.sleep(self.poll_ms / 1000.0)
        # entering round r proves all workers passed r-1, so nobody can
        # still be counting r-2 — safe to reclaim those markers
        if self._round >= 2:
            old = self._tag(self._round - 2)
            for f in os.listdir(self.dir):
                if f.startswith(old):
                    try:
                        os.remove(os.path.join(self.dir, f))
                    except OSError:
                        pass
        self._round += 1
