"""Model metrics: accuracy / AUC / F1 / MRR / MR / hit@k.

Parity: tf_euler/python/utils/metrics.py:23-97. Implemented as pure
jax.numpy functions (jit-able, no TF streaming-metric state); callers
average across batches themselves (the estimator does).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["accuracy", "auc", "f1_score", "micro_f1", "mrr", "mr", "hit_at_k",
           "masked_mean", "get_metric"]


def masked_mean(x: Array, mask) -> Array:
    """Mean of x over rows where mask (0/1, any shape raveling to [B]) is
    set; plain mean when mask is None."""
    if mask is None:
        return jnp.mean(x)
    m = mask.ravel().astype(jnp.float32)
    return jnp.sum(x * m) / jnp.maximum(m.sum(), 1.0)


def accuracy(logits: Array, labels: Array, mask=None) -> Array:
    """Multiclass (argmax over last dim) or binary (threshold 0.5).
    mask [B] (0/1) excludes padded rows from the mean."""
    if logits.ndim > 1 and logits.shape[-1] > 1:
        pred = jnp.argmax(logits, axis=-1)
        lab = labels if labels.ndim == logits.ndim - 1 else jnp.argmax(labels, -1)
        return masked_mean((pred == lab).astype(jnp.float32), mask)
    pred = (logits.ravel() > 0.5).astype(jnp.int32)
    return masked_mean((pred == labels.ravel().astype(jnp.int32)).astype(
        jnp.float32), mask)


def auc(scores: Array, labels: Array) -> Array:
    """Exact pairwise AUC (rank-based, handles ties by midrank)."""
    scores = scores.ravel()
    labels = labels.ravel().astype(jnp.float32)
    order = jnp.argsort(scores)
    ranks = jnp.zeros_like(scores).at[order].set(
        jnp.arange(1, scores.shape[0] + 1, dtype=scores.dtype))
    # midrank correction for ties: average rank within equal-score groups
    n_pos = labels.sum()
    n_neg = labels.shape[0] - n_pos
    pos_rank_sum = (ranks * labels).sum()
    return (pos_rank_sum - n_pos * (n_pos + 1) / 2) / jnp.maximum(
        n_pos * n_neg, 1.0)


def micro_f1(logits: Array, labels: Array, threshold: float = 0.5,
             mask=None) -> Array:
    """Micro-averaged F1 for multilabel (sigmoid) or one-hot multiclass.
    mask [B] (0/1) drops padded rows from every tp/fp/fn count."""
    if logits.ndim > 1 and labels.ndim == 1:
        pred = jax.nn.one_hot(jnp.argmax(logits, -1), logits.shape[-1])
        lab = jax.nn.one_hot(labels, logits.shape[-1])
    else:
        pred = (logits > threshold).astype(jnp.float32)
        lab = labels.astype(jnp.float32)
    if mask is not None:
        m = mask.astype(jnp.float32).reshape(
            mask.shape + (1,) * (pred.ndim - mask.ndim))
        pred = pred * m
        lab = lab * m
    tp = (pred * lab).sum()
    fp = (pred * (1 - lab)).sum()
    fn = ((1 - pred) * lab).sum()
    return 2 * tp / jnp.maximum(2 * tp + fp + fn, 1.0)


f1_score = micro_f1


def _ranks(scores: Array) -> Array:
    """Rank of column 0 (the positive) among all columns, per row.
    scores: [B, 1+num_neg], higher = better."""
    pos = scores[:, :1]
    return 1.0 + (scores[:, 1:] >= pos).sum(axis=1).astype(jnp.float32)


def mrr(scores: Array) -> Array:
    """Mean reciprocal rank; scores[:, 0] is the positive candidate."""
    return jnp.mean(1.0 / _ranks(scores))


def mr(scores: Array) -> Array:
    """Mean rank."""
    return jnp.mean(_ranks(scores))


def hit_at_k(scores: Array, k: int) -> Array:
    return jnp.mean((_ranks(scores) <= k).astype(jnp.float32))


def get_metric(name: str):
    name = name.lower()
    table = {
        "acc": accuracy, "accuracy": accuracy,
        "auc": auc,
        "f1": micro_f1, "micro_f1": micro_f1,
        "mrr": mrr, "mr": mr,
        "hit1": lambda s: hit_at_k(s, 1),
        "hit3": lambda s: hit_at_k(s, 3),
        "hit10": lambda s: hit_at_k(s, 10),
    }
    try:
        return table[name]
    except KeyError:
        raise ValueError(f"unknown metric {name!r}") from None
