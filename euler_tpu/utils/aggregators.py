"""Dense neighborhood aggregators for the fanout/encoder path.

Parity: tf_euler/python/utils/aggregators.py:25-117 (Mean, MeanPool,
MaxPool, GCN aggregators). TPU-first: these operate on regular [B, K, D]
sampled-neighbor tensors — pure dense reductions + matmuls, no scatter at
all, which is the shape the MXU/VPU wants. This is the primary scalable
path (the reference's encoders use exactly these).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["MeanAggregator", "MeanPoolAggregator", "MaxPoolAggregator",
           "GCNAggregator", "get_aggregator"]


class MeanAggregator(nn.Module):
    """concat(W_self x, W_nbr mean_k(nbr)) → [B, 2*dim] (or sum if concat=False)."""

    dim: int
    activation: str = "relu"
    concat: bool = True

    @nn.compact
    def __call__(self, x: Array, nbr: Array) -> Array:
        act = getattr(nn, self.activation) if self.activation else (lambda v: v)
        h_self = act(nn.Dense(self.dim, name="self")(x))
        h_nbr = act(nn.Dense(self.dim, name="nbr")(nbr.mean(axis=1)))
        if self.concat:
            return jnp.concatenate([h_self, h_nbr], axis=-1)
        return h_self + h_nbr


class MeanPoolAggregator(nn.Module):
    """MLP per neighbor then mean-pool, concat with self transform."""

    dim: int
    activation: str = "relu"
    concat: bool = True

    @nn.compact
    def __call__(self, x: Array, nbr: Array) -> Array:
        act = getattr(nn, self.activation) if self.activation else (lambda v: v)
        h_self = act(nn.Dense(self.dim, name="self")(x))
        pooled = act(nn.Dense(self.dim, name="mlp")(nbr)).mean(axis=1)
        h_nbr = act(nn.Dense(self.dim, name="nbr")(pooled))
        if self.concat:
            return jnp.concatenate([h_self, h_nbr], axis=-1)
        return h_self + h_nbr


class MaxPoolAggregator(nn.Module):
    """MLP per neighbor then max-pool, concat with self transform."""

    dim: int
    activation: str = "relu"
    concat: bool = True

    @nn.compact
    def __call__(self, x: Array, nbr: Array) -> Array:
        act = getattr(nn, self.activation) if self.activation else (lambda v: v)
        h_self = act(nn.Dense(self.dim, name="self")(x))
        pooled = act(nn.Dense(self.dim, name="mlp")(nbr)).max(axis=1)
        h_nbr = act(nn.Dense(self.dim, name="nbr")(pooled))
        if self.concat:
            return jnp.concatenate([h_self, h_nbr], axis=-1)
        return h_self + h_nbr


class GCNAggregator(nn.Module):
    """W · mean(concat(x, nbr)) — single shared transform, GCN-style."""

    dim: int
    activation: str = "relu"

    @nn.compact
    def __call__(self, x: Array, nbr: Array) -> Array:
        act = getattr(nn, self.activation) if self.activation else (lambda v: v)
        both = jnp.concatenate([x[:, None, :], nbr], axis=1)
        return act(nn.Dense(self.dim, name="w")(both.mean(axis=1)))


_AGGREGATORS = {
    "mean": MeanAggregator,
    "meanpool": MeanPoolAggregator,
    "maxpool": MaxPoolAggregator,
    "gcn": GCNAggregator,
}


def get_aggregator(name: str):
    try:
        return _AGGREGATORS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown aggregator {name!r}; options: {sorted(_AGGREGATORS)}"
        ) from None
