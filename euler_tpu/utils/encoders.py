"""Node encoders over sampled fanouts — the scalable training path.

Parity: tf_euler/python/utils/encoders.py:32-872 (ShallowEncoder,
GCNEncoder, ScalableGCNEncoder, SageEncoder, ScalableSageEncoder,
LayerEncoder, SparseSageEncoder, GenieEncoder, LGCEncoder).

TPU-first redesign: the reference's encoders issue graph queries from
inside the TF graph; here sampling happens host-side (dataflow builds a
`FanoutBatch` of per-hop feature tensors with static shapes) and encoders
are pure flax modules: hop h's neighbors reshape to [n_h, k, D] and
aggregate densely — no scatter, all MXU-friendly reductions. The
"scalable" encoders keep per-node activation caches as a mutable flax
variable collection ("cache") updated functionally each step, replacing
the reference's TF variable assign machinery (encoders.py:294,629).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.utils.aggregators import get_aggregator
from euler_tpu.utils.layers import AttLayer, Embedding, LSTMLayer, SparseEmbedding, bucketize_ids

Array = jax.Array


class ShallowEncoder(nn.Module):
    """Id-embedding and/or dense-feature encoder (reference encoders.py:32).

    combiner: 'concat' or 'add' of [id embedding, W·dense_feature].
    """

    dim: int
    max_id: int = 0              # >0 enables the id embedding
    use_feature: bool = True
    combiner: str = "concat"

    @nn.compact
    def __call__(self, ids: Array, feats: Optional[Array] = None) -> Array:
        parts = []
        if self.max_id > 0:
            parts.append(Embedding(self.max_id + 1, self.dim, name="id_emb")(ids))
        if self.use_feature and feats is not None:
            parts.append(nn.Dense(self.dim, name="feat")(feats))
        if not parts:
            raise ValueError("ShallowEncoder has neither id embedding nor features")
        if len(parts) == 1:
            return parts[0]
        if self.combiner == "add":
            return sum(parts)
        return jnp.concatenate(parts, axis=-1)


def _hop_neighbors(child: Array, parent: Array) -> Array:
    """Reshape hop h+1's flat layer to [n_h, k, D], deriving k from the
    (jit-static) shapes. Shared by all fanout encoders so the divisibility
    invariant lives in one place."""
    n = parent.shape[0]
    assert child.shape[0] % n == 0, (
        f"layer of {child.shape[0]} rows is not a whole fanout of the "
        f"{n}-row parent layer")
    return child.reshape(n, child.shape[0] // n, -1)


class SageEncoder(nn.Module):
    """GraphSAGE encoder over a sampled fanout (reference encoders.py SageEncoder).

    layers[h]: feature tensor of hop h, shape [B·Πk_{<h}, D]. Aggregates
    deepest-first with fresh aggregator params per hop. Per-hop widths k
    are derived from the layer shapes (static under jit), so parameters
    are fanout-independent — evaluation may use wider fanouts than
    training (pass a bigger-fanout eval_dataflow to NodeEstimator);
    `fanouts` only fixes the hop count.
    """

    dim: int
    fanouts: Sequence[int]
    aggregator: str = "mean"
    concat: bool = True

    @nn.compact
    def __call__(self, layers: Sequence[Array]) -> Array:
        n_hops = len(self.fanouts)
        assert len(layers) == n_hops + 1, (
            f"need {n_hops + 1} feature layers for {n_hops} fanouts"
        )
        agg_cls = get_aggregator(self.aggregator)
        hidden = list(layers)
        for depth in range(n_hops):
            agg = agg_cls(dim=self.dim, concat=self.concat,
                          name=f"agg_{depth}")
            next_hidden = []
            for hop in range(n_hops - depth):
                x = hidden[hop]
                nbr = _hop_neighbors(hidden[hop + 1], x)
                next_hidden.append(agg(x, nbr))
            hidden = next_hidden
        return hidden[0]


class GCNEncoder(nn.Module):
    """GCN-style encoder over a fanout (reference GCNEncoder): shared
    transform of self+neighbors, mean-combined, final layer linear."""

    dim: int
    fanouts: Sequence[int]

    @nn.compact
    def __call__(self, layers: Sequence[Array]) -> Array:
        n_hops = len(self.fanouts)
        assert len(layers) == n_hops + 1, (
            f"need {n_hops + 1} feature layers for {n_hops} fanouts")
        hidden = list(layers)
        for depth in range(n_hops):
            w = nn.Dense(self.dim, use_bias=False, name=f"w_{depth}")
            last = depth == n_hops - 1
            next_hidden = []
            for hop in range(n_hops - depth):
                x = hidden[hop]
                nbr = _hop_neighbors(hidden[hop + 1], x)
                both = jnp.concatenate([x[:, None, :], nbr], axis=1)
                h = w(both.mean(axis=1))
                next_hidden.append(h if last else nn.relu(h))
            hidden = next_hidden
        return hidden[0]


def _ema_update(old: Array, fresh: Array, decay: float) -> Array:
    """Bias-corrected cache write: rows never written before (all-zero —
    the init value) take the fresh activation at FULL scale; visited
    rows blend decay·old + (1-decay)·fresh. Without this, a node's
    first write lands at (1-decay)·h ≈ 0.1·h and rarely-visited nodes'
    cached activations stay massively under-scaled — the zero-init bias
    of a plain EMA. (A live activation that is exactly all-zero would be
    re-written at full scale too, which is the same value — harmless.)"""
    seen = jnp.any(old != 0, axis=-1, keepdims=True)
    return jnp.where(seen, decay * old + (1 - decay) * fresh, fresh)


class _ScalableCache(nn.Module):
    """Per-node activation cache: [max_id+1, dim] rows in the 'cache'
    collection, read for neighbor ids, written for the batch's own ids.

    dtype picks the stored row precision: bfloat16 halves the HBM
    footprint AND the per-step read bytes at products scale (the whole
    point of the cache is replacing a bigger gather); reads are upcast
    to float32 before use."""

    max_id: int
    dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, read_ids: Array, write_ids: Optional[Array] = None,
                 write_vals: Optional[Array] = None) -> Array:
        cache = self.variable(
            "cache", "h",
            lambda: jnp.zeros((self.max_id + 1, self.dim), self.dtype))
        out = jnp.take(cache.value, bucketize_ids(read_ids, self.max_id + 1),
                       axis=0).astype(jnp.float32)
        if (write_ids is not None and write_vals is not None
                and self.is_mutable_collection("cache")):
            # eval/infer apply the module with the cache frozen; historical
            # activations are read-only there (reference ScalableGCNEncoder
            # only updates stores inside the training op).
            rows = bucketize_ids(write_ids, self.max_id + 1)
            cache.value = cache.value.at[rows].set(
                write_vals.astype(self.dtype))
        return out


class ScalableGCNEncoder(nn.Module):
    """Scalable GCN (reference encoders.py:294): depth-L GCN but only 1-hop
    sampling — deeper-hop activations come from the historical cache, and
    this batch's fresh layer-l activations are written back.

    Inputs: ids [B], x [B, D] features, nbr_ids [B, K], nbr_x [B, K, D].
    Run with mutable=['cache'] during training.
    """

    dim: int
    num_layers: int
    max_id: int
    store_decay: float = 0.9
    cache_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids: Array, x: Array, nbr_ids: Array,
                 nbr_x: Array) -> Array:
        b, k = nbr_ids.shape
        # one cache module per non-input layer, created once
        caches = {layer: _ScalableCache(self.max_id, self.dim,
                                        dtype=self.cache_dtype,
                                        name=f"cache_{layer}")
                  for layer in range(1, self.num_layers)}
        h_self = x
        for layer in range(self.num_layers):
            w = nn.Dense(self.dim, use_bias=False, name=f"w_{layer}")
            if layer == 0:
                nbr_h = nbr_x
            else:
                nbr_h = caches[layer](nbr_ids.ravel()).reshape(b, k, self.dim)
            both = jnp.concatenate([h_self[:, None, :], nbr_h], axis=1)
            h_self = w(both.mean(axis=1))
            if layer < self.num_layers - 1:
                h_self = nn.relu(h_self)
                # store this batch's layer-(l+1) input activations
                store = caches[layer + 1]
                old = store(ids)
                new = _ema_update(old, h_self, self.store_decay)
                store(ids, write_ids=ids, write_vals=new)
        return h_self


class ScalableSageEncoder(nn.Module):
    """Scalable GraphSAGE (reference encoders.py:629): same cache trick,
    SAGE concat aggregation."""

    dim: int
    num_layers: int
    max_id: int
    store_decay: float = 0.9
    cache_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids: Array, x: Array, nbr_ids: Array,
                 nbr_x: Array) -> Array:
        b, k = nbr_ids.shape
        caches = {layer: _ScalableCache(self.max_id, self.dim,
                                        dtype=self.cache_dtype,
                                        name=f"cache_{layer}")
                  for layer in range(1, self.num_layers)}
        h_self = x
        for layer in range(self.num_layers):
            if layer == 0:
                nbr_h = nbr_x
            else:
                nbr_h = caches[layer](nbr_ids.ravel()).reshape(b, k, self.dim)
            h_cat = jnp.concatenate([h_self, nbr_h.mean(axis=1)], axis=-1)
            h_new = nn.Dense(self.dim, name=f"w_{layer}")(h_cat)
            if layer < self.num_layers - 1:
                h_new = nn.relu(h_new)
                store = caches[layer + 1]
                old = store(ids)
                upd = _ema_update(old, h_new, self.store_decay)
                store(ids, write_ids=ids, write_vals=upd)
            h_self = h_new
        return h_self


class LayerEncoder(nn.Module):
    """Layerwise (FastGCN/LADIES) encoder (reference LayerEncoder):
    h_{l+1} = act(Â_l h_l W_l) over importance-sampled layer pools.

    adjs[l]: dense [m_l, m_{l+1}] normalized adjacency between pools
    (built host-side by LayerwiseDataFlow); layers[l]: [m_l, D] features,
    layers[-1] is the deepest pool, layers[0] the batch nodes.
    """

    dim: int
    dropout: float = 0.0  # input dropout per layer (standard FastGCN setup)

    @nn.compact
    def __call__(self, layers: Sequence[Array], adjs: Sequence[Array]) -> Array:
        h = layers[-1]
        n_layers = len(adjs)
        for i in range(n_layers - 1, -1, -1):
            if self.dropout > 0.0:
                h = nn.Dropout(self.dropout)(
                    h, deterministic=not self.has_rng("dropout"))
            w = nn.Dense(self.dim, use_bias=False, name=f"w_{i}")
            h = adjs[i] @ w(h)
            if i > 0:
                h = nn.relu(h)
        return h


class SparseSageEncoder(nn.Module):
    """SAGE over sparse-id features (reference SparseSageEncoder): per-hop
    sparse embeddings + SageEncoder aggregation.

    sparse_layers[h]: padded sparse-id tensor [n_h, L]."""

    dim: int
    fanouts: Sequence[int]
    num_embeddings: int
    aggregator: str = "mean"
    concat: bool = True

    @nn.compact
    def __call__(self, sparse_layers: Sequence[Array]) -> Array:
        emb = SparseEmbedding(self.num_embeddings, self.dim, name="sp_emb")
        dense_layers = [emb(s) for s in sparse_layers]
        return SageEncoder(self.dim, self.fanouts, self.aggregator,
                           self.concat, name="sage")(dense_layers)


class GenieEncoder(nn.Module):
    """GeniePath (reference GenieEncoder): adaptive breadth (attention) +
    depth (LSTM gating) over a fanout."""

    dim: int
    fanouts: Sequence[int]

    @nn.compact
    def __call__(self, layers: Sequence[Array]) -> Array:
        n_hops = len(self.fanouts)
        assert len(layers) == n_hops + 1, (
            f"need {n_hops + 1} feature layers for {n_hops} fanouts")
        # project all layers to dim
        proj = nn.Dense(self.dim, name="proj")
        hidden = [proj(h) for h in layers]
        # adaptive depth: collect the root representation after every
        # breadth layer (reference encoders.py:265-277 depth_fc per layer)
        h_t = [nn.Dense(self.dim, name="depth_fc_0")(hidden[0])]
        # breadth: attention-pool each hop's neighborhood into the target
        for depth in range(n_hops):
            att = AttLayer(self.dim, name=f"att_{depth}")
            next_hidden = []
            for hop in range(n_hops - depth):
                x = hidden[hop]
                nbr = _hop_neighbors(hidden[hop + 1], x)
                pooled = att(jnp.concatenate([x[:, None, :], nbr], axis=1))
                next_hidden.append(nn.tanh(
                    nn.Dense(self.dim, name=f"w_{depth}_{hop}")(pooled)))
            hidden = next_hidden
            h_t.append(
                nn.Dense(self.dim, name=f"depth_fc_{depth + 1}")(hidden[0]))
        # depth gating: LSTM over the depth sequence [B, L+1, dim]. The
        # paper reads the final state; the reference's code reads
        # timestep 0 (encoders.py:287), which discards the gating — we
        # follow the paper.
        seq = jnp.stack(h_t, axis=1)
        out = LSTMLayer(self.dim, name="depth_lstm")(seq)
        return out[:, -1, :]


class LGCEncoder(nn.Module):
    """LGCN encoder (reference LGCEncoder): per-feature top-k ordering of
    neighbor values then 1-D conv over the ordered sequence."""

    dim: int
    k: int = 4

    @nn.compact
    def __call__(self, x: Array, nbr: Array) -> Array:
        # nbr: [B, K, D] with K >= k. top-k per feature channel
        b, K, d = nbr.shape
        topk = jax.lax.top_k(jnp.swapaxes(nbr, 1, 2), self.k)[0]  # [B, D, k]
        seq = jnp.concatenate([x[:, :, None], topk], axis=-1)     # [B, D, k+1]
        seq = jnp.swapaxes(seq, 1, 2)                             # [B, k+1, D]
        h = nn.Conv(features=self.dim, kernel_size=(self.k + 1,),
                    padding="VALID", name="conv")(seq)            # [B, 1, dim]
        return h[:, 0, :]
