"""Optimizer factory. Parity: tf_euler/python/utils/optimizers.py
(sgd/adam/adagrad/momentum by name) → optax."""

from __future__ import annotations

import optax

__all__ = ["get"]


def get(name: str, learning_rate: float = 0.01, **kw):
    name = name.lower()
    weight_decay = kw.pop("weight_decay", 0.0)
    if weight_decay and name in ("adam", "adamw"):
        return optax.adamw(learning_rate, weight_decay=weight_decay, **kw)
    if weight_decay:
        raise ValueError(
            f"weight_decay is only supported with adam/adamw, got {name!r}")
    if name == "sgd":
        return optax.sgd(learning_rate)
    if name == "adam":
        return optax.adam(learning_rate, **kw)
    if name == "adagrad":
        return optax.adagrad(learning_rate, **kw)
    if name == "momentum":
        return optax.sgd(learning_rate, momentum=kw.pop("momentum", 0.9), **kw)
    if name == "rmsprop":
        return optax.rmsprop(learning_rate, **kw)
    if name == "adamw":
        return optax.adamw(learning_rate, **kw)
    raise ValueError(f"unknown optimizer {name!r}")
