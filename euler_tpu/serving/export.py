"""Model export: versioned, checksummed serving bundles.

The train→serve seam (the reference's real deployment loop: train node
embeddings offline, serve embedding-lookup / kNN queries online — the
same split TF-GNN makes the centerpiece of its production design). A
**ModelBundle** is a directory holding everything the serving tier
needs, with a manifest that makes corruption detectable at load:

  manifest.json     schema_version, model spec, per-file sha256 + sizes
  params.npz        flattened trained parameter pytree ("path" → array)
  embeddings.npy    [N, D] float32 node-embedding matrix (embed_all)
  ids.npy           [N] uint64 node ids, SORTED ascending (the serving
                    lookup is a searchsorted over this order)
  index.npz         IVFFlat coarse-quantizer state (tools/knn.py)

Loads verify the schema version and every file's checksum; a missing,
truncated, or bit-flipped file raises BundleCorruptionError instead of
serving garbage. Writes go through a temp directory + atomic rename so
a crashed export never leaves a half-written bundle at the target path.

**Sharded layout** (`save_sharded`): the serving-fleet analogue of the
contiguous 1/K row shards `parallel/partitioned_store.py` cuts device
tables into — shard s holds rows [lo_s, hi_s) of the SORTED id order,
so each shard's ids stay sorted (lookup is still a searchsorted) and
id-range routing is a binary search over shard lower bounds:

  manifest.json        one manifest for the whole fleet: schema, a
                       "shards" block (count, per-shard row + id
                       ranges) and per-file sha256 for EVERY shard
  params.npz           shared trained params (written once)
  embeddings.<s>.npy   shard s's [n_s, D] rows
  ids.<s>.npy          shard s's sorted ids
  index.<s>.npz        per-shard IVFFlat state (trained on the shard)

`load_shard(dir, s)` verifies and loads ONE shard (plus the shared
params) — corruption in shard 3 never blocks shard 0's replica from
serving. `load()` on a sharded dir reassembles the full bundle (the
concatenation of contiguous sorted shards is the original sorted
order), which is what parity tests diff the fleet against.

Bundles carry a **version** (meta key ``bundle_version``, defaulting
to the training step) — the identity the zero-downtime hot-swap
protocol flips between and reports in info()/healthz.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

__all__ = ["SCHEMA_VERSION", "BundleCorruptionError", "ModelBundle",
           "embed_all", "shard_bounds", "bundle_shard_count"]

SCHEMA_VERSION = 1

_PARAMS = "params.npz"
_EMB = "embeddings.npy"
_IDS = "ids.npy"
_INDEX = "index.npz"
_MANIFEST = "manifest.json"


def shard_bounds(count: int, shards: int):
    """Contiguous near-equal [lo, hi) row ranges — the same contiguous
    1/K convention the partitioned device tables use. Every shard is
    non-empty (a replica serving zero rows has no id range to route)."""
    if shards < 1:
        raise ValueError("shards must be >= 1")
    if count < shards:
        raise ValueError(
            f"cannot cut {count} embedding rows into {shards} shards")
    return [(round(i * count / shards), round((i + 1) * count / shards))
            for i in range(shards)]


class BundleCorruptionError(RuntimeError):
    """The bundle on disk does not match its manifest (missing file,
    checksum mismatch, unsupported schema) — refuse to serve it."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _json_safe(v: Any) -> Any:
    """Best-effort JSON projection of a model-spec value; non-trivial
    objects collapse to their repr (the spec is documentation, not a
    reconstruction format)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (np.integer, np.floating)):
        return v.item()
    return repr(v)


class ModelBundle:
    """In-memory view of an export bundle (see module docstring)."""

    def __init__(self, params: Dict[str, np.ndarray],
                 embeddings: np.ndarray, ids: np.ndarray,
                 index_state: Optional[Dict[str, np.ndarray]] = None,
                 model_spec: Optional[Dict[str, Any]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        embeddings = np.ascontiguousarray(embeddings, dtype=np.float32)
        if embeddings.ndim != 2 or embeddings.shape[0] != ids.shape[0]:
            raise ValueError(
                f"embeddings {embeddings.shape} must be [N, D] aligned "
                f"with ids {ids.shape}")
        if ids.size and not (ids[:-1] < ids[1:]).all():
            raise ValueError("ids must be sorted ascending and unique "
                             "(the serving lookup is a searchsorted)")
        self.params = dict(params or {})
        self.embeddings = embeddings
        self.ids = ids
        self.index_state = dict(index_state) if index_state else None
        self.model_spec = dict(model_spec or {})
        self.meta = dict(meta or {})

    @property
    def dim(self) -> int:
        return int(self.embeddings.shape[1]) if self.embeddings.size else 0

    @property
    def count(self) -> int:
        return int(self.ids.shape[0])

    @property
    def version(self) -> str:
        """Bundle identity for the hot-swap protocol: the explicit
        ``bundle_version`` meta when the export set one, else the
        training step it was cut at."""
        v = self.meta.get("bundle_version")
        if v is None:
            v = f"step{self.meta.get('global_step', 0)}"
        return str(v)

    @property
    def shard(self) -> int:
        """This bundle's shard index (0 for an unsharded bundle)."""
        return int(self.meta.get("shard", 0))

    @property
    def num_shards(self) -> int:
        return int(self.meta.get("num_shards", 1))

    def build_index(self):
        """IVFFlatIndex over this bundle's embeddings — from the stored
        state when present (exactly the exported clustering), trained
        fresh otherwise."""
        from euler_tpu.tools.knn import IVFFlatIndex

        if self.index_state is not None:
            return IVFFlatIndex.from_state(self.index_state,
                                           self.embeddings, self.ids)
        idx = IVFFlatIndex()
        idx.train_add(self.embeddings, self.ids)
        return idx

    # -- persistence -------------------------------------------------------
    def save(self, out_dir: str) -> str:
        """Write the bundle under out_dir (atomic: temp dir + rename).
        Returns out_dir."""
        out_dir = os.path.abspath(out_dir)
        tmp = out_dir + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.save(os.path.join(tmp, _EMB), self.embeddings)
        np.save(os.path.join(tmp, _IDS), self.ids)
        np.savez(os.path.join(tmp, _PARAMS),
                 **{k: np.asarray(v) for k, v in self.params.items()})
        files = [_EMB, _IDS, _PARAMS]
        if self.index_state is not None:
            np.savez(os.path.join(tmp, _INDEX), **self.index_state)
            files.append(_INDEX)
        manifest = {
            "schema_version": SCHEMA_VERSION,
            "model_spec": _json_safe(self.model_spec),
            "meta": _json_safe(self.meta),
            "embedding_count": self.count,
            "embedding_dim": self.dim,
            "files": {
                name: {"sha256": _sha256(os.path.join(tmp, name)),
                       "bytes": os.path.getsize(os.path.join(tmp, name))}
                for name in files
            },
        }
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        if os.path.isdir(out_dir):
            shutil.rmtree(out_dir)
        os.replace(tmp, out_dir)
        return out_dir

    # -- sharded persistence ----------------------------------------------
    def save_sharded(self, out_dir: str, shards: int, nlist: int = 64,
                     nprobe: int = 8, index: bool = True,
                     seed: int = 0) -> str:
        """Write a partitioned fleet bundle (see module docstring):
        contiguous 1/N row shards, a per-shard IVFFlat trained on each
        shard's rows, one manifest with every shard's sha256. Atomic
        like save(). Returns out_dir."""
        from euler_tpu.tools.knn import IVFFlatIndex

        bounds = shard_bounds(self.count, shards)
        out_dir = os.path.abspath(out_dir)
        tmp = out_dir + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, _PARAMS),
                 **{k: np.asarray(v) for k, v in self.params.items()})
        files = [_PARAMS]
        for s, (lo, hi) in enumerate(bounds):
            emb_s = np.ascontiguousarray(self.embeddings[lo:hi])
            ids_s = np.ascontiguousarray(self.ids[lo:hi])
            np.save(os.path.join(tmp, f"embeddings.{s}.npy"), emb_s)
            np.save(os.path.join(tmp, f"ids.{s}.npy"), ids_s)
            files += [f"embeddings.{s}.npy", f"ids.{s}.npy"]
            if index and hi - lo >= 2:
                idx = IVFFlatIndex(nlist=nlist, nprobe=nprobe, seed=seed)
                idx.train_add(emb_s, ids_s)
                np.savez(os.path.join(tmp, f"index.{s}.npz"),
                         **idx.state_dict())
                files.append(f"index.{s}.npz")
        manifest = {
            "schema_version": SCHEMA_VERSION,
            "model_spec": _json_safe(self.model_spec),
            "meta": _json_safe(self.meta),
            "embedding_count": self.count,
            "embedding_dim": self.dim,
            "shards": {
                "count": shards,
                "rows": [[lo, hi] for lo, hi in bounds],
                "id_ranges": [[int(self.ids[lo]), int(self.ids[hi - 1])]
                              for lo, hi in bounds],
            },
            "files": {
                name: {"sha256": _sha256(os.path.join(tmp, name)),
                       "bytes": os.path.getsize(os.path.join(tmp, name))}
                for name in files
            },
        }
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        if os.path.isdir(out_dir):
            shutil.rmtree(out_dir)
        os.replace(tmp, out_dir)
        return out_dir

    @classmethod
    def load(cls, bundle_dir: str, verify: bool = True) -> "ModelBundle":
        """Load + (by default) verify a bundle. A sharded bundle is
        reassembled whole (contiguous sorted shards concatenate back to
        the original sorted order; the global index is not stored, so
        index_state is None). Any mismatch between disk and manifest
        raises BundleCorruptionError."""
        manifest = _read_manifest(bundle_dir)
        files = manifest.get("files", {})
        sharding = manifest.get("shards")
        if sharding is not None:
            _check_files(bundle_dir, files, verify)
            n = int(sharding.get("count", 0))
            if n < 1:
                raise BundleCorruptionError(
                    f"sharded manifest with shard count {n}")
            if _PARAMS not in files:
                raise BundleCorruptionError(
                    f"manifest lists no {_PARAMS}")
            embs, idss = [], []
            for s in range(n):
                for name in (f"embeddings.{s}.npy", f"ids.{s}.npy"):
                    if name not in files:
                        raise BundleCorruptionError(
                            f"manifest lists no {name}")
                embs.append(np.load(
                    os.path.join(bundle_dir, f"embeddings.{s}.npy")))
                idss.append(np.load(
                    os.path.join(bundle_dir, f"ids.{s}.npy")))
            with np.load(os.path.join(bundle_dir, _PARAMS)) as z:
                params = {k: z[k] for k in z.files}
            bundle = cls(params, np.concatenate(embs),
                         np.concatenate(idss), None,
                         manifest.get("model_spec"), manifest.get("meta"))
        else:
            _check_files(bundle_dir, files, verify)
            for required in (_EMB, _IDS, _PARAMS):
                if required not in files:
                    raise BundleCorruptionError(
                        f"manifest lists no {required}")
            emb = np.load(os.path.join(bundle_dir, _EMB))
            ids = np.load(os.path.join(bundle_dir, _IDS))
            with np.load(os.path.join(bundle_dir, _PARAMS)) as z:
                params = {k: z[k] for k in z.files}
            index_state = None
            if _INDEX in files:
                with np.load(os.path.join(bundle_dir, _INDEX)) as z:
                    index_state = {k: z[k] for k in z.files}
            bundle = cls(params, emb, ids, index_state,
                         manifest.get("model_spec"), manifest.get("meta"))
        if bundle.count != manifest.get("embedding_count") \
                or bundle.dim != manifest.get("embedding_dim"):
            raise BundleCorruptionError(
                "embedding shape disagrees with manifest")
        return bundle

    @classmethod
    def load_shard(cls, bundle_dir: str, shard: int,
                   verify: bool = True) -> "ModelBundle":
        """Load ONE shard of a sharded bundle (plus the shared params)
        as a self-contained ModelBundle whose meta carries the shard
        identity (shard / num_shards). Only the shard's own files and
        params are checksummed, so corruption in another shard never
        blocks this replica."""
        manifest = _read_manifest(bundle_dir)
        sharding = manifest.get("shards")
        if sharding is None:
            raise BundleCorruptionError(
                f"{bundle_dir} is not a sharded bundle (no shards block "
                "in the manifest); load() serves it whole")
        n = int(sharding.get("count", 0))
        if not 0 <= shard < n:
            raise BundleCorruptionError(
                f"shard {shard} out of range for {n}-shard bundle")
        files = manifest.get("files", {})
        names = [_PARAMS, f"embeddings.{shard}.npy", f"ids.{shard}.npy"]
        index_name = f"index.{shard}.npz"
        if index_name in files:
            names.append(index_name)
        for name in names:
            if name not in files:
                raise BundleCorruptionError(f"manifest lists no {name}")
        _check_files(bundle_dir, {k: files[k] for k in names}, verify)
        emb = np.load(os.path.join(bundle_dir, f"embeddings.{shard}.npy"))
        ids = np.load(os.path.join(bundle_dir, f"ids.{shard}.npy"))
        with np.load(os.path.join(bundle_dir, _PARAMS)) as z:
            params = {k: z[k] for k in z.files}
        index_state = None
        if index_name in files:
            with np.load(os.path.join(bundle_dir, index_name)) as z:
                index_state = {k: z[k] for k in z.files}
        meta = dict(manifest.get("meta") or {})
        meta["shard"] = int(shard)
        meta["num_shards"] = n
        return cls(params, emb, ids, index_state,
                   manifest.get("model_spec"), meta)


def _read_manifest(bundle_dir: str) -> Dict[str, Any]:
    mpath = os.path.join(bundle_dir, _MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise BundleCorruptionError(
            f"unreadable manifest {mpath}: {e}") from e
    ver = manifest.get("schema_version")
    if ver != SCHEMA_VERSION:
        raise BundleCorruptionError(
            f"bundle schema_version {ver!r} unsupported "
            f"(this build reads {SCHEMA_VERSION})")
    return manifest


def _check_files(bundle_dir: str, files: Dict[str, Any],
                 verify: bool) -> None:
    """Presence + (when verify) size/sha256 check of the listed files."""
    for name, info in files.items():
        path = os.path.join(bundle_dir, name)
        if not os.path.isfile(path):
            raise BundleCorruptionError(f"bundle file missing: {name}")
        if not verify:
            continue
        size = os.path.getsize(path)
        if size != info.get("bytes"):
            raise BundleCorruptionError(
                f"{name}: size {size} != manifest {info.get('bytes')}")
        digest = _sha256(path)
        if digest != info.get("sha256"):
            raise BundleCorruptionError(
                f"{name}: sha256 mismatch (corrupt bundle)")


def bundle_shard_count(bundle_dir: str) -> int:
    """Shard count of the bundle at bundle_dir (1 for an unsharded
    bundle). Raises BundleCorruptionError on an unreadable manifest."""
    sharding = _read_manifest(bundle_dir).get("shards")
    return int(sharding["count"]) if sharding else 1


def embed_all(estimator, input_fn: Optional[Callable[[], Iterator]] = None,
              steps: int = 1_000_000
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Batched inference pass materializing the node-embedding matrix:
    (ids [N] uint64 sorted unique, embeddings [N, D] float32).

    Runs the estimator's jitted eval step over input_fn (default: the
    estimator's own infer_input_fn sweep) and keeps each id's FIRST
    embedding — a padded final batch repeats its last id, and dedup
    by first occurrence drops exactly the pad rows. Output is sorted
    by id: the canonical serving order (lookup = searchsorted)."""
    if input_fn is None:
        input_fn = getattr(estimator, "infer_input_fn", None)
        if input_fn is None:
            raise ValueError("estimator has no infer_input_fn; pass an "
                             "input_fn of batches carrying infer_ids")
    from euler_tpu.estimator.base_estimator import _merged, _to_device_tree

    it = input_fn() if callable(input_fn) else input_fn
    if estimator._eval_step is None:
        estimator._eval_step = estimator._build_eval_step()
    embs, ids = [], []
    for _ in range(steps):
        try:
            raw = next(it)
        except StopIteration:
            break
        batch = _to_device_tree(raw, estimator.max_id)
        if estimator.state is None:
            estimator._init_state(_merged(batch, estimator.static_batch))
            estimator.restore_checkpoint()
            estimator._eval_step = estimator._build_eval_step()
        _, _, emb = estimator._eval_step(
            estimator.state, _merged(batch, estimator.static_batch))
        emb = np.asarray(emb, dtype=np.float32)
        key = "infer_ids" if "infer_ids" in raw else (
            "ids" if "ids" in raw else None)
        if key is None:
            raise ValueError("export batches must carry infer_ids (or "
                             "ids) aligned with the embedding output")
        v = raw[key]
        v = v[0] if isinstance(v, list) else v
        v = np.asarray(v).ravel()[: emb.shape[0]]
        if v.shape[0] != emb.shape[0]:
            raise ValueError(
                f"batch carries {v.shape[0]} ids for {emb.shape[0]} "
                "embedding rows")
        embs.append(emb)
        ids.append(v.astype(np.uint64))
    if not embs:
        raise ValueError("export input_fn yielded no batches")
    all_ids = np.concatenate(ids)
    all_emb = np.concatenate(embs)
    uniq, first = np.unique(all_ids, return_index=True)
    return uniq, np.ascontiguousarray(all_emb[first])
