"""Dynamic micro-batching with admission control and load shedding.

The serving hot path's throughput lever: individual queries are tiny
(a handful of ids), but the per-dispatch cost — a jitted device call,
or an injected RPC RTT — is fixed, so the server coalesces concurrent
requests into one batch. Two flush triggers, whichever fires first:

  * the pending batch reaches ``max_batch`` rows (flush immediately);
  * the OLDEST pending request has waited ``flush_ms`` (bounded added
    latency — an idle server never delays a lone request longer than
    the window).

Admission control: past ``max_queue`` queued rows, submit() raises
ShedError synchronously — the caller turns that into an explicit SHED
reply. Shedding at admission (not after queueing) keeps the latency of
ADMITTED requests bounded by queue_depth/throughput instead of growing
without limit; sheds are counted, never silent.

Bucketed shapes: `bucket_ladder` / `run_bucketed` pad flush batches to
a fixed geometric ladder of row counts so a jitted apply sees only
ladder shapes — after one warmup pass per bucket it NEVER recompiles in
steady state, whatever request sizes arrive.

Metrics ({batcher=name} children on the obs registry):
  serving_batch_rows / serving_batch_requests / serving_queue_wait_ms
  histograms, serving_flushes_total{reason=full|timer},
  serving_shed_total, serving_inflight_rows gauge.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from euler_tpu import obs as _obs

__all__ = ["ShedError", "MicroBatcher", "bucket_ladder", "run_bucketed",
           "warm_ladder"]

_BATCHER_IDS = itertools.count()


class ShedError(RuntimeError):
    """Request refused by admission control (queue full) or abandoned
    at shutdown — ALWAYS surfaced explicitly, client-visible as a SHED
    status, never a silent drop."""


def bucket_ladder(max_batch: int, min_bucket: int = 8) -> Tuple[int, ...]:
    """Geometric (×2) padded-shape ladder up to max_batch: every flush
    pads to one of these row counts, so a jitted apply compiles at most
    len(ladder) variants and then never again."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    ladder = []
    b = min(min_bucket, max_batch)
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return tuple(ladder)


def run_bucketed(fn: Callable[..., np.ndarray],
                 arrays: Sequence[np.ndarray],
                 ladder: Sequence[int]) -> np.ndarray:
    """Apply `fn` over equal-length row arrays using ONLY ladder-sized
    (edge-padded) chunks; returns fn's rows trimmed back to the true
    length. A batch longer than the largest bucket runs as several
    largest-bucket chunks — shapes stay inside the ladder either way."""
    n = arrays[0].shape[0]
    outs = []
    at = 0
    while at < n:
        remaining = n - at
        bucket = next((b for b in ladder if b >= remaining), ladder[-1])
        take = min(bucket, remaining)
        chunk = []
        for a in arrays:
            c = a[at:at + take]
            if take < bucket:
                pad = np.repeat(c[-1:], bucket - take, axis=0) if take \
                    else np.zeros((bucket,) + c.shape[1:], c.dtype)
                c = np.concatenate([c, pad])
            chunk.append(c)
        outs.append(np.asarray(fn(*chunk))[:take])
        at += take
    return outs[0] if len(outs) == 1 else np.concatenate(outs)


def warm_ladder(ladder: Sequence[int], *fns: Callable[[np.ndarray],
                                                      object]) -> None:
    """Pre-compile a version-scoped pool of jitted applies at every
    ladder bucket. Each fn takes one int32 rows array sized to the
    bucket. Used at server startup AND before a hot-swap flips the
    serving pointer: a freshly loaded bundle's applies are warmed
    OFF-PATH, so neither a first request nor a just-promoted bundle
    ever pays a jit compile inside a client's deadline."""
    for b in ladder:
        rows = np.zeros(int(b), np.int32)
        for fn in fns:
            fn(rows)


class _Pending:
    __slots__ = ("payload", "rows", "future", "t_enq")

    def __init__(self, payload, rows: int):
        self.payload = payload
        self.rows = rows
        self.future: Future = Future()
        self.t_enq = time.monotonic()


class MicroBatcher:
    """Coalesces submit()ed requests into run_batch calls on a worker
    thread.

    run_batch(payloads: list) -> list of per-request results (same
    order/length); a raise fails every request in the flush with that
    exception. `rows` passed to submit() is the request's contribution
    to batch-size accounting (ids in the request, not 1 per request).
    """

    def __init__(self, run_batch: Callable[[List], List], *,
                 max_batch: int = 256, flush_ms: float = 2.0,
                 max_queue: int = 0, name: Optional[str] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        self.flush_ms = float(flush_ms)
        # default queue bound: 8 full batches of headroom
        self.max_queue = int(max_queue) if max_queue else 8 * self.max_batch
        self.name = name or f"batcher{next(_BATCHER_IDS)}"
        self._mu = threading.Condition()
        self._queue: List[_Pending] = []
        self._queued_rows = 0
        self._closed = False
        reg = _obs.default_registry()
        lab = {"batcher": self.name}
        self._hist_rows = reg.histogram(
            "serving_batch_rows", "rows per flushed micro-batch",
            ("batcher",)).labels(**lab)
        self._hist_reqs = reg.histogram(
            "serving_batch_requests", "requests per flushed micro-batch",
            ("batcher",)).labels(**lab)
        self._hist_wait = reg.histogram(
            "serving_queue_wait_ms",
            "admission→flush wait per request", ("batcher",)).labels(**lab)
        self._ctr_shed = reg.counter(
            "serving_shed_total",
            "requests refused by admission control",
            ("batcher",)).labels(**lab)
        self._ctr_flush = reg.counter(
            "serving_flushes_total", "micro-batch flushes",
            ("batcher", "reason"))
        self._g_inflight = reg.gauge(
            "serving_inflight_rows",
            "rows queued + in the running flush", ("batcher",)
        ).labels(**lab)
        self._worker = threading.Thread(
            target=self._loop, name=f"microbatch-{self.name}", daemon=True)
        self._worker.start()

    # -- submission --------------------------------------------------------
    def submit(self, payload, rows: int = 1) -> Future:
        """Queue one request; returns its Future. Raises ShedError
        synchronously when admission control refuses (queue full or
        batcher closed) — the shed is counted and explicit."""
        rows = max(int(rows), 1)
        with self._mu:
            if self._closed:
                raise ShedError("batcher closed")
            if self._queued_rows + rows > self.max_queue \
                    and self._queue:  # never shed into an empty queue
                self._ctr_shed.inc()
                raise ShedError(
                    f"overloaded: {self._queued_rows} rows queued "
                    f"(max_queue={self.max_queue})")
            p = _Pending(payload, rows)
            self._queue.append(p)
            self._queued_rows += rows
            self._g_inflight.set(self._queued_rows)
            self._mu.notify_all()
        return p.future

    @property
    def queue_depth(self) -> int:
        with self._mu:
            return self._queued_rows

    # -- worker ------------------------------------------------------------
    def _take_flush(self) -> Optional[Tuple[List[_Pending], str]]:
        """Block until a flush is due; pop it FIFO. None at close."""
        with self._mu:
            while True:
                if self._queue:
                    now = time.monotonic()
                    rows = 0
                    for p in self._queue:
                        rows += p.rows
                        if rows >= self.max_batch:
                            break
                    due = self._queue[0].t_enq + self.flush_ms / 1000.0
                    if rows >= self.max_batch:
                        reason = "full"
                    elif self._closed or now >= due:
                        reason = "timer"
                    else:
                        self._mu.wait(due - now)
                        continue
                    batch, total = [], 0
                    while self._queue:
                        nxt = self._queue[0]
                        if batch and total + nxt.rows > self.max_batch:
                            break
                        batch.append(self._queue.pop(0))
                        total += nxt.rows
                    self._queued_rows -= total
                    # inflight covers the running flush until it lands
                    self._g_inflight.set(self._queued_rows + total)
                    return batch, reason
                if self._closed:
                    return None
                self._mu.wait()

    def _loop(self) -> None:
        while True:
            taken = self._take_flush()
            if taken is None:
                return
            batch, reason = taken
            now = time.monotonic()
            for p in batch:
                # per-request queue wait, stamped onto the Future BEFORE
                # it resolves so the server's phase breakdown
                # (InferenceServer._wait → serving_phase_ms / request
                # spans) can read it after result() without extra
                # plumbing through the batcher API
                p.future.queue_wait_ms = (now - p.t_enq) * 1000.0
                self._hist_wait.observe(p.future.queue_wait_ms)
            self._hist_rows.observe(sum(p.rows for p in batch))
            self._hist_reqs.observe(len(batch))
            self._ctr_flush.labels(batcher=self.name, reason=reason).inc()
            try:
                results = self._run_batch([p.payload for p in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"run_batch returned {len(results)} results for "
                        f"{len(batch)} requests")
            except BaseException as e:
                exec_ms = (time.monotonic() - now) * 1000.0
                for p in batch:
                    p.future.exec_ms = exec_ms
                    if not p.future.done():
                        p.future.set_exception(e)
            else:
                # the flush's run time, attributed to every coalesced
                # request in it (micro-batching makes execute a shared
                # phase — that sharing is exactly what the breakdown
                # should show)
                exec_ms = (time.monotonic() - now) * 1000.0
                for p, r in zip(batch, results):
                    p.future.exec_ms = exec_ms
                    if not p.future.done():
                        p.future.set_result(r)
            finally:
                with self._mu:
                    self._g_inflight.set(self._queued_rows)

    # -- lifecycle ---------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Stop the worker. drain=True (default) flushes everything
        already admitted first; drain=False fails queued requests with
        ShedError (explicit, not a silent drop)."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            if not drain:
                abandoned, self._queue = self._queue, []
                self._queued_rows = 0
                for p in abandoned:
                    self._ctr_shed.inc()
                    if not p.future.done():
                        p.future.set_exception(
                            ShedError("batcher shut down"))
            self._mu.notify_all()
        self._worker.join(timeout=10.0)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
