"""Serving wire protocol: the framed-TCP conventions of the graph
service (core/cc/rpc.cc), spoken from Python.

Frame layout is byte-identical to the C++ stack's —
``u32 'ETFR' | u32 msg_type | u64 body_len | body`` (rpc.h:17) — so a
serving replica and a graph shard are the same kind of network citizen
(same framing, same registry, same proxy/chaos tooling applies).
Serving claims msg_type >= 100; the graph service owns 0..5, so a
serving frame hitting a graph shard (or vice versa) fails loudly as an
unknown type instead of misparsing.

Payloads are little-endian packed structs + raw numpy buffers (the
serde.h ByteWriter conventions: u32-length-prefixed strings, no
alignment padding) — same assumption the C++ engine already makes.

The registry half speaks the RegistryServer protocol (kRegPut /
kRegList / kRegRemove) and the shared-directory registry directly, so
serving replicas register and clients discover through the SAME
registry the graph shards use. Serving entries are named
``serve_<service>_<shard>_<replica>__<host>_<port>`` — index shards
and replicas-per-shard are discoverable exactly like graph shards.
The pre-fleet two-field form (``serve_<service>_<replica>__...``)
still parses as shard 0, so a mixed-version fleet stays discoverable
during a rollout (caveat: that back-compat form is ambiguous for
service names ending in a numeric component; new entries always carry
the explicit shard field). The C++ shard parser only accepts the
``shard_`` prefix, so serving entries are invisible to graph-shard
discovery (and shard entries to serving discovery) by construction.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "MAGIC", "HEADER", "MSG_EMBED", "MSG_KNN", "MSG_SCORE", "MSG_HEALTH",
    "MSG_INFO", "MSG_SWAP", "MSG_KNN_VEC", "STATUS_OK", "STATUS_SHED",
    "STATUS_ERROR", "WireError",
    "read_frame", "write_frame", "pack_str", "Reader",
    "registry_put", "registry_remove", "registry_list",
    "serve_entry_name", "parse_serve_entry", "discover_replicas",
    "discover_fleet",
]

MAGIC = 0x52465445                     # b'ETFR' little-endian
HEADER = struct.Struct("<IIQ")         # magic | msg_type | body_len

# graph service owns 0..5 (kExecute..kRegRemove); serving starts at 100
MSG_EMBED = 100
MSG_KNN = 101
MSG_SCORE = 102
MSG_HEALTH = 103
MSG_INFO = 104
MSG_SWAP = 105                         # admin: hot-swap the served bundle
MSG_KNN_VEC = 106                      # knn by query VECTORS (fleet fan-out)

# registry verbs (rpc.cc MsgType)
_REG_PUT = 3
_REG_LIST = 4
_REG_REMOVE = 5
_REG_LIST_VERSION = 2

STATUS_OK = 0
STATUS_SHED = 1                        # explicit load-shed, never silent
STATUS_ERROR = 2

# matches the C++ ReadFrame sanity cap (8 GiB); a corrupt header must
# not allocate the moon
_MAX_BODY = 1 << 33


class WireError(ConnectionError):
    """Framing/transport failure on a serving connection. Subclasses
    ConnectionError so retryable_error() classifies it as transport-
    shaped without any string matching."""


def _recv_all(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WireError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def write_frame(sock: socket.socket, msg_type: int, body: bytes) -> None:
    sock.sendall(HEADER.pack(MAGIC, msg_type, len(body)) + body)


def read_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = _recv_all(sock, HEADER.size)
    magic, msg_type, n = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise WireError(f"bad frame magic 0x{magic:08x}")
    if n > _MAX_BODY:
        raise WireError(f"frame body {n} exceeds sanity cap")
    return msg_type, _recv_all(sock, n) if n else b""


def pack_str(s: str) -> bytes:
    """serde.h PutStr: u32 length + raw bytes."""
    b = s.encode()
    return struct.pack("<I", len(b)) + b


class Reader:
    """Cursor over a packed body (serde.h ByteReader shape)."""

    __slots__ = ("_b", "_o")

    def __init__(self, body: bytes):
        self._b = body
        self._o = 0

    def u8(self) -> int:
        return self._unpack("<B", 1)

    def u32(self) -> int:
        return self._unpack("<I", 4)

    def i64(self) -> int:
        return self._unpack("<q", 8)

    def u64(self) -> int:
        return self._unpack("<Q", 8)

    def f32(self) -> float:
        return self._unpack("<f", 4)

    def _unpack(self, fmt: str, size: int):
        if self._o + size > len(self._b):
            raise WireError("truncated body")
        v = struct.unpack_from(fmt, self._b, self._o)[0]
        self._o += size
        return v

    def str_(self) -> str:
        n = self.u32()
        if self._o + n > len(self._b):
            raise WireError("truncated string")
        s = self._b[self._o:self._o + n].decode()
        self._o += n
        return s

    def array(self, dtype, count: int) -> np.ndarray:
        dt = np.dtype(dtype)
        nbytes = dt.itemsize * count
        if self._o + nbytes > len(self._b):
            raise WireError("truncated array")
        a = np.frombuffer(self._b, dtype=dt, count=count, offset=self._o)
        self._o += nbytes
        return a.copy()  # body buffer is reused; results must own memory

    def remaining(self) -> int:
        return len(self._b) - self._o


# ---------------------------------------------------------------------------
# Registry access (same registry the graph shards heartbeat into)
# ---------------------------------------------------------------------------
def _split_tcp_spec(spec: str) -> Optional[Tuple[str, int]]:
    if not spec.startswith("tcp:"):
        return None
    rest = spec[4:]
    host, _, port = rest.rpartition(":")
    return (host, int(port)) if host else None


def _dir_of_spec(spec: str) -> str:
    return spec[4:] if spec.startswith("dir:") else spec


def _registry_call(host: str, port: int, msg_type: int, body: bytes,
                   timeout_s: float = 3.0) -> bytes:
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        write_frame(s, msg_type, body)
        reply_type, reply = read_frame(s)
        if reply_type != msg_type:
            raise WireError(
                f"registry replied type {reply_type} to {msg_type}")
        return reply


def registry_put(spec: str, name: str) -> None:
    """Store/refresh `name` in the registry (tcp: server or shared
    directory) — the heartbeat verb serving replicas repeat."""
    tcp = _split_tcp_spec(spec)
    if tcp:
        _registry_call(tcp[0], tcp[1], _REG_PUT, name.encode())
        return
    d = _dir_of_spec(spec)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, name)
    with open(path, "w"):
        pass
    os.utime(path, None)  # refresh mtime: directory-registry heartbeat


def registry_remove(spec: str, name: str) -> None:
    """Best-effort clean-shutdown unregister (a crash just goes stale,
    exactly like a shard entry)."""
    tcp = _split_tcp_spec(spec)
    try:
        if tcp:
            _registry_call(tcp[0], tcp[1], _REG_REMOVE, name.encode())
        else:
            os.remove(os.path.join(_dir_of_spec(spec), name))
    except (OSError, WireError):
        pass


def registry_list(spec: str) -> Dict[str, int]:
    """Every live entry name → age_ms. Unlike gql.scan_registry (which
    parses only shard_ entries through the C API), this returns the raw
    namespace so serving entries are visible."""
    tcp = _split_tcp_spec(spec)
    if tcp:
        reply = _registry_call(tcp[0], tcp[1], _REG_LIST, b"")
        r = Reader(reply)
        ver = r.u32()
        if ver != _REG_LIST_VERSION:
            raise WireError(f"registry list version {ver} != "
                            f"{_REG_LIST_VERSION}")
        out = {}
        for _ in range(r.u32()):
            name = r.str_()
            age_ms = r.i64()
            r.u64()  # put-sequence: unused here
            out[name] = age_ms
        return out
    d = _dir_of_spec(spec)
    out = {}
    try:
        names = os.listdir(d)
    except OSError:
        return out
    now = time.time()
    for name in names:
        try:
            mtime = os.stat(os.path.join(d, name)).st_mtime
        except OSError:
            continue  # entry removed between listdir and stat
        out[name] = int(max(now - mtime, 0.0) * 1000)
    return out


def serve_entry_name(service: str, shard: int, replica: int, host: str,
                     port: int) -> str:
    if "__" in service:
        raise ValueError(f"service name must not contain '__': {service!r}")
    return f"serve_{service}_{int(shard)}_{int(replica)}__{host}_{port}"


def parse_serve_entry(name: str
                      ) -> Optional[Tuple[str, int, int, str, int]]:
    """(service, shard, replica, host, port), or None for foreign
    entries (shard_ heartbeats share the namespace). The pre-fleet
    two-field form parses as shard 0."""
    if not name.startswith("serve_"):
        return None
    left, sep, right = name.partition("__")
    if not sep:
        return None
    parts = left[len("serve_"):].split("_")
    if len(parts) >= 3 and parts[-1].isdigit() and parts[-2].isdigit():
        svc = "_".join(parts[:-2])
        shard, rep = int(parts[-2]), int(parts[-1])
    elif len(parts) >= 2 and parts[-1].isdigit():
        svc = "_".join(parts[:-1])
        shard, rep = 0, int(parts[-1])
    else:
        return None
    host, _, port = right.rpartition("_")
    if not (svc and host and port.lstrip("-").isdigit()):
        return None
    return svc, shard, rep, host, int(port)


def discover_fleet(spec: str, service: str, max_age_ms: int = 0
                   ) -> Dict[int, List[Tuple[str, int, int]]]:
    """{shard -> [(host, port, age_ms)] sorted by replica index} for
    the service's registered fleet. max_age_ms > 0 drops stale entries
    (crashed replicas whose heartbeat stopped)."""
    fleet: Dict[int, List[Tuple[int, str, int, int]]] = {}
    for name, age in registry_list(spec).items():
        parsed = parse_serve_entry(name)
        if parsed is None or parsed[0] != service:
            continue
        if max_age_ms > 0 and age > max_age_ms:
            continue
        _, shard, rep, host, port = parsed
        fleet.setdefault(shard, []).append((rep, host, port, age))
    return {s: [(h, p, a) for _, h, p, a in sorted(v)]
            for s, v in sorted(fleet.items())}


def discover_replicas(spec: str, service: str, max_age_ms: int = 0,
                      shard: Optional[int] = None
                      ) -> List[Tuple[str, int, int]]:
    """[(host, port, age_ms)] of the service's registered replicas,
    sorted by (shard, replica) — or a single shard's replicas when
    `shard` is given."""
    fleet = discover_fleet(spec, service, max_age_ms=max_age_ms)
    if shard is not None:
        return fleet.get(shard, [])
    out: List[Tuple[str, int, int]] = []
    for s in sorted(fleet):
        out.extend(fleet[s])
    return out
