"""euler_tpu.serving: the train→serve seam — export bundles, an online
embedding/KNN inference server, and a failover-capable client.

The first subsystem downstream of training: `BaseEstimator.
export_bundle()` materializes a versioned, checksummed **ModelBundle**
(trained params + node-embedding matrix + IVFFlat index + manifest),
an **InferenceServer** serves `embed` / `knn` / `score` over the
framed-TCP conventions with dynamic micro-batching (bucketed padded
shapes — the jitted apply never recompiles in steady state) and
explicit load shedding, and a **ServingClient** retries/fails over
across replicas discovered through the same registry the graph shards
heartbeat into.

    est.train(input_fn, max_steps=...)
    est.export_bundle("bundle/")                    # versioned artifact

    srv = InferenceServer("bundle/", registry="tcp:127.0.0.1:9191",
                          service="recs", replica=0)
    cli = ServingClient(registry="tcp:127.0.0.1:9191", service="recs")
    nbr_ids, scores = cli.knn(user_ids, k=10)       # online retrieval
"""

from euler_tpu.serving.batcher import (  # noqa: F401
    MicroBatcher,
    ShedError,
    bucket_ladder,
    run_bucketed,
    warm_ladder,
)
from euler_tpu.serving.client import (  # noqa: F401
    ServerOverloaded,
    ServingClient,
)
from euler_tpu.serving.export import (  # noqa: F401
    BundleCorruptionError,
    ModelBundle,
    bundle_shard_count,
    embed_all,
    shard_bounds,
)
from euler_tpu.serving.server import InferenceServer  # noqa: F401
from euler_tpu.serving.autoscale import ServingAutoscaler  # noqa: F401

__all__ = [
    "MicroBatcher", "ShedError", "bucket_ladder", "run_bucketed",
    "warm_ladder", "ServingClient", "ServerOverloaded",
    "BundleCorruptionError", "ModelBundle", "embed_all",
    "shard_bounds", "bundle_shard_count", "InferenceServer",
    "ServingAutoscaler",
]
