"""Serving-tier autoscaling: grow/shrink a shard's replica set on the
observed shed rate.

The PR 5/8 serving stack already has every mechanism a scale event
needs — explicit admission control (``ShedError`` → counted ``shed``,
never a silent drop), registry discovery with replica rotation/p2c on
the client, and zero-downtime drain semantics. This module adds the
POLICY: an autoscaler that polls the replicas' shed counters, scales
**up** (new ``InferenceServer`` replica over the same bundle, registry
discovery routes traffic to it within the clients' re-resolution TTL)
when the windowed shed rate crosses the threshold, and scales **down**
(``InferenceServer.drain()``: deregister → grace → bounded queue drain
→ stop) after enough consecutive calm windows.

Deliberately synchronous: ``step()`` evaluates one window and performs
at most ONE scale action. The caller owns the cadence (a loop thread, a
bench harness, a test) — policy stays testable and deterministic.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from euler_tpu import obs as _obs
from euler_tpu.serving.server import InferenceServer

__all__ = ["ServingAutoscaler"]


class ServingAutoscaler:
    """Shed-rate-driven replica autoscaler for ONE serving shard.

    bundle: bundle directory (or ModelBundle) every new replica loads.
    registry / service / shard: the discovery identity replicas join.
    min_replicas / max_replicas: the fleet-size clamp (1→3 is the
      acceptance shape).
    shed_rate_up: scale up when window sheds / window requests crosses
      this (sheds are EXPLICIT statuses — the client retried them, so
      every one is a user-visible latency event).
    calm_windows_down: scale down after this many consecutive windows
      with zero sheds (0 disables auto-down; tests drive explicitly).
    server_kwargs: forwarded to every InferenceServer the scaler
      starts (max_batch, flush_ms, max_queue, inject_* ...).
    """

    def __init__(self, bundle, registry: str, service: str = "default",
                 shard: int = 0, min_replicas: int = 1,
                 max_replicas: int = 3, shed_rate_up: float = 0.01,
                 calm_windows_down: int = 0,
                 server_kwargs: Optional[dict] = None):
        self.bundle = bundle
        self.registry = registry
        self.service = service
        self.shard = int(shard)
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.shed_rate_up = float(shed_rate_up)
        self.calm_windows_down = int(calm_windows_down)
        self.server_kwargs = dict(server_kwargs or {})
        self._mu = threading.Lock()
        self._replicas: Dict[int, InferenceServer] = {}
        self._next_idx = 0
        # per-replica last cumulative totals: diffs are computed per
        # replica so one replica's transient health() failure cannot
        # re-enter its lifetime totals as a fake window (the spurious
        # scale-up a fleet-wide diff suffers)
        self._last_by: Dict[int, dict] = {}
        self._calm = 0
        reg = _obs.default_registry()
        lab = {"service": service, "shard": str(self.shard)}
        self._ctr_up = reg.counter(
            "serving_autoscale_up_total",
            "replicas started by the autoscaler",
            ("service", "shard")).labels(**lab)
        self._ctr_down = reg.counter(
            "serving_autoscale_down_total",
            "replicas drained by the autoscaler",
            ("service", "shard")).labels(**lab)
        self._g_replicas = reg.gauge(
            "serving_autoscale_replicas",
            "replicas currently owned by the autoscaler",
            ("service", "shard")).labels(**lab)

    # -- fleet bookkeeping -------------------------------------------------
    def adopt(self, server: InferenceServer) -> None:
        """Take ownership of an already-running replica (the initial
        fleet the scaler grows from). Seeds the per-replica window
        bookkeeping with the server's CURRENT cumulative totals — a
        long-running adoptee's lifetime counts must not read as one
        giant first window (a guaranteed spurious scale-up)."""
        try:
            h = server.health()
            seed = {"requests": sum(h.get("requests", {}).values()),
                    "shed": int(h.get("shed", 0))}
        except (OSError, RuntimeError):
            seed = {"requests": 0, "shed": 0}
        with self._mu:
            self._replicas[server.replica] = server
            self._next_idx = max(self._next_idx, server.replica + 1)
            self._last_by[server.replica] = seed
            self._g_replicas.set(len(self._replicas))

    @property
    def replicas(self) -> Dict[int, InferenceServer]:
        with self._mu:
            return dict(self._replicas)

    def replica_count(self) -> int:
        with self._mu:
            return len(self._replicas)

    # -- observation -------------------------------------------------------
    def observe(self) -> dict:
        """Poll every replica's health() and diff PER REPLICA against
        its previous cumulative totals: {'requests', 'shed', 'rate',
        'replicas'}. A replica that cannot answer contributes nothing
        this window and keeps its last totals, so when it recovers the
        next diff covers only the gap — its lifetime counts never
        re-enter as a fake (scale-up-triggering) window."""
        d_req = 0
        d_shed = 0
        live = self.replicas
        for idx, srv in live.items():
            try:
                h = srv.health()
            except (OSError, RuntimeError):
                continue
            req = sum(h.get("requests", {}).values())
            shed = int(h.get("shed", 0))
            last = self._last_by.get(idx, {"requests": 0, "shed": 0})
            d_req += max(req - last["requests"], 0)
            d_shed += max(shed - last["shed"], 0)
            self._last_by[idx] = {"requests": req, "shed": shed}
        # drained/stopped replicas drop out of the bookkeeping
        for idx in list(self._last_by):
            if idx not in live:
                del self._last_by[idx]
        rate = (d_shed / d_req) if d_req > 0 else 0.0
        return {"requests": d_req, "shed": d_shed, "rate": rate,
                "replicas": self.replica_count()}

    # -- policy ------------------------------------------------------------
    def step(self) -> Optional[str]:
        """Evaluate one window; perform at most one scale action.
        Returns "up", "down", or None."""
        w = self.observe()
        if (w["shed"] > 0 and w["rate"] >= self.shed_rate_up
                and self.replica_count() < self.max_replicas):
            self._calm = 0
            self.scale_up()
            return "up"
        if w["shed"] == 0:
            self._calm += 1
            if (self.calm_windows_down > 0
                    and self._calm >= self.calm_windows_down
                    and self.replica_count() > self.min_replicas):
                self._calm = 0
                self.scale_down()
                return "down"
        else:
            self._calm = 0
        return None

    # -- actions -----------------------------------------------------------
    def scale_up(self) -> InferenceServer:
        """Start one more replica over the same bundle; registry
        discovery routes traffic to it within the clients'
        re-resolution TTL (no client restart)."""
        with self._mu:
            idx = self._next_idx
            self._next_idx += 1
        srv = InferenceServer(self.bundle, registry=self.registry,
                              service=self.service, shard=self.shard,
                              replica=idx, **self.server_kwargs)
        with self._mu:
            self._replicas[idx] = srv
            self._g_replicas.set(len(self._replicas))
        self._ctr_up.inc()
        return srv

    def scale_down(self, grace_s: float = 1.0) -> Optional[int]:
        """Drain the highest-index replica through the PR 8 discovery
        path (deregister → grace → bounded queue drain → stop). Never
        goes below min_replicas. Returns the drained replica index."""
        with self._mu:
            if len(self._replicas) <= self.min_replicas:
                return None
            idx = max(self._replicas)
            srv = self._replicas.pop(idx)
            self._g_replicas.set(len(self._replicas))
        srv.drain(grace_s=grace_s)
        self._ctr_down.inc()
        return idx

    def close(self, drain: bool = False) -> None:
        """Stop every owned replica (drain=True routes each through the
        graceful path; False stops immediately — test teardown)."""
        for idx, srv in sorted(self.replicas.items(), reverse=True):
            with self._mu:
                self._replicas.pop(idx, None)
                self._g_replicas.set(len(self._replicas))
            if drain:
                srv.drain(grace_s=0.0)
            else:
                srv.stop()

    # -- loop convenience --------------------------------------------------
    def run(self, interval_s: float, stop_event: threading.Event) -> None:
        """Caller-owned cadence loop (bench/daemon): step every
        interval until the event fires."""
        while not stop_event.wait(interval_s):
            self.step()
