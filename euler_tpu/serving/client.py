"""ServingClient: retrying, failover-capable, SHARD-AWARE client for a
fleet of InferenceServer replicas.

Reuses the graph client's resilience vocabulary wholesale: RetryPolicy
(exponential backoff, full jitter, per-call deadline, per-attempt
timeout) and the transport-vs-semantic error split of
`retryable_error`. Replicas come from a static ``hosts:h:p,h:p`` list
(treated as one shard) or are discovered live from the registry as a
FLEET — ``{shard -> [replicas]}`` parsed off the same namespace the
graph shards heartbeat into. A transport failure rotates replicas
WITHIN the failed shard and, under a registry, re-resolves the fleet —
a killed-and-restarted replica rejoins traffic within its heartbeat
interval, exactly like a graph shard does for trainers. Re-resolution
also DROPS cached connections to endpoints that left the replica set,
so a departed replica's socket never lingers until its next transport
error.

Scatter-gather (the multi-shard paths, thread-pool fan-out in the
style of the pipelined graph client):

  knn    two-phase: resolve each query id's embedding at its OWNING
         shard (an exact gather — a shard must never mistake another
         shard's id for an unknown), then broadcast the query VECTORS
         to every shard concurrently and merge per-shard top-k into
         the global top-k. Stable sorts end to end (each shard's
         brute force, then the merge over candidates concatenated in
         shard order) resolve ties in global row order, so the merged
         exact result is byte-identical to a single-index
         tools/knn.brute_force over the whole corpus — zero-vector
         unknown-id queries included.
  embed  scattered to owning shards by id range (binary search over
         shard lower bounds fetched once per fleet generation from
         info()), reassembled in request order. Byte-identical to the
         monolith (it is the same gather).
  score  same-shard pairs go to their shard's score verb; cross-shard
         pairs are resolved as two embed gathers + a client-side dot
         (float32 — summation order differs from the on-replica jitted
         reduce, so cross-shard scores match to fp tolerance, not
         bitwise).

An explicit SHED reply from an overloaded replica is retried on
another replica of the same shard under the same deadline; when the
deadline runs out the LAST explicit status is raised —
ServerOverloaded for sheds, RetryDeadlineExceeded for transport — so
no request ever ends without a status, and a fan-out raises the
failing shard's status rather than inventing a partial answer.

`swap_fleet(bundle_dir)` performs the rolling zero-downtime promotion:
every live replica, one at a time, loads vN+1 beside vN, warms, and
flips — traffic keeps flowing on the replicas not currently warming.
"""

from __future__ import annotations

import itertools
import json
import random
import select
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.core.lib import EngineError
from euler_tpu.graph.remote import (
    RetryDeadlineExceeded,
    RetryPolicy,
    retryable_error,
)
from euler_tpu.serving import wire

__all__ = ["ServingClient", "ServerOverloaded"]

_CLIENT_IDS = itertools.count()


class ServerOverloaded(EngineError):
    """Every attempted replica answered SHED for the whole deadline —
    the overload was explicit end to end."""


class ServingClient:
    """Client for a serving service (see module docstring).

    endpoints: "hosts:h:p,h:p" static replica list (single shard), OR
      None with `registry` set — a registry spec ("tcp:host:port" /
      "dir:/path") plus `service` to discover the fleet from.
    retry_policy: backoff/deadline/per-attempt-timeout; the default is
      a 10s deadline with a 5s per-attempt socket timeout.
    stale_ms: registry entries older than this are skipped (a crashed
      replica that never deregistered).
    fanout: max concurrent shard calls per scatter-gather (0 = one
      worker per shard).
    swap_timeout_s: per-replica bound on a hot-swap admin call (the
      replica loads + warms a bundle inside it, jit compiles included).
    """

    def __init__(self, endpoints: Optional[str] = None,
                 registry: Optional[str] = None, service: str = "default",
                 retry_policy: Optional[RetryPolicy] = None,
                 stale_ms: int = 10_000, seed: int = 0,
                 fanout: int = 0, swap_timeout_s: float = 120.0,
                 bounds_ttl_s: float = 30.0, hedge: bool = False,
                 hedge_quantile: float = 0.9, hedge_min_ms: float = 1.0,
                 hedge_max_ms: float = 200.0, p2c: bool = False,
                 rediscover_ttl_s: float = 0.0):
        """Tail-latency knobs (both opt-in, both byte-identical on the
        wire when off):

        hedge: adaptive straggler hedging per scatter-gather leg — a
          sub-call whose reply exceeds the hedge delay fires the SAME
          request on a SECOND replica of the same shard; the first
          reply wins and the loser is abandoned (its connection
          dropped so the stale reply can never be read into a later
          request). The delay adapts per shard: the hedge_quantile of
          the observed per-attempt latency histogram, clamped to
          [hedge_min_ms, hedge_max_ms] (max is also the cold-start
          delay). Counted hedge_fired / hedge_won / hedge_wasted.
        p2c: power-of-two-choices replica selection off the observed
          per-endpoint latency EWMA instead of blind rotation — two
          random replicas, take the historically faster one (unknown
          endpoints score as idle, so fresh replicas get explored).
        rediscover_ttl_s: > 0 re-resolves the registry at most every
          this-many seconds on the call path even when nothing failed —
          the elastic-fleet knob: replicas the AUTOSCALER just started
          begin receiving traffic within one TTL instead of only after
          a failure. 0 (default) keeps failure-driven re-resolution."""
        if not endpoints and not registry:
            raise ValueError("pass endpoints='hosts:h:p,...' or a "
                             "registry spec + service")
        self.service = service
        self.registry = registry
        self.stale_ms = int(stale_ms)
        self.fanout = int(fanout)
        self.swap_timeout_s = float(swap_timeout_s)
        self.bounds_ttl_s = float(bounds_ttl_s)
        self.rediscover_ttl_s = float(rediscover_ttl_s)
        self._next_rediscover = (time.monotonic() + self.rediscover_ttl_s
                                 if self.rediscover_ttl_s > 0 else None)
        self.retry = retry_policy or RetryPolicy(
            deadline_s=10.0, call_timeout_s=5.0)
        self.hedge = bool(hedge)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_max_ms = float(hedge_max_ms)
        self.p2c = bool(p2c)
        self._ep_lat: Dict[Tuple[str, int], float] = {}  # EWMA ms, _mu
        self._backoff_rng = random.Random(seed ^ 0x5E21 if seed else None)
        self._pick_rng = random.Random(seed ^ 0x9C2 if seed else None)
        self._static: Optional[List[Tuple[str, int]]] = None
        if endpoints:
            if not endpoints.startswith("hosts:"):
                raise ValueError("endpoints must be 'hosts:h:p,h:p'")
            self._static = []
            for part in endpoints[len("hosts:"):].split(","):
                host, _, port = part.strip().rpartition(":")
                self._static.append((host, int(port)))
        self._mu = threading.Lock()
        self._fleet: Dict[int, List[Tuple[str, int]]] = (
            {0: list(self._static)} if self._static else {})
        self._replicas: List[Tuple[str, int]] = list(self._static or [])
        self._rr: Dict[Optional[int], int] = {}
        # (generation, live endpoint set): bumped whenever re-resolution
        # changes the replica set; per-thread conn caches compare their
        # generation against this and drop sockets to departed endpoints
        self._live_state: Tuple[int, frozenset] = (
            0, frozenset(self._replicas))
        self._bounds: Optional[Tuple[List[int], np.ndarray]] = None
        self._bounds_gen = -1
        self._bounds_at = 0.0
        self._num_shards: Optional[int] = None  # fleet width, pinned
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_size = 0
        self._local = threading.local()  # per-thread connection cache
        self._obs_name = f"serving_client{next(_CLIENT_IDS)}"
        reg = _obs.default_registry()
        lab = {"client": self._obs_name}
        self._ctr = {
            k: reg.counter(f"serving_client_{k}_total", h,
                           ("client",)).labels(**lab)
            for k, h in (
                ("calls", "serving calls issued"),
                ("retries", "retry cycles (transport or shed)"),
                ("failovers", "calls that succeeded after >=1 failure"),
                ("sheds", "explicit SHED replies received"),
                ("deadline_exhausted", "calls that ran out of budget"),
                ("rediscoveries", "registry re-resolutions"),
                ("stale_conns_dropped",
                 "cached connections dropped because their endpoint "
                 "left the replica set"),
                ("swaps", "per-replica hot-swap admin calls issued"),
                ("hedge_fired", "hedge legs fired at straggling "
                                "sub-calls"),
                ("hedge_won", "hedged sub-calls won by the hedge leg"),
                ("hedge_wasted", "losing hedge legs abandoned after "
                                 "the other leg won"),
                ("p2c_picks", "replica selections decided by "
                              "power-of-two-choices"),
            )}
        self._ctr_fanout = {
            k: reg.counter(f"serving_fanout_{k}_total", h,
                           ("client",)).labels(**lab)
            for k, h in (
                ("queries", "logical queries scatter-gathered across "
                            "shards"),
                ("shard_calls", "per-shard sub-calls issued by "
                                "scatter-gather"),
                ("merges", "top-k merges performed"),
            )}
        self._hist_call_ms = reg.histogram(
            "serving_client_call_ms",
            "end-to-end serving call latency incl. retries",
            ("client",)).labels(**lab)
        self._hist_shard_ms = reg.histogram(
            "serving_client_shard_call_ms",
            "per-shard sub-call latency incl. retries",
            ("client", "shard"))
        # per-ATTEMPT wire latency (no backoff, no retries): the source
        # the adaptive hedge delay and p2c read their percentiles from
        self._hist_attempt_ms = reg.histogram(
            "serving_client_attempt_ms",
            "single-attempt wire latency per shard (hedge/p2c signal)",
            ("client", "shard"))
        self._last_error: Optional[str] = None
        _obs.register_health(self._obs_name, self.health)
        if self._static is None:
            self._rediscover(initial=True)

    # -- discovery ---------------------------------------------------------
    def _set_fleet(self, fleet: Dict[int, List[Tuple[str, int]]]) -> None:
        flat = [ep for s in sorted(fleet) for ep in fleet[s]]
        with self._mu:
            self._fleet = fleet
            self._replicas = flat
            gen, live = self._live_state
            new_live = frozenset(flat)
            if new_live != live:
                self._live_state = (gen + 1, new_live)

    def _rediscover(self, initial: bool = False) -> None:
        if self._static is not None:
            return
        try:
            found = wire.discover_fleet(self.registry, self.service,
                                        max_age_ms=self.stale_ms)
        except (OSError, wire.WireError) as e:
            if initial:
                raise
            with self._mu:
                self._last_error = f"registry scan: {e}"
            return
        self._ctr["rediscoveries"].inc()
        self._set_fleet(
            {s: [(h, p) for h, p, _ in eps] for s, eps in found.items()})

    def replicas(self) -> List[Tuple[str, int]]:
        with self._mu:
            return list(self._replicas)

    def shards(self) -> List[int]:
        with self._mu:
            return sorted(self._fleet)

    def _fleet_view(self) -> List[int]:
        """Registered shard list, validated against the fleet's declared
        width (num_shards from info(), fetched once per client — a swap
        can never change it, the server enforces shard identity). A
        shard whose every replica aged out of the registry must surface
        as an EXPLICIT error: quietly fanning out to the survivors would
        merge a partial top-k / zero-fill embeds of ids the fleet does
        hold — confidently wrong results with STATUS_OK."""
        shard_list = self.shards()
        if not shard_list:
            # never fall through to the single-shard path on an empty
            # scan: once re-resolution repopulates the fleet mid-call,
            # a shard=None retry would send the WHOLE query to one
            # arbitrary shard's replica — wrong results, STATUS_OK
            self._rediscover()
            shard_list = self.shards()
            if not shard_list:
                raise wire.WireError(
                    f"no live replicas for service {self.service!r} "
                    "(registry empty or all entries stale)")
        width = self._num_shards
        if width is None and shard_list:
            info = self._call(
                wire.MSG_INFO, lambda _r: b"",
                lambda r: json.loads(r.str_()),
                shard=shard_list[0], count=False)
            width = int(info.get("num_shards", 1))
            with self._mu:
                self._num_shards = width
        if width is not None and len(shard_list) < width:
            self._rediscover()
            shard_list = self.shards()
            if len(shard_list) < width:
                raise wire.WireError(
                    f"fleet incomplete: shards {shard_list} of "
                    f"{width} registered for service "
                    f"{self.service!r} — refusing a partial "
                    "scatter-gather")
        return shard_list

    def _next_replica(self, shard: Optional[int] = None,
                      avoid: Optional[Tuple[str, int]] = None
                      ) -> Tuple[str, int]:
        """Pick a replica (within `shard` when given): power-of-two-
        choices off the per-endpoint latency EWMA when p2c is on, blind
        rotation otherwise. `avoid` excludes one endpoint — the hedge
        leg must land on a DIFFERENT replica than its primary."""
        with self._mu:
            pool = self._replicas if shard is None \
                else self._fleet.get(shard, [])
            if avoid is not None:
                pool = [ep for ep in pool if ep != avoid]
                if pool:
                    # hedge-leg pick: the historically fastest OTHER
                    # replica, WITHOUT advancing the rotation counter —
                    # a hedge consuming rotation slots would lock the
                    # primary rotation's parity onto one replica
                    return min(pool,
                               key=lambda e: self._ep_lat.get(e, 0.0))
            if not pool:
                # WireError subclasses ConnectionError → the call loop
                # treats an (often transient) empty replica set as
                # retryable and keeps re-resolving until the deadline
                where = f"shard {shard} of " if shard is not None else ""
                raise wire.WireError(
                    f"no live replicas for {where}service "
                    f"{self.service!r} (registry empty or all entries "
                    "stale)")
            if self.p2c and len(pool) >= 2:
                a, b = self._pick_rng.sample(range(len(pool)), 2)
                # unknown endpoints score 0.0 (idle): a fresh replica
                # gets explored instead of starved behind history
                la = self._ep_lat.get(pool[a], 0.0)
                lb = self._ep_lat.get(pool[b], 0.0)
                self._ctr["p2c_picks"].inc()
                return pool[a] if la <= lb else pool[b]
            i = self._rr.get(shard, 0)
            self._rr[shard] = i + 1
            return pool[i % len(pool)]

    def _observe_attempt(self, ep: Tuple[str, int],
                         shard: Optional[int], ms: float) -> None:
        """Per-attempt latency bookkeeping: the per-shard histogram the
        adaptive hedge delay reads, and the per-endpoint EWMA p2c
        ranks replicas by."""
        if shard is not None:
            self._hist_attempt_ms.labels(
                client=self._obs_name, shard=str(shard)).observe(ms)
        with self._mu:
            old = self._ep_lat.get(ep)
            self._ep_lat[ep] = ms if old is None \
                else 0.7 * old + 0.3 * ms

    def _hedge_delay_s(self, shard: int) -> float:
        """Adaptive hedge trigger: the hedge_quantile of this shard's
        observed per-attempt latency, clamped to [hedge_min_ms,
        hedge_max_ms]; the max is also the cold-start delay before any
        observations exist."""
        q = self._hist_attempt_ms.labels(
            client=self._obs_name, shard=str(shard)).quantile(
            self.hedge_quantile)
        ms = self.hedge_max_ms if q is None else min(
            max(float(q), self.hedge_min_ms), self.hedge_max_ms)
        return ms / 1000.0

    def _abandon(self, ep: Tuple[str, int], wasted: bool = True) -> None:
        """Abandon a hedge leg: its connection carries an unread reply
        that would poison the NEXT request on a cached socket, so the
        conn is dropped (closed), the reply discarded unread — it never
        reaches a decoder, so it cannot mutate anything. wasted=True
        counts the leg (exactly the abandoned-after-a-winner legs)."""
        self._drop_conn(ep)
        if wasted:
            self._ctr["hedge_wasted"].inc()

    def _exchange_hedged(self, s: socket.socket, ep: Tuple[str, int],
                         shard: int, msg_type: int, body: bytes,
                         deadline: float):
        """One request/reply exchange with adaptive hedging: write on
        the primary; if no reply lands inside the hedge delay, fire the
        SAME request at a second replica and take the first readable
        reply — the loser is abandoned (connection dropped, reply
        discarded unread). Returns (reply_type, reply, winner_ep).

        Latency attribution is per LEG: the winner records its own
        write→reply time, and an abandoned leg records its elapsed
        time at abandonment — a truthful lower bound that keeps a
        straggler ranked slow in the p2c EWMA and keeps the straggle
        visible to the adaptive-delay histogram (observing winners
        only would shrink the quantile toward hedge_min and over-fire
        hedges)."""
        t0 = time.monotonic()
        wire.write_frame(s, msg_type, body)
        remaining = deadline - t0
        delay = min(self._hedge_delay_s(shard), max(remaining, 0.001))
        readable, _, _ = select.select([s], [], [], max(delay, 0.0))
        if readable:
            rt, rb = wire.read_frame(s)
            self._observe_attempt(ep, shard,
                                  (time.monotonic() - t0) * 1000.0)
            return rt, rb, ep
        try:
            ep2 = self._next_replica(shard, avoid=ep)
        except wire.WireError:
            ep2 = None  # single-replica shard: nothing to hedge to
        s2 = None
        if ep2 is not None:
            try:
                s2 = self._conn(ep2)
                t1 = time.monotonic()
                wire.write_frame(s2, msg_type, body)
                self._ctr["hedge_fired"].inc()
            except (OSError, wire.WireError):
                # the hedge replica is unreachable: fall back to the
                # primary leg alone (a failed hedge must not fail a
                # call its primary could still win)
                self._drop_conn(ep2)
                s2 = None
        if s2 is None:
            rt, rb = wire.read_frame(s)
            self._observe_attempt(ep, shard,
                                  (time.monotonic() - t0) * 1000.0)
            return rt, rb, ep
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # no winner inside the budget: both legs are failures
                # (not wasted hedges); both conns carry straggling
                # replies and must go
                self._abandon(ep, wasted=False)
                self._abandon(ep2, wasted=False)
                raise socket.timeout(
                    "hedged call: no leg answered inside the deadline")
            readable, _, _ = select.select([s, s2], [], [], remaining)
            if not readable:
                continue
            winner_is_primary = readable[0] is s
            try:
                rt, rb = wire.read_frame(s if winner_is_primary else s2)
            except (OSError, wire.WireError):
                # the winning socket died mid-frame: abandon both legs
                # (the other carries an unread reply) and let the retry
                # machinery classify the failure
                self._abandon(ep, wasted=False)
                self._abandon(ep2, wasted=False)
                raise
            now = time.monotonic()
            if winner_is_primary:
                # the hedge leg lost a SHORT race — its elapsed says
                # nothing about the replica's speed, so it records no
                # sample (an optimistic tiny value would flatter it)
                self._observe_attempt(ep, shard, (now - t0) * 1000.0)
                self._abandon(ep2)
                return rt, rb, ep
            self._ctr["hedge_won"].inc()
            self._observe_attempt(ep2, shard, (now - t1) * 1000.0)
            # the abandoned primary was outrun by delay+race: its
            # elapsed is a truthful LOWER BOUND — recorded so the
            # straggle stays visible to the EWMA and the delay quantile
            self._observe_attempt(ep, shard, (now - t0) * 1000.0)
            self._abandon(ep)
            return rt, rb, ep2

    # -- connections (one cached socket per thread per endpoint) ----------
    def _conn(self, ep: Tuple[str, int]) -> socket.socket:
        st = self._local
        conns = getattr(st, "conns", None)
        if conns is None:
            conns = st.conns = {}
        gen, live = self._live_state
        if getattr(st, "gen", -1) != gen:
            # the replica set changed since this thread last looked:
            # drop sockets to departed endpoints NOW instead of keeping
            # them around until their next transport error
            for dead in [e for e in conns if e not in live]:
                s = conns.pop(dead)
                self._ctr["stale_conns_dropped"].inc()
                try:
                    s.close()
                except OSError:
                    pass
            st.gen = gen
        s = conns.get(ep)
        if s is None:
            timeout = self.retry.call_timeout_s or 5.0
            s = socket.create_connection(ep, timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns[ep] = s
        return s

    def _drop_conn(self, ep: Tuple[str, int]) -> None:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            return
        s = conns.pop(ep, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    # -- core call loop ----------------------------------------------------
    def _call(self, msg_type: int, make_body, decode,
              shard: Optional[int] = None, count: bool = True):
        """One logical call under RetryPolicy: transport failures and
        SHED replies rotate replicas (within `shard` when given) with
        backoff until the deadline; semantic ERROR replies raise
        immediately. count=False keeps client-internal probes (the
        one-time fleet-width info fetch) out of the calls counter, so
        calls == user requests stays an exact accounting identity."""
        pol = self.retry
        if count:
            self._ctr["calls"].inc()
        if self._next_rediscover is not None \
                and time.monotonic() >= self._next_rediscover:
            # TTL re-resolution (elastic fleet): autoscaled-up replicas
            # join the rotation within one TTL, not only after failures
            self._next_rediscover = (time.monotonic()
                                     + self.rediscover_ttl_s)
            self._rediscover()
        deadline = time.monotonic() + max(pol.deadline_s, 0.0)
        attempt = 0
        last_shed: Optional[str] = None
        t_start = time.monotonic()
        try:
            while True:
                remaining = deadline - time.monotonic()
                ep = None
                try:
                    ep = self._next_replica(shard)
                    s = self._conn(ep)
                    body = make_body(max(remaining, 0.001))
                    if self.hedge and shard is not None:
                        # per-LEG latency attribution happens inside:
                        # charging the whole exchange (primary straggle
                        # + hedge delay) to the winner would rank the
                        # rescuing replica as the slow one
                        reply_type, reply, ep = self._exchange_hedged(
                            s, ep, shard, msg_type, body, deadline)
                    else:
                        t_att = time.monotonic()
                        wire.write_frame(s, msg_type, body)
                        reply_type, reply = wire.read_frame(s)
                        self._observe_attempt(
                            ep, shard,
                            (time.monotonic() - t_att) * 1000.0)
                    if reply_type != msg_type:
                        raise wire.WireError(
                            f"reply type {reply_type} != {msg_type}")
                    r = wire.Reader(reply)
                    status = r.u32()
                    if status == wire.STATUS_OK:
                        if attempt:
                            self._ctr["failovers"].inc()
                        return decode(r)
                    reason = r.str_()
                    if status == wire.STATUS_SHED:
                        self._ctr["sheds"].inc()
                        last_shed = reason
                        raise ServerOverloaded(f"{ep[0]}:{ep[1]} shed: "
                                               f"{reason}")
                    raise EngineError(
                        f"serving error from {ep[0]}:{ep[1]}: {reason}")
                except (ServerOverloaded, ConnectionError, OSError,
                        socket.timeout, EngineError) as e:
                    transient = isinstance(
                        e, (ServerOverloaded, ConnectionError, OSError,
                            socket.timeout)) or retryable_error(e)
                    if ep is not None and not isinstance(e,
                                                         ServerOverloaded):
                        self._drop_conn(ep)
                    if not transient:
                        raise
                    attempt += 1
                    with self._mu:
                        self._last_error = str(e)
                    now = time.monotonic()
                    exhausted = (now >= deadline
                                 or (pol.max_attempts
                                     and attempt >= pol.max_attempts))
                    if exhausted:
                        self._ctr["deadline_exhausted"].inc()
                        if last_shed is not None and isinstance(
                                e, ServerOverloaded):
                            raise ServerOverloaded(
                                f"serving gave up after {attempt} "
                                f"attempt(s): shed ({last_shed})") from e
                        raise RetryDeadlineExceeded(
                            f"serving call gave up after {attempt} "
                            f"attempt(s) ({pol.deadline_s:.1f}s "
                            f"deadline): {e}") from e
                    self._ctr["retries"].inc()
                    self._rediscover()
                    sleep = min(pol.backoff_s(attempt, self._backoff_rng),
                                max(deadline - now, 0.0))
                    time.sleep(sleep)
        finally:
            dt_ms = (time.monotonic() - t_start) * 1000.0
            self._hist_call_ms.observe(dt_ms)
            if shard is not None:
                self._hist_shard_ms.labels(
                    client=self._obs_name, shard=str(shard)).observe(dt_ms)

    @staticmethod
    def _deadline_ms(remaining_s: float) -> int:
        return int(min(max(remaining_s, 0.001) * 1000.0, 0xFFFFFFFF))

    # -- fan-out machinery -------------------------------------------------
    def _submit_all(self, jobs: List) -> List:
        """Grow-if-needed the fan-out pool and submit every job under
        ONE lock hold: a concurrent grower replaces (and shuts down)
        the pool, so fetch-then-submit as two steps could submit on a
        just-shut-down executor and raise RuntimeError outside the
        retry machinery. Submission is enqueue-only — cheap to hold
        the lock across."""
        with self._mu:
            want = max(len(jobs), 2)
            if self.fanout > 0:
                want = min(want, self.fanout)
            if self._pool is None or self._pool_size < want:
                old = self._pool
                self._pool = ThreadPoolExecutor(
                    max_workers=want,
                    thread_name_prefix=f"{self._obs_name}-fanout")
                self._pool_size = want
                if old is not None:
                    old.shutdown(wait=False)
            return [self._pool.submit(j) for j in jobs]

    def _fanout(self, jobs: List) -> List:
        """Run thunks concurrently on the fan-out pool; re-raise the
        first failure (a shard that ran out its whole retry deadline
        surfaces ITS explicit status — never a silent partial merge).
        A fan-out issued FROM a fan-out worker runs inline instead:
        parents parked on a pool slot waiting for children that need a
        pool slot is a deadlock, not parallelism."""
        self._ctr_fanout["shard_calls"].inc(len(jobs))
        if len(jobs) == 1 or threading.current_thread().name.startswith(
                f"{self._obs_name}-fanout"):
            return [j() for j in jobs]
        return [f.result() for f in self._submit_all(jobs)]

    def _shard_bounds(self) -> Tuple[List[int], np.ndarray]:
        """(shard ids, uint64 lower id bound per shard) for id-range
        routing, fetched from each shard's info() and cached per fleet
        generation with a bounds_ttl_s expiry. The TTL matters beyond
        freshness: a hot-swap that shifts shard boundaries does NOT
        change the endpoint set, so generation alone would leave every
        client that didn't issue the swap routing on stale bounds
        forever — the TTL bounds that window."""
        gen = self._live_state[0]
        with self._mu:
            if (self._bounds is not None and self._bounds_gen == gen
                    and (time.monotonic() - self._bounds_at)
                    < self.bounds_ttl_s):
                return self._bounds
        shard_ids = self.shards()
        infos = self._fanout([
            (lambda s=s: (s, self._call(
                wire.MSG_INFO, lambda _r: b"",
                lambda r: json.loads(r.str_()), shard=s, count=False)))
            for s in shard_ids])
        los = []
        for s, info in infos:
            lo = info.get("id_lo")
            # an empty shard owns no ids: push its bound past every
            # possible id so routing never lands on it
            los.append(int(lo) if lo is not None else (1 << 64) - 1)
        bounds = (shard_ids, np.asarray(los, dtype=np.uint64))
        with self._mu:
            self._bounds = bounds
            self._bounds_gen = gen
            self._bounds_at = time.monotonic()
        return bounds

    def _owners(self, ids: np.ndarray) -> Tuple[List[int], np.ndarray]:
        """(shard ids, owning-shard POSITION per query id). Ids below
        the first bound clip to shard 0; ids in nobody's range route to
        the range they fall in and come back as zeros — the same
        unknown-id semantics the monolith has."""
        shard_ids, los = self._shard_bounds()
        pos = np.searchsorted(los, ids.astype(np.uint64),
                              side="right").astype(np.int64) - 1
        return shard_ids, np.clip(pos, 0, len(shard_ids) - 1)

    # -- verbs -------------------------------------------------------------
    def embed(self, ids) -> np.ndarray:
        """[n, D] float32 embedding rows (zeros for unknown ids).
        Multi-shard fleets scatter by owning id range and reassemble —
        byte-identical to the monolith gather."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        shard_list = self._fleet_view()
        if len(shard_list) > 1 and ids.size:
            self._ctr_fanout["queries"].inc()
        return self._embed_ids(ids, shard_list)

    def _embed_ids(self, ids: np.ndarray,
                   shard_list: List[int]) -> np.ndarray:
        """embed() body without the logical-query counter: knn phase 1
        and cross-shard score ride through here so ONE logical query
        counts once, however many internal gathers it needs."""
        if len(shard_list) <= 1 or ids.size == 0:
            return self._embed_one(
                ids, shard_list[0] if shard_list else None)
        shard_ids, pos = self._owners(ids)
        groups = [(shard_ids[p], np.nonzero(pos == p)[0])
                  for p in np.unique(pos)]
        parts = self._fanout([
            (lambda s=s, idx=idx: (idx, self._embed_one(ids[idx], s)))
            for s, idx in groups])
        dim = parts[0][1].shape[1] if parts else 0
        out = np.zeros((ids.size, dim), np.float32)
        for idx, rows in parts:
            out[idx] = rows
        return out

    def _embed_one(self, ids: np.ndarray,
                   shard: Optional[int]) -> np.ndarray:
        def body(remaining):
            return struct.pack("<II", self._deadline_ms(remaining),
                               ids.size) + ids.tobytes()

        def decode(r: wire.Reader):
            n = r.u32()
            dim = r.u32()
            return r.array(np.float32, n * dim).reshape(n, dim)

        return self._call(wire.MSG_EMBED, body, decode, shard=shard)

    def knn(self, ids, k: int = 10,
            exact: bool = True) -> Tuple[np.ndarray, np.ndarray]:
        """Per-query top-k: (neighbor ids [n, k] uint64, inner-product
        scores [n, k] float32). On a multi-shard fleet this is the
        scatter-gather: query vectors resolved at their owning shard,
        broadcast to every shard concurrently, per-shard top-k stable-
        merged into the global top-k — with exact=True the result is
        byte-identical to a single-index tools/knn.brute_force over the
        whole corpus (see module docstring). exact=False routes through
        each shard's IVFFlat index (approximate, faster at corpus
        scale; the merge is the same but carries no bitwise guarantee).
        The returned k may be clipped to the corpus size."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        shard_list = self._fleet_view()
        if len(shard_list) <= 1:
            return self._knn_ids(
                ids, k, exact, shard_list[0] if shard_list else None)
        # phase 1: exact query vectors from the owning shards
        vecs = self._embed_ids(ids, shard_list)
        # phase 2: broadcast vectors, gather per-shard top-k
        self._ctr_fanout["queries"].inc()
        parts = self._fanout([
            (lambda s=s: self._knn_vec(vecs, k, exact, s))
            for s in shard_list])
        return self._merge_topk(parts, k)

    def _knn_ids(self, ids: np.ndarray, k: int, exact: bool,
                 shard: Optional[int]
                 ) -> Tuple[np.ndarray, np.ndarray]:
        def body(remaining):
            return struct.pack(
                "<IIBI", self._deadline_ms(remaining), int(k),
                1 if exact else 0, ids.size) + ids.tobytes()

        return self._call(wire.MSG_KNN, body, self._decode_topk,
                          shard=shard)

    def _knn_vec(self, vecs: np.ndarray, k: int, exact: bool,
                 shard: int) -> Tuple[np.ndarray, np.ndarray]:
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)

        def body(remaining):
            return struct.pack(
                "<IIBII", self._deadline_ms(remaining), int(k),
                1 if exact else 0, vecs.shape[0], vecs.shape[1]) \
                + vecs.tobytes()

        return self._call(wire.MSG_KNN_VEC, body, self._decode_topk,
                          shard=shard)

    @staticmethod
    def _decode_topk(r: wire.Reader):
        n = r.u32()
        got_k = r.u32()
        nbr = r.array(np.uint64, n * got_k).reshape(n, got_k)
        sims = r.array(np.float32, n * got_k).reshape(n, got_k)
        return nbr, sims

    def _merge_topk(self, parts, k: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Merge per-shard top-k into the global top-k. Candidates are
        concatenated in SHARD ORDER (= ascending global row order for
        contiguous shards) and selected with a STABLE sort on -sims, so
        ties resolve toward the lower global row — exactly the total
        order the stable single-index brute force uses. Byte-identical
        by construction (per-shard sims are bitwise slices of the full
        GEMM: the reduction runs over the same D either way)."""
        self._ctr_fanout["merges"].inc()
        nbr = np.concatenate([p[0] for p in parts], axis=1)
        sims = np.concatenate([p[1] for p in parts], axis=1)
        kk = min(int(k), nbr.shape[1])
        order = np.argsort(-sims, axis=1, kind="stable")[:, :kk]
        return (np.take_along_axis(nbr, order, axis=1),
                np.take_along_axis(sims, order, axis=1))

    def score(self, src, dst) -> np.ndarray:
        """Inner product per (src, dst) pair: [n] float32 (0.0 when
        either end is unknown). Same-shard pairs are scored on their
        replica; cross-shard pairs resolve both embeddings and dot on
        the client (fp tolerance vs the monolith, see module
        docstring)."""
        src = np.ascontiguousarray(src, dtype=np.uint64).ravel()
        dst = np.ascontiguousarray(dst, dtype=np.uint64).ravel()
        if src.size != dst.size:
            raise ValueError(f"src has {src.size} ids, dst {dst.size}")
        shard_list = self._fleet_view()
        if len(shard_list) <= 1 or src.size == 0:
            return self._score_one(
                src, dst, shard_list[0] if shard_list else None)
        shard_ids, spos = self._owners(src)
        _, dpos = self._owners(dst)
        same = spos == dpos
        out = np.zeros(src.size, np.float32)
        self._ctr_fanout["queries"].inc()
        # cross-shard pairs first (embed() fans out internally); then
        # the same-shard groups in one concurrent wave
        cross = np.nonzero(~same)[0]
        if cross.size:
            # one deduplicated embed over BOTH ends: two sequential
            # embed() calls would pay two full fan-out waves
            uniq, inv = np.unique(
                np.concatenate([src[cross], dst[cross]]),
                return_inverse=True)
            emb_u = self._embed_ids(uniq, shard_list)
            out[cross] = np.einsum(
                "ij,ij->i", emb_u[inv[:cross.size]],
                emb_u[inv[cross.size:]]).astype(np.float32)
        jobs = []
        for p in np.unique(spos[same]):
            idx = np.nonzero(same & (spos == p))[0]
            jobs.append((lambda s=shard_ids[p], idx=idx:
                         (idx, self._score_one(src[idx], dst[idx], s))))
        if jobs:
            for idx, vals in self._fanout(jobs):
                out[idx] = vals
        return out

    def _score_one(self, src: np.ndarray, dst: np.ndarray,
                   shard: Optional[int]) -> np.ndarray:
        def body(remaining):
            return struct.pack("<II", self._deadline_ms(remaining),
                               src.size) + src.tobytes() + dst.tobytes()

        def decode(r: wire.Reader):
            n = r.u32()
            return r.array(np.float32, n)

        return self._call(wire.MSG_SCORE, body, decode, shard=shard)

    def server_health(self, shard: Optional[int] = None) -> Dict:
        """One replica's health() dict (round-robin pick, optionally
        pinned to a shard)."""
        return self._call(wire.MSG_HEALTH, lambda _r: b"",
                          lambda r: json.loads(r.str_()), shard=shard)

    def info(self, shard: Optional[int] = None) -> Dict:
        """Service/bundle identity of one replica (dim, count, shard,
        bundle_version, id range)."""
        return self._call(wire.MSG_INFO, lambda _r: b"",
                          lambda r: json.loads(r.str_()), shard=shard)

    def fleet_info(self) -> Dict[int, Dict]:
        """{shard -> info()} across the fleet (concurrent)."""
        shard_list = self.shards()
        return dict(self._fanout([
            (lambda s=s: (s, self.info(shard=s))) for s in shard_list]))

    # -- zero-downtime promotion -------------------------------------------
    def swap_fleet(self, bundle_dir: str) -> Dict[str, Dict]:
        """Rolling zero-downtime promotion: tell EVERY live replica,
        one at a time, to load `bundle_dir` beside its serving bundle,
        warm it, and flip (wire MSG_SWAP). Sequential on purpose — the
        fleet keeps serving on the replicas not currently warming.
        Returns {"host:port": swap reply}. Raises on the first replica
        that fails, leaving the fleet mixed-version; re-running
        converges (an already-promoted replica just swaps to the same
        version again)."""
        with self._mu:
            eps = list(self._replicas)
        if not eps:
            raise wire.WireError(
                f"no live replicas for service {self.service!r}")
        out: Dict[str, Dict] = {}
        for ep in eps:
            self._ctr["swaps"].inc()
            out[f"{ep[0]}:{ep[1]}"] = self._swap_one(ep, bundle_dir)
        # the promoted bundle may shard the id space differently (same
        # shard count, shifted contiguous boundaries): drop the cached
        # id-range routing table so the next routed call refetches it
        with self._mu:
            self._bounds = None
        return out

    def _swap_one(self, ep: Tuple[str, int], bundle_dir: str) -> Dict:
        """One replica's swap on a DEDICATED socket (load+warm can take
        far longer than the cached data-path sockets' timeout)."""
        body = wire.pack_str(bundle_dir)
        with socket.create_connection(
                ep, timeout=self.swap_timeout_s) as s:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            wire.write_frame(s, wire.MSG_SWAP, body)
            reply_type, reply = wire.read_frame(s)
            if reply_type != wire.MSG_SWAP:
                raise wire.WireError(
                    f"reply type {reply_type} != {wire.MSG_SWAP}")
            r = wire.Reader(reply)
            status = r.u32()
            if status != wire.STATUS_OK:
                raise EngineError(
                    f"swap failed on {ep[0]}:{ep[1]}: {r.str_()}")
            return json.loads(r.str_())

    # -- introspection / lifecycle -----------------------------------------
    def health(self) -> Dict:
        """Client-side counter view (obs registry children): calls,
        retries, failovers, sheds, deadline_exhausted, rediscoveries,
        stale-conn drops, swap calls, fan-out counters, last_error,
        live replica/shard counts."""
        out = {k: int(c.value) for k, c in self._ctr.items()}
        out["fanout"] = {k: int(c.value)
                        for k, c in self._ctr_fanout.items()}
        with self._mu:
            out["last_error"] = self._last_error
            out["replicas"] = len(self._replicas)
            out["shards"] = len(self._fleet)
        return out

    def close(self) -> None:
        _obs.unregister_health(self._obs_name)
        with self._mu:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        conns = getattr(self._local, "conns", None)
        if conns:
            for s in conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            conns.clear()

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
