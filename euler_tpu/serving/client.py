"""ServingClient: retrying, failover-capable client for InferenceServer
replicas.

Reuses the graph client's resilience vocabulary wholesale: RetryPolicy
(exponential backoff, full jitter, per-call deadline, per-attempt
timeout) and the transport-vs-semantic error split of
`retryable_error`. Replicas come from a static ``hosts:h:p,h:p`` list
or are discovered live from the registry (the same registry the graph
shards heartbeat into); a transport failure fails over to the next
replica and, under a registry, re-resolves the replica set — so a
killed-and-restarted replica rejoins traffic within its heartbeat
interval, exactly like a graph shard does for trainers.

An explicit SHED reply from an overloaded replica is retried on
another replica under the same deadline (counted separately from
transport retries); when the deadline runs out the LAST explicit
status is raised — ServerOverloaded for sheds, RetryDeadlineExceeded
for transport — so no request ever ends without a status.
"""

from __future__ import annotations

import itertools
import json
import random
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.core.lib import EngineError
from euler_tpu.graph.remote import (
    RetryDeadlineExceeded,
    RetryPolicy,
    retryable_error,
)
from euler_tpu.serving import wire

__all__ = ["ServingClient", "ServerOverloaded"]

_CLIENT_IDS = itertools.count()


class ServerOverloaded(EngineError):
    """Every attempted replica answered SHED for the whole deadline —
    the overload was explicit end to end."""


class ServingClient:
    """Client for a serving service (see module docstring).

    endpoints: "hosts:h:p,h:p" static replica list, OR None with
      `registry` set — a registry spec ("tcp:host:port" / "dir:/path")
      plus `service` to discover replicas from.
    retry_policy: backoff/deadline/per-attempt-timeout; the default is
      a 10s deadline with a 5s per-attempt socket timeout.
    stale_ms: registry entries older than this are skipped (a crashed
      replica that never deregistered).
    """

    def __init__(self, endpoints: Optional[str] = None,
                 registry: Optional[str] = None, service: str = "default",
                 retry_policy: Optional[RetryPolicy] = None,
                 stale_ms: int = 10_000, seed: int = 0):
        if not endpoints and not registry:
            raise ValueError("pass endpoints='hosts:h:p,...' or a "
                             "registry spec + service")
        self.service = service
        self.registry = registry
        self.stale_ms = int(stale_ms)
        self.retry = retry_policy or RetryPolicy(
            deadline_s=10.0, call_timeout_s=5.0)
        self._backoff_rng = random.Random(seed ^ 0x5E21 if seed else None)
        self._static: Optional[List[Tuple[str, int]]] = None
        if endpoints:
            if not endpoints.startswith("hosts:"):
                raise ValueError("endpoints must be 'hosts:h:p,h:p'")
            self._static = []
            for part in endpoints[len("hosts:"):].split(","):
                host, _, port = part.strip().rpartition(":")
                self._static.append((host, int(port)))
        self._mu = threading.Lock()
        self._replicas: List[Tuple[str, int]] = list(self._static or [])
        self._rr = 0
        self._local = threading.local()  # per-thread connection cache
        self._obs_name = f"serving_client{next(_CLIENT_IDS)}"
        reg = _obs.default_registry()
        lab = {"client": self._obs_name}
        self._ctr = {
            k: reg.counter(f"serving_client_{k}_total", h,
                           ("client",)).labels(**lab)
            for k, h in (
                ("calls", "serving calls issued"),
                ("retries", "retry cycles (transport or shed)"),
                ("failovers", "calls that succeeded after >=1 failure"),
                ("sheds", "explicit SHED replies received"),
                ("deadline_exhausted", "calls that ran out of budget"),
                ("rediscoveries", "registry re-resolutions"),
            )}
        self._hist_call_ms = reg.histogram(
            "serving_client_call_ms",
            "end-to-end serving call latency incl. retries",
            ("client",)).labels(**lab)
        self._last_error: Optional[str] = None
        _obs.register_health(self._obs_name, self.health)
        if self._static is None:
            self._rediscover(initial=True)

    # -- discovery ---------------------------------------------------------
    def _rediscover(self, initial: bool = False) -> None:
        if self._static is not None:
            return
        try:
            found = wire.discover_replicas(self.registry, self.service,
                                           max_age_ms=self.stale_ms)
        except (OSError, wire.WireError) as e:
            if initial:
                raise
            with self._mu:
                self._last_error = f"registry scan: {e}"
            return
        self._ctr["rediscoveries"].inc()
        with self._mu:
            self._replicas = [(h, p) for h, p, _ in found]

    def replicas(self) -> List[Tuple[str, int]]:
        with self._mu:
            return list(self._replicas)

    def _next_replica(self) -> Tuple[str, int]:
        with self._mu:
            if not self._replicas:
                # WireError subclasses ConnectionError → the call loop
                # treats an (often transient) empty replica set as
                # retryable and keeps re-resolving until the deadline
                raise wire.WireError(
                    f"no live replicas for service {self.service!r} "
                    "(registry empty or all entries stale)")
            ep = self._replicas[self._rr % len(self._replicas)]
            self._rr += 1
            return ep

    # -- connections (one cached socket per thread per endpoint) ----------
    def _conn(self, ep: Tuple[str, int]) -> socket.socket:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        s = conns.get(ep)
        if s is None:
            timeout = self.retry.call_timeout_s or 5.0
            s = socket.create_connection(ep, timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns[ep] = s
        return s

    def _drop_conn(self, ep: Tuple[str, int]) -> None:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            return
        s = conns.pop(ep, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    # -- core call loop ----------------------------------------------------
    def _call(self, msg_type: int, make_body, decode):
        """One logical call under RetryPolicy: transport failures and
        SHED replies rotate replicas with backoff until the deadline;
        semantic ERROR replies raise immediately."""
        pol = self.retry
        self._ctr["calls"].inc()
        deadline = time.monotonic() + max(pol.deadline_s, 0.0)
        attempt = 0
        last_shed: Optional[str] = None
        t_start = time.monotonic()
        try:
            while True:
                remaining = deadline - time.monotonic()
                ep = None
                try:
                    ep = self._next_replica()
                    s = self._conn(ep)
                    body = make_body(max(remaining, 0.001))
                    wire.write_frame(s, msg_type, body)
                    reply_type, reply = wire.read_frame(s)
                    if reply_type != msg_type:
                        raise wire.WireError(
                            f"reply type {reply_type} != {msg_type}")
                    r = wire.Reader(reply)
                    status = r.u32()
                    if status == wire.STATUS_OK:
                        if attempt:
                            self._ctr["failovers"].inc()
                        return decode(r)
                    reason = r.str_()
                    if status == wire.STATUS_SHED:
                        self._ctr["sheds"].inc()
                        last_shed = reason
                        raise ServerOverloaded(f"{ep[0]}:{ep[1]} shed: "
                                               f"{reason}")
                    raise EngineError(
                        f"serving error from {ep[0]}:{ep[1]}: {reason}")
                except (ServerOverloaded, ConnectionError, OSError,
                        socket.timeout, EngineError) as e:
                    transient = isinstance(
                        e, (ServerOverloaded, ConnectionError, OSError,
                            socket.timeout)) or retryable_error(e)
                    if ep is not None and not isinstance(e,
                                                         ServerOverloaded):
                        self._drop_conn(ep)
                    if not transient:
                        raise
                    attempt += 1
                    with self._mu:
                        self._last_error = str(e)
                    now = time.monotonic()
                    exhausted = (now >= deadline
                                 or (pol.max_attempts
                                     and attempt >= pol.max_attempts))
                    if exhausted:
                        self._ctr["deadline_exhausted"].inc()
                        if last_shed is not None and isinstance(
                                e, ServerOverloaded):
                            raise ServerOverloaded(
                                f"serving gave up after {attempt} "
                                f"attempt(s): shed ({last_shed})") from e
                        raise RetryDeadlineExceeded(
                            f"serving call gave up after {attempt} "
                            f"attempt(s) ({pol.deadline_s:.1f}s "
                            f"deadline): {e}") from e
                    self._ctr["retries"].inc()
                    self._rediscover()
                    sleep = min(pol.backoff_s(attempt, self._backoff_rng),
                                max(deadline - now, 0.0))
                    time.sleep(sleep)
        finally:
            self._hist_call_ms.observe(
                (time.monotonic() - t_start) * 1000.0)

    @staticmethod
    def _deadline_ms(remaining_s: float) -> int:
        return int(min(max(remaining_s, 0.001) * 1000.0, 0xFFFFFFFF))

    # -- verbs -------------------------------------------------------------
    def embed(self, ids) -> np.ndarray:
        """[n, D] float32 embedding rows (zeros for unknown ids)."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()

        def body(remaining):
            return struct.pack("<II", self._deadline_ms(remaining),
                               ids.size) + ids.tobytes()

        def decode(r: wire.Reader):
            n = r.u32()
            dim = r.u32()
            return r.array(np.float32, n * dim).reshape(n, dim)

        return self._call(wire.MSG_EMBED, body, decode)

    def knn(self, ids, k: int = 10,
            exact: bool = True) -> Tuple[np.ndarray, np.ndarray]:
        """Per-query top-k: (neighbor ids [n, k] uint64, inner-product
        scores [n, k] float32). exact=True is byte-identical to offline
        tools/knn.brute_force over the bundle; exact=False uses the
        bundle's IVFFlat index (approximate, faster at corpus scale).
        The returned k may be clipped to the corpus size."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()

        def body(remaining):
            return struct.pack(
                "<IIBI", self._deadline_ms(remaining), int(k),
                1 if exact else 0, ids.size) + ids.tobytes()

        def decode(r: wire.Reader):
            n = r.u32()
            got_k = r.u32()
            nbr = r.array(np.uint64, n * got_k).reshape(n, got_k)
            sims = r.array(np.float32, n * got_k).reshape(n, got_k)
            return nbr, sims

        return self._call(wire.MSG_KNN, body, decode)

    def score(self, src, dst) -> np.ndarray:
        """Inner product per (src, dst) pair: [n] float32 (0.0 when
        either end is unknown)."""
        src = np.ascontiguousarray(src, dtype=np.uint64).ravel()
        dst = np.ascontiguousarray(dst, dtype=np.uint64).ravel()
        if src.size != dst.size:
            raise ValueError(f"src has {src.size} ids, dst {dst.size}")

        def body(remaining):
            return struct.pack("<II", self._deadline_ms(remaining),
                               src.size) + src.tobytes() + dst.tobytes()

        def decode(r: wire.Reader):
            n = r.u32()
            return r.array(np.float32, n)

        return self._call(wire.MSG_SCORE, body, decode)

    def server_health(self) -> Dict:
        """One replica's health() dict (round-robin pick)."""
        return self._call(wire.MSG_HEALTH, lambda _r: b"",
                          lambda r: json.loads(r.str_()))

    def info(self) -> Dict:
        """Service/bundle identity of one replica (dim, count, spec)."""
        return self._call(wire.MSG_INFO, lambda _r: b"",
                          lambda r: json.loads(r.str_()))

    # -- introspection / lifecycle -----------------------------------------
    def health(self) -> Dict:
        """Client-side counter view (obs registry children): calls,
        retries, failovers, sheds, deadline_exhausted, rediscoveries,
        last_error, live replica count."""
        out = {k: int(c.value) for k, c in self._ctr.items()}
        with self._mu:
            out["last_error"] = self._last_error
            out["replicas"] = len(self._replicas)
        return out

    def close(self) -> None:
        _obs.unregister_health(self._obs_name)
        conns = getattr(self._local, "conns", None)
        if conns:
            for s in conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            conns.clear()

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
