"""InferenceServer: one serving-fleet replica over one bundle shard.

Serves over the framed-TCP conventions (wire.py):

  embed(ids)        [n, D] float32 embedding rows
  knn(ids, k)       per-query top-k neighbor ids + inner-product scores
                    (exact brute-force by default — byte-identical to
                    tools/knn.brute_force over the served shard — or
                    the shard's IVFFlat index with exact=False)
  knn_vec(vecs, k)  same, but queries arrive as raw float32 vectors —
                    the fleet fan-out verb: the client resolves each
                    query id's embedding at its OWNING shard, then
                    broadcasts the vectors to every shard, so a shard
                    never mistakes another shard's id for an unknown
  score(src, dst)   inner product per (src, dst) pair
  swap(bundle_dir)  admin: zero-downtime versioned hot-swap (below)

Every data verb funnels through a per-verb dynamic MicroBatcher:
concurrent requests coalesce into one flush (flush at max_batch rows or
flush_ms), padded to a fixed bucket ladder so the jitted device apply
never recompiles in steady state. Past max_queue queued rows, admission
control replies an explicit SHED status instead of queueing — overload
degrades loudly and boundedly, never as silent latency growth. A
request whose deadline_ms expires while queued also gets SHED.

**Fleet**: a replica serves ONE contiguous shard of a partitioned
bundle (export.save_sharded) and registers
``serve_<service>_<shard>_<replica>__<host>_<port>`` in the same
registry the graph shards heartbeat into — shards and replicas-per-
shard are discoverable exactly like graph shards. kNN sims are
computed PER REQUEST (not coalesced across a flush): per-request GEMM
keeps each answer's bits independent of what else happened to share
the flush, which is what lets the client's scatter-gather merge be
byte-identical to a single-index brute-force reference. The flush
still amortizes the per-dispatch cost — that cost is per flush, not
per request.

**Zero-downtime hot-swap**: all bundle-scoped state (arrays, the
jitted applies, the lazy IVF index) lives in a _BundleEngine. swap()
loads bundle vN+1 BESIDE vN, warms the new engine's jitted applies
over the whole bucket ladder and rebuilds its index off-path, then
atomically flips the serving pointer (one reference assignment). A
flush in progress keeps the engine it started with; queued requests
pick up whichever engine their flush starts under — every in-flight
request completes with a status either way, no request is dropped.
``bundle_version`` is exposed in info()/health()/healthz and every
completed swap increments serving_swap_total.

Unknown ids (not in the served shard) embed as zero rows and score 0 —
counted in serving_unknown_ids_total, never an error.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.serving import wire
from euler_tpu.serving.batcher import (
    MicroBatcher,
    ShedError,
    bucket_ladder,
    run_bucketed,
    warm_ladder,
)
from euler_tpu.serving.export import ModelBundle, bundle_shard_count

__all__ = ["InferenceServer"]

_DEFAULT_DEADLINE_S = 30.0


class _BundleEngine:
    """Version-scoped serving state: one loaded bundle (shard) plus its
    jitted applies and lazy IVF index. Built (and warmed) OFF the
    serving path; the server serves whichever engine its atomic
    pointer names. Immutable after construction except the lazily
    built index."""

    def __init__(self, bundle: ModelBundle):
        import jax
        import jax.numpy as jnp

        self.bundle = bundle
        self.ids = bundle.ids                     # sorted uint64
        self.emb = bundle.embeddings              # [N, D] float32 host
        self.shard = bundle.shard
        self.num_shards = bundle.num_shards
        self.version = bundle.version
        self._index = None
        self._index_mu = threading.Lock()

        table = jnp.asarray(self.emb) if self.emb.size else None
        self.jit_gather = jax.jit(
            (lambda rows: table[rows]) if table is not None
            else (lambda rows: jnp.zeros((rows.shape[0], 0), jnp.float32)))
        self.jit_score = jax.jit(
            (lambda a, b: jnp.sum(table[a] * table[b], axis=-1))
            if table is not None
            else (lambda a, b: jnp.zeros((a.shape[0],), jnp.float32)))

    def warm(self, ladder: Tuple[int, ...]) -> None:
        """Compile every ladder bucket of both applies BEFORE this
        engine takes traffic (startup and pre-swap both come through
        here), and rebuild the stored IVF clustering so the first
        approximate query after a flip doesn't pay the build."""
        import jax.numpy as jnp

        warm_ladder(ladder,
                    lambda rows: self.jit_gather(jnp.asarray(rows)),
                    lambda rows: self.jit_score(jnp.asarray(rows),
                                                jnp.asarray(rows)))
        if self.bundle.index_state is not None:
            self.get_index()

    def lookup_rows(self, qids: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """(row indices int32, valid mask, n_unknown) for query ids
        against this shard's sorted id order; unknown ids map to row 0,
        masked."""
        qids = np.ascontiguousarray(qids, dtype=np.uint64)
        if self.ids.size == 0:
            return (np.zeros(qids.size, np.int32),
                    np.zeros(qids.size, bool), int(qids.size))
        rows = np.searchsorted(self.ids, qids).clip(0, self.ids.size - 1)
        valid = self.ids[rows] == qids
        return rows.astype(np.int32), valid, int((~valid).sum())

    def get_index(self):
        with self._index_mu:
            if self._index is None:
                self._index = self.bundle.build_index()
            return self._index

    def id_range(self) -> Tuple[Optional[int], Optional[int]]:
        if self.ids.size == 0:
            return None, None
        return int(self.ids[0]), int(self.ids[-1])


class InferenceServer:
    """One serving replica over one bundle (shard) — see module
    docstring.

    bundle: a ModelBundle, a bundle directory, or a SHARDED bundle
      directory (export.save_sharded) — pass `shard` to pick which
      shard this replica serves; loads verify checksums.
    registry: optional registry spec ("tcp:host:port", "dir:/path", or
      a plain directory) to register in for discovery.
    service / shard / replica: the discovery identity.
    max_batch / flush_ms / max_queue: MicroBatcher knobs (rows).
    inject_apply_latency_ms: fixed sleep per flushed apply — models the
      per-dispatch cost on CPU-bound test containers (chaos/bench only).
    inject_scan_ms_per_krow: sleep per flushed KNN apply scaled by the
      served corpus size (ms per 1000 rows) — models the corpus-
      proportional device scan a brute-force search costs, which is the
      cost sharding divides (chaos/bench only).
    inject_stall_ms / inject_stall_p / inject_seed: per-replica
      STRAGGLER injection — each flushed apply independently stalls
      inject_stall_ms with probability inject_stall_p (seeded) — the
      GC-pause / noisy-neighbor tail the hedging A/B measures against
      (chaos/bench only).
    """

    def __init__(self, bundle: Union[ModelBundle, str],
                 host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[str] = None, service: str = "default",
                 shard: Optional[int] = None, replica: int = 0,
                 max_batch: int = 256,
                 flush_ms: float = 2.0, max_queue: int = 0,
                 heartbeat_s: float = 1.0,
                 inject_apply_latency_ms: float = 0.0,
                 inject_scan_ms_per_krow: float = 0.0,
                 inject_stall_ms: float = 0.0,
                 inject_stall_p: float = 0.1,
                 inject_seed: int = 0):
        if isinstance(bundle, str):
            bundle = self._load_bundle(bundle, shard)
        elif shard is not None and int(shard) != bundle.shard:
            raise ValueError(
                f"shard={shard} but the bundle object is shard "
                f"{bundle.shard}")
        self.service = service
        self.replica = int(replica)
        self._inject_s = float(inject_apply_latency_ms) / 1000.0
        self._scan_s_per_row = float(inject_scan_ms_per_krow) / 1e6
        self._stall_s = float(inject_stall_ms) / 1000.0
        self._stall_p = float(inject_stall_p)
        self._stall_mu = threading.Lock()  # batcher workers share the rng
        import random as _random

        self._stall_rng = _random.Random(inject_seed)
        self.ladder = bucket_ladder(max_batch)
        self._swap_mu = threading.Lock()
        engine = _BundleEngine(bundle)
        # warm every ladder bucket BEFORE accepting traffic: first-
        # request jit compiles would otherwise land inside a client's
        # per-attempt timeout, and steady state must never compile
        engine.warm(self.ladder)
        self._engine = engine

        # -- metrics / health ----------------------------------------------
        reg = _obs.default_registry()
        lab = {"service": service, "shard": str(engine.shard),
               "replica": str(self.replica)}
        self._ctr_requests = reg.counter(
            "serving_requests_total", "serving requests by verb",
            ("service", "shard", "replica", "verb"))
        self._hist_request_ms = reg.histogram(
            "serving_request_ms", "end-to-end in-server request latency",
            ("service", "shard", "replica", "verb"))
        # per-request phase breakdown — the serving-tier analogue of the
        # graph server's native queue/decode/execute/serialize
        # histograms: queue = admission→flush pickup in the micro-
        # batcher, execute = the flush run serving this request
        self._hist_phase_ms = reg.histogram(
            "serving_phase_ms",
            "per-request serving phase time (queue = batcher wait, "
            "execute = micro-batch flush run)",
            ("service", "shard", "replica", "verb", "phase"))
        self._ctr_deadline = reg.counter(
            "serving_deadline_shed_total",
            "admitted requests whose deadline expired in queue (SHED "
            "replied)", ("service", "shard", "replica")).labels(**lab)
        self._ctr_unknown = reg.counter(
            "serving_unknown_ids_total",
            "queried ids absent from the served shard (served as zeros)",
            ("service", "shard", "replica")).labels(**lab)
        self._ctr_errors = reg.counter(
            "serving_errors_total", "requests answered with ERROR status",
            ("service", "shard", "replica")).labels(**lab)
        self._ctr_swap = reg.counter(
            "serving_swap_total",
            "completed zero-downtime bundle hot-swaps",
            ("service", "shard", "replica")).labels(**lab)
        self._g_connections = reg.gauge(
            "serving_connections", "live client connections",
            ("service", "shard", "replica")).labels(**lab)
        self._lab = lab

        name = f"{service}.{engine.shard}.{self.replica}"
        self._batchers = {
            "embed": MicroBatcher(self._run_embed, max_batch=max_batch,
                                  flush_ms=flush_ms, max_queue=max_queue,
                                  name=f"{name}.embed"),
            "knn": MicroBatcher(self._run_knn, max_batch=max_batch,
                                flush_ms=flush_ms, max_queue=max_queue,
                                name=f"{name}.knn"),
            "score": MicroBatcher(self._run_score, max_batch=max_batch,
                                  flush_ms=flush_ms, max_queue=max_queue,
                                  name=f"{name}.score"),
        }

        # -- listener ------------------------------------------------------
        self._stopping = threading.Event()
        self._draining = threading.Event()  # drain(): stop heartbeating
        # serializes registry put/remove between the heartbeat thread
        # and drain()/stop(): without it an in-flight heartbeat put can
        # land AFTER drain's remove and resurrect a permanently stale
        # entry pointing at a stopped server
        self._reg_mu = threading.Lock()
        self._conn_mu = threading.Lock()
        self._conns: List[Tuple[threading.Thread, socket.socket]] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # same-port restart (the chaos kill/restart cycle): a predecessor
        # replica's connections may still be draining — retry the bind
        # briefly instead of failing the restart
        bind_deadline = time.monotonic() + 5.0
        while True:
            try:
                self._listener.bind((host, port))
                break
            except OSError:
                if port == 0 or time.monotonic() >= bind_deadline:
                    raise
                time.sleep(0.1)
        self._listener.listen(64)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"serve-{name}", daemon=True)
        self._accept_thread.start()

        # -- discovery -----------------------------------------------------
        self.registry = registry
        self._entry = wire.serve_entry_name(service, engine.shard,
                                            self.replica, self.host,
                                            self.port)
        self._hb_thread = None
        if registry:
            wire.registry_put(registry, self._entry)
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(float(heartbeat_s),),
                name=f"serve-hb-{name}", daemon=True)
            self._hb_thread.start()
        self._obs_name = (f"serving_{service}_{engine.shard}_"
                          f"{self.replica}_{self.port}")
        _obs.register_health(self._obs_name, self.health)

    # -- bundle / engine ---------------------------------------------------
    @staticmethod
    def _load_bundle(path: str, shard: Optional[int]) -> ModelBundle:
        n = bundle_shard_count(path)
        if n > 1:
            return ModelBundle.load_shard(path, int(shard or 0))
        if shard not in (None, 0):
            raise ValueError(
                f"shard={shard} requested but {path} is unsharded")
        return ModelBundle.load(path, verify=True)

    @property
    def bundle(self) -> ModelBundle:
        return self._engine.bundle

    @property
    def shard(self) -> int:
        return self._engine.shard

    @property
    def bundle_version(self) -> str:
        return self._engine.version

    def swap(self, bundle: Union[ModelBundle, str]) -> Dict:
        """Zero-downtime versioned hot-swap: load the new bundle (same
        shard identity as the one served — a replica never changes
        shards mid-life), warm its jitted applies over the whole bucket
        ladder and rebuild its index OFF the serving path, then
        atomically flip the serving pointer. In-flight requests
        complete against whichever engine their flush started under;
        no request ends without a status. Returns the new identity."""
        with self._swap_mu:
            cur = self._engine
            if isinstance(bundle, str):
                n = bundle_shard_count(bundle)
                if cur.num_shards > 1:
                    if n != cur.num_shards:
                        raise ValueError(
                            f"swap bundle has {n} shard(s) but this "
                            f"replica serves shard {cur.shard} of "
                            f"{cur.num_shards}")
                    bundle = ModelBundle.load_shard(bundle, cur.shard)
                else:
                    if n > 1:
                        raise ValueError(
                            f"swap bundle has {n} shards but this "
                            "replica serves an unsharded bundle")
                    bundle = ModelBundle.load(bundle, verify=True)
            elif (bundle.shard, bundle.num_shards) != (cur.shard,
                                                       cur.num_shards):
                raise ValueError(
                    f"swap bundle is shard {bundle.shard}/"
                    f"{bundle.num_shards} but this replica serves "
                    f"{cur.shard}/{cur.num_shards}")
            if bundle.dim != cur.bundle.dim and cur.bundle.count \
                    and bundle.count:
                raise ValueError(
                    f"swap bundle dim {bundle.dim} != served dim "
                    f"{cur.bundle.dim}")
            engine = _BundleEngine(bundle)
            engine.warm(self.ladder)        # off-path: vN still serving
            self._engine = engine           # the atomic flip
            self._ctr_swap.inc()
            return {"bundle_version": engine.version,
                    "previous_version": cur.version,
                    "shard": engine.shard, "count": bundle.count,
                    "dim": bundle.dim}

    # -- applies (run on the batcher workers) ------------------------------
    def _maybe_inject(self, eng: _BundleEngine, scan: bool) -> None:
        s = self._inject_s
        if scan:
            # corpus-proportional scan cost: the share a shard pays is
            # its corpus share — the cost partitioning divides
            s += self._scan_s_per_row * eng.ids.size
        if self._stall_s > 0:
            # per-replica straggler: an occasional seeded stall on this
            # flush — the tail the hedging A/B is gated against
            with self._stall_mu:
                stalled = self._stall_rng.random() < self._stall_p
            if stalled:
                s += self._stall_s
        if s > 0:
            time.sleep(s)

    def _run_embed(self, payloads: List[np.ndarray]) -> List[np.ndarray]:
        """One bucketed jitted gather over every request's ids."""
        import jax.numpy as jnp

        eng = self._engine
        self._maybe_inject(eng, scan=False)
        flat = np.concatenate(payloads) if payloads else \
            np.zeros(0, np.uint64)
        rows, valid, n_unknown = eng.lookup_rows(flat)
        if n_unknown:
            self._ctr_unknown.inc(n_unknown)
        if flat.size:
            out = run_bucketed(
                lambda r: np.asarray(eng.jit_gather(jnp.asarray(r))),
                [rows], self.ladder)
            # copy=True: jax device buffers surface as read-only numpy
            out = np.array(out, dtype=np.float32)
            out[~valid] = 0.0
        else:
            out = np.zeros((0, eng.bundle.dim), np.float32)
        results, at = [], 0
        for p in payloads:
            results.append(out[at:at + p.size])
            at += p.size
        return results

    def _run_knn(self, payloads: List[Tuple[np.ndarray, int, bool]]
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Top-k per request. Queries are either uint64 ids (resolved
        against this shard, unknown → zero vector) or a float32 [n, D]
        vector matrix (the fleet fan-out verb). Sims are computed with
        one GEMM PER REQUEST: a request's bits must not depend on what
        else coalesced into the flush (BLAS picks different kernels by
        batch shape), or the fleet merge could never be byte-identical
        to the single-index reference. The flush still amortizes the
        per-dispatch (injected) cost."""
        from euler_tpu.tools.knn import brute_force

        eng = self._engine
        self._maybe_inject(eng, scan=True)
        results = []
        for q, k, exact in payloads:
            if isinstance(q, np.ndarray) and q.dtype == np.float32:
                # dim checked even for empty/zero-dim query matrices —
                # a (n, 0) frame would otherwise raise inside the GEMM
                if q.ndim != 2 or (eng.bundle.dim
                                   and q.shape[1] != eng.bundle.dim):
                    # a malformed request fails ALONE: raising here
                    # would set the exception on every future coalesced
                    # into this flush
                    results.append(ValueError(
                        f"knn_vec queries {q.shape} do not match "
                        f"served dim {eng.bundle.dim}"))
                    continue
                queries = q
            else:
                rows, valid, n_unknown = eng.lookup_rows(q)
                if n_unknown:
                    self._ctr_unknown.inc(n_unknown)
                queries = eng.emb[rows].copy() if eng.ids.size else \
                    np.zeros((q.size, eng.bundle.dim), np.float32)
                queries[~valid] = 0.0
            k_eff = max(1, min(int(k), max(eng.ids.size, 1)))
            if exact or eng.ids.size == 0:
                nbr, sims = brute_force(eng.emb, eng.ids, queries, k_eff)
            else:
                nbr, sims = eng.get_index().search(queries, k_eff)
            results.append((nbr.astype(np.uint64),
                            sims.astype(np.float32)))
        return results

    def _run_score(self, payloads: List[Tuple[np.ndarray, np.ndarray]]
                   ) -> List[np.ndarray]:
        import jax.numpy as jnp

        eng = self._engine
        self._maybe_inject(eng, scan=False)
        src = np.concatenate([p[0] for p in payloads]) if payloads \
            else np.zeros(0, np.uint64)
        dst = np.concatenate([p[1] for p in payloads]) if payloads \
            else np.zeros(0, np.uint64)
        a_rows, a_ok, a_unk = eng.lookup_rows(src)
        b_rows, b_ok, b_unk = eng.lookup_rows(dst)
        if a_unk or b_unk:
            self._ctr_unknown.inc(a_unk + b_unk)
        if src.size:
            out = run_bucketed(
                lambda a, b: np.asarray(
                    eng.jit_score(jnp.asarray(a), jnp.asarray(b))),
                [a_rows, b_rows], self.ladder)
            # copy=True: jax device buffers surface as read-only numpy
            out = np.array(out, dtype=np.float32)
            out[~(a_ok & b_ok)] = 0.0
        else:
            out = np.zeros(0, np.float32)
        results, at = [], 0
        for p in payloads:
            results.append(out[at:at + p[0].size])
            at += p[0].size
        return results

    def jit_cache_sizes(self) -> Dict[str, int]:
        """Compiled-variant counts of the SERVING engine's jitted
        applies (steady-state no-recompile assertions): stays <=
        len(ladder) per fn — including right after a hot-swap, whose
        engine was warmed before the flip."""
        eng = self._engine
        return {"gather": int(eng.jit_gather._cache_size()),
                "score": int(eng.jit_score._cache_size())}

    # -- network -----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_mu:
                if self._stopping.is_set():
                    # raced stop(): it already swapped the conn list out,
                    # so nothing would ever shut this connection down
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                # reap finished connection threads (heartbeat-style
                # short-lived health probes would otherwise accumulate)
                self._conns = [(t, s) for t, s in self._conns
                               if t.is_alive()]
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                self._conns.append((t, conn))
            self._g_connections.set(len(self._conns))
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stopping.is_set():
                try:
                    msg_type, body = wire.read_frame(conn)
                except (wire.WireError, OSError):
                    return  # client went away / stop() shut us down
                try:
                    reply = self._dispatch(msg_type, body)
                except ShedError as e:
                    reply = struct_status(wire.STATUS_SHED, str(e))
                except Exception as e:  # semantic/internal: explicit ERROR
                    self._ctr_errors.inc()
                    reply = struct_status(
                        wire.STATUS_ERROR, f"{type(e).__name__}: {e}")
                try:
                    wire.write_frame(conn, msg_type, reply)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg_type: int, body: bytes) -> bytes:
        verb = {wire.MSG_EMBED: "embed", wire.MSG_KNN: "knn",
                wire.MSG_KNN_VEC: "knn_vec", wire.MSG_SCORE: "score",
                wire.MSG_HEALTH: "health", wire.MSG_INFO: "info",
                wire.MSG_SWAP: "swap"}.get(msg_type)
        if verb is None:
            raise ValueError(f"unknown serving msg_type {msg_type}")
        self._ctr_requests.labels(verb=verb, **self._lab).inc()
        t0 = time.monotonic()
        # One tracer span per request (the PR-13 deferred serving-tier
        # item): this process's exported trace file now carries the
        # serving requests, so tools/trace_dump.py --merge lays the
        # serving tier onto the same wall-clock timeline as the train
        # loop and the graph shards. Queue/execute phase attrs are
        # attached in _wait once the batcher stamps them.
        sp = _obs.span("serving_request", verb=verb,
                       shard=self._lab["shard"],
                       replica=self._lab["replica"])
        sp.__enter__()
        try:
            if msg_type == wire.MSG_HEALTH:
                return struct.pack("<I", wire.STATUS_OK) + \
                    wire.pack_str(json.dumps(self.health()))
            if msg_type == wire.MSG_INFO:
                eng = self._engine
                lo, hi = eng.id_range()
                info = {"service": self.service, "shard": eng.shard,
                        "num_shards": eng.num_shards,
                        "replica": self.replica,
                        "bundle_version": eng.version,
                        "id_lo": lo, "id_hi": hi,
                        "dim": eng.bundle.dim, "count": eng.bundle.count,
                        "model_spec": eng.bundle.model_spec}
                return struct.pack("<I", wire.STATUS_OK) + \
                    wire.pack_str(json.dumps(info))
            if msg_type == wire.MSG_SWAP:
                r = wire.Reader(body)
                out = self.swap(r.str_())
                return struct.pack("<I", wire.STATUS_OK) + \
                    wire.pack_str(json.dumps(out))
            r = wire.Reader(body)
            deadline_ms = r.u32()
            timeout = (deadline_ms / 1000.0) if deadline_ms \
                else _DEFAULT_DEADLINE_S
            if msg_type == wire.MSG_EMBED:
                n = r.u32()
                ids = r.array(np.uint64, n)
                fut = self._batchers["embed"].submit(ids, rows=n)
                emb = self._wait(fut, timeout, verb=verb, span=sp)
                return (struct.pack("<III", wire.STATUS_OK, n,
                                    emb.shape[1] if emb.ndim == 2 else 0)
                        + np.ascontiguousarray(emb, np.float32).tobytes())
            if msg_type in (wire.MSG_KNN, wire.MSG_KNN_VEC):
                k = r.u32()
                exact = bool(r.u8())
                n = r.u32()
                if msg_type == wire.MSG_KNN:
                    q = r.array(np.uint64, n)
                else:
                    dim = r.u32()
                    q = r.array(np.float32, n * dim).reshape(n, dim)
                fut = self._batchers["knn"].submit((q, k, exact), rows=n)
                res = self._wait(fut, timeout, verb=verb, span=sp)
                if isinstance(res, Exception):
                    raise res  # per-request validation failure
                nbr, sims = res
                return (struct.pack("<III", wire.STATUS_OK, n,
                                    nbr.shape[1] if nbr.size else 0)
                        + np.ascontiguousarray(nbr, np.uint64).tobytes()
                        + np.ascontiguousarray(sims, np.float32).tobytes())
            # MSG_SCORE
            n = r.u32()
            src = r.array(np.uint64, n)
            dst = r.array(np.uint64, n)
            fut = self._batchers["score"].submit((src, dst), rows=n)
            scores = self._wait(fut, timeout, verb=verb, span=sp)
            return (struct.pack("<II", wire.STATUS_OK, n)
                    + np.ascontiguousarray(scores, np.float32).tobytes())
        finally:
            self._hist_request_ms.labels(verb=verb, **self._lab).observe(
                (time.monotonic() - t0) * 1000.0)
            sp.__exit__(None, None, None)

    def _wait(self, fut, timeout: float, verb: str = "", span=None):
        from concurrent.futures import TimeoutError as FutTimeout

        try:
            result = fut.result(timeout=max(timeout, 0.001))
        except FutTimeout:
            # the flush may still land later; its result is discarded.
            # The client gets an EXPLICIT shed, never a hang.
            self._ctr_deadline.inc()
            if span is not None:
                span.set(shed=True)
            raise ShedError("deadline expired while queued") from None
        # phase breakdown: the batcher stamped queue wait (admission →
        # flush pickup) and the flush run time onto the future before
        # resolving it — record both into the registry and onto the
        # request span so trace_dump --merge shows where serving time
        # went without any Python in the batcher's measurement path
        if verb:
            q_ms = getattr(fut, "queue_wait_ms", None)
            e_ms = getattr(fut, "exec_ms", None)
            if q_ms is not None:
                self._hist_phase_ms.labels(
                    verb=verb, phase="queue", **self._lab).observe(q_ms)
            if e_ms is not None:
                self._hist_phase_ms.labels(
                    verb=verb, phase="execute", **self._lab).observe(e_ms)
            if span is not None and q_ms is not None:
                span.set(queue_ms=round(q_ms, 3),
                         exec_ms=round(e_ms, 3) if e_ms is not None
                         else None)
        return result

    # -- discovery heartbeat ----------------------------------------------
    def _heartbeat_loop(self, interval_s: float) -> None:
        while not self._stopping.wait(interval_s):
            with self._reg_mu:
                # flag re-checked UNDER the lock drain()/stop() remove
                # under: once they removed, no put can land after
                if self._draining.is_set() or self._stopping.is_set():
                    continue
                try:
                    wire.registry_put(self.registry, self._entry)
                except (OSError, wire.WireError):
                    pass  # registry outage: entry goes stale, not fatal

    def drain(self, grace_s: float = 1.0,
              queue_timeout_s: float = 5.0) -> None:
        """Graceful scale-down (the autoscaler's down path, riding the
        PR 8 discovery machinery): deregister (and stop heartbeating,
        so the entry cannot come back) → clients re-resolve away within
        their registry TTL → wait `grace_s` plus for the admission
        queues to empty (bounded) → stop. In-flight requests complete
        with a status; new connections during the grace window are
        still served — no request ends without a status."""
        self._draining.set()
        if self.registry:
            with self._reg_mu:  # after this remove, no put can land
                wire.registry_remove(self.registry, self._entry)
        time.sleep(max(grace_s, 0.0))
        deadline = time.monotonic() + max(queue_timeout_s, 0.0)
        while time.monotonic() < deadline:
            if all(b.queue_depth == 0 for b in self._batchers.values()):
                break
            time.sleep(0.05)
        self.stop()

    # -- introspection -----------------------------------------------------
    def health(self) -> Dict:
        """Counter surface (also served via obs /healthz): request /
        shed / unknown-id / error / swap totals, per-verb queue depths,
        shard + bundle identity."""
        eng = self._engine
        shed = 0
        queues = {}
        for verb, b in self._batchers.items():
            queues[verb] = b.queue_depth
            shed += int(b._ctr_shed.value)
        reqs = {
            verb: int(self._ctr_requests.labels(
                verb=verb, **self._lab).value)
            for verb in ("embed", "knn", "knn_vec", "score", "health",
                         "info", "swap")}
        return {
            "service": self.service, "shard": eng.shard,
            "num_shards": eng.num_shards, "replica": self.replica,
            "port": self.port, "bundle_version": eng.version,
            "requests": reqs,
            "shed": shed + int(self._ctr_deadline.value),
            "deadline_shed": int(self._ctr_deadline.value),
            "unknown_ids": int(self._ctr_unknown.value),
            "errors": int(self._ctr_errors.value),
            "swaps": int(self._ctr_swap.value),
            "queue_rows": queues,
            "bundle": {"count": eng.bundle.count, "dim": eng.bundle.dim},
        }

    # -- lifecycle ---------------------------------------------------------
    def stop(self) -> None:
        """Shut the replica down: deregister, close the listener and
        every live connection (in-flight clients see a transport error
        — an explicit failure they fail over on, never a hang), drain
        the batchers."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        if self.registry:
            with self._reg_mu:  # same contract as drain(): no put after
                wire.registry_remove(self.registry, self._entry)
        try:
            # shutdown BEFORE close: close() alone does not unblock a
            # thread parked in accept(), leaving the port in LISTEN
            # (same order the C++ RegistryServer::Stop uses)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_mu:
            conns, self._conns = self._conns, []
        for _, s in conns:
            try:
                # RST on close (SO_LINGER 0): clients see an immediate,
                # explicit connection reset — and no FIN_WAIT socket
                # blocks a same-port replica restart
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._accept_thread.join(timeout=5.0)
        for t, _ in conns:
            t.join(timeout=5.0)
        for b in self._batchers.values():
            b.close(drain=False)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        _obs.unregister_health(self._obs_name)
        self._g_connections.set(0)

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def struct_status(status: int, message: str) -> bytes:
    """Non-OK reply body: u32 status + reason string."""
    return struct.pack("<I", status) + wire.pack_str(message)
