"""InferenceServer: the online query endpoint over an exported bundle.

Serves three verbs over the framed-TCP conventions (wire.py):

  embed(ids)        [n, D] float32 embedding rows
  knn(ids, k)       per-query top-k neighbor ids + inner-product scores
                    (exact brute-force by default — byte-identical to
                    tools/knn.brute_force over the bundle — or the
                    bundle's IVFFlat index with exact=False)
  score(src, dst)   inner product per (src, dst) pair

Every verb funnels through a per-verb dynamic MicroBatcher: concurrent
requests coalesce into one apply (flush at max_batch rows or flush_ms,
whichever first), padded to a fixed bucket ladder so the jitted device
apply (embedding gather / pair scoring) never recompiles in steady
state. Past max_queue queued rows, admission control replies an
explicit SHED status instead of queueing — overload degrades loudly
and boundedly, never as silent latency growth. A request whose
deadline_ms expires while queued also gets SHED (the batch result is
discarded), so no admitted request hangs past its deadline.

Replicas register in the SAME registry the graph shards use
(``serve_<service>_<replica>__<host>_<port>``, heartbeat-refreshed),
so ServingClient discovers them exactly like trainers discover shards.
health() registers on the obs registry → /healthz, and every counter/
histogram is a labeled child on the shared default registry.

Unknown ids (not in the bundle) embed as zero rows and score 0 —
counted in serving_unknown_ids_total, never an error: a freshly-added
node simply has no embedding until the next export.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.serving import wire
from euler_tpu.serving.batcher import (
    MicroBatcher,
    ShedError,
    bucket_ladder,
    run_bucketed,
)
from euler_tpu.serving.export import ModelBundle

__all__ = ["InferenceServer"]

_DEFAULT_DEADLINE_S = 30.0


class InferenceServer:
    """One serving replica over one ModelBundle (see module docstring).

    bundle: a ModelBundle or a bundle directory path (loaded with
      checksum verification — a corrupt bundle refuses to serve).
    registry: optional registry spec ("tcp:host:port", "dir:/path", or
      a plain directory) to register in for discovery.
    service / replica: the discovery identity.
    max_batch / flush_ms / max_queue: MicroBatcher knobs (rows).
    inject_apply_latency_ms: adds a fixed sleep to every flushed apply —
      the honest way to model per-dispatch cost on CPU-bound test
      containers (chaos/bench use only).
    """

    def __init__(self, bundle, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[str] = None, service: str = "default",
                 replica: int = 0, max_batch: int = 256,
                 flush_ms: float = 2.0, max_queue: int = 0,
                 heartbeat_s: float = 1.0,
                 inject_apply_latency_ms: float = 0.0):
        if isinstance(bundle, str):
            bundle = ModelBundle.load(bundle, verify=True)
        self.bundle = bundle
        self.service = service
        self.replica = int(replica)
        self._inject_s = float(inject_apply_latency_ms) / 1000.0
        self._ids = bundle.ids                      # sorted uint64
        self._emb = bundle.embeddings               # [N, D] float32 host
        self._index = None                          # built lazily (IVF)
        self._index_mu = threading.Lock()

        import jax
        import jax.numpy as jnp

        table = jnp.asarray(self._emb) if self._emb.size else None
        self._jit_gather = jax.jit(
            (lambda rows: table[rows]) if table is not None
            else (lambda rows: jnp.zeros((rows.shape[0], 0), jnp.float32)))
        self._jit_score = jax.jit(
            (lambda a, b: jnp.sum(table[a] * table[b], axis=-1))
            if table is not None
            else (lambda a, b: jnp.zeros((a.shape[0],), jnp.float32)))
        self.ladder = bucket_ladder(max_batch)
        # warm every ladder bucket BEFORE accepting traffic: first-
        # request jit compiles would otherwise land inside a client's
        # per-attempt timeout, and steady state must never compile
        for b in self.ladder:
            rows = jnp.asarray(np.zeros(b, np.int32))
            self._jit_gather(rows)
            self._jit_score(rows, rows)

        # -- metrics / health ----------------------------------------------
        reg = _obs.default_registry()
        lab = {"service": service, "replica": str(self.replica)}
        self._ctr_requests = reg.counter(
            "serving_requests_total", "serving requests by verb",
            ("service", "replica", "verb"))
        self._hist_request_ms = reg.histogram(
            "serving_request_ms", "end-to-end in-server request latency",
            ("service", "replica", "verb"))
        self._ctr_deadline = reg.counter(
            "serving_deadline_shed_total",
            "admitted requests whose deadline expired in queue (SHED "
            "replied)", ("service", "replica")).labels(**lab)
        self._ctr_unknown = reg.counter(
            "serving_unknown_ids_total",
            "queried ids absent from the bundle (served as zeros)",
            ("service", "replica")).labels(**lab)
        self._ctr_errors = reg.counter(
            "serving_errors_total", "requests answered with ERROR status",
            ("service", "replica")).labels(**lab)
        self._g_connections = reg.gauge(
            "serving_connections", "live client connections",
            ("service", "replica")).labels(**lab)
        self._lab = lab

        name = f"{service}.{self.replica}"
        self._batchers = {
            "embed": MicroBatcher(self._run_embed, max_batch=max_batch,
                                  flush_ms=flush_ms, max_queue=max_queue,
                                  name=f"{name}.embed"),
            "knn": MicroBatcher(self._run_knn, max_batch=max_batch,
                                flush_ms=flush_ms, max_queue=max_queue,
                                name=f"{name}.knn"),
            "score": MicroBatcher(self._run_score, max_batch=max_batch,
                                  flush_ms=flush_ms, max_queue=max_queue,
                                  name=f"{name}.score"),
        }

        # -- listener ------------------------------------------------------
        self._stopping = threading.Event()
        self._conn_mu = threading.Lock()
        self._conns: List[Tuple[threading.Thread, socket.socket]] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # same-port restart (the chaos kill/restart cycle): a predecessor
        # replica's connections may still be draining — retry the bind
        # briefly instead of failing the restart
        bind_deadline = time.monotonic() + 5.0
        while True:
            try:
                self._listener.bind((host, port))
                break
            except OSError:
                if port == 0 or time.monotonic() >= bind_deadline:
                    raise
                time.sleep(0.1)
        self._listener.listen(64)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"serve-{name}", daemon=True)
        self._accept_thread.start()

        # -- discovery -----------------------------------------------------
        self.registry = registry
        self._entry = wire.serve_entry_name(service, self.replica,
                                            self.host, self.port)
        self._hb_thread = None
        if registry:
            wire.registry_put(registry, self._entry)
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(float(heartbeat_s),),
                name=f"serve-hb-{name}", daemon=True)
            self._hb_thread.start()
        self._obs_name = f"serving_{service}_{self.replica}_{self.port}"
        _obs.register_health(self._obs_name, self.health)

    # -- applies (run on the batcher workers) ------------------------------
    def _lookup_rows(self, qids: np.ndarray) -> Tuple[np.ndarray,
                                                      np.ndarray]:
        """(row indices int32, valid mask) for query ids against the
        bundle's sorted id order; unknown ids map to row 0, masked."""
        qids = np.ascontiguousarray(qids, dtype=np.uint64)
        if self._ids.size == 0:
            return (np.zeros(qids.size, np.int32),
                    np.zeros(qids.size, bool))
        rows = np.searchsorted(self._ids, qids).clip(0, self._ids.size - 1)
        valid = self._ids[rows] == qids
        n_unknown = int((~valid).sum())
        if n_unknown:
            self._ctr_unknown.inc(n_unknown)
        return rows.astype(np.int32), valid

    def _maybe_inject(self) -> None:
        if self._inject_s > 0:
            time.sleep(self._inject_s)

    def _run_embed(self, payloads: List[np.ndarray]) -> List[np.ndarray]:
        """One bucketed jitted gather over every request's ids."""
        import jax.numpy as jnp

        self._maybe_inject()
        flat = np.concatenate(payloads) if payloads else \
            np.zeros(0, np.uint64)
        rows, valid = self._lookup_rows(flat)
        if flat.size:
            out = run_bucketed(
                lambda r: np.asarray(self._jit_gather(jnp.asarray(r))),
                [rows], self.ladder)
            # copy=True: jax device buffers surface as read-only numpy
            out = np.array(out, dtype=np.float32)
            out[~valid] = 0.0
        else:
            out = np.zeros((0, self.bundle.dim), np.float32)
        results, at = [], 0
        for p in payloads:
            results.append(out[at:at + p.size])
            at += p.size
        return results

    def _run_knn(self, payloads: List[Tuple[np.ndarray, int, bool]]
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Batched top-k: ONE sims pass for the whole flush at the max
        requested k, sliced per request. The exact path is literally
        tools/knn.brute_force over the bundle arrays — byte-identical
        to offline retrieval by construction; exact=False routes
        through the bundle's IVFFlat index instead."""
        from euler_tpu.tools.knn import brute_force

        self._maybe_inject()
        results = []
        for exact in (True, False):
            group = [(i, p) for i, p in enumerate(payloads)
                     if bool(p[2]) == exact]
            if not group:
                continue
            flat = np.concatenate([p[0] for _, p in group])
            rows, valid = self._lookup_rows(flat)
            queries = self._emb[rows].copy()
            queries[~valid] = 0.0
            max_k = max(int(p[1]) for _, p in group)
            max_k = max(1, min(max_k, max(self._ids.size, 1)))
            if exact or self._ids.size == 0:
                nbr, sims = brute_force(self._emb, self._ids, queries,
                                        max_k)
            else:
                nbr, sims = self._get_index().search(queries, max_k)
            at = 0
            for i, (q, k, _) in group:
                k = max(1, min(int(k), max_k))
                results.append(
                    (i, (nbr[at:at + q.size, :k].astype(np.uint64),
                         sims[at:at + q.size, :k].astype(np.float32))))
                at += q.size
        results.sort(key=lambda t: t[0])
        return [r for _, r in results]

    def _run_score(self, payloads: List[Tuple[np.ndarray, np.ndarray]]
                   ) -> List[np.ndarray]:
        import jax.numpy as jnp

        self._maybe_inject()
        src = np.concatenate([p[0] for p in payloads]) if payloads \
            else np.zeros(0, np.uint64)
        dst = np.concatenate([p[1] for p in payloads]) if payloads \
            else np.zeros(0, np.uint64)
        a_rows, a_ok = self._lookup_rows(src)
        b_rows, b_ok = self._lookup_rows(dst)
        if src.size:
            out = run_bucketed(
                lambda a, b: np.asarray(
                    self._jit_score(jnp.asarray(a), jnp.asarray(b))),
                [a_rows, b_rows], self.ladder)
            # copy=True: jax device buffers surface as read-only numpy
            out = np.array(out, dtype=np.float32)
            out[~(a_ok & b_ok)] = 0.0
        else:
            out = np.zeros(0, np.float32)
        results, at = [], 0
        for p in payloads:
            results.append(out[at:at + p[0].size])
            at += p[0].size
        return results

    def _get_index(self):
        with self._index_mu:
            if self._index is None:
                self._index = self.bundle.build_index()
            return self._index

    def jit_cache_sizes(self) -> Dict[str, int]:
        """Compiled-variant counts of the jitted applies (steady-state
        no-recompile assertions): stays <= len(ladder) per fn."""
        return {"gather": int(self._jit_gather._cache_size()),
                "score": int(self._jit_score._cache_size())}

    # -- network -----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_mu:
                if self._stopping.is_set():
                    # raced stop(): it already swapped the conn list out,
                    # so nothing would ever shut this connection down
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                # reap finished connection threads (heartbeat-style
                # short-lived health probes would otherwise accumulate)
                self._conns = [(t, s) for t, s in self._conns
                               if t.is_alive()]
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                self._conns.append((t, conn))
            self._g_connections.set(len(self._conns))
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stopping.is_set():
                try:
                    msg_type, body = wire.read_frame(conn)
                except (wire.WireError, OSError):
                    return  # client went away / stop() shut us down
                try:
                    reply = self._dispatch(msg_type, body)
                except ShedError as e:
                    reply = struct_status(wire.STATUS_SHED, str(e))
                except Exception as e:  # semantic/internal: explicit ERROR
                    self._ctr_errors.inc()
                    reply = struct_status(
                        wire.STATUS_ERROR, f"{type(e).__name__}: {e}")
                try:
                    wire.write_frame(conn, msg_type, reply)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg_type: int, body: bytes) -> bytes:
        verb = {wire.MSG_EMBED: "embed", wire.MSG_KNN: "knn",
                wire.MSG_SCORE: "score", wire.MSG_HEALTH: "health",
                wire.MSG_INFO: "info"}.get(msg_type)
        if verb is None:
            raise ValueError(f"unknown serving msg_type {msg_type}")
        self._ctr_requests.labels(verb=verb, **self._lab).inc()
        t0 = time.monotonic()
        try:
            if msg_type == wire.MSG_HEALTH:
                return struct.pack("<I", wire.STATUS_OK) + \
                    wire.pack_str(json.dumps(self.health()))
            if msg_type == wire.MSG_INFO:
                info = {"service": self.service, "replica": self.replica,
                        "dim": self.bundle.dim, "count": self.bundle.count,
                        "model_spec": self.bundle.model_spec}
                return struct.pack("<I", wire.STATUS_OK) + \
                    wire.pack_str(json.dumps(info))
            r = wire.Reader(body)
            deadline_ms = r.u32()
            timeout = (deadline_ms / 1000.0) if deadline_ms \
                else _DEFAULT_DEADLINE_S
            if msg_type == wire.MSG_EMBED:
                n = r.u32()
                ids = r.array(np.uint64, n)
                fut = self._batchers["embed"].submit(ids, rows=n)
                emb = self._wait(fut, timeout)
                return (struct.pack("<III", wire.STATUS_OK, n,
                                    emb.shape[1] if emb.ndim == 2 else 0)
                        + np.ascontiguousarray(emb, np.float32).tobytes())
            if msg_type == wire.MSG_KNN:
                k = r.u32()
                exact = bool(r.u8())
                n = r.u32()
                ids = r.array(np.uint64, n)
                fut = self._batchers["knn"].submit((ids, k, exact), rows=n)
                nbr, sims = self._wait(fut, timeout)
                return (struct.pack("<III", wire.STATUS_OK, n,
                                    nbr.shape[1] if nbr.size else 0)
                        + np.ascontiguousarray(nbr, np.uint64).tobytes()
                        + np.ascontiguousarray(sims, np.float32).tobytes())
            # MSG_SCORE
            n = r.u32()
            src = r.array(np.uint64, n)
            dst = r.array(np.uint64, n)
            fut = self._batchers["score"].submit((src, dst), rows=n)
            scores = self._wait(fut, timeout)
            return (struct.pack("<II", wire.STATUS_OK, n)
                    + np.ascontiguousarray(scores, np.float32).tobytes())
        finally:
            self._hist_request_ms.labels(verb=verb, **self._lab).observe(
                (time.monotonic() - t0) * 1000.0)

    def _wait(self, fut, timeout: float):
        from concurrent.futures import TimeoutError as FutTimeout

        try:
            return fut.result(timeout=max(timeout, 0.001))
        except FutTimeout:
            # the flush may still land later; its result is discarded.
            # The client gets an EXPLICIT shed, never a hang.
            self._ctr_deadline.inc()
            raise ShedError("deadline expired while queued") from None

    # -- discovery heartbeat ----------------------------------------------
    def _heartbeat_loop(self, interval_s: float) -> None:
        while not self._stopping.wait(interval_s):
            try:
                wire.registry_put(self.registry, self._entry)
            except (OSError, wire.WireError):
                pass  # registry outage: entry goes stale, not fatal

    # -- introspection -----------------------------------------------------
    def health(self) -> Dict:
        """Counter surface (also served via obs /healthz): request /
        shed / unknown-id / error totals, per-verb queue depths, bundle
        identity."""
        shed = 0
        queues = {}
        for verb, b in self._batchers.items():
            queues[verb] = b.queue_depth
            shed += int(b._ctr_shed.value)
        reqs = {
            verb: int(self._ctr_requests.labels(
                verb=verb, **self._lab).value)
            for verb in ("embed", "knn", "score", "health", "info")}
        return {
            "service": self.service, "replica": self.replica,
            "port": self.port, "requests": reqs,
            "shed": shed + int(self._ctr_deadline.value),
            "deadline_shed": int(self._ctr_deadline.value),
            "unknown_ids": int(self._ctr_unknown.value),
            "errors": int(self._ctr_errors.value),
            "queue_rows": queues,
            "bundle": {"count": self.bundle.count, "dim": self.bundle.dim},
        }

    # -- lifecycle ---------------------------------------------------------
    def stop(self) -> None:
        """Shut the replica down: deregister, close the listener and
        every live connection (in-flight clients see a transport error
        — an explicit failure they fail over on, never a hang), drain
        the batchers."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        if self.registry:
            wire.registry_remove(self.registry, self._entry)
        try:
            # shutdown BEFORE close: close() alone does not unblock a
            # thread parked in accept(), leaving the port in LISTEN
            # (same order the C++ RegistryServer::Stop uses)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_mu:
            conns, self._conns = self._conns, []
        for _, s in conns:
            try:
                # RST on close (SO_LINGER 0): clients see an immediate,
                # explicit connection reset — and no FIN_WAIT socket
                # blocks a same-port replica restart
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._accept_thread.join(timeout=5.0)
        for t, _ in conns:
            t.join(timeout=5.0)
        for b in self._batchers.values():
            b.close(drain=False)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        _obs.unregister_health(self._obs_name)
        self._g_connections.set(0)

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def struct_status(status: int, message: str) -> bytes:
    """Non-OK reply body: u32 status + reason string."""
    return struct.pack("<I", status) + wire.pack_str(message)
