"""Graph-level readout pools.

Parity: tf_euler/python/graph_pool/ (base_pool, attention_pool,
set2set_pool). Inputs: node embeddings [N, D] + graph_index [N] mapping
each node to its graph; num_graphs static.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp

Array = jax.Array


class SumPool(nn.Module):
    @nn.compact
    def __call__(self, x: Array, graph_index: Array, num_graphs: int) -> Array:
        return mp.scatter_add(x, graph_index, num_graphs)


class MeanPool(nn.Module):
    @nn.compact
    def __call__(self, x: Array, graph_index: Array, num_graphs: int) -> Array:
        return mp.scatter_mean(x, graph_index, num_graphs)


class MaxPool(nn.Module):
    @nn.compact
    def __call__(self, x: Array, graph_index: Array, num_graphs: int) -> Array:
        return mp.scatter_max(x, graph_index, num_graphs)


class AttentionPool(nn.Module):
    """Gated attention readout (reference attention_pool.py):
    Σ softmax(gate(x)) · proj(x) per graph."""

    dim: int

    @nn.compact
    def __call__(self, x: Array, graph_index: Array, num_graphs: int) -> Array:
        gate = nn.Dense(1, name="gate")(x)[:, 0]
        att = mp.scatter_softmax(gate, graph_index, num_graphs)
        h = nn.Dense(self.dim, name="proj")(x)
        return mp.scatter_add(h * att[:, None], graph_index, num_graphs)


class Set2SetPool(nn.Module):
    """Set2Set readout (reference set2set_pool.py): LSTM-driven iterative
    attention; processing_steps static → lax-friendly python loop."""

    dim: int
    processing_steps: int = 3

    @nn.compact
    def __call__(self, x: Array, graph_index: Array, num_graphs: int) -> Array:
        cell = nn.OptimizedLSTMCell(features=self.dim)
        h = nn.Dense(self.dim, name="proj")(x)            # [N, dim]
        carry = cell.initialize_carry(jax.random.key(0), (num_graphs, 2 * self.dim))
        q_star = jnp.zeros((num_graphs, 2 * self.dim))
        for _ in range(self.processing_steps):
            carry, q = cell(carry, q_star)                # q: [G, dim]
            e = (h * q[graph_index]).sum(-1)              # [N]
            a = mp.scatter_softmax(e, graph_index, num_graphs)
            r = mp.scatter_add(h * a[:, None], graph_index, num_graphs)
            q_star = jnp.concatenate([q, r], axis=-1)
        return q_star
