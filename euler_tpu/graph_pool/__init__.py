from euler_tpu.graph_pool.base_pool import (  # noqa: F401
    AttentionPool,
    MaxPool,
    MeanPool,
    Set2SetPool,
    SumPool,
)
