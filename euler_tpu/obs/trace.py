"""Tracing: `span()` context managers → a bounded ring of finished
spans → chrome://tracing JSON.

The tracing half of euler_tpu.obs. A span is a named, attributed wall-
clock interval; nesting is tracked per-thread (a span opened while
another is active on the same thread records that span as its parent),
so the exported trace shows e.g. a `graph_rpc` span nested under the
train loop's `input_wait` phase without any plumbing between the two
layers.

Finished spans land in an in-memory ring (deque with maxlen — O(1)
append, old spans fall off; tracing a week-long run cannot OOM the
host). `chrome_trace()` / `export()` render the ring as the Trace Event
Format JSON that chrome://tracing and https://ui.perfetto.dev load
directly — complete "X" (duration) events with microsecond `ts`/`dur`.

Disabled-path cost: when the tracer (or the whole subsystem, see
euler_tpu.obs.disable()) is off, `span()` returns a shared no-op
singleton — one attribute check, no allocation (measured ~0.1µs/call;
PERF.md "observability overhead").
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import random
import tempfile
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Tracer", "Span", "NULL_SPAN"]


class _NullSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()
    span_id = 0
    parent_id = 0
    trace_id = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One wall-clock interval. Use as a context manager; `set(**attrs)`
    attaches attributes mid-flight (they export under chrome `args`).

    `trace_id` correlates spans ACROSS processes: a root span (no parent
    on its thread) draws a fresh process-unique 64-bit trace id at
    __enter__, children inherit their parent's. The graph client stamps
    (trace_id, span_id) into v2 request frames so a shard's server-side
    timing breakdown stitches under this span in a merged trace."""

    __slots__ = ("_tracer", "name", "attrs", "span_id", "parent_id",
                 "trace_id", "_t0", "ts_us", "dur_us", "tid")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = next(tracer._ids)
        self.parent_id = 0
        self.trace_id = 0
        self._t0 = 0.0
        self.ts_us = 0.0
        self.dur_us = 0.0
        self.tid = 0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        tr = self._tracer
        stack = tr._stack()
        if stack:
            self.parent_id = stack[-1].span_id
            self.trace_id = stack[-1].trace_id
        else:
            # a new root: fresh trace id (process-unique base + counter
            # so two processes' traces can never collide in a merge)
            self.trace_id = tr._trace_base + next(tr._trace_ids)
        stack.append(self)
        self.tid = threading.get_ident()
        self._t0 = time.perf_counter()
        self.ts_us = (self._t0 - tr._epoch) * 1e6
        return self

    def __exit__(self, *exc) -> bool:
        self.dur_us = (time.perf_counter() - self._t0) * 1e6
        tr = self._tracer
        stack = tr._stack()
        # pop self even if an inner span leaked (defensive: a span that
        # escaped its with-block must not reparent the rest of the run)
        while stack and stack.pop() is not self:
            pass
        tr._record(self)
        return False


class Tracer:
    """Span factory + bounded ring of finished spans."""

    def __init__(self, capacity: int = 65536):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._ids = itertools.count(1)
        # trace-id space: 64-bit random base (never 0) + counter — ids
        # stay unique across the processes a merged trace combines
        self._trace_base = (random.getrandbits(63) | (1 << 62)) & ~0xFFFFF
        self._trace_ids = itertools.count(1)
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()
        self.enabled = True

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _record(self, span: Span) -> None:
        with self._mu:
            self._ring.append(span)

    def span(self, name: str, **attrs):
        """A new span (or the shared no-op when tracing is off)."""
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, attrs)

    def current_span(self):
        """Innermost active span on THIS thread (None outside any)."""
        st = self._stack()
        return st[-1] if st else None

    # -- ring access -------------------------------------------------------
    def spans(self) -> List[Span]:
        with self._mu:
            return list(self._ring)

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)

    # -- export ------------------------------------------------------------
    def chrome_trace(self) -> Dict:
        """Trace Event Format dict: complete ("ph": "X") events with
        microsecond ts/dur, one chrome 'thread' per real thread, span/
        trace ids and parents under args. Loadable by chrome://tracing
        and Perfetto as-is; `otherData.epoch_unix` anchors ts=0 on the
        wall clock so tools/trace_dump.py --merge can align exports
        from different processes onto one timeline.

        Safe under concurrent recording: the ring is snapshotted under
        the tracer lock and each span's attrs dict is copied before
        iteration (a recording thread may still be attaching attributes
        to a span another thread is exporting — the harness dumps
        traces while load is draining)."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            args = {"span_id": s.span_id, "parent_id": s.parent_id,
                    "trace_id": s.trace_id}
            # dict(...) snapshots attrs: iterating the live dict races
            # a concurrent sp.set() ("dict changed size during
            # iteration"). The copy itself is safe — dict reads/writes
            # are GIL-atomic per op and copy retries internally.
            for k, v in dict(s.attrs).items():
                args[k] = v if isinstance(v, (int, float, bool, str)) \
                    or v is None else str(v)
            events.append({
                "name": s.name, "ph": "X", "cat": "obs",
                "ts": round(s.ts_us, 3), "dur": round(s.dur_us, 3),
                "pid": pid, "tid": s.tid, "args": args,
            })
        events.sort(key=lambda e: e["ts"])
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix": self._epoch_unix,
                "exporter": "euler_tpu.obs",
            },
        }

    def export(self, path: str) -> str:
        """Write chrome_trace() JSON to `path` (atomic rename). Returns
        the path; view with chrome://tracing, ui.perfetto.dev, or
        `python tools/trace_dump.py <path>`. Concurrency-safe: the temp
        file is unique per call (two threads exporting to the same path
        used to share one ".tmp" and could interleave writes into a
        corrupt file), and recording threads may keep appending spans
        throughout."""
        trace = self.chrome_trace()
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                                   suffix=".tmp", dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(trace, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path
