"""Thread-safe metrics: Counter / Gauge / Histogram on a Registry.

The metrics half of euler_tpu.obs (see the package docstring for the
full map). Deliberately dependency-free — stdlib only — so every layer
of the stack (ctypes graph client, input pipeline, train loop, bench)
can instrument itself without import-order or optional-dep concerns.

Model (a small subset of the Prometheus client data model):

  * a Registry owns named metrics; names are unique per registry and a
    second registration with the same name must agree on kind and label
    names (get-or-create — wiring code in N instances shares one metric
    and distinguishes itself by label values);
  * each metric has zero or more LABEL NAMES; `metric.labels(a="x")`
    returns (creating on first use) the child holding the actual value
    for that label combination. Label-less metrics act as their own
    child (`counter.inc()` just works);
  * Histogram uses FIXED bucket bounds chosen at creation — default
    log-scale (powers of two) millisecond bounds — with Prometheus
    `le`-inclusive semantics and cumulative exposition;
  * `snapshot()` renders the whole registry to a plain, JSON-safe dict
    (bench artifacts embed it verbatim); `render_prometheus()` renders
    the text exposition format `obs.serve()` publishes on /metrics.

Collectors: `add_collector(fn)` registers a zero-arg callable invoked
before every snapshot/exposition — the bridge for engine-side counters
that live outside Python (gql.Query.stats(), the UDF result cache).
A collector that returns False is dropped (its source is gone); a
collector that raises is dropped too, with the failure counted on the
registry's own `obs_collector_errors_total`.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["Registry", "Counter", "Gauge", "Histogram", "log2_buckets",
           "DEFAULT_MS_BUCKETS", "snapshot_delta", "bucket_quantile"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_INF = float("inf")


def log2_buckets(lo: float = 0.001, count: int = 24) -> Tuple[float, ...]:
    """Fixed log-scale bucket bounds: lo, 2*lo, 4*lo, ... (`count` of
    them). The default (lo=1µs expressed in ms, 24 buckets) spans 1µs
    to ~8.4s — wide enough for a counter bump and a black-holed RPC on
    the same axis."""
    return tuple(lo * (2.0 ** i) for i in range(count))


DEFAULT_MS_BUCKETS = log2_buckets()


def bucket_quantile(counts, bounds, q: float) -> Optional[float]:
    """Bucket-interpolated quantile over RAW (non-cumulative) per-bucket
    counts — the Prometheus histogram_quantile convention: find the
    bucket holding the q-th observation and interpolate linearly inside
    its [lower, upper] bounds; the first bucket interpolates from 0 and
    the +Inf bucket clamps to the last finite bound. None when empty.
    `counts` may have one more entry than `bounds` (the overflow
    bucket). Shared by _HistogramChild.quantile and the native
    server-trace bridge (euler_tpu.gql.server_trace_hist)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    n = sum(counts)
    if n == 0:
        return None
    target = q * n
    cum = 0.0
    lower = 0.0
    for le, c in zip(bounds, counts):
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            return lower + (le - lower) * min(max(frac, 0.0), 1.0)
        cum += c
        lower = le
    # target lands in the overflow bucket: clamp to the last finite edge
    return float(bounds[-1])


def _fmt(v: float) -> str:
    """Exposition number format: integral values render as integers so
    golden-text tests and human eyes don't churn on '3.0' vs '3'."""
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


class _CounterChild:
    """Monotonic float accumulator (one label combination)."""

    __slots__ = ("_mu", "_v")

    def __init__(self):
        self._mu = threading.Lock()
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._mu:
            self._v += n

    @property
    def value(self) -> float:
        with self._mu:
            return self._v


class _GaugeChild:
    """Settable value (one label combination)."""

    __slots__ = ("_mu", "_v")

    def __init__(self):
        self._mu = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._mu:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._mu:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        with self._mu:
            self._v -= n

    @property
    def value(self) -> float:
        with self._mu:
            return self._v


class _HistogramChild:
    """Fixed-bound histogram (one label combination). `le`-inclusive
    bucket assignment, cumulative counts at exposition time."""

    __slots__ = ("_mu", "bounds", "_counts", "_sum", "_n")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        self._mu = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._n = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # first bound >= v → v <= bound, the Prometheus `le` convention
        # (a value exactly ON a bucket edge lands in that bucket)
        i = bisect.bisect_left(self.bounds, v)
        with self._mu:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    @property
    def value(self) -> Dict:
        """{"count", "sum", "buckets": [[le, cumulative], ...]} with le
        "+Inf" on the last entry — plain data, JSON-safe."""
        with self._mu:
            counts = list(self._counts)
            s, n = self._sum, self._n
        out, cum = [], 0
        for le, c in zip(self.bounds, counts):
            cum += c
            out.append([le, cum])
        out.append(["+Inf", n])
        return {"count": n, "sum": s, "buckets": out}

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (the Prometheus
        histogram_quantile convention): find the bucket holding the
        q-th observation and interpolate linearly inside its [lower,
        upper] bounds, assuming observations are uniform within a
        bucket. The first bucket interpolates from 0; the +Inf bucket
        clamps to the last finite bound (an estimate cannot exceed what
        the buckets resolve). None when the histogram is empty.

        Exact for values ON bucket edges, within one bucket's width
        otherwise — good enough for adaptive hedge delays and p2c,
        which only need the tail's order of magnitude."""
        with self._mu:
            counts = list(self._counts)
        return bucket_quantile(counts, self.bounds, q)


class _Metric:
    """Shared label-family machinery. Subclasses set `kind` and
    `_child_cls`; label-less metrics proxy child methods directly."""

    kind = ""
    _child_cls = None

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._mu = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        return self._child_cls()

    def labels(self, **labels):
        """The child for this label-value combination (created on first
        use). Every label name declared at registration must be given."""
        if tuple(sorted(labels)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        key = tuple(str(labels[ln]) for ln in self.labelnames)
        with self._mu:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def remove(self, **labels) -> None:
        """Drop one child (label combination) from exposition. The child
        object itself stays valid for anyone still holding it — only the
        registry's view forgets it. For retiring a whole instance's
        series, see Registry.prune()."""
        key = tuple(str(labels[ln]) for ln in self.labelnames)
        with self._mu:
            self._children.pop(key, None)

    def _prune_label(self, labelname: str, value: str) -> None:
        if labelname not in self.labelnames:
            return
        i = self.labelnames.index(labelname)
        v = str(value)
        with self._mu:
            for key in [k for k in self._children if k[i] == v]:
                del self._children[key]

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} has labels {self.labelnames}; "
                "use .labels(...)")
        return self._children[()]

    @property
    def value(self):
        return self._default().value

    def _items(self):
        with self._mu:
            return list(self._children.items())

    def _snapshot_values(self) -> Dict[str, object]:
        return {
            ",".join(f"{ln}={lv}" for ln, lv in zip(self.labelnames, key)):
                child.value
            for key, child in self._items()
        }


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        bounds = tuple(sorted(float(b) for b in
                              (buckets if buckets is not None
                               else DEFAULT_MS_BUCKETS)))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile of the label-less child (see
        _HistogramChild.quantile); labeled histograms call
        .labels(...).quantile(q)."""
        return self._default().quantile(q)


def snapshot_delta(before: Dict, after: Dict) -> Dict:
    """Measured-region view of two snapshot() dicts: cumulative metrics
    (counters; histogram count/sum/buckets) report `after - before`,
    gauges report their `after` level (they are not accumulators).
    Children absent from `before` diff against zero — the bench uses
    this to attach the metrics of exactly the measured region next to
    the lifetime snapshot."""
    out = {}
    for name, m in after.items():
        kind = m["type"]
        b_vals = before.get(name, {}).get("values", {})
        vals = {}
        for key, av in m["values"].items():
            bv = b_vals.get(key)
            if kind == "gauge":
                vals[key] = av
            elif kind == "histogram":
                b_buckets = {tuple(x[:1]): x[1]
                             for x in (bv or {}).get("buckets", [])}
                vals[key] = {
                    "count": av["count"] - (bv or {}).get("count", 0),
                    "sum": av["sum"] - (bv or {}).get("sum", 0.0),
                    "buckets": [[le, cum - b_buckets.get((le,), 0)]
                                for le, cum in av["buckets"]],
                }
            else:
                vals[key] = av - (bv or 0)
        out[name] = {"type": kind, "help": m["help"], "values": vals}
    return out


class Registry:
    """Named-metric container + collector hooks. Thread-safe; cheap to
    construct (tests use throwaway instances, production code shares
    the process-global one from euler_tpu.obs.default_registry())."""

    def __init__(self):
        self._mu = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: list = []

    # -- registration ------------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help,
                                              labelnames=labelnames, **kw)
                return m
        if not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}")
        if tuple(labelnames) != m.labelnames:
            raise ValueError(
                f"metric {name!r} registered with labels {m.labelnames}, "
                f"re-requested with {tuple(labelnames)}")
        want = kw.get("buckets")
        if want is not None:
            # a silently-dropped bucket spec would park every observe in
            # the wrong bounds with no signal — conflict must raise like
            # the kind/label mismatches above
            want = tuple(sorted(float(b) for b in want))
            if want != m.buckets:
                raise ValueError(
                    f"histogram {name!r} registered with buckets "
                    f"{m.buckets}, re-requested with {want}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._mu:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._metrics.pop(name, None)

    def prune(self, labelname: str, value: str) -> None:
        """Drop every child across all metrics whose `labelname` label
        equals `value` — retires a dead instance's series (e.g.
        prune("estimator", "estimator7") in a sweep harness that builds
        thousands of estimators) so long-lived processes don't grow the
        scrape without bound. Deliberately NOT called automatically on
        close(): a closed engine's final counters staying visible until
        the operator retires them is the Prometheus convention."""
        with self._mu:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._prune_label(labelname, value)

    # -- collectors --------------------------------------------------------
    def add_collector(self, fn) -> None:
        """fn() runs before every snapshot/exposition. Return False to
        be dropped (source gone); raising drops the collector and bumps
        obs_collector_errors_total."""
        with self._mu:
            self._collectors.append(fn)

    def collect(self) -> None:
        with self._mu:
            collectors = list(self._collectors)
        dead = []
        for fn in collectors:
            try:
                if fn() is False:
                    dead.append(fn)
            except Exception:
                dead.append(fn)
                self.counter(
                    "obs_collector_errors_total",
                    "collectors dropped after raising during scrape").inc()
        if dead:
            with self._mu:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]

    # -- export ------------------------------------------------------------
    def snapshot(self, run_collectors: bool = True) -> Dict:
        """Plain-dict view of every metric:
        {name: {"type", "help", "values": {"label=value,...": v}}} where
        v is a number (counter/gauge) or the histogram dict. JSON-safe —
        bench artifacts embed it verbatim."""
        if run_collectors:
            self.collect()
        with self._mu:
            metrics = sorted(self._metrics.items())
        return {name: {"type": m.kind, "help": m.help,
                       "values": m._snapshot_values()}
                for name, m in metrics}

    def render_prometheus(self, run_collectors: bool = True) -> str:
        """Prometheus text exposition format (text/plain version 0.0.4),
        metrics sorted by name, children in insertion order."""
        if run_collectors:
            self.collect()
        with self._mu:
            metrics = sorted(self._metrics.items())
        lines = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for key, child in m._items():
                base = ",".join(
                    f'{ln}="{_escape_label(lv)}"'
                    for ln, lv in zip(m.labelnames, key))
                if m.kind == "histogram":
                    h = child.value
                    for le, cum in h["buckets"]:
                        le_s = "+Inf" if le == "+Inf" else _fmt(le)
                        sep = "," if base else ""
                        lines.append(
                            f'{name}_bucket{{{base}{sep}le="{le_s}"}} '
                            f'{cum}')
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{name}_sum{sfx} {_fmt(h['sum'])}")
                    lines.append(f"{name}_count{sfx} {h['count']}")
                else:
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{name}{sfx} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"
