"""euler_tpu.obs: unified metrics + tracing for every layer.

One dependency-free (stdlib-only) subsystem answering "where did this
step's milliseconds go?" across the whole stack — host sampling, RPC
wait, retry sleep, device dispatch — instead of the per-layer ad-hoc
surfaces (`RemoteGraphEngine.health()`, `BaseEstimator.health()`,
`Query.stats()`, hand-rolled time deltas) it unifies:

  metrics.py   Counter / Gauge / Histogram on a thread-safe Registry;
               labeled children, plain-dict snapshot(), Prometheus text
  trace.py     span("name", **attrs) context managers with per-thread
               parenting, a bounded ring of finished spans, and a
               chrome://tracing JSON exporter
  server.py    obs.serve(port): /metrics + /healthz on a stdlib
               http.server daemon thread

Module-level convenience API (the process-global default registry and
tracer — what the wired layers use)::

    from euler_tpu import obs

    obs.counter("my_events_total").inc()
    with obs.span("load", shard=3):
        ...
    obs.dump_trace("run.json")        # → chrome://tracing / Perfetto
    srv = obs.serve(port=9464)        # scrape http://127.0.0.1:9464/metrics

Wired out of the box: `graph/remote.py` (per-call spans; retry /
failover / degrade counters — `health()` is a view over these),
`estimator/base_estimator.py` (per-step `input_wait` / `device_step` /
`hook` phase spans + histograms), `parallel/train.py`, `gql.py`
(engine-side Query.stats() + UDF-cache gauges via collectors),
`graph/chaos.py` (`chaos_injected_total{kind=...}`), and `bench.py`
(`detail.obs` snapshot on every artifact; `--trace out.json`).

`obs.disable()` turns the span path into a shared no-op (~0.1µs/call);
counters stay live — they are the health() bookkeeping. See PERF.md
"observability overhead" for measured costs.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from euler_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_MS_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    log2_buckets,
    snapshot_delta,
)
from euler_tpu.obs.server import (  # noqa: F401
    ObsServer,
    health_snapshot,
    register_health,
    unregister_health,
)
from euler_tpu.obs.trace import NULL_SPAN, Span, Tracer  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Tracer", "Span",
    "ObsServer", "default_registry", "default_tracer", "counter", "gauge",
    "histogram", "span", "timed_span", "serve", "snapshot",
    "snapshot_delta", "render_prometheus", "dump_trace", "clear_trace",
    "enable", "disable", "enabled", "register_health",
    "unregister_health", "health_snapshot", "log2_buckets",
    "DEFAULT_MS_BUCKETS", "reset_for_tests",
]

_mu = threading.Lock()
_registry: Optional[Registry] = None
_tracer: Optional[Tracer] = None
_enabled = True


def default_registry() -> Registry:
    """The process-global registry every wired layer reports into."""
    global _registry
    with _mu:
        if _registry is None:
            _registry = Registry()
        return _registry


def default_tracer() -> Tracer:
    """The process-global tracer behind obs.span()."""
    global _tracer
    with _mu:
        if _tracer is None:
            _tracer = Tracer()
        return _tracer


# -- metrics shorthands (default registry) --------------------------------
def counter(name: str, help: str = "", labelnames=()) -> Counter:
    return default_registry().counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge:
    return default_registry().gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames=(),
              buckets=None) -> Histogram:
    return default_registry().histogram(name, help, labelnames, buckets)


def snapshot() -> dict:
    """Plain-dict (JSON-safe) view of the default registry."""
    return default_registry().snapshot()


def render_prometheus() -> str:
    return default_registry().render_prometheus()


# -- tracing shorthands (default tracer) ----------------------------------
def span(name: str, **attrs):
    """Context manager timing a named interval on the default tracer.
    A shared no-op when tracing is disabled (obs.disable())."""
    if not _enabled:
        return NULL_SPAN
    return default_tracer().span(name, **attrs)


class _TimedSpan:
    """Span + millisecond-histogram observation in one context manager
    (the wired layers' shared timing idiom: estimator phases, graph rpc
    calls). Class-based — never a @contextmanager — so exceptions,
    including StopIteration, propagate untouched; the histogram is
    observed on BOTH the success and the raise path. __enter__ returns
    the span so callers can sp.set(...) attributes mid-flight."""

    __slots__ = ("_sp", "_hist", "_t0")

    def __init__(self, sp, hist):
        self._sp = sp
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        self._sp.__enter__()
        return self._sp

    def __exit__(self, *exc):
        self._sp.__exit__(*exc)
        self._hist.observe((time.monotonic() - self._t0) * 1000.0)
        return False


def timed_span(name: str, hist, **attrs) -> _TimedSpan:
    """`with obs.timed_span("phase", hist_ms, **attrs) as sp:` — a span
    on the default tracer whose wall time also lands in `hist` (in ms),
    success or raise."""
    return _TimedSpan(span(name, **attrs), hist)


def dump_trace(path: str) -> str:
    """Export the default tracer's span ring as chrome://tracing JSON."""
    return default_tracer().export(path)


def clear_trace() -> None:
    """Drop all finished spans (start of a measured region)."""
    default_tracer().clear()


# -- global switch ---------------------------------------------------------
def enable() -> None:
    """(Re-)enable span recording (the default)."""
    global _enabled
    _enabled = True
    default_tracer().enabled = True


def disable() -> None:
    """Disable span recording: obs.span() returns a shared no-op (~0.1µs
    per call). Counters/gauges stay live — health() compat views and
    /metrics depend on them, and a bump is already ≲1µs."""
    global _enabled
    _enabled = False
    default_tracer().enabled = False


def enabled() -> bool:
    return _enabled


# -- serving ---------------------------------------------------------------
def serve(port: int = 0, registry: Optional[Registry] = None,
          addr: str = "127.0.0.1") -> ObsServer:
    """Start the /metrics + /healthz endpoint (daemon thread). port=0
    picks an ephemeral port — read srv.port; srv.close() shuts down
    cleanly (no leaked thread, port freed)."""
    return ObsServer(port=port, registry=registry, addr=addr)


def reset_for_tests() -> None:
    """Fresh default registry + tracer (hermetic tests only — production
    code must never drop live counters out from under health() views)."""
    global _registry, _tracer, _enabled
    with _mu:
        _registry = Registry()
        _tracer = Tracer()
        _enabled = True
