"""Exposition endpoint: /metrics (Prometheus text) + /healthz (JSON)
on a stdlib http.server thread.

The serving half of euler_tpu.obs. `serve(port)` starts a daemon
ThreadingHTTPServer bound to localhost; `/metrics` renders the
registry's Prometheus text format (collectors run per scrape, so
engine-side gql/UDF-cache gauges are fresh), `/healthz` merges every
registered health provider — the existing `RemoteGraphEngine.health()`
/ `BaseEstimator.health()` dicts — into one JSON document.

Health providers register with `register_health(name, fn)`. Bound
methods are held via weakref.WeakMethod so registering an object's
health() does not keep the object alive; dead providers silently drop
off the next scrape.
"""

from __future__ import annotations

import json
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

__all__ = ["ObsServer", "register_health", "unregister_health",
           "health_snapshot"]

_health_mu = threading.Lock()
_health_providers: Dict[str, object] = {}


def register_health(name: str, fn: Callable[[], dict]) -> None:
    """Register `fn` (→ dict) under `name` on /healthz. Bound methods
    are weakly referenced; re-registering a name replaces it."""
    try:
        ref = weakref.WeakMethod(fn)
    except TypeError:  # plain function / lambda: hold it directly
        ref = None
    with _health_mu:
        _health_providers[name] = ref if ref is not None else fn


def unregister_health(name: str) -> None:
    with _health_mu:
        _health_providers.pop(name, None)


def health_snapshot() -> Dict[str, dict]:
    """{provider: health dict} for every live provider; a provider that
    raises reports {"error": ...} instead of failing the scrape."""
    with _health_mu:
        items = list(_health_providers.items())
    out, dead = {}, []
    for name, ref in items:
        fn = ref() if isinstance(ref, weakref.WeakMethod) else ref
        if fn is None:
            dead.append(name)
            continue
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    if dead:
        with _health_mu:
            for name in dead:
                if isinstance(_health_providers.get(name),
                              weakref.WeakMethod) \
                        and _health_providers[name]() is None:
                    _health_providers.pop(name, None)
    return out


class ObsServer:
    """The /metrics + /healthz endpoint. port=0 picks an ephemeral port
    (read it back from .port); close() shuts the thread down and frees
    the port — no leak, no port-in-use flake on restart."""

    def __init__(self, port: int = 0, registry=None,
                 addr: str = "127.0.0.1"):
        if registry is None:
            from euler_tpu.obs import default_registry

            registry = default_registry()
        self.registry = registry

        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = srv.registry.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body = json.dumps(
                        {"status": "ok",
                         "providers": health_snapshot()}).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404, "try /metrics or /healthz")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name=f"obs-serve-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.addr}:{self.port}"

    def close(self) -> None:
        """Stop serving and release the port; joins the serve thread so
        a test can assert nothing leaked."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "ObsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
