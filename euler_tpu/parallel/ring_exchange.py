"""Ring all-to-all embedding exchange over ICI.

The GSPMD path in sharded_embedding lets XLA choose the collective for a
row-sharded table lookup (typically all-gather of hit rows). For very
large tables the all-gather of a big lookup batch can spike ICI + HBM;
the classic alternative is a ring exchange (the pattern ring attention
uses for KV blocks, applied here to embedding rows — SURVEY.md §5's
"optional ICI all-to-all embedding exchange"):

  each device holds rows [d·R/K, (d+1)·R/K) of the table and a shard of
  the lookup ids. In K steps, the id shard ppermutes around the ring;
  every device answers the ids that fall in its row range, accumulating
  into a result buffer that travels with the ids. Peak ICI traffic per
  step is 1/K of the all-gather, and each step's sends overlap the next
  lookup's compute.

ring_lookup runs under shard_map over a 1-d mesh axis; a pure-jnp
reference (same math, no collectives) backs the single-device path and
the tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def _local_answer(table_shard: Array, ids: Array, shard_lo: Array) -> Array:
    """Rows for ids that fall inside this shard's range, zeros elsewhere."""
    local = ids - shard_lo
    in_range = (local >= 0) & (local < table_shard.shape[0])
    rows = jnp.take(table_shard, jnp.clip(local, 0, table_shard.shape[0] - 1),
                    axis=0)
    return jnp.where(in_range[:, None], rows, 0.0)


def ring_lookup(table: Array, ids: Array, mesh: Mesh,
                axis: str = "model") -> Array:
    """Distributed embedding lookup via a K-step ppermute ring.

    table: [R, D] row-sharded over `axis`; ids: [B] int32 in [0, R),
    sharded over `axis` too (each device starts with B/K ids). Returns
    [B, D] with the same sharding as ids.
    """
    k = mesh.shape[axis]
    rows_per = table.shape[0] // k

    def body(table_shard, ids_shard):
        me = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % k) for i in range(k)]

        def step(carry, _):
            cur_ids, acc = carry
            # answer the visiting ids that fall in this device's rows
            shard_lo = (me * rows_per).astype(cur_ids.dtype)
            acc = acc + _local_answer(table_shard, cur_ids, shard_lo)
            # pass ids + partial results to the next device in the ring
            cur_ids = jax.lax.ppermute(cur_ids, axis, perm)
            acc = jax.lax.ppermute(acc, axis, perm)
            return (cur_ids, acc), None

        acc0 = jnp.zeros((ids_shard.shape[0], table_shard.shape[1]),
                         table_shard.dtype)
        # the new shard_map tracks per-axis varyingness: the carry must
        # enter the scan already device-varying because ppermute makes it
        # so on the way out (pcast on jax >= 0.9, pvary before)
        if hasattr(jax.lax, "pcast"):
            acc0 = jax.lax.pcast(acc0, axis, to="varying")
        elif hasattr(jax.lax, "pvary"):
            acc0 = jax.lax.pvary(acc0, axis)
        (_, acc), _ = jax.lax.scan(step, (ids_shard, acc0), None, length=k)
        # after k hops every id shard (and its answers) is home again
        return acc

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(axis, None),
    )
    return fn(table, ids)


def reference_lookup(table: Array, ids: Array) -> Array:
    """Single-device equivalent: plain take (the numbers ring_lookup must
    reproduce)."""
    return jnp.take(table, ids, axis=0)
