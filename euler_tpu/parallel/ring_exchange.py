"""Ring all-to-all embedding exchange over ICI.

The GSPMD path in sharded_embedding lets XLA choose the collective for a
row-sharded table lookup (typically all-gather of hit rows). For very
large tables the all-gather of a big lookup batch can spike ICI + HBM;
the classic alternative is a ring exchange (the pattern ring attention
uses for KV blocks, applied here to embedding rows — SURVEY.md §5's
"optional ICI all-to-all embedding exchange"):

  each device holds rows [d·R/K, (d+1)·R/K) of the table and a shard of
  the lookup ids. In K steps, the id shard ppermutes around the ring;
  every device answers the ids that fall in its row range, accumulating
  into a result buffer that travels with the ids. Peak ICI traffic per
  step is 1/K of the all-gather, and each step's sends overlap the next
  lookup's compute.

ring_lookup runs under shard_map over a 1-d mesh axis; a pure-jnp
reference (same math, no collectives) backs the single-device path and
the tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def _local_answer(table_shard: Array, ids: Array, shard_lo: Array) -> Array:
    """Rows for ids that fall inside this shard's range, zeros elsewhere.
    The masked fill is a typed zero (not the float literal 0.0): the
    partitioned store runs int8-quantized tables through this path, and
    a weakly-typed float zero would silently promote the whole answer to
    f32 — breaking the byte-identity gate."""
    local = ids - shard_lo
    in_range = (local >= 0) & (local < table_shard.shape[0])
    rows = jnp.take(table_shard, jnp.clip(local, 0, table_shard.shape[0] - 1),
                    axis=0)
    return jnp.where(in_range[:, None], rows, jnp.zeros((), rows.dtype))


def ring_lookup(table: Array, ids: Array, mesh: Mesh,
                axis: str = "model") -> Array:
    """Distributed embedding lookup via a K-step ppermute ring.

    table: [R, D] row-sharded over `axis`; ids: [B] int32 in [0, R),
    sharded over `axis` too (each device starts with B/K ids). Returns
    [B, D] with the same sharding as ids.
    """
    k = mesh.shape[axis]
    rows_per = table.shape[0] // k

    def body(table_shard, ids_shard):
        me = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % k) for i in range(k)]

        def step(carry, _):
            cur_ids, acc = carry
            # answer the visiting ids that fall in this device's rows
            shard_lo = (me * rows_per).astype(cur_ids.dtype)
            acc = acc + _local_answer(table_shard, cur_ids, shard_lo)
            # pass ids + partial results to the next device in the ring
            cur_ids = jax.lax.ppermute(cur_ids, axis, perm)
            acc = jax.lax.ppermute(acc, axis, perm)
            return (cur_ids, acc), None

        acc0 = jnp.zeros((ids_shard.shape[0], table_shard.shape[1]),
                         table_shard.dtype)
        # the new shard_map tracks per-axis varyingness: the carry must
        # enter the scan already device-varying because ppermute makes it
        # so on the way out (pcast on jax >= 0.9, pvary before)
        if hasattr(jax.lax, "pcast"):
            acc0 = jax.lax.pcast(acc0, axis, to="varying")
        elif hasattr(jax.lax, "pvary"):
            acc0 = jax.lax.pvary(acc0, axis)
        (_, acc), _ = jax.lax.scan(step, (ids_shard, acc0), None, length=k)
        # after k hops every id shard (and its answers) is home again
        return acc

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(axis, None),
    )
    return fn(table, ids)


def allgather_lookup(table: Array, ids: Array, mesh: Mesh,
                     axis: str = "model") -> Array:
    """The one-collective alternative to ring_lookup: all-gather the id
    shards over `axis`, answer the ids that fall in this device's rows,
    then reduce-scatter the summed answers back so each device keeps its
    own B/K slice. Same calling convention and the same bytes-exact
    output as ring_lookup (every id has exactly one owning shard, so the
    sum has one nonzero contributor per row — exact for float AND int8).

    Tradeoff vs the ring (the cost model in pick_lookup_strategy):
    2 collective launches instead of 2K ppermutes — wins when the batch
    is small/latency-bound — but it materializes the full [B, D] answer
    buffer on every chip before the scatter, so peak per-chip memory and
    ICI burst scale with B·D·K where the ring stays at B·D/K per step.
    """
    k = mesh.shape[axis]
    rows_per = table.shape[0] // k

    def body(table_shard, ids_shard):
        me = jax.lax.axis_index(axis)
        all_ids = jax.lax.all_gather(ids_shard, axis).reshape(-1)   # [B]
        shard_lo = (me * rows_per).astype(all_ids.dtype)
        ans = _local_answer(table_shard, all_ids, shard_lo)         # [B, D]
        # one owner per id → the scatter-sum reassembles exact rows
        return jax.lax.psum_scatter(ans, axis, scatter_dimension=0,
                                    tiled=True)

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(axis, None),
    )
    return fn(table, ids)


# Per-chip byte budget below which the all-gather variant's full [B, D]
# answer buffer (replicated K ways before the scatter) is considered
# cheap: under it the 2-launch all-gather wins on dispatch latency, over
# it the ring's 1/K peak footprint wins. Tuned for ~v4/v5e VMEM-adjacent
# staging; override per call site when measured on-chip.
ALLGATHER_MAX_BYTES = 64 << 20


def pick_lookup_strategy(n_ids: int, k: int, dim: int,
                         elem_bytes: int = 4,
                         allgather_max_bytes: int = ALLGATHER_MAX_BYTES
                         ) -> str:
    """Per-step lookup-strategy pick on batch ids shipped × K.

    n_ids is the id count that actually enters the exchange — the full
    batch today (neither variant deduplicates; pass the deduplicated
    count iff a dedup stage runs upstream). Both variants move the same
    total row bytes over ICI; what differs is launch count (all-gather:
    2 collectives; ring: 2K ppermutes) vs peak footprint (all-gather
    stages the full n_ids·D·elem answer on EVERY chip — a K-way
    replicated burst — where the ring holds 1/K of that per step). So:
    small batches on big meshes are launch-bound → 'allgather'; once
    n_ids·K·D·elem crosses the budget the burst dominates → 'ring'.
    K <= 1 means the table isn't partitioned at all → 'local' (plain
    take, no collective)."""
    if k <= 1:
        return "local"
    if n_ids * k * dim * elem_bytes <= allgather_max_bytes:
        return "allgather"
    return "ring"


def reference_lookup(table: Array, ids: Array) -> Array:
    """Single-device equivalent: plain take (the numbers ring_lookup and
    allgather_lookup must reproduce byte-for-byte)."""
    return jnp.take(table, ids, axis=0)
