"""Device-resident neighbor sampling: the TPU-first answer to the host
sampling bottleneck.

The reference's whole input design exists to amortize CPU-side neighbor
sampling (one-RPC chained fanout, tf_euler/kernels/sample_fanout_op.cc:
36-48). On TPU that leaves the chip idle: measured on v5e-1, the jitted
GraphSAGE train step sustains 11-24 steps/s while a 2-core host produces
at most ~3 fanout batches/s — the accelerator waits on the feeder 4-10×
over. When the graph fits in HBM the right design is to move sampling
itself onto the device:

  - neighbor rows [N, C] (int32, capped at C per node) and inclusive
    cumulative weights [N, C] (float32) live in HBM — the
    CompactWeightedCollection layout (reference
    euler/common/compact_weighted_collection.h:55) transposed into two
    dense tables an XLA gather can hit;
  - per hop, sampling is: uniform draw → per-row inverse-CDF over C
    cumulative weights (C compares on the VPU) → gather neighbor rows.
    Pure XLA inside the jitted train step; composes with lax.scan
    (steps_per_loop) and pjit;
  - the host ships ONLY root rows (~131KB for batch 32768) — everything
    else (sampling, feature gather, labels) reads HBM-resident tables.

Memory: 8 bytes × N × C (e.g. 200k nodes × C=32 → 51MB) next to the
DeviceFeatureStore feature table.

Fidelity: nodes with degree ≤ C sample exactly the host engine's
weighted-with-replacement distribution. Nodes with degree > C sample
from a C-subset drawn once at build time (weighted, without
replacement) — the standard neighbor-cap approximation (GraphSAGE §3.1
uses fixed-size uniform subsets the same way). Pass cap >= max degree
for exact parity.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class DeviceNeighborTable:
    """Builds the HBM neighbor/cum-weight tables from a graph engine.

    Row order matches `graph.all_node_ids()` (the DeviceFeatureStore
    convention) so the same int32 rows index features, labels, and
    adjacency. Row N (= pad_row) is an all-pad row: sampling from it
    yields pad_row again, mirroring the host sampler's default_id pads.

    alias=True additionally builds the per-row Vose alias table
    (build_alias_tables): one packed int32 word per slot, enabling the
    O(1) alias draw in sample_hop(alias_table=...) — the device
    transpose of the reference's euler/common/alias_method.h. Replicated
    split tables only (raises with fused/shard_rows): the alias draw
    derives per-row degree from the words themselves and pad from the
    table shape, neither of which survives the fused bitcast layout or
    the row-sharded shape padding.
    """

    def __init__(self, graph, cap: int = 32, edge_types=None,
                 seed: int = 0,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 keep_host: bool = False, shard_rows: bool = False,
                 fused: bool = False, alias: bool = False):
        self.shard_rows = bool(shard_rows)
        self.fused = bool(fused)
        self.alias = bool(alias)
        _check_alias_layout(self.alias, self.fused, self.shard_rows)
        # retained for patch_rows: a delta patch must re-derive dirty
        # rows under the SAME edge-type filter and draw keys
        self._edge_types = edge_types
        self._seed = int(seed)
        self._mesh = mesh
        ids = graph.all_node_ids()
        n = len(ids)
        self.cap = int(cap)
        self.pad_row = n
        offs, nbrs, ws, _ = graph.get_full_neighbor(ids, edge_types)
        offs = offs.astype(np.int64)
        deg = np.diff(offs)
        nbr_rows = graph.node_rows(nbrs, missing=n).astype(np.int32)
        del nbrs
        ws = ws.astype(np.float32)
        nbr_tab, cum, alias_tab = self._build_tables(
            n, deg, nbr_rows, ws, seed)
        # host copies are opt-in (cache writers like bench): pinning them
        # by default would double host RAM for every training caller
        self.host_tables = (nbr_tab, cum) if keep_host else None
        self._place(nbr_tab, cum, mesh, alias_tab)

    @classmethod
    def from_arrays(cls, nbr_tab: np.ndarray, cum_tab: np.ndarray,
                    stats: Optional[dict] = None,
                    mesh: Optional[jax.sharding.Mesh] = None,
                    shard_rows: bool = False, fused: bool = False,
                    alias: bool = False):
        """Rehydrate from prebuilt [N+1, C] tables (e.g. a bench/dataset
        cache) without a live graph engine. alias=True rebuilds the
        alias table from the cum rows (chunked — caches carry only
        nbr/cum)."""
        self = cls.__new__(cls)
        self.shard_rows = bool(shard_rows)
        self.fused = bool(fused)
        self.alias = bool(alias)
        _check_alias_layout(self.alias, self.fused, self.shard_rows)
        # rehydrated tables carry no build provenance: patch_rows against
        # a live graph assumes the cache was built with seed 0 and no
        # edge-type filter (the bench/dataset cache convention)
        self._edge_types = None
        self._seed = 0
        self._mesh = mesh
        self.cap = int(nbr_tab.shape[1])
        self.pad_row = int(nbr_tab.shape[0]) - 1
        for k in ("hub_frac", "edge_keep_frac", "max_degree"):
            setattr(self, k, (stats or {}).get(k))
        # caches written before the round-5 uniform lever carry no
        # uniform_rows stat — recompute from the tables (the slot
        # weights are exactly recoverable from the inclusive cumsum).
        # Chunked: a full-table astype + diff would hold two ~3.5GB
        # transients at products scale (advisor r5)
        u = (stats or {}).get("uniform_rows")
        if u is None:
            u = True
            pad = self.pad_row
            for lo in range(0, cum_tab.shape[0], _CHUNK_ROWS):
                cc = np.asarray(cum_tab[lo:lo + _CHUNK_ROWS]) \
                    .astype(np.float32, copy=False)
                w = np.diff(cc, axis=1,
                            prepend=np.zeros((cc.shape[0], 1),
                                             np.float32))
                if not _detect_uniform_rows(
                        np.asarray(nbr_tab[lo:lo + _CHUNK_ROWS]), w,
                        pad=pad):
                    u = False
                    break
        self.uniform_rows = bool(u)
        self.host_tables = None
        alias_tab = build_alias_tables(
            np.asarray(nbr_tab), cum_tab=np.asarray(cum_tab)) \
            if self.alias else None
        self._place(np.ascontiguousarray(nbr_tab),
                    np.ascontiguousarray(cum_tab), mesh, alias_tab)
        return self

    def _build_tables(self, n, deg, nbr_rows, ws, seed):
        C = self.cap
        nbr_tab = np.full((n + 1, C), n, dtype=np.int32)
        w_tab = np.zeros((n + 1, C), dtype=np.float32)
        _fill_table_rows(C, n, np.arange(n, dtype=np.int64), deg,
                         nbr_rows, ws, seed,
                         out_nbr=nbr_tab[:n], out_w=w_tab[:n])

        # truncation telemetry (bench reports these: VERDICT r2 weak #2)
        hubs = deg > C
        self.hub_frac = float(hubs.mean()) if n else 0.0
        kept = np.minimum(deg, C).sum()
        self.edge_keep_frac = float(kept / max(len(nbr_rows), 1))
        self.max_degree = int(deg.max()) if n else 0
        self.uniform_rows = _detect_uniform_rows(nbr_tab, w_tab)

        # alias table built from the exact slot weights BEFORE they are
        # folded into the cumsum (no f32 diff round-trip on this path)
        alias_tab = build_alias_tables(nbr_tab, w_tab=w_tab) \
            if getattr(self, "alias", False) else None
        cum = np.cumsum(w_tab, axis=1, dtype=np.float32)
        return nbr_tab, cum, alias_tab

    def _place(self, nbr_tab, cum, mesh, alias_tab=None):
        from euler_tpu.parallel.placement import (
            put_replicated, put_row_sharded,
        )

        if getattr(self, "fused", False):
            # one [N+1, 2C] i32 table (ids + bitcast cum): one row gather
            # per hop in sample_hop_fused. Split views are not uploaded —
            # fused mode exists to cut HBM gathers, not to double memory.
            # Composes with shard_rows: the fused rows split over 'model'
            # exactly like the split tables (the masked-take+psum gather
            # is dtype-exact for the bitcast f32 lanes — the one owning
            # shard contributes the bits, all others contribute i32
            # zeros), so the HBM-capacity lever and the gather-count
            # lever stack.
            fused_tab = fuse_tables_host(nbr_tab, cum)
            if self.shard_rows:
                self.fused_table = put_row_sharded(fused_tab, mesh)
            else:
                self.fused_table = put_replicated(fused_tab, mesh)
            self.neighbors = None
            self.cum_weights = None
        elif self.shard_rows:
            self.neighbors = put_row_sharded(nbr_tab, mesh)
            self.cum_weights = put_row_sharded(cum, mesh)
        else:
            self.neighbors = put_replicated(nbr_tab, mesh)
            self.cum_weights = put_replicated(cum, mesh)
        self.alias_table = put_replicated(alias_tab, mesh) \
            if alias_tab is not None else None

    @property
    def tables(self):
        """Arrays to merge into the estimator's static_batch."""
        if getattr(self, "fused", False):
            return {"nbrcum_table": self.fused_table}
        out = {"nbr_table": self.neighbors, "cum_table": self.cum_weights}
        if getattr(self, "alias_table", None) is not None:
            out["alias_table"] = self.alias_table
        return out

    def patch_rows(self, graph, dirty_ids) -> dict:
        """O(dirty) table maintenance after graph.apply_delta(...):
        re-derive ONLY the dirty rows (one neighbor query over the dirty
        ids, one _fill_table_rows block, one per-row Vose rebuild for
        the alias words) instead of rebuilding all N rows — the chunked
        per-row-chunk build machinery applied to exactly one chunk. New
        nodes (engine rows past the old pad) grow the tables; old pad
        sentinels are remapped to the new pad id in one vectorized pass
        (a memory pass, not a rebuild — 0 rows re-derived by it).

        The patched table is byte-identical to a from-scratch build on
        the final edge set: row content is row-local by construction
        (see _fill_table_rows), untouched rows are bit-copied, and the
        engine's append-only row identity keeps neighbor row ids valid.

        Replicated split tables only (fused/shard_rows layouts raise —
        same constraint family as alias=True). O(dirty) end to end on
        the common path: when the delta adds no nodes (no table growth)
        and the tables are not mesh-placed, the device copies are
        updated with an `.at[rows].set` row scatter — no O(N) host
        round-trip, no full re-upload. Growth (shape change) or a mesh
        placement falls back to the full re-place; stats["upload"] says
        which path ran ("row_scatter" / "replace" / "none"). Counted on
        the obs registry: alias_rows_patched_total (vs
        alias_rows_rebuilt_total for full builds). Returns
        {rows_patched, rows_total, grown_rows, rebuild_frac, upload}."""
        if self.fused or self.shard_rows:
            raise ValueError(
                "patch_rows supports replicated split tables only — the "
                "fused bitcast layout and row-sharded shape padding "
                "would both need a full re-place anyway; rebuild those "
                "tables instead")
        dirty_ids = np.asarray(dirty_ids, dtype=np.uint64).ravel()
        old_pad = self.pad_row
        n_new = int(graph.node_count)
        if n_new < old_pad:
            raise ValueError(
                f"graph shrank ({n_new} nodes < table's {old_pad}) — "
                "deltas are append-only; rebuild the table")
        C = self.cap
        grown = n_new - old_pad
        has_alias = getattr(self, "alias_table", None) is not None
        # device-side row scatter: no growth (table shapes unchanged)
        # and no mesh placement (a scatter on a mesh-sharded array would
        # reshard under jit defaults) — the dirty rows go straight onto
        # the device arrays with .at[rows].set, no O(N) host pull and no
        # full re-upload. Growth or a mesh falls back to re-place.
        scatter = grown == 0 and self._mesh is None
        nbr = cum = alias_tab = None
        if not scatter:
            if self.host_tables is not None:
                nbr = np.array(self.host_tables[0], copy=True)
                cum = np.array(self.host_tables[1], copy=True)
            else:
                nbr = np.asarray(self.neighbors).copy()
                cum = np.asarray(self.cum_weights).copy()
            alias_tab = (np.asarray(self.alias_table).copy()
                         if has_alias else None)
        if grown:
            g_nbr = np.full((n_new + 1, C), n_new, dtype=np.int32)
            g_cum = np.zeros((n_new + 1, C), dtype=np.float32)
            # old pad sentinels point at the MOVED pad row: remap in one
            # compare+where pass (alias words are column-relative and
            # need none)
            old_rows = nbr[:old_pad]
            g_nbr[:old_pad] = np.where(old_rows == old_pad, n_new,
                                       old_rows)
            g_cum[:old_pad] = cum[:old_pad]
            nbr, cum = g_nbr, g_cum
            if alias_tab is not None:
                g_alias = np.full((n_new + 1, C), ALIAS_SENTINEL,
                                  dtype=np.int32)
                g_alias[:old_pad] = alias_tab[:old_pad]
                alias_tab = g_alias
        # dirty ids → engine rows, resolved ONCE; ids this shard/graph
        # does not know (foreign dsts in a broadcast delta) resolve to
        # the pad row and drop out
        all_rows = graph.node_rows(dirty_ids, missing=n_new) \
            .astype(np.int64)
        ok = all_rows < n_new
        order = np.argsort(all_rows[ok], kind="stable")
        sorted_rows = all_rows[ok][order]
        keep_first = np.ones(sorted_rows.size, bool)
        keep_first[1:] = sorted_rows[1:] != sorted_rows[:-1]
        rows = sorted_rows[keep_first]      # unique, ascending
        stats = {"rows_patched": int(rows.size), "rows_total": n_new,
                 "grown_rows": int(grown),
                 "rebuild_frac": float(rows.size / max(n_new, 1)),
                 "upload": ("none" if scatter and rows.size == 0
                            else "row_scatter" if scatter else "replace")}
        if rows.size:
            # the dirty ids in ROW order (dedup'd) so the CSR block from
            # get_full_neighbor lines up 1:1 with `rows`
            ids = dirty_ids[ok][order][keep_first]
            offs, nbrs, ws, _ = graph.get_full_neighbor(
                ids, self._edge_types)
            offs = offs.astype(np.int64)
            deg = np.diff(offs)
            nbr_rows = graph.node_rows(nbrs, missing=n_new).astype(np.int32)
            blk_nbr, blk_w = _fill_table_rows(
                C, n_new, rows, deg, nbr_rows, ws.astype(np.float32),
                self._seed)
            blk_cum = np.cumsum(blk_w, axis=1, dtype=np.float32)
            blk_alias = (_alias_rows_block(blk_nbr, blk_w, n_new)
                         if has_alias else None)
            if scatter:
                # host copies (when kept) mutate in place — the device
                # arrays own their own memory, so this cannot alias
                if self.host_tables is not None:
                    self.host_tables[0][rows] = blk_nbr
                    self.host_tables[1][rows] = blk_cum
                self.neighbors = self.neighbors.at[rows].set(blk_nbr)
                self.cum_weights = self.cum_weights.at[rows].set(blk_cum)
                if has_alias:
                    self.alias_table = \
                        self.alias_table.at[rows].set(blk_alias)
            else:
                nbr[rows] = blk_nbr
                cum[rows] = blk_cum
                if alias_tab is not None:
                    alias_tab[rows] = blk_alias
            # stats stay correct conservatively: uniform_rows may only
            # turn False (correctness-neutral — False just keeps the
            # general inverse-CDF path); hub telemetry tracks the max
            self.uniform_rows = bool(
                getattr(self, "uniform_rows", False)
                and _detect_uniform_rows(blk_nbr, blk_w, pad=n_new))
            if deg.size:
                self.max_degree = max(
                    int(getattr(self, "max_degree", 0) or 0),
                    int(deg.max()))
        self.pad_row = n_new
        if not scatter:
            if self.host_tables is not None:
                self.host_tables = (nbr, cum)
            self._place(nbr, cum, self._mesh, alias_tab)
        _alias_patch_counter("patched").inc(stats["rows_patched"])
        return stats


def _edge_uniforms(seed: int, rows: np.ndarray,
                   pos: np.ndarray) -> np.ndarray:
    """Stateless per-edge uniforms in [0, 1): a splitmix64 finalizer
    over (seed, global row, position-within-row). Replacing the shared
    rng stream makes every table row's hub draw a pure function of
    (seed, row, its edge list) — the property that lets patch_rows
    rebuild ONLY dirty rows and still match a from-scratch build on the
    final edge set byte-for-byte (a sequential stream would shift every
    row's draws whenever any earlier row's degree changed)."""
    with np.errstate(over="ignore"):
        x = (rows.astype(np.uint64) << np.uint64(32)) \
            ^ pos.astype(np.uint64)
        x ^= np.uint64(seed & 0xFFFFFFFFFFFFFFFF) * \
            np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return (x >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)


def _fill_table_rows(C: int, pad: int, global_rows: np.ndarray,
                     deg: np.ndarray, nbr_rows: np.ndarray,
                     ws: np.ndarray, seed: int,
                     out_nbr: np.ndarray = None,
                     out_w: np.ndarray = None):
    """[k, C] (nbr, weight) table rows for the k selected nodes, from
    their concatenated CSR neighbor lists. Shared by the full build
    (global_rows = arange(n)) and patch_rows (global_rows = the dirty
    rows): every row's content depends only on (seed, its global row
    id, its own edge list) — row-local by construction, so a patched
    row is byte-identical to the same row in a from-scratch build.

    Rows with degree <= C front-pack their edges; hubs draw a weighted
    C-subset without replacement (vectorized Efraimidis–Spirakis over
    the stateless per-edge uniforms: the C largest keys u^(1/w) per row
    ARE such a draw; zero-weight edges get keys in (-2,-1] so they only
    fill slots left over after every positive-weight edge; rows whose
    total weight is <= 0 stay all-pad, the zero-degree convention).

    out_nbr/out_w: optional pre-initialized (pad / zero) destination
    views — the full build fills its final tables IN PLACE through
    them, avoiding a whole extra (N, C) transient pair at table scale
    (this file's standing memory contract)."""
    k = int(len(deg))
    nbr_tab = out_nbr if out_nbr is not None \
        else np.full((k, C), pad, dtype=np.int32)
    w_tab = out_w if out_w is not None \
        else np.zeros((k, C), dtype=np.float32)
    if k == 0:
        return nbr_tab, w_tab
    deg = np.asarray(deg, dtype=np.int64)
    edge_node = np.repeat(np.arange(k, dtype=np.int64), deg)
    offs0 = np.concatenate([[0], np.cumsum(deg)])
    pos_in_row = (np.arange(len(nbr_rows), dtype=np.int64)
                  - np.repeat(offs0[:-1], deg))
    small = deg <= C
    if small.any():
        keep = small[edge_node]
        nbr_tab[edge_node[keep], pos_in_row[keep]] = nbr_rows[keep]
        w_tab[edge_node[keep], pos_in_row[keep]] = ws[keep]
        del keep
    hubs = ~small
    if hubs.any():
        hub_edge = hubs[edge_node]
        he_node = edge_node[hub_edge]
        he_w = ws[hub_edge].astype(np.float64)
        he_nbr = nbr_rows[hub_edge]
        u = _edge_uniforms(seed, np.asarray(global_rows)[he_node],
                           pos_in_row[hub_edge])
        with np.errstate(divide="ignore", over="ignore"):
            key = np.where(he_w > 0,
                           np.exp(np.log(np.maximum(u, 1e-300)) /
                                  np.maximum(he_w, 1e-300)),
                           u - 2.0)
        del u
        # lexsort ≡ (row asc, key desc) at FULL key precision. The old
        # composite trick (row*4.0 − key in one f64) absorbed keys
        # smaller than the row index's ulp, so a row's subset silently
        # depended on its numeric index scale — patch blocks (small
        # local indices) and full builds (large global indices) would
        # tie-break differently and byte parity broke. Equal keys
        # (underflowed tiny weights) still break by within-row edge
        # order, which is row-local in both paths.
        order = np.lexsort((-key, he_node))
        del key
        he_node = he_node[order]
        # rank within row = position − first position of that row
        counts = np.bincount(he_node, minlength=k).astype(np.int64)
        starts = np.concatenate([[0], np.cumsum(counts)])
        rank = np.arange(he_node.size, dtype=np.int64) - starts[he_node]
        top = rank < C
        rows_t, cols_t = he_node[top], rank[top]
        sel = order[top]  # gather only kept entries — a full
        # he_*[order] copy would peak ~1GB transient at bench scale
        nbr_tab[rows_t, cols_t] = he_nbr[sel]
        w_tab[rows_t, cols_t] = he_w[sel].astype(np.float32)
        # rows with zero total weight revert to all-pad
        tot_by_row = np.bincount(edge_node[hub_edge],
                                 weights=ws[hub_edge], minlength=k)
        dead = hubs & (tot_by_row <= 0)
        if dead.any():
            nbr_tab[dead] = pad
            w_tab[dead] = 0.0
    return nbr_tab, w_tab


def _detect_uniform_rows(nbr_tab: np.ndarray, w_tab: np.ndarray,
                         pad: Optional[int] = None) -> bool:
    """True iff every row's positive-weight slots carry ONE equal weight
    and the positive slots are exactly the non-pad slots — the unweighted
    -graph case (cora/pubmed/ogbn-products and the bench graph all build
    with default edge weight 1.0). Under this condition the inverse-CDF
    draw is distribution-identical to a uniform draw over the row's
    degree, and sample_hop(uniform=True) may skip the cum-row gather
    entirely. Any weighted row (or an edge whose endpoint was missing
    and mapped to pad while keeping weight) clears the flag — a false
    positive would silently change the sampling distribution.

    The non-pad slots must additionally be FRONT-PACKED (contiguous in
    columns [0, deg)): the uniform draw's col = floor(u·deg) only ever
    reads that prefix, so an externally built from_arrays table with an
    interior pad slot would otherwise pass detection and silently sample
    pad rows while skipping real neighbors (advisor r5). Every in-repo
    builder front-packs; this guards the public rehydrate API.

    pad: the pad row id — pass it when nbr_tab is a ROW CHUNK of a
    larger table (from_arrays' chunked recompute), where shape[0] - 1
    is not the pad id. Chunk-wise conjunction is exact: every condition
    here is row-local."""
    if pad is None:
        pad = nbr_tab.shape[0] - 1
    C = nbr_tab.shape[1]
    nonpad = nbr_tab != pad
    pos = w_tab > 0
    if not (pos == nonpad).all():
        return False
    deg = nonpad.sum(axis=1)
    if not (nonpad == (np.arange(C) < deg[:, None])).all():
        return False
    rmax = w_tab.max(axis=1, keepdims=True)
    return bool(((w_tab == 0) | (w_tab == rmax)).all())


# Row-chunk size for table-scale host passes: bounds transients to
# chunk-sized arrays instead of full-table copies (~3.5GB at products
# scale, advisor r5). The uniform recompute holds ~2 f32 arrays per
# chunk; the Vose build holds ~8 f64/i64 working arrays per chunk, so
# it chunks finer to stay under one full-table f32 copy at any scale
# (the products-scale memory smoke pins this).
_CHUNK_ROWS = 262_144
_ALIAS_CHUNK_ROWS = 32_768

# Packed alias word layout (one int32 per slot; the layout contract for
# build_alias_tables and _alias_pick):
#   bits 16..30: alias column index (C <= 255 → 8 bits used)
#   bits  0..15: acceptance probability, quantized to uint16
#                (P(keep) = prob / 65535 — exact at 0 and 1)
# Pad/inactive slots and dead rows (total weight <= 0) hold -1: the
# sign bit doubles as the sentinel, so the device derives per-row
# active-column count as (word >= 0).sum(-1). Max packed value is
# 254<<16 | 65535 = 2^24 - 65537 < 2^24, so words always ride an f32
# lane exactly and _pick_cols' masked lane-sum applies unconditionally.
ALIAS_SENTINEL = np.int32(-1)
_ALIAS_PROB_MAX = 65535


def _check_alias_layout(alias: bool, fused: bool, shard_rows: bool):
    if alias and fused:
        raise ValueError(
            "DeviceNeighborTable(alias=True) needs the split nbr/cum "
            "layout — the fused [N+1, 2C] table has no slot for the "
            "alias words. Build with fused=False.")
    if alias and shard_rows:
        raise ValueError(
            "DeviceNeighborTable(alias=True) supports replicated tables "
            "only: the alias draw derives pad from the table shape, "
            "which row-sharding pads to the model-axis multiple. Use "
            "the weighted inverse-CDF path with row-sharded tables.")


def _vose_rows(w: np.ndarray, active: np.ndarray) -> np.ndarray:
    """Vectorized per-row Vose alias construction.

    w [R, C] float slot weights, active [R, C] bool (the columns the
    draw can land on: col0 = floor(u·K) with K = active.sum(row)) →
    packed int32 words [R, C] (layout above). Rows whose active weight
    totals <= 0 come back all-sentinel — the draw side resolves them to
    pad (the zero-degree convention).

    Two-pointer robin hood over per-row sorted scaled probabilities:
    each iteration finalizes exactly one column per live row (a small
    against the current large, a depleted large against the next one,
    or the terminal column), so the loop runs at most C+1 times with
    O(R) work per step — O(R·C) overall, no per-row Python loop."""
    R, C = w.shape
    out = np.full((R, C), ALIAS_SENTINEL, dtype=np.int32)
    if R == 0:
        return out
    w = np.where(active, w, 0.0).astype(np.float64)
    K = active.sum(axis=1).astype(np.int64)                 # [R]
    W = w.sum(axis=1)                                       # [R]
    live = (K > 0) & (W > 0)
    if not live.any():
        return out
    with np.errstate(invalid="ignore", divide="ignore"):
        p = w * (K[:, None] / W[:, None])                   # target 1.0
    # inactive columns sort to the far right and are never entered
    # (l starts at K-1); dead rows are skipped entirely
    p = np.where(active & live[:, None], p, np.inf)
    order = np.argsort(p, axis=1, kind="stable")            # ascending
    p_ord = np.take_along_axis(p, order, axis=1)            # [R, C]
    prob = np.ones((R, C))          # final prob, by sorted position
    alias = order.copy()            # final alias TARGET COLUMN, ditto
    s = np.zeros(R, dtype=np.int64)                 # next small (left)
    l = np.maximum(K - 1, 0)                        # current large
    rem = np.take_along_axis(p_ord, l[:, None], axis=1)[:, 0]
    done = ~live
    for _ in range(C + 1):
        a = np.flatnonzero(~done)
        if a.size == 0:
            break
        fin = s[a] >= l[a]
        f = a[fin]
        if f.size:
            # terminal column: mass conservation leaves rem ≈ 1 here
            prob[f, l[f]] = np.clip(rem[f], 0.0, 1.0)
            done[f] = True
        r = a[~fin]
        if r.size:
            sm = rem[r] >= 1.0
            rs = r[sm]          # finalize the next small against l
            if rs.size:
                ps = p_ord[rs, s[rs]]
                prob[rs, s[rs]] = np.clip(ps, 0.0, 1.0)
                alias[rs, s[rs]] = order[rs, l[rs]]
                rem[rs] += ps - 1.0
                s[rs] += 1
            rd = r[~sm]         # current large depleted: it becomes a
            if rd.size:         # small, finalized against the next one
                prob[rd, l[rd]] = np.clip(rem[rd], 0.0, 1.0)
                alias[rd, l[rd]] = order[rd, l[rd] - 1]
                l[rd] -= 1
                rem[rd] = p_ord[rd, l[rd]] + rem[rd] - 1.0
    q = np.rint(prob * _ALIAS_PROB_MAX).astype(np.int64)
    words = (alias.astype(np.int64) << 16) | q
    # scatter back from sorted position to actual column, live active
    # slots only — everything else keeps the sentinel
    keep = live[:, None] & (np.arange(C)[None, :] < K[:, None])
    ri, pi = np.nonzero(keep)
    out[ri, order[ri, pi]] = words[ri, pi].astype(np.int32)
    return out


def _alias_patch_counter(kind: str):
    """alias_rows_{patched,rebuilt}_total: rows whose Vose alias words
    were re-derived incrementally (patched — O(dirty) delta maintenance)
    vs by a full-table build (rebuilt). The streaming-mutation bench
    gates on patched/rebuilt staying ≤ 10% for a 1% delta."""
    from euler_tpu import obs

    helps = {
        "patched": "alias/table rows re-derived by incremental patching",
        "rebuilt": "alias table rows built by full-table builds",
    }
    return obs.default_registry().counter(
        f"alias_rows_{kind}_total", helps[kind])


def _alias_rows_block(nb: np.ndarray, w: np.ndarray,
                      pad: int) -> np.ndarray:
    """Packed alias words for one row block (explicit pad id — the
    block need not carry the table's trailing pad row). Per row the
    active draw columns are the front-packed non-pad prefix [0, deg)
    when the row IS front-packed, else all C columns (pad slots then
    carry prob 0 and alias into a real slot)."""
    C = nb.shape[1]
    cols = np.arange(C)
    nonpad = nb != pad
    deg = nonpad.sum(axis=1)
    front = (nonpad == (cols < deg[:, None])).all(axis=1)
    active = np.where(front[:, None], cols < deg[:, None], True)
    return _vose_rows(w, active)


def build_alias_tables(nbr_tab: np.ndarray,
                       cum_tab: Optional[np.ndarray] = None,
                       w_tab: Optional[np.ndarray] = None,
                       chunk_rows: int = _ALIAS_CHUNK_ROWS) -> np.ndarray:
    """[N+1, C] neighbor table (+ slot weights, given directly or as the
    inclusive cumsum) → [N+1, C] packed int32 alias table (word layout
    at ALIAS_SENTINEL above) — the device transpose of the reference's
    euler/common/alias_method.h, built once per table like the
    CompactWeightedCollection cum rows.

    Per row the active draw columns are the front-packed non-pad prefix
    [0, deg) when the row IS front-packed, else all C columns (pad slots
    then carry prob 0 and alias into a real slot) — either way the
    device-side count of non-sentinel words equals the builder's K, so
    col = floor(u·K) is always in range and never skips a real slot,
    even for externally built from_arrays tables with interior pads.

    Chunked over rows: peak transient is O(chunk_rows · C) floats, never
    a full-table f32 copy (the products-scale memory contract, pinned by
    the slow alias-build smoke)."""
    if (cum_tab is None) == (w_tab is None):
        raise ValueError(
            "build_alias_tables needs exactly one of cum_tab / w_tab")
    n_rows, C = nbr_tab.shape
    if C > 255:
        raise ValueError(
            f"alias words pack the column index into 8 bits — cap C "
            f"must be <= 255, got {C}")
    pad = n_rows - 1
    out = np.empty((n_rows, C), dtype=np.int32)
    for lo in range(0, n_rows, max(int(chunk_rows), 1)):
        hi = min(lo + max(int(chunk_rows), 1), n_rows)
        nb = np.asarray(nbr_tab[lo:hi])
        if w_tab is not None:
            w = np.asarray(w_tab[lo:hi]).astype(np.float32, copy=False)
        else:
            cc = np.asarray(cum_tab[lo:hi]).astype(np.float32,
                                                   copy=False)
            w = np.diff(cc, axis=1,
                        prepend=np.zeros((cc.shape[0], 1), np.float32))
        out[lo:hi] = _alias_rows_block(nb, w, pad)
    _alias_patch_counter("rebuilt").inc(n_rows)
    return out


def _pick_cols(row: jax.Array, col: jax.Array, exact_f32: bool):
    """row [n, C], col [n, k] → row[i, col[i, j]] [n, k].

    take_along_axis lowers to an n·k single-element gather on TPU —
    element-count-bound exactly like the retired flat pick (round-5
    probes: 4.9M picks ≈ 40ms inside the 90ms hop-2 sample while the
    row gather itself is 22ms). When ids fit f32 exactly (table rows
    <= 2^24) the pick is instead a masked lane-sum over the C columns
    already staged by the row gather — fused VPU work, no gather."""
    if not exact_f32:
        return jnp.take_along_axis(row, col, axis=1)
    C = row.shape[1]
    iota = jnp.arange(C, dtype=jnp.int32)
    ind = iota[None, None, :] == col[:, :, None]          # [n, k, C]
    return (row[:, None, :].astype(jnp.float32) * ind).sum(-1) \
        .astype(row.dtype)


def fuse_tables_host(nbr_tab: np.ndarray, cum_tab: np.ndarray) -> np.ndarray:
    """Host-side fuse_tables (numpy view bitcast, no device transfer) —
    the layout contract is defined ONCE here; fuse_tables mirrors it on
    device and a unit test pins the two equal bit-for-bit."""
    return np.concatenate(
        [np.asarray(nbr_tab).astype(np.int32, copy=False),
         np.asarray(cum_tab).astype(np.float32, copy=False)
            .view(np.int32)], axis=1)


def fuse_tables(nbr_tab, cum_tab):
    """Interleave neighbor ids and cumulative weights into one
    [N+1, 2C] int32 table (cum bitcast to i32): sample_hop then reads a
    node's full sampling state with ONE 2C-wide row gather instead of a
    cum-row gather plus a separate flattened neighbor-id gather. At
    products scale the per-hop gathers are the step's dominant cost, so
    halving the gather count on the sampling side is a direct win; the
    f32 bits ride an i32 lane and are bitcast back in-jit (exact).
    Layout contract shared with fuse_tables_host."""
    import jax.numpy as jnp

    nbr = jnp.asarray(nbr_tab)
    cum_bits = jax.lax.bitcast_convert_type(
        jnp.asarray(cum_tab, jnp.float32), jnp.int32)
    return jnp.concatenate([nbr.astype(jnp.int32), cum_bits], axis=1)


def sample_hop_fused(fused_table: jax.Array, rows: jax.Array,
                     count: int, key, gather=None) -> jax.Array:
    """sample_hop over a fuse_tables() layout: one row gather yields
    both the C neighbor ids and the C cumulative weights; the chosen
    column is then picked locally with take_along_axis (operand already
    in registers/VMEM — no second HBM gather).

    gather (make_table_gather) routes the row read for row-sharded fused
    tables: one masked local take + psum per hop — still half the
    collectives of the split-sharded path."""
    C = fused_table.shape[1] // 2
    n = rows.shape[0]
    if gather is None:
        row = jnp.take(fused_table, rows, axis=0)          # [n, 2C]
    else:
        row = gather(fused_table, rows)                    # [n, 2C]
    nbr = row[:, :C]
    cum = jax.lax.bitcast_convert_type(row[:, C:], jnp.float32)
    total = cum[:, -1]
    u = jax.random.uniform(key, (n, count)) * total[:, None]
    col = (cum[:, None, :] <= u[:, :, None]).sum(-1)
    col = jnp.clip(col, 0, C - 1).astype(jnp.int32)
    return jnp.take_along_axis(nbr, col, axis=1).reshape(-1)


def sample_fanout_rows_fused(fused_table: jax.Array, roots: jax.Array,
                             fanouts: Sequence[int], key, gather=None):
    """sample_fanout_rows over a fuse_tables() layout."""
    layers = [roots]
    cur = roots
    for k in fanouts:
        key, sub = jax.random.split(key)
        cur = sample_hop_fused(fused_table, cur, int(k), sub, gather)
        layers.append(cur)
    return layers


def is_model_sharded(mesh: Optional[jax.sharding.Mesh],
                     axis: str = "model") -> bool:
    """True when `mesh` has a non-trivial model axis — i.e. HBM tables
    built against it are actually row-sharded and reads must go through
    make_table_gather's masked-take+psum path. The single definition of
    the triviality rule (models and make_table_gather both use it)."""
    return mesh is not None and dict(mesh.shape).get(axis, 1) > 1


def make_table_gather(mesh: Optional[jax.sharding.Mesh] = None,
                      axis: str = "model", data_axis: str = "data",
                      hub_cache=None):
    """gather(table, rows) → table[rows] for HBM-resident tables.

    Replicated tables (mesh None / trivial model axis) → a plain local
    take. Row-sharded tables (placement.put_row_sharded) → the classic
    TPU sharded-embedding lookup: each chip takes its local row slice
    with out-of-range rows masked to zero, then one psum over the
    'model' axis reassembles full rows. One collective per gather, rides
    ICI; per-chip table memory stays 1/mp. rows must be shardable over
    the 'data' axis (batch and hop widths are multiples of it).

    hub_cache (a replicated [H, ...] copy of the table's first H rows —
    the PartitionedFeatureStore hub-first layout) wraps the gather in
    cache-first routing: rows < H are served from the local replica and
    never enter the psum leg (partitioned_store.hub_routed_take). Only
    meaningful for tables sharing that layout; pass per-table, since
    each table has its own cache."""
    if not is_model_sharded(mesh, axis):
        base = lambda tab, rows: jnp.take(tab, rows, axis=0)  # noqa: E731
        if hub_cache is not None:
            from euler_tpu.parallel.partitioned_store import (
                hub_routed_take,
            )

            return hub_routed_take(base, hub_cache)
        return base
    from functools import partial

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mp = dict(mesh.shape)[axis]

    def gather(tab, rows):
        if tab.shape[0] % mp:
            raise ValueError(
                f"make_table_gather: table has {tab.shape[0]} rows, not "
                f"divisible by the '{axis}' axis size {mp}. Row-sharded "
                "tables must be placed with placement.put_row_sharded "
                "(which pads rows to a multiple of the axis); a "
                "replicated table should use the local-take path "
                "(model table_mesh=None / shard_rows=False throughout)")
        per = tab.shape[0] // mp
        shape = rows.shape
        rows_flat = rows.reshape(-1)
        nd = tab.ndim - 1

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(axis, *([None] * nd)), P(data_axis)),
                 out_specs=P(data_axis, *([None] * nd)))
        def _g(tab_loc, r_loc):
            lo = jax.lax.axis_index(axis) * per
            loc = r_loc - lo
            ok = (loc >= 0) & (loc < per)
            loc = jnp.clip(loc, 0, per - 1)
            out = jnp.take(tab_loc, loc, axis=0)
            mask = ok.reshape(ok.shape + (1,) * nd)
            out = jnp.where(mask, out, jnp.zeros((), out.dtype))
            return jax.lax.psum(out, axis)

        return _g(tab, rows_flat).reshape(shape + tab.shape[1:])

    if hub_cache is not None:
        from euler_tpu.parallel.partitioned_store import hub_routed_take

        # flatten before routing: hub_routed_take's [..., None] mask
        # broadcast and the pad redirect both operate on flat rows,
        # exactly like the sharded gather itself
        routed = hub_routed_take(gather, hub_cache)

        def gather_hub(tab, rows):
            shape = rows.shape
            out = routed(tab, rows.reshape(-1))
            return out.reshape(shape + tab.shape[1:])

        return gather_hub
    return gather


def slot_weights(cum_rows: jax.Array) -> jax.Array:
    """Inclusive cumulative-weight rows [n, C] → per-slot edge weights
    [n, C]. The inverse of the cumsum in DeviceNeighborTable's layout —
    defined HERE, next to the layout contract, and shared by every
    consumer that needs raw slot weights (device_walk's node2vec bias,
    device_layerwise's pool draws)."""
    return jnp.diff(cum_rows, axis=1,
                    prepend=jnp.zeros_like(cum_rows[:, :1]))


def _alias_pick(alias_rows: jax.Array, u1: jax.Array, u2: jax.Array):
    """alias_rows [n, C] packed words, u1/u2 [n, k] uniforms →
    (col [n, k] int32, deg [n] int32): the O(1) alias draw.

    col0 = floor(u1·deg) over the row's active columns (deg = count of
    non-sentinel words — C compares on data the row gather already
    staged, the same trick the uniform path uses for pad counting),
    then ONE word read decides: keep col0 with P = prob/65535, else
    jump to the packed alias column. No [n, k, C] f32 broadcast-compare
    and no per-draw dependence on C — the inverse-CDF's cum-row scan is
    what the round-5 profile fingered inside the 90ms hop-2 draw. The
    word read uses _pick_cols' masked lane-sum (packed words always fit
    f32 exactly — see the layout note at ALIAS_SENTINEL).

    Dead rows (all-sentinel: pad row, zero-degree, zero-total-weight)
    come back with deg = 0 and col = 0 — callers resolve them to the
    pad row."""
    C = alias_rows.shape[1]
    deg = (alias_rows >= 0).sum(-1).astype(jnp.int32)          # [n]
    col0 = jnp.minimum(
        (u1 * deg[:, None].astype(jnp.float32)).astype(jnp.int32),
        jnp.maximum(deg[:, None] - 1, 0))                      # [n, k]
    word = _pick_cols(alias_rows, col0, True)                  # [n, k]
    prob = jnp.bitwise_and(word, _ALIAS_PROB_MAX)
    ali = jnp.right_shift(word, 16)                # arithmetic: -1 → -1
    keep = u2 * float(_ALIAS_PROB_MAX) < prob.astype(jnp.float32)
    col = jnp.where(keep, col0, ali)
    return jnp.clip(col, 0, C - 1).astype(jnp.int32), deg


def sample_hop(nbr_table: jax.Array, cum_table: jax.Array,
               rows: jax.Array, count: int, key,
               gather=None, uniform: bool = False,
               alias_table=None) -> jax.Array:
    """One weighted neighbor draw per (row, slot): [n] → [n * count].

    Inverse-CDF over each row's C inclusive cumulative weights — the
    device transpose of CompactWeightedCollection's binary search (C is
    small and fixed, so C vectorized compares beat a gather-heavy
    log-search). Zero-degree rows (total weight 0) resolve to the pad
    slot, whose neighbor entry is pad_row.

    The neighbor pick is count-aware (round-5 on-chip probes,
    PERF.md): TPU gather cost here is element-count-bound, not
    byte-bound — at products scale a flat pick of n·count single int32
    elements ran 77.9ms where a row gather of the same n nodes ran
    21.7ms — so for count >= 4 the whole [n, C] neighbor row is
    gathered once per node and the count columns are picked locally
    (draw-for-draw identical output; _pick_cols uses a masked lane-sum
    instead of take_along_axis when ids fit f32, which on TPU also
    lowers to an element-count-bound gather). For small count (the walk
    family's count=1 chains) the flat pick moves C× fewer bytes at the
    same element count and stays the right shape.

    uniform=True (tables whose rows are unit-weight —
    DeviceNeighborTable.uniform_rows) skips the cum-row gather
    entirely: ONE neighbor-row gather per hop, degree derived from the
    row's pad count, column = floor(u·deg). Distribution-identical to
    the inverse-CDF draw on such tables (not draw-for-draw — different
    u consumption). Replicated tables only: the row-sharded layout pads
    the row count up to the model-axis multiple, so pad cannot be
    derived from shape there (walk_rows has the same constraint).

    alias_table (DeviceNeighborTable(alias=True) /
    build_alias_tables): the Vose alias draw — O(1) per draw via one
    packed-word read instead of the C-wide inverse-CDF scan, at the
    same gather element count (the alias row gather replaces the
    cum-row gather). Distribution-identical to the inverse-CDF draw up
    to the uint16 prob quantization (< 1e-5 per slot; chi-squared
    pinned in tests), NOT draw-for-draw (different u consumption).
    Composes with the count-aware pick on both sides (row pick for
    count >= 4, flat pick for the walk family's count = 1 chains).
    Replicated split tables only, and exclusive with uniform=True —
    callers resolve precedence explicitly.

    gather (make_table_gather) routes table reads for row-sharded
    tables; that path always has the full rows and picks locally."""
    C = nbr_table.shape[1]
    n = rows.shape[0]
    exact = nbr_table.shape[0] <= (1 << 24)  # ids ride f32 exactly
    if alias_table is not None:
        if gather is not None:
            raise ValueError(
                "sample_hop(alias_table=...) supports replicated tables "
                "only: the alias draw resolves dead rows to the pad id "
                "derived from the table shape, which row-sharding pads "
                "to the model-axis multiple. Use the weighted path "
                "(alias_table=None) with row-sharded tables.")
        if uniform:
            raise ValueError(
                "sample_hop: uniform=True and alias_table are exclusive "
                "— resolve the precedence at the call site (the alias "
                "draw already covers unit-weight tables)")
        arow = jnp.take(alias_table, rows, axis=0)     # [n, C]
        u = jax.random.uniform(key, (2, n, count))
        col, deg = _alias_pick(arow, u[0], u[1])
        pad = nbr_table.shape[0] - 1
        if count < 4:
            flat = rows[:, None] * C + col             # [n, k]
            out = jnp.take(nbr_table.reshape(-1),
                           flat.reshape(-1)).reshape(n, count)
        else:
            nbr = jnp.take(nbr_table, rows, axis=0)    # [n, C]
            out = _pick_cols(nbr, col, exact)
        # dead rows (zero degree / zero total weight) resolve to pad
        return jnp.where(deg[:, None] > 0, out, pad).reshape(-1)
    if uniform:
        if gather is not None:
            raise ValueError(
                "sample_hop(uniform=True) supports replicated tables "
                "only: a row-sharded table's row count is padded to the "
                "model-axis multiple, so the pad id cannot be derived "
                "from its shape. Use the weighted path (uniform=False) "
                "with row-sharded tables.")
        nbr = jnp.take(nbr_table, rows, axis=0)        # [n, C]
        pad = nbr_table.shape[0] - 1
        deg = (nbr != pad).sum(-1).astype(jnp.float32)             # [n]
        u = jax.random.uniform(key, (n, count))
        col = jnp.minimum((u * deg[:, None]).astype(jnp.int32),
                          jnp.maximum(
                              deg[:, None].astype(jnp.int32) - 1, 0))
        return _pick_cols(nbr, col, exact).reshape(-1)
    if gather is None:
        cum = jnp.take(cum_table, rows, axis=0)        # [n, C]
    else:
        cum = gather(cum_table, rows)
    total = cum[:, -1]
    u = jax.random.uniform(key, (n, count)) * total[:, None]   # [n, k]
    col = (cum[:, None, :] <= u[:, :, None]).sum(-1)   # [n, k]
    col = jnp.clip(col, 0, C - 1).astype(jnp.int32)
    if gather is None:
        if count < 4:
            flat = rows[:, None] * C + col             # [n, k]
            return jnp.take(nbr_table.reshape(-1), flat.reshape(-1))
        nbr = jnp.take(nbr_table, rows, axis=0)        # [n, C]
    else:
        nbr = gather(nbr_table, rows)                  # [n, C]
    return _pick_cols(nbr, col, exact).reshape(-1)


def sample_fanout_rows(nbr_table: jax.Array, cum_table: jax.Array,
                       roots: jax.Array, fanouts: Sequence[int], key,
                       gather=None, uniform: bool = False,
                       alias_table=None):
    """Multi-hop on-device fanout: returns [roots, hop1, hop2, ...] row
    arrays (layer h has roots.shape[0] * prod(fanouts[:h]) entries) —
    the shape contract of FanoutDataFlow, produced without touching the
    host. uniform=True → the one-gather unit-weight path per hop;
    alias_table → the O(1) alias draw per hop (see sample_hop)."""
    layers = [roots]
    cur = roots
    for k in fanouts:
        key, sub = jax.random.split(key)
        cur = sample_hop(nbr_table, cum_table, cur, int(k), sub, gather,
                         uniform=uniform, alias_table=alias_table)
        layers.append(cur)
    return layers
