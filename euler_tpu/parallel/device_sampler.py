"""Device-resident neighbor sampling: the TPU-first answer to the host
sampling bottleneck.

The reference's whole input design exists to amortize CPU-side neighbor
sampling (one-RPC chained fanout, tf_euler/kernels/sample_fanout_op.cc:
36-48). On TPU that leaves the chip idle: measured on v5e-1, the jitted
GraphSAGE train step sustains 11-24 steps/s while a 2-core host produces
at most ~3 fanout batches/s — the accelerator waits on the feeder 4-10×
over. When the graph fits in HBM the right design is to move sampling
itself onto the device:

  - neighbor rows [N, C] (int32, capped at C per node) and inclusive
    cumulative weights [N, C] (float32) live in HBM — the
    CompactWeightedCollection layout (reference
    euler/common/compact_weighted_collection.h:55) transposed into two
    dense tables an XLA gather can hit;
  - per hop, sampling is: uniform draw → per-row inverse-CDF over C
    cumulative weights (C compares on the VPU) → gather neighbor rows.
    Pure XLA inside the jitted train step; composes with lax.scan
    (steps_per_loop) and pjit;
  - the host ships ONLY root rows (~131KB for batch 32768) — everything
    else (sampling, feature gather, labels) reads HBM-resident tables.

Memory: 8 bytes × N × C (e.g. 200k nodes × C=32 → 51MB) next to the
DeviceFeatureStore feature table.

Fidelity: nodes with degree ≤ C sample exactly the host engine's
weighted-with-replacement distribution. Nodes with degree > C sample
from a C-subset drawn once at build time (weighted, without
replacement) — the standard neighbor-cap approximation (GraphSAGE §3.1
uses fixed-size uniform subsets the same way). Pass cap >= max degree
for exact parity.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class DeviceNeighborTable:
    """Builds the HBM neighbor/cum-weight tables from a graph engine.

    Row order matches `graph.all_node_ids()` (the DeviceFeatureStore
    convention) so the same int32 rows index features, labels, and
    adjacency. Row N (= pad_row) is an all-pad row: sampling from it
    yields pad_row again, mirroring the host sampler's default_id pads.
    """

    def __init__(self, graph, cap: int = 32, edge_types=None,
                 seed: int = 0,
                 mesh: Optional[jax.sharding.Mesh] = None):
        ids = graph.all_node_ids()
        n = len(ids)
        self.cap = int(cap)
        self.pad_row = n
        offs, nbrs, ws, _ = graph.get_full_neighbor(ids, edge_types)
        offs = offs.astype(np.int64)
        deg = np.diff(offs)
        nbr_rows = graph.node_rows(nbrs, missing=n).astype(np.int32)
        ws = ws.astype(np.float32)

        C = self.cap
        nbr_tab = np.full((n + 1, C), n, dtype=np.int32)
        w_tab = np.zeros((n + 1, C), dtype=np.float32)

        # common case: degree <= C — one vectorized ragged scatter
        small = deg <= C
        if small.any():
            edge_node = np.repeat(np.arange(n), deg)
            edge_col = np.arange(len(nbr_rows)) - np.repeat(offs[:-1], deg)
            keep = small[edge_node]
            nbr_tab[edge_node[keep], edge_col[keep]] = nbr_rows[keep]
            w_tab[edge_node[keep], edge_col[keep]] = ws[keep]
        # hubs: weighted C-subset without replacement, drawn once
        rng = np.random.default_rng(seed)
        for i in np.where(~small)[0]:
            lo, hi = offs[i], offs[i + 1]
            w = ws[lo:hi]
            tot = w.sum()
            nnz = int((w > 0).sum())
            if tot <= 0:
                pick = rng.choice(hi - lo, size=C, replace=False)
            elif nnz >= C:
                pick = rng.choice(hi - lo, size=C, replace=False, p=w / tot)
            else:
                # fewer positive-weight edges than slots: keep them all,
                # pad with zero-weight edges (never drawn by the CDF)
                pos = np.where(w > 0)[0]
                zero = np.where(w <= 0)[0]
                pick = np.concatenate(
                    [pos, rng.choice(zero, C - nnz, replace=False)])
            nbr_tab[i, :] = nbr_rows[lo + pick]
            w_tab[i, :] = ws[lo + pick]

        cum = np.cumsum(w_tab, axis=1, dtype=np.float32)
        from euler_tpu.parallel.placement import put_replicated

        self.neighbors = put_replicated(nbr_tab, mesh)
        self.cum_weights = put_replicated(cum, mesh)

    @property
    def tables(self):
        """Arrays to merge into the estimator's static_batch."""
        return {"nbr_table": self.neighbors, "cum_table": self.cum_weights}


def sample_hop(nbr_table: jax.Array, cum_table: jax.Array,
               rows: jax.Array, count: int, key) -> jax.Array:
    """One weighted neighbor draw per (row, slot): [n] → [n * count].

    Inverse-CDF over each row's C inclusive cumulative weights — the
    device transpose of CompactWeightedCollection's binary search (C is
    small and fixed, so C vectorized compares beat a gather-heavy
    log-search). Zero-degree rows (total weight 0) resolve to the pad
    slot, whose neighbor entry is pad_row.
    """
    C = nbr_table.shape[1]
    n = rows.shape[0]
    cum = jnp.take(cum_table, rows, axis=0)            # [n, C]
    total = cum[:, -1]
    u = jax.random.uniform(key, (n, count)) * total[:, None]   # [n, k]
    col = (cum[:, None, :] <= u[:, :, None]).sum(-1)   # [n, k]
    col = jnp.clip(col, 0, C - 1).astype(jnp.int32)
    flat = rows[:, None] * C + col                     # [n, k]
    out = jnp.take(nbr_table.reshape(-1), flat.reshape(-1))
    return out


def sample_fanout_rows(nbr_table: jax.Array, cum_table: jax.Array,
                       roots: jax.Array, fanouts: Sequence[int], key):
    """Multi-hop on-device fanout: returns [roots, hop1, hop2, ...] row
    arrays (layer h has roots.shape[0] * prod(fanouts[:h]) entries) —
    the shape contract of FanoutDataFlow, produced without touching the
    host."""
    layers = [roots]
    cur = roots
    for k in fanouts:
        key, sub = jax.random.split(key)
        cur = sample_hop(nbr_table, cum_table, cur, int(k), sub)
        layers.append(cur)
    return layers
