"""Device-resident random walks, skip-gram pair generation, and negative
sampling — the TPU-first input path for the walk/unsupervised model
family (DeepWalk / node2vec / LINE / unsupervised GraphSAGE).

The reference runs walks on the graph engine (random_walk_op.cc:34-172:
per-node neighbor queries + client-side p/q bias) and generates pairs on
the host (gen_pair_op.cc:28). On TPU that re-creates the host feeder
bottleneck the device sampler removed for the supervised path: measured
on v5e-1, the jitted skip-gram step runs orders of magnitude faster than
a 1-2 core host can walk. With the DeviceNeighborTable already in HBM, a
walk is just `walk_len` chained single-neighbor draws; pairs are static
index arithmetic; negatives are an inverse-CDF draw over a node-weight
table — all VPU work inside the jitted step, composing with lax.scan
(steps_per_loop) and pjit.

Fidelity notes:
  - walks draw from the capped neighbor table, so hub nodes walk over
    the same weighted C-subset the supervised device sampler uses;
  - node2vec's second-order p/q bias is computed EXACTLY over the capped
    table: membership of each candidate in the previous node's kept
    neighbor row (C x C compares on the VPU — the reference computes the
    same bias from two full-neighbor queries, random_walk_op.cc:70-110);
  - dead ends stick at pad_row, and pad-touching pairs are masked out of
    the loss (the host path trains default_id=0 on dead ends — the
    device path is strictly cleaner).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from euler_tpu.parallel.device_sampler import slot_weights, sample_hop


class DeviceNodeSampler:
    """Weighted global node sampling on device (negatives, root pools).

    The device transpose of the engine's per-type FastWeightedCollection
    (reference euler/common/fast_weighted_collection.h:28): a row pool +
    inclusive cumulative weights; draws are uniform * total -> one
    searchsorted (log N) per sample.
    """

    def __init__(self, graph, node_type: int = -1,
                 mesh: Optional[jax.sharding.Mesh] = None):
        ids = graph.all_node_ids()
        types = graph.get_node_type(ids)
        rows = np.arange(len(ids), dtype=np.int32)
        w = graph.all_node_weights()
        if node_type >= 0:
            keep = types == node_type
            rows, w = rows[keep], w[keep]
        self.pool = rows
        cum = np.cumsum(w, dtype=np.float32)
        from euler_tpu.parallel.placement import put_replicated

        self.rows = put_replicated(rows, mesh)
        self.cum = put_replicated(cum, mesh)

    @property
    def tables(self):
        return {"neg_rows": self.rows, "neg_cum": self.cum}


def sample_global_rows(pool_rows: jax.Array, pool_cum: jax.Array,
                       key, shape: Tuple[int, ...]) -> jax.Array:
    """Weighted draw of `shape` rows from a (pool, cum) node sampler."""
    total = pool_cum[-1]
    u = jax.random.uniform(key, shape) * total
    idx = jnp.searchsorted(pool_cum, u)
    idx = jnp.clip(idx, 0, pool_rows.shape[0] - 1)
    return jnp.take(pool_rows, idx)


def walk_rows(nbr_table: jax.Array, cum_table: jax.Array,
              roots: jax.Array, walk_len: int, key,
              p: float = 1.0, q: float = 1.0,
              gather=None, uniform: bool = False,
              alias_table=None) -> jax.Array:
    """[B] roots → [B, walk_len+1] row walks, column 0 = roots.

    p == q == 1: each step is one weighted neighbor draw (sample_hop);
    uniform=True routes those draws through the one-gather unit-weight
    path (DeviceNeighborTable.uniform_rows tables, replicated only);
    alias_table routes them through the O(1) alias draw — the walk
    family's chained count=1 draws are where the per-draw constant
    matters most, and the flat neighbor pick stays. Otherwise node2vec
    second-order bias: candidate weights are scaled 1/p when returning
    to the previous node, 1 when the candidate is a kept neighbor of
    the previous node, 1/q otherwise — computed over the capped rows
    with C x C equality compares, no host round-trip (the biased path
    always reads the cum table: the bias math needs raw slot weights,
    so uniform/alias are ignored there).
    """
    C = nbr_table.shape[1]
    unif = uniform and gather is None and alias_table is None
    atab = alias_table if gather is None else None

    def take(tab, r):
        return gather(tab, r) if gather is not None else \
            jnp.take(tab, r, axis=0)

    cols = [roots]
    key, sub = jax.random.split(key)
    cur = sample_hop(nbr_table, cum_table, roots, 1, sub, gather,
                     uniform=unif, alias_table=atab)
    cols.append(cur)
    prev = roots
    for _ in range(walk_len - 1):
        key, sub = jax.random.split(key)
        if p == 1.0 and q == 1.0:
            nxt = sample_hop(nbr_table, cum_table, cur, 1, sub, gather,
                             uniform=unif, alias_table=atab)
        else:
            cand = take(nbr_table, cur)                     # [B, C]
            w = slot_weights(take(cum_table, cur))          # [B, C]
            prev_nbr = take(nbr_table, prev)                # [B, C]
            is_prev = cand == prev[:, None]
            in_prev_nbr = (cand[:, :, None]
                           == prev_nbr[:, None, :]).any(-1)
            # pad candidates keep weight 0 regardless of bias
            bias = jnp.where(is_prev, 1.0 / p,
                             jnp.where(in_prev_nbr, 1.0, 1.0 / q))
            bw = w * bias
            bcum = jnp.cumsum(bw, axis=1)
            total = bcum[:, -1]
            u = jax.random.uniform(sub, (cand.shape[0],)) * total
            col = (bcum <= u[:, None]).sum(-1)
            col = jnp.clip(col, 0, C - 1).astype(jnp.int32)
            nxt = jnp.take_along_axis(cand, col[:, None], axis=1)[:, 0]
            # zero-total rows (dead end / pad): every candidate slot of
            # such a row already holds the table's DATA pad value (the
            # builder fills dead rows with pad), so cand[:, 0] is the
            # correct sentinel. Deriving it from nbr_table.shape[0]-1
            # would be wrong for row-sharded tables, whose row count is
            # padded up to the model-axis multiple (code-review r4).
            nxt = jnp.where(total > 0, nxt, cand[:, 0])
        cols.append(nxt)
        prev, cur = cur, nxt
    return jnp.stack(cols, axis=1)


def gen_pair_offsets(walk_cols: int, left_win: int,
                     right_win: int) -> Sequence[Tuple[int, int]]:
    """Static (center, context) index pairs for an L-column walk —
    boundary-clipped like ops.walk_ops.gen_pair."""
    out = []
    for i in range(walk_cols):
        for off in range(-left_win, right_win + 1):
            j = i + off
            if off == 0 or j < 0 or j >= walk_cols:
                continue
            out.append((i, j))
    return out


def gen_pair_rows(walks: jax.Array, left_win: int,
                  right_win: int) -> jax.Array:
    """[B, L] walks → [B, P, 2] skip-gram pairs (same pair order as the
    host gen_pair, so models are interchangeable across paths)."""
    L = walks.shape[1]
    offs = gen_pair_offsets(L, left_win, right_win)
    if not offs:
        return jnp.zeros((walks.shape[0], 0, 2), walks.dtype)
    ii = jnp.array([i for i, _ in offs])
    jj = jnp.array([j for _, j in offs])
    return jnp.stack([walks[:, ii], walks[:, jj]], axis=-1)
