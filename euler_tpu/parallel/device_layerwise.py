"""Device-resident layerwise (LADIES/FastGCN) sampling.

Completes the on-device input family: fanout (device_sampler.py) and
walks (device_walk.py) already run in-jit; this moves the third
sampling strategy — per-layer importance-sampled pools + dense
inter-pool adjacency (reference API_SAMPLE_L / sample_layer_op.cc:74 and
LayerwiseDataFlow, tf_euler/python/dataflow/layerwise_dataflow.py) —
into the jitted step as well. The host ships only root rows + a seed.

Per layer, over the capped HBM tables (DeviceNeighborTable layout):
  - candidates are the current level's neighbor slots [n_l, C] with
    their edge weights (diff of the inclusive cum rows);
  - the pool is a weighted draw of m_l slots via the Gumbel-max trick
    (keys log(w) + Gumbel noise, lax.top_k) — slots of the same node
    may repeat, which under row-normalization splits that node's mass
    across duplicate columns instead of changing it (the static-shape
    substitute for the host sampler's distinct-node pools);
  - the next level is concat(current, pool) — the LADIES connectivity
    guarantee (each level contains the previous one, so self-loops
    always find a column), mirroring LayerwiseDataFlow.__call__;
  - the dense adjacency [n_l, n_{l+1}] is rebuilt on the VPU by
    comparing neighbor slots against the level columns, + self-loops,
    row-normalized — the same Â = A + I math as
    LayerwiseDataFlow._dense_adj.

Shapes are fully static: n_0 = B, n_{l+1} = n_l + m_l.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def _slot_weights(cum_row):
    """Inclusive cum rows [n, C] → per-slot weights [n, C]."""
    return jnp.diff(cum_row, axis=1, prepend=jnp.zeros_like(cum_row[:, :1]))


def sample_layerwise_rows(nbr_table: jax.Array, cum_table: jax.Array,
                          roots: jax.Array, layer_sizes: Sequence[int],
                          key):
    """roots [B] int32 → (levels, adjs): levels[l] is an int32 row array
    (level 0 = roots, level l+1 = level l ++ pool of layer_sizes[l]);
    adjs[l] is the row-normalized dense [n_l, n_{l+1}] adjacency of
    Â = A + I restricted to the pools — exactly the batch geometry
    LayerwiseDataFlow produces and LayerEncoder consumes."""
    C = int(nbr_table.shape[1])
    n = int(roots.shape[0])
    for li, m in enumerate(layer_sizes):
        if int(m) > n * C:
            raise ValueError(
                f"layer_sizes[{li}]={m} exceeds the {n}*{C}={n * C} "
                f"candidate neighbor slots of level {li} — lower the "
                f"layer size or raise batch_size/sampler cap")
        n += int(m)
    levels = [roots]
    adjs = []
    cur = roots
    for m in layer_sizes:
        key, kg = jax.random.split(key)
        nbr = jnp.take(nbr_table, cur, axis=0)          # [n, C] rows
        w = _slot_weights(jnp.take(cum_table, cur, axis=0))
        # Gumbel-max over slots: P(slot) ∝ w; zero-weight slots (pads,
        # zero-weight edges) get -inf keys and lose to any real slot
        g = jax.random.gumbel(kg, w.shape, dtype=jnp.float32)
        keys = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-30)) + g,
                         -jnp.inf)
        _, idx = jax.lax.top_k(keys.reshape(-1), int(m))
        pool = jnp.take(nbr.reshape(-1), idx)           # [m]
        nxt = jnp.concatenate([cur, pool])              # [n + m]
        # dense Â = A + I between cur and nxt, row-normalized
        hit = (nbr[:, :, None] == nxt[None, None, :])   # [n, C, n+m]
        adj = (w[:, :, None] * hit).sum(axis=1)
        adj = adj + (cur[:, None] == nxt[None, :]).astype(adj.dtype)
        adj = adj / jnp.maximum(adj.sum(axis=1, keepdims=True), 1e-12)
        adjs.append(adj)
        levels.append(nxt)
        cur = nxt
    return levels, adjs
