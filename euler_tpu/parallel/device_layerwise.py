"""Device-resident layerwise (LADIES/FastGCN) sampling.

Completes the on-device input family: fanout (device_sampler.py) and
walks (device_walk.py) already run in-jit; this moves the third
sampling strategy — per-layer importance-sampled pools + dense
inter-pool adjacency (reference API_SAMPLE_L / sample_layer_op.cc:74 and
LayerwiseDataFlow, tf_euler/python/dataflow/layerwise_dataflow.py) —
into the jitted step as well. The host ships only root rows + a seed.

Per layer, over the capped HBM tables (DeviceNeighborTable layout):
  - pool candidates are the FRONTIER's neighbor slots — the previous
    layer's pool (the roots at layer 0) — with their edge weights
    (diff of the inclusive cum rows); drawing from the frontier only
    matches the host engine's layerwise sampler (SampleLayerwise,
    core/cc/ops.cc), which expands each layer from the nodes drawn in
    the previous one, not from the whole accumulated level (advisor
    r3: the concatenated-level draw skewed candidate mass toward
    earlier/duplicated nodes);
  - the pool is m_l WITH-REPLACEMENT draws ∝ slot weight (inverse-CDF
    over the flattened slot weights): P(neighbor) ∝ its total incident
    edge weight from the frontier — distributionally the engine's
    per-unique-neighbor accumulated-weight draw, with duplicates
    arising exactly as they do on the host path (each duplicate
    carries the full edge weight into the adjacency; _dense_adj does
    the same);
  - the next level is concat(current, pool) — the LADIES connectivity
    guarantee (each level contains the previous one, so self-loops
    always find a column), mirroring LayerwiseDataFlow.__call__;
  - the dense adjacency [n_l, n_{l+1}] is rebuilt on the VPU by
    comparing neighbor slots against the level columns, + self-loops,
    row-normalized — the same Â = A + I math as
    LayerwiseDataFlow._dense_adj.

Shapes are fully static: n_0 = B, n_{l+1} = n_l + m_l.

Envelope: the adjacency build materializes an [n_l, C, n_{l+1}] bool
hit tensor on the VPU — fine for the FastGCN/LADIES training regime
(batches 64-512, pools 128-512: ≤ ~50M elements), not for the
fanout-style batch-32k regime; giant batches belong to the fanout
sampler (device_sampler.py), whose cost is linear in drawn edges.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


from euler_tpu.parallel.device_sampler import (  # noqa: E402
    _alias_pick, slot_weights,
)


def sample_layerwise_rows(nbr_table: jax.Array, cum_table: jax.Array,
                          roots: jax.Array, layer_sizes: Sequence[int],
                          key, alias_table=None):
    """roots [B] int32 → (levels, adjs): levels[l] is an int32 row array
    (level 0 = roots, level l+1 = level l ++ pool of layer_sizes[l]);
    adjs[l] is the row-normalized dense [n_l, n_{l+1}] adjacency of
    Â = A + I restricted to the pools — exactly the batch geometry
    LayerwiseDataFlow produces and LayerEncoder consumes.

    alias_table (DeviceNeighborTable(alias=True)): the pool draw
    becomes two-stage — frontier node ∝ its total incident weight (an
    inverse-CDF over n_frontier row totals instead of n_frontier·C
    slots), then the O(1) alias draw inside the chosen row. P(node) ·
    P(slot|node) = (W_i/ΣW)·(w_ij/W_i) = w_ij/ΣW: distribution-
    identical to the flat slot draw, with the cumsum/searchsorted
    shrunk C×. The adjacency build is unchanged (it needs the raw slot
    weights either way)."""
    levels = [roots]
    adjs = []
    cur = roots
    n_frontier = roots.shape[0]  # frontier = last pool (roots at l=0)
    for m in layer_sizes:
        key, kg = jax.random.split(key)
        nbr = jnp.take(nbr_table, cur, axis=0)          # [n, C] rows
        w = slot_weights(jnp.take(cum_table, cur, axis=0))
        # pool draw expands the FRONTIER (a suffix of cur) only — the
        # host engine's layer-by-layer semantics; the full cur rows are
        # still needed below for the inter-level adjacency.
        # With-replacement inverse-CDF over the flat slot weights:
        # P(slot) ∝ w, zero-weight slots (pads, zero-weight edges) are
        # never hit while any real slot exists — without top-k's
        # shortfall when fewer than m positive slots exist
        nbr_f = nbr[-n_frontier:]
        if alias_table is not None:
            cur_f = cur[-n_frontier:]
            tot_cum = jnp.cumsum(w[-n_frontier:].sum(-1))   # [nf]
            u = jax.random.uniform(kg, (int(m),)) * tot_cum[-1]
            idx = jnp.searchsorted(tot_cum, u, side="right")
            idx = jnp.minimum(idx,
                              tot_cum.shape[0] - 1).astype(jnp.int32)
            arow = jnp.take(alias_table, jnp.take(cur_f, idx),
                            axis=0)                         # [m, C]
            key, ka = jax.random.split(key)
            ua = jax.random.uniform(ka, (2, int(m), 1))
            col, deg = _alias_pick(arow, ua[0], ua[1])      # [m, 1]
            pool = jnp.take_along_axis(jnp.take(nbr_f, idx, axis=0),
                                       col, axis=1)[:, 0]   # [m]
            # zero-total frontier rows carry no draw mass; if the WHOLE
            # frontier is dead every draw resolves to pad explicitly
            pool = jnp.where(deg > 0, pool, nbr_table.shape[0] - 1)
        else:
            flat_cum = jnp.cumsum(w[-n_frontier:].reshape(-1))
            total = flat_cum[-1]
            u = jax.random.uniform(kg, (int(m),)) * total
            idx = jnp.searchsorted(flat_cum, u, side="right")
            idx = jnp.minimum(idx,
                              flat_cum.shape[0] - 1).astype(jnp.int32)
            pool = jnp.take(nbr_f.reshape(-1), idx)         # [m]
        nxt = jnp.concatenate([cur, pool])              # [n + m]
        n_frontier = int(m)
        # dense Â = A + I between cur and nxt, row-normalized
        hit = (nbr[:, :, None] == nxt[None, None, :])   # [n, C, n+m]
        adj = (w[:, :, None] * hit).sum(axis=1)
        adj = adj + (cur[:, None] == nxt[None, :]).astype(adj.dtype)
        adj = adj / jnp.maximum(adj.sum(axis=1, keepdims=True), 1e-12)
        adjs.append(adj)
        levels.append(nxt)
        cur = nxt
    return levels, adjs
