"""HBM-sharded embedding tables — the TPU-native replacement for the
reference's parameter-server embedding sharding.

Parity: tf_euler/python/utils/layers.py:119-171 (partitioned
Embedding/SparseEmbedding on TF PS) + embedding.py partial updates
(SURVEY.md §2.4 "Embedding-table model parallelism").

Design: the table's rows are partitioned over the mesh's 'model' axis via
flax partitioning metadata. Under jit with GSPMD, a lookup jnp.take(table,
rows) on a model-sharded table lowers to an on-device gather + ICI
collective (all-gather of the hit rows), and the backward scatter-add of
gradients is likewise distributed — no parameter server, no Python-side
partial_update protocol (reference embedding.py:24,61).
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from euler_tpu.utils.layers import bucketize_ids

Array = jax.Array

__all__ = ["ShardedEmbedding", "param_shardings", "apply_param_shardings"]


class ShardedEmbedding(nn.Module):
    """Embedding table partitioned row-wise over the 'model' mesh axis."""

    num_embeddings: int
    dim: int
    init_scale: float = 0.05
    partition_axis: str = "model"

    @nn.compact
    def __call__(self, ids: Array) -> Array:
        table = self.param(
            "table",
            nn.with_partitioning(
                nn.initializers.uniform(scale=self.init_scale),
                (self.partition_axis, None),
            ),
            (self.num_embeddings, self.dim),
        )
        rows = bucketize_ids(ids, self.num_embeddings)
        return jnp.take(jnp.asarray(table), rows, axis=0)


def param_shardings(variables: Dict, mesh: Mesh) -> Dict:
    """PyTree of NamedShardings from flax partitioning metadata: boxed
    nn.Partitioned leaves get their spec, everything else replicates."""

    def to_sharding(leaf):
        if isinstance(leaf, nn.Partitioned):
            return NamedSharding(mesh, P(*leaf.names))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(
        to_sharding, variables,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def apply_param_shardings(variables: Dict, mesh: Mesh) -> Dict:
    """device_put the (unboxed) variables per their metadata shardings."""
    shardings = param_shardings(variables, mesh)
    unboxed = nn.meta.unbox(variables)
    flat_s = jax.tree_util.tree_leaves(shardings)
    flat_v, treedef = jax.tree_util.tree_flatten(unboxed)
    placed = [jax.device_put(v, s) for v, s in zip(flat_v, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed)
