"""HBM-sharded embedding tables — the TPU-native replacement for the
reference's parameter-server embedding sharding.

Parity: tf_euler/python/utils/layers.py:119-171 (partitioned
Embedding/SparseEmbedding on TF PS) + embedding.py partial updates
(SURVEY.md §2.4 "Embedding-table model parallelism").

Design: the table's rows are partitioned over the mesh's 'model' axis via
flax partitioning metadata. Under jit with GSPMD, a lookup jnp.take(table,
rows) on a model-sharded table lowers to an on-device gather + ICI
collective (all-gather of the hit rows), and the backward scatter-add of
gradients is likewise distributed — no parameter server, no Python-side
partial_update protocol (reference embedding.py:24,61).
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from euler_tpu.utils.layers import bucketize_ids

Array = jax.Array

__all__ = ["ShardedEmbedding", "param_shardings", "apply_param_shardings"]


class ShardedEmbedding(nn.Module):
    """Embedding table partitioned row-wise over the 'model' mesh axis.

    lookup picks how the partitioned rows are fetched:
      'gspmd'      (default) a plain take on the metadata-sharded table;
                   XLA GSPMD chooses the collective — the historical
                   behavior.
      'ring'       explicit K-step ppermute exchange
                   (ring_exchange.ring_lookup) under shard_map: peak
                   ICI/buffer footprint is 1/K of the all-gather, the
                   large-batch regime. Requires `mesh`.
      'allgather'  explicit all-gather + reduce-scatter
                   (ring_exchange.allgather_lookup): two collective
                   launches, the small-batch/latency regime. Requires
                   `mesh`.
    Both explicit modes are differentiable (ppermute/psum_scatter carry
    transposes), produce the same numbers as 'gspmd', and exist as the
    staged on-chip A/B against GSPMD's choice. num_embeddings must
    divide the mesh's partition axis for the explicit modes (the shard
    layout put_row_sharded would otherwise pad)."""

    num_embeddings: int
    dim: int
    init_scale: float = 0.05
    partition_axis: str = "model"
    lookup: str = "gspmd"
    mesh: Any = None

    @nn.compact
    def __call__(self, ids: Array) -> Array:
        table = self.param(
            "table",
            nn.with_partitioning(
                nn.initializers.uniform(scale=self.init_scale),
                (self.partition_axis, None),
            ),
            (self.num_embeddings, self.dim),
        )
        rows = bucketize_ids(ids, self.num_embeddings)
        tab = jnp.asarray(table)
        if self.lookup == "gspmd":
            return jnp.take(tab, rows, axis=0)
        if self.lookup not in ("ring", "allgather"):
            raise ValueError(
                f"ShardedEmbedding.lookup must be 'gspmd', 'ring' or "
                f"'allgather', got {self.lookup!r}")
        mesh = self.mesh
        k = 1 if mesh is None else int(
            dict(mesh.shape).get(self.partition_axis, 1))
        if k <= 1:  # no real partition axis — explicit modes degenerate
            return jnp.take(tab, rows, axis=0)
        if self.num_embeddings % k:
            raise ValueError(
                f"ShardedEmbedding.lookup={self.lookup!r} needs "
                f"num_embeddings ({self.num_embeddings}) divisible by "
                f"the '{self.partition_axis}' axis size {k}")
        from euler_tpu.parallel.ring_exchange import (
            allgather_lookup, ring_lookup,
        )

        fn = ring_lookup if self.lookup == "ring" else allgather_lookup
        flat = rows.reshape(-1)
        pad = (-flat.shape[0]) % k
        if pad:  # id shards must divide evenly; pads gather row 0
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        # pin the id vector REPLICATED before it enters shard_map: on a
        # mesh with a non-trivial data axis, GSPMD may shard this
        # in-jit intermediate over 'data', and shard_map's implicit
        # reshard to P(axis) then reads wrong values on jax without
        # pvary/pcast (observed on 0.4.37: whole rows wrong while the
        # eager path is fine). No-op when already replicated.
        flat = jax.lax.with_sharding_constraint(
            flat, NamedSharding(mesh, P()))
        out = fn(tab, flat, mesh, self.partition_axis)
        if pad:
            out = out[:-pad]
        return out.reshape(rows.shape + (self.dim,))


def param_shardings(variables: Dict, mesh: Mesh) -> Dict:
    """PyTree of NamedShardings from flax partitioning metadata: boxed
    nn.Partitioned leaves get their spec, everything else replicates."""

    def to_sharding(leaf):
        if isinstance(leaf, nn.Partitioned):
            return NamedSharding(mesh, P(*leaf.names))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(
        to_sharding, variables,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def apply_param_shardings(variables: Dict, mesh: Mesh) -> Dict:
    """device_put the (unboxed) variables per their metadata shardings."""
    shardings = param_shardings(variables, mesh)
    unboxed = nn.meta.unbox(variables)
    flat_s = jax.tree_util.tree_leaves(shardings)
    flat_v, treedef = jax.tree_util.tree_flatten(unboxed)
    placed = [jax.device_put(v, s) for v, s in zip(flat_v, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed)
