"""Device-resident feature store: the TPU-first answer to per-step
feature shipping.

The reference streams features from the graph engine to the trainer on
every batch (GetDenseFeature over gRPC, tf_euler/kernels/
get_dense_feature_op.cc). On TPU the host↔device link (PCIe, or a tunnel)
is the bottleneck: a 15×10 fanout batch of 100-dim float features is
~66MB/step, while the same batch as int32 row ids is ~0.7MB. When the
node feature matrix fits in HBM (ogbn-products at 100-dim f32 is ~1GB),
the right design is: upload the table ONCE, ship only rows, gather on
device (one MXU-adjacent take() — sub-ms).

For multi-chip, pass a mesh: the table is replicated by default (row
sharding composes with ShardedEmbedding when the table itself is
trainable — here it's frozen input data, and replication keeps the
gather local, no collective per step).

Tier selection: when the table no longer fits one chip's HBM
replicated, use euler_tpu.parallel.partitioned_store
.PartitionedFeatureStore instead — contiguous 1/K row shards over the
'model' axis, a replicated hub cache (top hub_cache_frac
highest-degree rows, gathers routed cache-first), and host-RAM
overflow behind CachedGraphEngine. Its int8 path reuses quantize_int8
/ dequantize_rows below, with ONE scale computed over the full table
so partitioned and replicated lookups stay byte-identical;
memory_plan.plan_partitioned_table emits the per-chip fit verdict.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def quantize_int8(feats: np.ndarray):
    """Per-column symmetric int8 quantization: q = round(x/scale),
    scale = colmax|x|/127. Returns (q int8, scale f32[D]). Halves the
    bytes every feature-row gather moves out of HBM vs bf16 (the hop-2
    gather dominates step HBM traffic at products scale) and halves the
    table's HBM footprint; dequant (q·scale) runs after the gather,
    fused into the consumer by XLA. All-zero columns get scale 1."""
    scale = np.abs(feats).max(axis=0).astype(np.float32) / 127.0
    scale[scale == 0] = 1.0
    q = np.clip(np.rint(feats.astype(np.float32, copy=False) / scale),
                -127, 127)
    return q.astype(np.int8), scale


def dequantize_rows(x, scale):
    """Inverse of quantize_int8 for gathered rows; output dtype follows
    scale (store the scale in the dtype you want features to train in)."""
    return x.astype(scale.dtype) * scale


class DeviceFeatureStore:
    """Uploads dense node features (and optionally labels) to device HBM
    once; translates u64 node ids → int32 table rows on the host.

    Usage:
        store = DeviceFeatureStore(graph, ["feature"], label_fid="label",
                                   label_dim=C)
        rows = store.lookup(ids_u64)        # host, ~µs/kid
        feats = store.features[rows_dev]    # device gather, in-jit
    """

    def __init__(self, graph, feature_ids: Sequence, label_fid=None,
                 label_dim: Optional[int] = None,
                 dtype=jnp.float32,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 keep_host: bool = False, shard_rows: bool = False,
                 quantize: Optional[str] = None):
        """quantize='int8' stores the feature table int8 with a
        per-column scale (quantize_int8); the store exposes
        feature_scale and models dequantize after the gather."""
        self.shard_rows = bool(shard_rows)
        # table rows follow ENGINE row order so lookup() is the engine's
        # O(1) hash translation (etg_node_rows), not a binary search
        ids = graph.all_node_ids()
        self.ids = ids
        self._graph = graph
        # row N is a dedicated all-zero pad row: unknown ids and sampling
        # pads gather zeros, matching GetDenseFeature's unknown-id
        # behavior on the host path
        self.pad_row = len(ids)
        feats = graph.get_dense_feature(ids, list(feature_ids))
        if isinstance(feats, list):
            feats = np.concatenate(feats, axis=1)
        feats = np.concatenate(
            [feats, np.zeros((1, feats.shape[1]), feats.dtype)])
        feats = feats.astype(np.dtype(dtype), copy=False)
        from euler_tpu.parallel.placement import (
            put_replicated, put_row_sharded,
        )

        put = (lambda x: put_row_sharded(x, mesh)) if shard_rows else \
            (lambda x: put_replicated(x, mesh))
        self.feature_scale = None
        if quantize == "int8":
            q, scale = quantize_int8(np.asarray(feats, np.float32))
            self.features = put(q)
            self.feature_scale = put_replicated(
                scale.astype(np.dtype(dtype), copy=False), mesh)
        elif quantize is not None:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        else:
            self.features = put(feats)
        self.labels = None
        labels = None
        if label_fid is not None:
            labels = graph.get_dense_feature(ids, label_fid, label_dim)
            labels = np.concatenate(
                [labels, np.zeros((1, labels.shape[1]), labels.dtype)])
            labels = labels.astype(np.float32, copy=False)
            self.labels = put(labels)
        # host copies are opt-in (cache writers like bench): pinning them
        # by default would double host RAM for every training caller
        self.host_arrays = (feats, labels) if keep_host else None

    @classmethod
    def from_arrays(cls, features: np.ndarray,
                    labels: Optional[np.ndarray] = None,
                    ids: Optional[np.ndarray] = None,
                    mesh: Optional[jax.sharding.Mesh] = None,
                    shard_rows: bool = False,
                    pad_dim_to: Optional[int] = None,
                    quantize: Optional[str] = None,
                    scale_dtype=jnp.float32):
        """Rehydrate from prebuilt arrays (a cache) without a graph
        engine. `features`/`labels` must already carry the trailing pad
        row; `ids` (sorted u64, len N) backs lookup() via searchsorted —
        when omitted, node ids are taken to BE table rows (dense-id
        graphs, e.g. the bench cache). pad_dim_to zero-pads the feature
        dim up to a lane multiple (e.g. 128) so each gathered row is an
        aligned tile — a throughput knob; downstream Dense layers see
        the wider (zero-extended) features."""
        self = cls.__new__(cls)
        self._graph = None
        self.host_arrays = None
        self.pad_row = int(features.shape[0]) - 1
        self.ids = ids if ids is not None else np.arange(
            self.pad_row, dtype=np.uint64)
        self._sorted_ids = ids is not None
        self.shard_rows = bool(shard_rows)
        from euler_tpu.parallel.placement import (
            put_replicated, put_row_sharded,
        )

        put = (lambda x: put_row_sharded(x, mesh)) if shard_rows else \
            (lambda x: put_replicated(x, mesh))
        if pad_dim_to is not None and features.shape[1] < pad_dim_to:
            features = np.concatenate(
                [features,
                 np.zeros((features.shape[0],
                           pad_dim_to - features.shape[1]),
                          features.dtype)], axis=1)
        self.feature_scale = None
        if quantize == "int8":
            q, scale = quantize_int8(np.asarray(features, np.float32))
            self.features = put(np.ascontiguousarray(q))
            self.feature_scale = put_replicated(
                scale.astype(np.dtype(scale_dtype), copy=False), mesh)
        elif quantize is not None:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        else:
            self.features = put(np.ascontiguousarray(features))
        self.labels = None
        if labels is not None:
            self.labels = put(
                np.ascontiguousarray(labels.astype(np.float32, copy=False)))
        return self

    @property
    def dim(self) -> int:
        return int(self.features.shape[-1])

    def lookup(self, ids) -> np.ndarray:
        """u64 node ids → int32 rows into the device tables. Unknown ids
        (including default_id=0 sampling pads) map to the zero pad row."""
        if self._graph is not None:
            return self._graph.node_rows(ids, missing=self.pad_row)
        ids = np.asarray(ids, np.uint64).ravel()
        if not self._sorted_ids:
            rows = ids.astype(np.int64)
            return np.where(rows < self.pad_row, rows,
                            self.pad_row).astype(np.int32)
        pos = np.searchsorted(self.ids, ids)
        pos = np.minimum(pos, len(self.ids) - 1)
        hit = self.ids[pos] == ids
        return np.where(hit, pos, self.pad_row).astype(np.int32)
