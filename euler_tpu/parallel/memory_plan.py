"""Per-chip HBM arithmetic for the device table layouts.

Makes the multi-chip products-scale claim checkable as arithmetic
instead of hope (VERDICT r4 #8): given the same layout rules the
builders use (DeviceNeighborTable [N+1, C] i32 + [N+1, C] f32 — or the
fused [N+1, 2C] i32; DeviceFeatureStore [N+1, D] in bf16/int8 with a
[D] f32 scale; placement.put_row_sharded padding rows to a multiple of
the 'model' axis), compute exactly how many bytes each chip holds for a
given mesh. The formulas are pinned to the real builders by
tests/test_memory_math.py, which builds small tables and asserts
byte-for-byte agreement (replicated AND row-sharded), so they cannot
drift silently.

Reference analog: the reference sizes its partitioned graph by shard
count in scripts/dist_tf_euler.sh:28-43; here the budget is per-chip
HBM instead of per-worker RAM.
"""

from __future__ import annotations

from typing import Dict, Optional


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def plan_tables(n_nodes: int, cap: int = 32, feat_dim: int = 100,
                label_dim: int = 16, mp: int = 1, fused: bool = False,
                quantize: Optional[str] = "int8",
                feat_dtype_bytes: int = 2,
                pad_dim_to: Optional[int] = None,
                shard_rows: bool = True,
                act_cache_dim: int = 0,
                act_cache_dtype_bytes: int = 2,
                act_cache_sharded: bool = False) -> Dict:
    """Per-chip bytes for one replica group's HBM-resident tables.

    mp — size of the 'model' mesh axis; with shard_rows the row-sharded
    tables hold ceil(rows/mp) rows per chip (put_row_sharded pads rows
    to a multiple of mp). shard_rows=False models the replicated
    placement (every chip holds full tables). The activation cache
    (DeviceSampledScalableSage) is replicated by default;
    act_cache_sharded models models/graphsage.shard_act_cache — the
    cache row-sharded over 'model' (GSPMD keeps it sharded through the
    train step; test_act_cache_row_sharded), dividing its bytes by mp
    like the tables.
    """
    rows = n_nodes + 1  # + the trailing pad row (builders' convention)

    def per_chip(r: int) -> int:
        if mp <= 1 or not shard_rows:
            return r
        return _ceil_div(r, mp)

    entries: Dict[str, int] = {}
    if fused:
        # fuse_tables packs cum f32 bits + nbr i32 into one [N+1, 2C]
        # i32 row: same bytes as split, half the gathers
        entries["nbrcum_table"] = per_chip(rows) * 2 * cap * 4
    else:
        entries["nbr_table"] = per_chip(rows) * cap * 4
        entries["cum_table"] = per_chip(rows) * cap * 4
    d = feat_dim if (pad_dim_to is None or pad_dim_to <= feat_dim) \
        else pad_dim_to
    fb = 1 if quantize == "int8" else feat_dtype_bytes
    entries["feature_table"] = per_chip(rows) * d * fb
    if quantize == "int8":
        entries["feature_scale"] = d * 4  # [D] f32, replicated
    if label_dim:
        entries["label_table"] = per_chip(rows) * label_dim * 4
    if act_cache_dim:
        # independent of shard_rows: shard_act_cache only needs a
        # non-trivial model axis, not sharded graph tables
        c_rows = _ceil_div(rows, mp) if (act_cache_sharded and mp > 1) \
            else rows
        entries["act_cache"] = c_rows * act_cache_dim * act_cache_dtype_bytes
    return {
        "per_chip_table_bytes": entries,
        "per_chip_total_bytes": sum(entries.values()),
        "rows": rows,
        "mp": mp,
        "fused": fused,
        "shard_rows": bool(shard_rows and mp > 1),
    }


# Per-chip HBM on the generations the plans are quoted against. v4-8 is
# the canonical quote target (ISSUE 6): 4 chips × 32 GiB.
HBM_BYTES = {"v4": 32 << 30, "v5e": 16 << 30, "v5p": 95 << 30}


def plan_partitioned_table(n_nodes: int, feat_dim: int = 100,
                           k_shards: int = 4,
                           hub_cache_frac: float = 0.01,
                           quantize: Optional[str] = "int8",
                           feat_dtype_bytes: int = 2,
                           label_dim: int = 0,
                           device_rows: Optional[int] = None,
                           hbm_budget_bytes: Optional[int] = None,
                           chip: str = "v4") -> Dict:
    """Per-chip bytes for the PartitionedFeatureStore tier, by the same
    layout rules the builder uses (pinned by tests/test_memory_math.py
    against a real store):

      shard      ceil((device_rows + 1 pad sentinel) padded-to-K / K)
                 rows × D × elem bytes on each chip
      hub cache  round(hub_cache_frac · N) rows × D × elem bytes,
                 REPLICATED on every chip (the rows also stay in the
                 partition — the cache is a routing copy, not a move)
      scale      [D] f32 replicated when int8-quantized
      labels     optional [rows, label_dim] f32, sharded like the table
      host       rows past device_rows never upload (the
                 CachedGraphEngine overflow tier) — reported, not
                 counted against HBM

    Emits a verdict ("fits on <chip>-<4K> HBM at N nodes, K shards,
    f hub" or the factor it misses by) against hbm_budget_bytes
    (default: the chip generation's HBM)."""
    if k_shards < 1:
        raise ValueError(f"k_shards must be >= 1, got {k_shards}")
    if not 0.0 <= float(hub_cache_frac) < 1.0:
        raise ValueError(
            f"hub_cache_frac must be in [0, 1), got {hub_cache_frac}")
    dev = n_nodes if device_rows is None else min(int(device_rows),
                                                  n_nodes)
    hub = int(round(float(hub_cache_frac) * n_nodes))
    dev = max(dev, hub)          # the builder clamps the same way
    rows = dev + 1               # + trailing pad sentinel
    padded = _ceil_div(rows, k_shards) * k_shards
    fb = 1 if quantize == "int8" else feat_dtype_bytes
    entries: Dict[str, int] = {
        "feature_shard": _ceil_div(rows, k_shards) * feat_dim * fb,
        "hub_cache": hub * feat_dim * fb,
    }
    if quantize == "int8":
        entries["feature_scale"] = feat_dim * 4
    if label_dim:
        entries["label_shard"] = _ceil_div(rows, k_shards) * label_dim * 4
    total = sum(entries.values())
    budget = hbm_budget_bytes if hbm_budget_bytes is not None \
        else HBM_BYTES[chip]
    fits = total <= budget
    where = f"{chip}-{4 * k_shards}"
    verdict = (
        f"fits on {where} HBM at {n_nodes} nodes, {k_shards} shards, "
        f"{hub_cache_frac:g} hub ({total / 2**30:.2f} of "
        f"{budget / 2**30:.0f} GiB/chip)" if fits else
        f"EXCEEDS {where} HBM at {n_nodes} nodes, {k_shards} shards, "
        f"{hub_cache_frac:g} hub by {total / budget:.2f}x — raise K, "
        f"lower device_rows (host overflow), or quantize")
    return {
        "per_chip_table_bytes": entries,
        "per_chip_total_bytes": total,
        "rows": rows,
        "padded_rows": padded,
        "k_shards": k_shards,
        "hub_rows": hub,
        "host_rows": n_nodes - dev,
        "hbm_budget_bytes": budget,
        "fits": fits,
        "verdict": verdict,
    }
