from euler_tpu.parallel.mesh import (  # noqa: F401
    data_sharding,
    make_mesh,
    mesh_shape_for,
    replicated,
    shard_batch,
)
from euler_tpu.parallel.sharded_embedding import (  # noqa: F401
    ShardedEmbedding,
    apply_param_shardings,
    param_shardings,
)
from euler_tpu.parallel.device_sampler import (  # noqa: F401
    DeviceNeighborTable,
    build_alias_tables,
    fuse_tables,
    make_table_gather,
    sample_fanout_rows,
    sample_fanout_rows_fused,
    sample_hop,
    sample_hop_fused,
)
from euler_tpu.parallel.placement import (  # noqa: F401
    put_replicated,
    put_row_sharded,
)
from euler_tpu.parallel.device_walk import (  # noqa: F401
    DeviceNodeSampler,
    gen_pair_rows,
    sample_global_rows,
    walk_rows,
)
from euler_tpu.parallel.feature_store import DeviceFeatureStore  # noqa: F401
from euler_tpu.parallel.partitioned_store import (  # noqa: F401
    PartitionedFeatureStore,
    hub_routed_take,
)
from euler_tpu.parallel.ring_exchange import (  # noqa: F401
    allgather_lookup,
    pick_lookup_strategy,
    ring_lookup,
)
from euler_tpu.parallel.train import make_spmd_train_step, spmd_init  # noqa: F401
