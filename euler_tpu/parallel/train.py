"""SPMD training step over a device mesh.

The multi-chip training path (SURVEY.md §2.4 mapping): batch arrays are
sharded over the 'data' axis, embedding tables over 'model', everything
else replicated; the jitted step lets XLA GSPMD insert gradient
all-reduces over ICI. Used by the estimator (mesh=...), bench.py's
multi-chip mode, and __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh

from euler_tpu.parallel.mesh import shard_batch
from euler_tpu.parallel.sharded_embedding import apply_param_shardings


def spmd_init(model: nn.Module, tx: optax.GradientTransformation,
              sample_batch: Dict, mesh: Mesh, seed: int = 0) -> Dict[str, Any]:
    """Initializes sharded train state: params placed per their
    partitioning metadata (embedding rows over 'model'), optimizer state
    mirrors the param placement."""
    rng = jax.random.key(seed)
    batch = shard_batch(sample_batch, mesh)
    variables = model.init(rng, batch)
    variables = apply_param_shardings(variables, mesh)
    params = variables.pop("params")
    opt_state = tx.init(params)
    return {"params": params, "opt_state": opt_state,
            "extra_vars": variables, "step": jnp.zeros((), jnp.int32)}


def make_spmd_train_step(model: nn.Module,
                         tx: optax.GradientTransformation,
                         mutable_keys: Tuple[str, ...] = ()) -> Callable:
    """Jitted (state, batch) → (state, loss, metric). State buffers are
    donated so HBM is reused across steps."""

    def train_step(state, batch):
        def loss_fn(p):
            variables = {"params": p, **state["extra_vars"]}
            if mutable_keys:
                out, new_vars = model.apply(variables, batch,
                                            mutable=list(mutable_keys))
            else:
                out = model.apply(variables, batch)
                new_vars = state["extra_vars"]
            return out.loss, (out.metric, new_vars)

        (loss, (metric, new_vars)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "extra_vars": new_vars, "step": state["step"] + 1},
            loss,
            metric,
        )

    return jax.jit(train_step, donate_argnums=(0,))
