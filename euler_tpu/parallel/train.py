"""SPMD training step over a device mesh.

The multi-chip training path (SURVEY.md §2.4 mapping): batch arrays are
sharded over the 'data' axis, embedding tables over 'model', everything
else replicated; the jitted step lets XLA GSPMD insert gradient
all-reduces over ICI. Used by the estimator (mesh=...), bench.py's
multi-chip mode, and __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh

from euler_tpu import obs as _obs
from euler_tpu.parallel.mesh import shard_batch
from euler_tpu.parallel.sharded_embedding import apply_param_shardings


def spmd_init(model: nn.Module, tx: optax.GradientTransformation,
              sample_batch: Dict, mesh: Mesh, seed: int = 0) -> Dict[str, Any]:
    """Initializes sharded train state: params placed per their
    partitioning metadata (embedding rows over 'model'), optimizer state
    mirrors the param placement."""
    rng = jax.random.key(seed)
    batch = shard_batch(sample_batch, mesh)
    variables = model.init(rng, batch)
    variables = apply_param_shardings(variables, mesh)
    params = variables.pop("params")
    opt_state = tx.init(params)
    return {"params": params, "opt_state": opt_state,
            "extra_vars": variables, "step": jnp.zeros((), jnp.int32),
            "skipped_steps": jnp.zeros((), jnp.int32)}


def make_spmd_train_step(model: nn.Module,
                         tx: optax.GradientTransformation,
                         mutable_keys: Tuple[str, ...] = (),
                         nonfinite_guard: bool = True,
                         table_store=None,
                         table_rows_key: str = "rows") -> Callable:
    """Jitted (state, batch) → (state, loss, metric). State buffers are
    donated so HBM is reused across steps — which is exactly why the
    nonfinite guard defaults on: one NaN loss applied to donated buffers
    destroys the only copy of the params. A guarded bad step keeps the
    old params/opt_state and bumps state['skipped_steps'].

    table_store (a PartitionedFeatureStore) turns on per-step gather
    accounting in the HOST wrapper: each dispatch's table rows
    (batch[table_rows_key], a row array or list of per-hop row arrays)
    are routed through store.observe_batch before the device call, so
    the table_gather_{local,cached,remote}_rows counters track exactly
    the dispatched steps. Pass HOST row arrays — a device-resident
    array here costs a blocking device→host fetch per step. (The
    estimator path does its own counting in NodeEstimator._node_batch/
    _sampler_batch; this hook serves raw spmd-loop callers.)"""

    def train_step(state, batch):
        # states built before spmd_init grew the counter (hand-rolled
        # dicts) can't be guarded — structure of both cond branches must
        # match the input pytree
        has_ctr = "skipped_steps" in state

        def loss_fn(p):
            variables = {"params": p, **state["extra_vars"]}
            if mutable_keys:
                out, new_vars = model.apply(variables, batch,
                                            mutable=list(mutable_keys))
            else:
                out = model.apply(variables, batch)
                new_vars = state["extra_vars"]
            return out.loss, (out.metric, new_vars)

        (loss, (metric, new_vars)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])

        def apply_update(_):
            updates, opt_state = tx.update(grads, state["opt_state"],
                                           state["params"])
            params = optax.apply_updates(state["params"], updates)
            new = {"params": params, "opt_state": opt_state,
                   "extra_vars": new_vars, "step": state["step"] + 1}
            if has_ctr:
                new["skipped_steps"] = state["skipped_steps"]
            return new

        def skip_update(_):
            new = dict(state)
            new["step"] = state["step"] + 1
            if has_ctr:
                new["skipped_steps"] = state["skipped_steps"] + 1
            return new

        if nonfinite_guard and has_ctr:
            # loss AND grads: backward-pass overflow can produce NaN
            # grads under a finite loss
            ok = jnp.isfinite(loss)
            for g in jax.tree_util.tree_leaves(grads):
                ok &= jnp.all(jnp.isfinite(g))
            new_state = jax.lax.cond(ok, apply_update, skip_update, None)
        else:
            new_state = apply_update(None)
        return new_state, loss, metric

    jitted = jax.jit(train_step, donate_argnums=(0,))
    reg = _obs.default_registry()
    c_steps = reg.counter("spmd_steps_total",
                          "SPMD train-step dispatches")
    h_dispatch = reg.histogram(
        "spmd_dispatch_ms",
        "host-side SPMD train-step dispatch latency (async: excludes "
        "device time the host did not wait for)")

    def stepped(state, batch):
        t0 = time.monotonic()
        if table_store is not None and table_rows_key in batch:
            rows = batch[table_rows_key]
            for r in (rows if isinstance(rows, (list, tuple)) else [rows]):
                table_store.observe_batch(np.asarray(r))
        with _obs.span("spmd_train_step"):
            out = jitted(state, batch)
        c_steps.inc()
        h_dispatch.observe((time.monotonic() - t0) * 1000.0)
        return out

    return stepped
