"""Partitioned device feature tables with a hub-aware replication cache.

The giant-graph tier (ROADMAP item 4): the canonical products config
(2.45M nodes / 122M edges) is one order of magnitude from outgrowing a
single chip's HBM, and the measured degree skew (hub_frac ≈ 0.996 at
cap 32) means a tiny replicated hot-set can absorb most gathers. This
module replaces the all-or-nothing placement choice (replicated vs
plain row-sharded) with a three-tier layout:

  hub cache   top hub_cache_frac highest-degree rows, REPLICATED on
              every chip — gathers route cache-first, so the hot mass
              never crosses ICI;
  partition   each chip holds a contiguous 1/K row shard of the table
              (plus the pad sentinel), cold gathers cross ICI via
              ring_exchange.ring_lookup or its all-gather variant,
              picked per step by a cost model on batch-unique ids × K;
  host        rows past an optional device budget stay in host RAM,
              served through CachedGraphEngine behind the existing
              degrade/retry machinery.

The load-bearing trick is a HUB-FIRST ROW PERMUTATION: rows are
relabeled in descending-degree order (degree ranking comes from the
graph engine at build time), so hub membership is simply `row < H` —
no device-resident membership map, and the hub cache is literally the
table's first H rows. The same permutation is the degree-sorted
locality layout bench.py already A/Bs (_degree_sort_tables), so the
neighbor tables compose by `apply_permutation`.

Correctness contract: `gather()` on the mesh is byte-identical to
`ring_exchange.reference_lookup` on the unpartitioned table for every
dtype the store supports (float32 and int8-quantized) — hub rows come
from a verbatim replicated copy, cold rows from the masked
single-owner exchange, and the combine is a select, never arithmetic.
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.parallel.feature_store import quantize_int8
from euler_tpu.parallel.ring_exchange import (
    allgather_lookup,
    pick_lookup_strategy,
    ring_lookup,
)

__all__ = ["PartitionedFeatureStore", "hub_routed_take"]

_STORE_IDS = itertools.count()


def hub_routed_take(base_take, hub_cache: jax.Array):
    """Wrap a table gather with cache-first hub routing.

    `base_take(table, rows)` is the cold-leg gather (plain take for a
    replicated table, make_table_gather's masked-take+psum or the
    ring/all-gather exchange for a partitioned one). Rows below the
    hub-cache height H are served from the replicated `hub_cache` (the
    table's first H rows verbatim — the hub-first permutation makes
    membership a compare, not a map); only the cold tail reaches
    `base_take`, with hub positions routed to the table's trailing zero
    row so a hub row NEVER rides the remote leg. The final combine is a
    select, so output bytes equal an unrouted gather exactly (int8
    included)."""
    H = int(hub_cache.shape[0])
    if H == 0:
        return base_take

    def take(table, rows):
        is_hub = rows < H
        cached = jnp.take(hub_cache, jnp.minimum(rows, H - 1), axis=0)
        cold = base_take(
            table, jnp.where(is_hub, table.shape[0] - 1, rows))
        return jnp.where(is_hub[..., None], cached, cold)

    return take


class PartitionedFeatureStore:
    """Mesh-partitioned node feature table + replicated hub cache.

    Device-row layout (after the hub-first degree permutation):
      [0, H)            hub rows — the first rows of the partition AND
                        replicated verbatim as `hub_cache`
      [H, dev_rows)     cold rows, contiguous 1/K shards over `axis`
      dev_rows          the all-zero pad sentinel (unknown ids, sampling
                        pads) — the DeviceFeatureStore convention
      > dev_rows        put_row_sharded zero padding; no live index
                        reaches it

    Rank space past dev_rows (host_rows of them) is the host-RAM
    overflow tier: those rows never upload; lookup_with_overflow flags
    them and fetch_host_rows serves them through CachedGraphEngine.

    Usage mirrors DeviceFeatureStore:
        store = PartitionedFeatureStore(graph, ["feature"], mesh=mesh,
                                        hub_cache_frac=0.01)
        rows = store.lookup(ids_u64)          # host: ids → device rows
        out = store.make_gather()(rows_dev)   # on-mesh, parity-exact
    plus `tables` for the estimator static_batch and `apply_permutation`
    for remapping neighbor/label tables into the same row space.
    """

    def __init__(self, graph, feature_ids: Sequence, *,
                 mesh: jax.sharding.Mesh, axis: str = "model",
                 hub_cache_frac: float = 0.0,
                 device_rows: Optional[int] = None,
                 dtype=jnp.float32, quantize: Optional[str] = None,
                 host_cache_bytes: int = 64 << 20,
                 name: Optional[str] = None):
        ids = graph.all_node_ids()
        feats = graph.get_dense_feature(ids, list(feature_ids))
        if isinstance(feats, list):
            feats = np.concatenate(feats, axis=1)
        feats = feats.astype(np.dtype(dtype), copy=False)
        # degree ranking from the engine at build time: the hub set is
        # the measured skew, not a guess
        offs = graph.get_full_neighbor(ids)[0].astype(np.int64)
        degrees = np.diff(offs)
        self._init_from(feats, degrees, mesh=mesh, axis=axis,
                        hub_cache_frac=hub_cache_frac,
                        device_rows=device_rows, quantize=quantize,
                        scale_dtype=dtype, name=name)
        self._graph = graph
        self._feature_ids = list(feature_ids)
        # host overflow reads go through the immutable-graph client
        # cache — and whatever degrade/retry machinery the wrapped
        # engine already carries (RemoteGraphEngine's RetryPolicy)
        self._host_engine = None
        if self.host_rows > 0:
            from euler_tpu.graph import CachedGraphEngine

            self._host_engine = CachedGraphEngine(
                graph, budget_bytes=int(host_cache_bytes),
                name=f"{self.name}_host")

    @classmethod
    def from_arrays(cls, features: np.ndarray, degrees: np.ndarray, *,
                    mesh: jax.sharding.Mesh, axis: str = "model",
                    hub_cache_frac: float = 0.0,
                    quantize: Optional[str] = None,
                    scale_dtype=jnp.float32,
                    name: Optional[str] = None):
        """Rehydrate from a prebuilt [N+1, D] table (trailing pad row,
        the builders' convention) + per-node degrees [N] — the bench
        cache path. Node ids are taken to BE original table rows
        (dense-id graphs). No graph engine → no host-overflow tier."""
        self = cls.__new__(cls)
        self._graph = None
        self._feature_ids = None
        self._host_engine = None
        self._init_from(np.asarray(features), np.asarray(degrees),
                        mesh=mesh, axis=axis,
                        hub_cache_frac=hub_cache_frac,
                        device_rows=None, quantize=quantize,
                        scale_dtype=scale_dtype, name=name)
        return self

    # -- build -------------------------------------------------------------
    def _init_from(self, feats: np.ndarray, degrees: np.ndarray, *,
                   mesh, axis, hub_cache_frac, device_rows, quantize,
                   scale_dtype, name):
        from euler_tpu.parallel.placement import (
            put_replicated, put_row_sharded,
        )

        n = int(degrees.shape[0])
        if feats.shape[0] == n:          # engine path: pad row not yet
            feats = np.concatenate(
                [feats, np.zeros((1, feats.shape[1]), feats.dtype)])
        if feats.shape[0] != n + 1:
            raise ValueError(
                f"features must be [N, D] or [N+1, D] for N={n} degrees,"
                f" got {feats.shape}")
        self.mesh = mesh
        self.axis = axis
        self.k = int(dict(mesh.shape).get(axis, 1))
        self.name = name or f"ptable{next(_STORE_IDS)}"
        if not 0.0 <= float(hub_cache_frac) < 1.0:
            raise ValueError(
                f"hub_cache_frac must be in [0, 1), got {hub_cache_frac}")
        self.hub_size = int(round(float(hub_cache_frac) * n))
        self.dev_rows = n if device_rows is None else int(device_rows)
        self.dev_rows = max(min(self.dev_rows, n), self.hub_size)
        self.host_rows = n - self.dev_rows
        self.pad_row = self.dev_rows
        # hub-first permutation, old row → device row. Host-resident
        # ranks shift +1 past the pad sentinel (which takes device row
        # dev_rows), so no rank collides with it.
        order = np.argsort(-degrees, kind="stable").astype(np.int64)
        rank = np.arange(n, dtype=np.int32)
        perm = np.empty(n + 1, np.int32)
        perm[order] = np.where(rank < self.dev_rows, rank, rank + 1)
        perm[n] = self.dev_rows                   # old pad → sentinel
        self.permutation = perm                   # old row → new row
        self.order = order                        # degree rank → old row
        # hub mass: the share of total degree the cached rows carry —
        # the expected gather-traffic reduction on a degree-biased
        # batch (a random edge endpoint is proportionally a hub)
        tot = float(degrees.sum())
        self.hub_mass = float(
            degrees[order[:self.hub_size]].sum() / tot) if tot else 0.0
        self.degree_max = int(degrees.max()) if n else 0
        self.degree_mean = float(degrees.mean()) if n else 0.0

        self.feature_scale = None
        if quantize == "int8":
            # scale computed over the FULL table so hub cache, shard and
            # reference share one quantization — parity stays byte-exact
            feats, scale = quantize_int8(np.asarray(feats, np.float32))
            self.feature_scale = put_replicated(
                scale.astype(np.dtype(scale_dtype), copy=False), mesh)
        elif quantize is not None:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        dev = np.empty((self.dev_rows + 1, feats.shape[1]), feats.dtype)
        np.take(feats, order[:self.dev_rows], axis=0, out=dev[:-1])
        dev[-1] = 0                               # pad sentinel row
        self.hub_cache = put_replicated(
            np.ascontiguousarray(dev[:self.hub_size]), mesh)
        self.features = put_row_sharded(dev, mesh, axis=axis)
        # optional replicated label table in the SAME permuted row space
        # (callers set it via apply_permutation + put_replicated — labels
        # are label_dim-wide, not worth sharding)
        self.labels = None
        self.dim = int(dev.shape[1])
        self._elem_bytes = dev.dtype.itemsize
        # per-chip byte accounting (the memory_plan formulas, live)
        shard_rows = -(-int(self.features.shape[0]) // max(self.k, 1))
        self.per_chip_bytes = (
            shard_rows * self.dim * self._elem_bytes
            + self.hub_size * self.dim * self._elem_bytes
            + (self.dim * 4 if self.feature_scale is not None else 0))
        self._wire_obs()

    def _wire_obs(self):
        reg = _obs.default_registry()
        lab = {"store": self.name}
        self._ctr = {
            leg: reg.counter(
                f"table_gather_{leg}_rows_total",
                h, ("store",)).labels(**lab)
            for leg, h in (
                ("local", "gathered rows owned by the requesting shard"),
                ("cached", "gathered rows served by the hub cache"),
                ("remote", "gathered rows crossing ICI (cold, non-local)"),
                ("host", "gathered rows served from host RAM overflow"),
            )}
        self._ctr_hub_hits = reg.counter(
            "hub_cache_hits_total",
            "table gathers answered by the replicated hub cache",
            ("store",)).labels(**lab)
        self._ctr_hub_misses = reg.counter(
            "hub_cache_misses_total",
            "table gathers past the hub cache (local + remote + host)",
            ("store",)).labels(**lab)
        self._g_hbm = reg.gauge(
            "table_hbm_bytes",
            "per-chip HBM bytes held by the partitioned table tier "
            "(shard + hub cache + scale)", ("store",)).labels(**lab)
        self._g_hbm.set(self.per_chip_bytes)
        _obs.register_health(self.name, self.cache_stats)

    # -- host side ---------------------------------------------------------
    def lookup(self, ids) -> np.ndarray:
        """u64 node ids → int32 DEVICE rows (hub-first space). Unknown
        ids map to the pad sentinel. Ids whose rows were evicted to the
        host tier are refused here — route them through
        lookup_with_overflow / fetch_host_rows instead (a silent pad
        would train on zeros where data exists)."""
        rows, host = self.lookup_with_overflow(ids)
        if host.any():
            raise ValueError(
                f"{int(host.sum())} of {host.size} ids resolve to "
                "host-overflow rows; use lookup_with_overflow() + "
                "fetch_host_rows() on this store (device_rows="
                f"{self.dev_rows} < {self.dev_rows + self.host_rows})")
        return rows

    def lookup_with_overflow(self, ids):
        """(device_rows int32, host_mask bool): host-resident ids come
        back with the pad sentinel in `device_rows` and True in
        `host_mask`; fetch their features with fetch_host_rows(ids)."""
        ids = np.asarray(ids, np.uint64).ravel()
        if self._graph is not None:
            old = self._graph.node_rows(
                ids, missing=len(self.permutation) - 1)
        else:
            old = np.minimum(ids.astype(np.int64),
                             len(self.permutation) - 1)
        new = self.permutation[np.asarray(old, np.int64)]
        host = new > self.dev_rows  # shifted ranks past the sentinel
        return (np.where(host, self.pad_row, new).astype(np.int32),
                host)

    def fetch_host_rows(self, ids) -> np.ndarray:
        """Dense features for host-overflow ids, via the
        CachedGraphEngine tier (deterministic reads cached client-side;
        retries/degrade per the wrapped engine). Counted as the 'host'
        gather leg."""
        if self._host_engine is None:
            raise ValueError("store has no host tier "
                             "(device_rows covers every row)")
        ids = np.asarray(ids, np.uint64).ravel()
        feats = self._host_engine.get_dense_feature(
            ids, list(self._feature_ids))
        if isinstance(feats, list):
            feats = np.concatenate(feats, axis=1)
        self._ctr["host"].inc(int(ids.size))
        self._ctr_hub_misses.inc(int(ids.size))
        return feats

    def apply_permutation(self, table: np.ndarray,
                          remap_values: bool = False) -> np.ndarray:
        """Permute a [N+1, ...] companion table (neighbor/cum/label
        rows in the ORIGINAL row space, trailing pad row) into this
        store's hub-first row space, so one set of int32 device rows
        indexes every table. remap_values=True additionally rewrites
        int32 row VALUES (neighbor ids) — the _degree_sort_tables
        contract. Host-overflow stores refuse: a neighbor value
        pointing at an evicted row has no device representation."""
        if self.host_rows:
            raise ValueError(
                "apply_permutation needs a fully device-resident store "
                f"(host_rows={self.host_rows}): companion tables cannot "
                "reference host-evicted rows")
        n = len(self.permutation) - 1
        if table.shape[0] != n + 1:
            raise ValueError(
                f"companion table has {table.shape[0]} rows, store row "
                f"space is {n + 1}")
        out = np.empty_like(table)
        np.take(table, self.order, axis=0, out=out[:-1])
        out[-1] = table[-1]                       # pad row kept verbatim
        if remap_values:
            np.take(self.permutation, out, out=out)
        return out

    def route_batch(self, rows) -> dict:
        """Deterministic per-batch traffic split for device rows [B]
        (duplicates count — the gather issues every row). Ring
        semantics: the flat batch splits into K contiguous position
        blocks (shard_map's P(axis) layout); a cold row is 'local' when
        its owner shard is the requesting block, 'remote' otherwise.
        The all-gather variant physically moves every non-hub row
        through the collective, so 'remote' is the hardware-traffic
        proxy both variants are judged by."""
        rows = np.asarray(rows).ravel()
        b = int(rows.size)
        hub = rows < self.hub_size
        if self.k <= 1:
            local = int((~hub).sum())
            remote = 0
        else:
            rows_per = int(self.features.shape[0]) // self.k
            owner = np.minimum(rows // max(rows_per, 1), self.k - 1)
            block = np.arange(b) * self.k // max(b, 1)
            local = int(((~hub) & (owner == block)).sum())
            remote = int(((~hub) & (owner != block)).sum())
        # strategy fed the SAME input make_gather('auto') uses (total
        # rows shipped — the exchanges don't deduplicate, and the
        # all-gather burst scales with B, not unique ids), so the
        # recorded strategy always matches the executed one
        return {"rows": b, "cached": int(hub.sum()), "local": local,
                "remote": remote,
                "strategy": pick_lookup_strategy(
                    b, self.k, self.dim, self._elem_bytes)}

    def observe_batch(self, rows) -> dict:
        """route_batch + bump the obs counters (the per-step
        table_gather_* split bench.py's detail.obs captures)."""
        r = self.route_batch(rows)
        self._ctr["cached"].inc(r["cached"])
        self._ctr["local"].inc(r["local"])
        self._ctr["remote"].inc(r["remote"])
        self._ctr_hub_hits.inc(r["cached"])
        self._ctr_hub_misses.inc(r["local"] + r["remote"])
        return r

    def cache_stats(self) -> dict:
        """Registry-backed stats view (the /healthz provider — same
        pattern as CachedGraphEngine.cache_stats)."""
        hits = int(self._ctr_hub_hits.value)
        misses = int(self._ctr_hub_misses.value)
        return {
            "k_shards": self.k,
            "hub_size": self.hub_size,
            "hub_mass": round(self.hub_mass, 6),
            "dev_rows": self.dev_rows,
            "host_rows": self.host_rows,
            "degree_max": self.degree_max,
            "degree_mean": round(self.degree_mean, 3),
            "per_chip_bytes": self.per_chip_bytes,
            "hub_hits": hits,
            "hub_misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 6),
            "gather_rows": {
                leg: int(c.value) for leg, c in self._ctr.items()},
        }

    # -- device side -------------------------------------------------------
    @property
    def tables(self) -> dict:
        """static_batch keys: the row-sharded table, the replicated hub
        cache (gather_feature_rows routes cache-first when present) and
        the int8 scale."""
        out = {"feature_table": self.features}
        if self.hub_size > 0:
            out["hub_cache"] = self.hub_cache
        if self.feature_scale is not None:
            out["feature_scale"] = self.feature_scale
        if self.labels is not None:
            out["label_table"] = self.labels
        return out

    def make_gather(self, strategy: str = "auto",
                    n_ids_hint: Optional[int] = None):
        """gather(rows) → rows' features on the mesh, byte-identical to
        reference_lookup on the unpartitioned table.

        strategy: 'allgather' (masked-answer + reduce-scatter, 2
        collective launches), 'ring' (K-step ppermute, 1/K peak
        footprint), or 'auto' — the pick_lookup_strategy cost model on
        batch ids shipped × K (n_ids_hint, else resolved per call from
        the row count; route_batch records the same pick). An unpartitioned store (K == 1) always takes
        the plain local path. Hub rows are routed cache-first in every
        strategy. Rows are padded to a multiple of K with the pad
        sentinel (sliced back off), so any batch length works; each
        strategy jit-compiles once and is cached."""
        if strategy not in ("auto", "allgather", "ring"):
            raise ValueError(f"unknown gather strategy {strategy!r}")
        if self.k <= 1:
            routed = hub_routed_take(
                lambda t, r: jnp.take(t, r, axis=0), self.hub_cache)
            return lambda rows: jax.jit(routed)(self.features, rows)

        def exchange(kind):
            fn = ring_lookup if kind == "ring" else allgather_lookup

            def base(table, rows):
                b = rows.shape[0]
                pad = (-b) % self.k
                if pad:
                    rows = jnp.concatenate(
                        [rows, jnp.full((pad,), self.pad_row,
                                        rows.dtype)])
                # pin REPLICATED before shard_map: on a mesh with a
                # non-trivial data axis, GSPMD may shard this in-jit
                # intermediate over 'data' and the implicit reshard to
                # P(axis) reads wrong values on jax without pvary/pcast
                # (observed on 0.4.37); no-op when already replicated
                from jax.sharding import NamedSharding, PartitionSpec

                rows = jax.lax.with_sharding_constraint(
                    rows, NamedSharding(self.mesh, PartitionSpec()))
                out = fn(table, rows, self.mesh, self.axis)
                return out[:b] if pad else out

            return hub_routed_take(base, self.hub_cache)

        jitted = {}

        def gather(rows):
            kind = strategy
            if kind == "auto":
                n = n_ids_hint or int(rows.shape[0])
                kind = pick_lookup_strategy(n, self.k, self.dim,
                                            self._elem_bytes)
            if kind not in jitted:
                jitted[kind] = jax.jit(exchange(kind))
            return jitted[kind](self.features, rows)

        return gather
