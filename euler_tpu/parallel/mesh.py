"""Device-mesh utilities for SPMD training.

This replaces the reference's distribution machinery (TF parameter servers
+ between-graph replication, tf_euler/scripts/dist_tf_euler.sh, SURVEY.md
§2.4): data parallelism and embedding-table model parallelism are
expressed as shardings over a jax.sharding.Mesh, and XLA GSPMD inserts the
ICI collectives (all-reduce for gradients, all-gather / reduce-scatter
for sharded tables).

Axes convention: 'data' = batch-parallel replicas, 'model' = parameter
(embedding-row) sharding. A v5e-16 slice would be Mesh((4, 4),
('data', 'model')) or (16, 1) for pure DP.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_sharding", "replicated", "shard_batch",
           "mesh_shape_for"]


def mesh_shape_for(n_devices: int, model_parallel: int = 1) -> Tuple[int, int]:
    assert n_devices % model_parallel == 0
    return (n_devices // model_parallel, model_parallel)


def make_mesh(model_parallel: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    dp, mp = mesh_shape_for(len(devices), model_parallel)
    arr = np.asarray(devices).reshape(dp, mp)
    return Mesh(arr, ("data", "model"))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch arrays: leading axis over 'data'."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# batch keys that carry HBM-resident lookup tables rather than per-step
# data — replicated by default in shard_batch unless the caller already
# placed them (e.g. row-sharded over 'model' via put_row_sharded).
# hub_cache (PartitionedFeatureStore's replicated hot-row tier) rides
# here too: splitting it over 'data' would turn the cache-first fast
# path into a collective.
REPLICATED_TABLE_KEYS = ("feature_table", "feature_scale", "label_table",
                         "nbr_table", "cum_table", "nbrcum_table",
                         "alias_table", "hub_cache")


def shard_batch(batch: Dict, mesh: Mesh,
                replicated_keys=REPLICATED_TABLE_KEYS) -> Dict:
    """device_put every array in the batch with its leading axis split over
    'data' (arrays whose leading dim doesn't divide fall back to
    replication — e.g. scalar counts). Top-level keys in replicated_keys
    are replicated unless the caller already placed them on THIS mesh
    (NamedSharding — e.g. row-sharded over 'model' via put_row_sharded),
    in which case their placement is kept. They are never sharded over
    'data': HBM-resident lookup tables (feature/label/neighbor) split by
    batch would turn every in-step gather into a cross-device
    collective."""
    dsh = data_sharding(mesh)
    rsh = replicated(mesh)
    n_data = mesh.shape["data"]

    def put(v):
        # no np.asarray on jax arrays: that would gather device-resident
        # tables back to host; device_put is a no-op when already placed
        shape = getattr(v, "shape", None)
        if shape is None:
            v = np.asarray(v)
            shape = v.shape
        if len(shape) >= 1 and shape[0] % n_data == 0 and shape[0] > 0:
            return jax.device_put(v, dsh)
        return jax.device_put(v, rsh)

    def put_table(x):
        # tables the caller already placed on THIS mesh keep their
        # placement: force-replicating a row-sharded table
        # (placement.put_row_sharded over 'model') would all-gather the
        # full table onto every chip, defeating the HBM-capacity lever
        # in exactly the regime it exists for. Tables placed on a
        # DIFFERENT mesh are re-placed replicated as before — keeping a
        # stale device assignment would fail inside jit.
        if isinstance(x, jax.Array) and isinstance(
                getattr(x, "sharding", None), NamedSharding) \
                and x.sharding.mesh == mesh \
                and "data" not in jax.tree_util.tree_leaves(
                    tuple(x.sharding.spec)):
            return x
        return jax.device_put(x, rsh)

    if not isinstance(batch, dict):
        return jax.tree_util.tree_map(put, batch)
    out = {}
    for k, v in batch.items():
        if k in replicated_keys:
            out[k] = jax.tree_util.tree_map(put_table, v)
        else:
            out[k] = jax.tree_util.tree_map(put, v)
    return out
