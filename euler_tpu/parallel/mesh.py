"""Device-mesh utilities for SPMD training.

This replaces the reference's distribution machinery (TF parameter servers
+ between-graph replication, tf_euler/scripts/dist_tf_euler.sh, SURVEY.md
§2.4): data parallelism and embedding-table model parallelism are
expressed as shardings over a jax.sharding.Mesh, and XLA GSPMD inserts the
ICI collectives (all-reduce for gradients, all-gather / reduce-scatter
for sharded tables).

Axes convention: 'data' = batch-parallel replicas, 'model' = parameter
(embedding-row) sharding. A v5e-16 slice would be Mesh((4, 4),
('data', 'model')) or (16, 1) for pure DP.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_sharding", "replicated", "shard_batch",
           "mesh_shape_for"]


def mesh_shape_for(n_devices: int, model_parallel: int = 1) -> Tuple[int, int]:
    assert n_devices % model_parallel == 0
    return (n_devices // model_parallel, model_parallel)


def make_mesh(model_parallel: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    dp, mp = mesh_shape_for(len(devices), model_parallel)
    arr = np.asarray(devices).reshape(dp, mp)
    return Mesh(arr, ("data", "model"))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch arrays: leading axis over 'data'."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: Dict, mesh: Mesh) -> Dict:
    """device_put every array in the batch with its leading axis split over
    'data' (arrays whose leading dim doesn't divide fall back to
    replication — e.g. scalar counts)."""
    dsh = data_sharding(mesh)
    rsh = replicated(mesh)
    n_data = mesh.shape["data"]

    def put(v):
        a = np.asarray(v)
        if a.ndim >= 1 and a.shape[0] % n_data == 0 and a.shape[0] > 0:
            return jax.device_put(a, dsh)
        return jax.device_put(a, rsh)

    return jax.tree_util.tree_map(put, batch)
