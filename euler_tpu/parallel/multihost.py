"""Multi-host training bootstrap.

Parity: the reference's PS/worker launch scripts
(tf_euler/scripts/dist_tf_euler.sh:28-43 — per-host TF_CONFIG wiring +
worker exit barrier, hooks.py:25 SyncExitHook). TPU-native redesign:
no parameter servers — every host joins one jax.distributed job
(coordination service), the global device mesh spans all hosts, and XLA
GSPMD moves gradients/embeddings over ICI/DCN collectives. The graph
service remains a separate host-side cluster each trainer host connects
to (RemoteGraphEngine over the registry), exactly like the reference's
worker ↔ euler-shard split (SURVEY.md §3.4).

Typical per-host entry (see tools/launch_multihost.py):

    cfg = MultihostConfig(coordinator="10.0.0.1:9999",
                          num_processes=4, process_id=host_idx)
    initialize_multihost(cfg)
    mesh = make_mesh(model_parallel=2)        # global devices
    remote = RemoteGraphEngine(f"dir:{registry}")  # graph cluster
    ... train ...
    finalize_multihost(barrier_dir, cfg)      # exit rendezvous
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional


@dataclass
class MultihostConfig:
    coordinator: str          # "host:port" of process 0
    num_processes: int
    process_id: int

    @classmethod
    def from_env(cls) -> "MultihostConfig":
        """EULER_TPU_COORDINATOR / _NUM_HOSTS / _HOST_IDX (the launcher
        sets these; on cloud TPU pods jax.distributed auto-detects and
        this config is unnecessary)."""
        return cls(
            coordinator=os.environ["EULER_TPU_COORDINATOR"],
            num_processes=int(os.environ["EULER_TPU_NUM_HOSTS"]),
            process_id=int(os.environ["EULER_TPU_HOST_IDX"]),
        )


def initialize_multihost(cfg: Optional[MultihostConfig] = None) -> int:
    """Joins the jax.distributed job and returns this process's id.

    Must run before the first jax device query. With cfg=None, tries the
    environment (launcher-set vars), then jax's own auto-detection
    (TPU pods); single-process if neither applies.
    """
    import jax

    if cfg is None:
        try:
            cfg = MultihostConfig.from_env()
        except KeyError:
            cfg = None
    if cfg is None:
        # no launcher vars — let jax auto-detect the cluster (TPU pods,
        # SLURM, GKE); argless initialize raises RuntimeError/ValueError
        # where no cluster env exists, which is the single-process case.
        # Only THOSE are swallowed (with a warning carrying the error):
        # a genuinely misconfigured cluster failing some other way must
        # not silently train single-process.
        try:
            jax.distributed.initialize()
            return jax.process_index()
        except (RuntimeError, ValueError) as e:
            logging.getLogger(__name__).warning(
                "jax.distributed.initialize() auto-detect failed; "
                "continuing single-process (set EULER_TPU_COORDINATOR/"
                "_NUM_HOSTS/_HOST_IDX to force a cluster): %s", e)
            return 0
    if cfg.num_processes <= 1:
        return 0
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    assert jax.process_count() == cfg.num_processes
    return cfg.process_id


def finalize_multihost(barrier_dir: Optional[str] = None,
                       cfg: Optional[MultihostConfig] = None,
                       run_id: str = "exit") -> None:
    """Worker exit rendezvous (reference SyncExitHook, hooks.py:25): a
    host that finishes early keeps serving collectives until everyone
    arrives, then all shut down together."""
    import jax

    if jax.process_count() <= 1:
        return
    if barrier_dir:
        from euler_tpu.utils.hooks import FileBarrier

        n = cfg.num_processes if cfg else jax.process_count()
        pid = cfg.process_id if cfg else jax.process_index()
        FileBarrier(barrier_dir, n, run_id=run_id).wait(pid)
    jax.distributed.shutdown()


def process_batch_slice(global_batch: int) -> slice:
    """This host's rows of a globally-sharded batch: host i feeds
    devices [i·L, (i+1)·L) of the 'data' axis, so it samples only its
    slice of each global batch (per-host graph clients, no broadcast)."""
    import jax

    n, i = jax.process_count(), jax.process_index()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n} hosts")
    per = global_batch // n
    return slice(i * per, (i + 1) * per)
