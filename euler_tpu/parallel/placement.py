"""Shared device placement for HBM-resident input tables
(DeviceFeatureStore, DeviceNeighborTable).

Two policies, one helper module so the table classes cannot diverge:

- put_replicated: every chip holds the full table; per-step gathers stay
  local, no collective per step. Right when the graph fits one chip's
  HBM — the single-chip bench configuration.
- put_row_sharded: rows split over the mesh's 'model' axis (the
  reference's PS-sharded embedding capability, tf_euler/python/utils/
  layers.py:119-171): per-chip memory shrinks ~linearly with the model
  axis, and gathers become a masked local take + psum over 'model'
  (device_sampler.make_table_gather). Right when the graph outgrows one
  chip.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def _global_put(x: np.ndarray, sharding) -> jax.Array:
    """device_put that also works when `sharding` spans OTHER hosts'
    devices (multi-process mesh): every process calls this with the
    same full array and contributes its addressable shards."""
    if all(d.process_index == jax.process_index()
           for d in sharding.device_set):
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding,
                                        lambda idx: x[idx])


def put_replicated(x: np.ndarray,
                   mesh: Optional[jax.sharding.Mesh] = None) -> jax.Array:
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        return _global_put(x, NamedSharding(mesh, PartitionSpec()))
    return jax.device_put(x)


def put_row_sharded(x: np.ndarray, mesh: Optional[jax.sharding.Mesh],
                    axis: str = "model") -> jax.Array:
    """Rows over `axis`; falls back to replication when the mesh has no
    (or a trivial) model axis. Rows are zero-padded up to a multiple of
    the axis size — the pad rows sit PAST the table's own trailing
    pad_row, so no live index ever reaches them."""
    if mesh is None or dict(mesh.shape).get(axis, 1) <= 1:
        return put_replicated(x, mesh)
    from jax.sharding import NamedSharding, PartitionSpec

    mp = dict(mesh.shape)[axis]
    pad = (-x.shape[0]) % mp
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    spec = PartitionSpec(axis, *([None] * (x.ndim - 1)))
    return _global_put(x, NamedSharding(mesh, spec))
