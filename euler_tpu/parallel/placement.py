"""Shared device placement for HBM-resident input tables
(DeviceFeatureStore, DeviceNeighborTable): replicated across the mesh so
per-step gathers stay local — no collective per step. One helper so the
two table classes cannot diverge in placement policy."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def put_replicated(x: np.ndarray,
                   mesh: Optional[jax.sharding.Mesh] = None) -> jax.Array:
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    return jax.device_put(x)
