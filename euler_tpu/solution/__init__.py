from euler_tpu.solution.base_solution import (  # noqa: F401
    CosineLogits,
    DenseLogits,
    PosNegLogits,
    PosNegSampler,
    SuperviseSolution,
    UnsuperviseSolution,
    sigmoid_loss,
    xent_loss,
)
