"""Composable industrial pipeline ("solution" layer).

Parity: tf_euler/python/solution/ (SuperviseSolution / UnsuperviseSolution
base_sample.py:28-95, pluggable logits.py / losses.py / samplers.py).
A Solution wires: a root sampler → encoder model → logits head → loss,
and yields estimator-ready input_fns — the "assemble a production model
from parts" API.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from euler_tpu.dataflow import FanoutDataFlow
from euler_tpu.graph import GraphEngine
from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.utils import metrics as M
from euler_tpu.utils.encoders import SageEncoder

Array = jax.Array


# ---- logits heads (solution/logits.py parity) ----
class DenseLogits(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, emb: Array, ctx: Optional[Array] = None) -> Array:
        return nn.Dense(self.num_classes, name="logits")(emb)


class PosNegLogits(nn.Module):
    """Dot-product scores for (emb, pos, negs)."""

    @nn.compact
    def __call__(self, emb: Array, pos: Array, negs: Array):
        pos_logit = jnp.einsum("bd,bkd->bk", emb, pos)
        neg_logit = jnp.einsum("bd,bkd->bk", emb, negs)
        return pos_logit, neg_logit


class CosineLogits(nn.Module):
    scale: float = 10.0

    @nn.compact
    def __call__(self, emb: Array, pos: Array, negs: Array):
        def norm(v):
            return v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True),
                                   1e-12)
        emb, pos, negs = norm(emb), norm(pos), norm(negs)
        return (self.scale * jnp.einsum("bd,bkd->bk", emb, pos),
                self.scale * jnp.einsum("bd,bkd->bk", emb, negs))


# ---- losses (solution/losses.py parity) ----
def sigmoid_loss(pos_logit: Array, neg_logit: Array) -> Array:
    return (optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean())


def xent_loss(logits: Array, labels: Array) -> Array:
    if labels.ndim == logits.ndim:
        return optax.softmax_cross_entropy(
            logits, labels.astype(jnp.float32)).mean()
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels.astype(jnp.int32)).mean()


# ---- samplers (solution/samplers.py parity) ----
class PosNegSampler:
    """Positives from neighbors (optionally typed), negatives globally
    (reference SamplePosWithTypes:42 / SampleNegWithTypes:23)."""

    def __init__(self, graph: GraphEngine, num_negs: int = 5,
                 pos_edge_types=None, neg_node_type: int = -1):
        self.graph = graph
        self.num_negs = num_negs
        self.pos_edge_types = pos_edge_types
        self.neg_node_type = neg_node_type

    def __call__(self, roots: np.ndarray) -> Dict[str, np.ndarray]:
        pos, _, _ = self.graph.sample_neighbor(
            roots, 1, edge_types=self.pos_edge_types)
        negs = self.graph.sample_node(
            len(roots) * self.num_negs, self.neg_node_type
        ).reshape(len(roots), self.num_negs)
        return {"pos": pos[:, 0], "negs": negs}


# ---- solutions ----
class _SageSupModel(nn.Module):
    dim: int
    fanouts: Sequence[int]
    num_classes: int
    multilabel: bool

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = SageEncoder(self.dim, tuple(self.fanouts), name="enc")(
            batch["layers"])
        logits = DenseLogits(self.num_classes, name="head")(emb)
        labels = batch["labels"]
        if self.multilabel:
            loss = optax.sigmoid_binary_cross_entropy(
                logits, labels.astype(jnp.float32)).sum(-1).mean()
            metric = M.micro_f1(jax.nn.sigmoid(logits), labels)
        else:
            loss = xent_loss(logits, labels)
            metric = M.micro_f1(
                logits, jnp.argmax(labels, -1) if labels.ndim > 1 else labels)
        return ModelOutput(emb, loss, "f1", metric)


class _SageUnsupModel(nn.Module):
    dim: int
    fanouts: Sequence[int]
    max_id: int
    logits_name: str = "dot"

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        from euler_tpu.utils.layers import Embedding

        emb = SageEncoder(self.dim, tuple(self.fanouts), concat=False,
                          name="enc")(batch["layers"])
        ctx = Embedding(self.max_id + 1, self.dim, name="ctx")
        pos = ctx(batch["pos"])[:, None, :]
        negs = ctx(batch["negs"])
        head = (CosineLogits(name="head") if self.logits_name == "cosine"
                else PosNegLogits(name="head"))
        pos_logit, neg_logit = head(emb, pos, negs)
        loss = sigmoid_loss(pos_logit, neg_logit)
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        return ModelOutput(emb, loss, "mrr", M.mrr(scores))


class SuperviseSolution:
    """Supervised node classification assembled from parts."""

    def __init__(self, graph: GraphEngine, fanouts=(10, 10), dim=64,
                 num_classes=2, multilabel=False, feature_ids=("feature",),
                 label_fid="label", batch_size=64, train_node_type=0):
        self.graph = graph
        self.flow = FanoutDataFlow(graph, list(fanouts),
                                   feature_ids=list(feature_ids))
        self.model = _SageSupModel(dim, tuple(fanouts), num_classes,
                                   multilabel)
        self.label_fid = label_fid
        self.num_classes = num_classes
        self.batch_size = batch_size
        self.train_node_type = train_node_type

    def input_fn(self, node_type: Optional[int] = None) -> Iterator[Dict]:
        nt = self.train_node_type if node_type is None else node_type
        while True:
            roots = self.graph.sample_node(self.batch_size, nt)
            batch = self.flow(roots)
            batch["labels"] = self.graph.get_dense_feature(
                roots, self.label_fid, self.num_classes)
            batch["infer_ids"] = roots
            yield batch


class UnsuperviseSolution:
    """Unsupervised embedding learning assembled from parts."""

    def __init__(self, graph: GraphEngine, fanouts=(10, 10), dim=64,
                 max_id=0, num_negs=5, feature_ids=("feature",),
                 batch_size=64, logits="dot", pos_edge_types=None):
        self.graph = graph
        self.flow = FanoutDataFlow(graph, list(fanouts),
                                   feature_ids=list(feature_ids))
        self.sampler = PosNegSampler(graph, num_negs, pos_edge_types)
        self.model = _SageUnsupModel(dim, tuple(fanouts), max_id, logits)
        self.batch_size = batch_size

    def input_fn(self) -> Iterator[Dict]:
        while True:
            roots = self.graph.sample_node(self.batch_size, -1)
            batch = self.flow(roots)
            batch.update(self.sampler(roots))
            batch["infer_ids"] = roots
            yield batch
