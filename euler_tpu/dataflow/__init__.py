from euler_tpu.dataflow.base_dataflow import (  # noqa: F401
    Block,
    DataFlow,
    FanoutDataFlow,
    FastGCNDataFlow,
    FullBatchDataFlow,
    LayerwiseDataFlow,
    RelationDataFlow,
    WholeDataFlow,
)

# Reference-name aliases (tf_euler/python/dataflow/): SageDataFlow and
# NeighborDataFlow are fanout-based; GCNDataFlow's full-neighbor mode is
# WholeDataFlow.
SageDataFlow = FanoutDataFlow
NeighborDataFlow = FanoutDataFlow
GCNDataFlow = WholeDataFlow
# UniqueDataFlow's dedup-per-hop geometry is WholeDataFlow's unique node
# table + edge_index; LayerwiseEach shares LayerwiseDataFlow's sampler.
UniqueDataFlow = WholeDataFlow
LayerwiseEachDataFlow = LayerwiseDataFlow
