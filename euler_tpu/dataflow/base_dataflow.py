"""Mini-batch subgraph builders ("dataflows").

Parity: tf_euler/python/dataflow/ (DataFlow/Block base_dataflow.py:22-37,
SageDataFlow, GCNDataFlow, FastGCNDataFlow, LayerwiseDataFlow,
WholeDataFlow, RelationDataFlow, NeighborDataFlow/UniqueDataFlow).

TPU-first redesign: a dataflow is a host-side callable
roots → batch dict of fixed-shape numpy arrays (the same roots count →
the same shapes every step, so the jitted train step never recompiles).
Two batch geometries are produced:

  * fanout batches — per-hop node ids + features; hop h has exactly
    n_roots·Πk_{≤h} rows (sampling pads with default_id). Feeds the dense
    encoders (euler_tpu.utils.encoders) — no scatter on device.
  * edge_index batches — a node table + [2, E] edge list for the conv zoo
    (whole-graph or k-hop closure training, Cora-scale).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from euler_tpu.graph import GraphEngine


@dataclass
class Block:
    """One hop of a sampled subgraph (parity: reference Block
    base_dataflow.py:22 — n_id, res_n_id, edge_index, size)."""

    n_id: np.ndarray          # [n_src] source node ids (uint64)
    res_n_id: np.ndarray      # [n_tgt] target node ids
    edge_index: np.ndarray    # [2, E] int32 (src_row, tgt_row)
    size: tuple               # (n_src, n_tgt)


class DataFlow:
    """Base: fetches features for id tensors; subclasses build topology."""

    def __init__(self, graph: GraphEngine, feature_ids: Sequence = (),
                 feature_dims: Optional[Sequence[int]] = None,
                 default_id: int = 0):
        self.graph = graph
        self.feature_ids = list(feature_ids)
        self.feature_dims = list(feature_dims) if feature_dims else None
        self.default_id = default_id

    def features(self, ids: np.ndarray) -> np.ndarray:
        """Concatenated dense features [n, sum(dims)] for ids."""
        if not self.feature_ids:
            raise ValueError("dataflow has no feature_ids configured")
        feats = self.graph.get_dense_feature(ids, self.feature_ids,
                                             self.feature_dims)
        if isinstance(feats, list):
            return np.concatenate(feats, axis=1)
        return feats

    def __call__(self, roots: np.ndarray) -> Dict:
        raise NotImplementedError


class FanoutDataFlow(DataFlow):
    """Multi-hop fanout batches (≈ reference SageDataFlow/NeighborDataFlow).

    Batch dict:
      ids:    list of L+1 uint64 arrays, ids[0] = roots
      layers: list of L+1 float32 feature arrays (if feature_ids set)
      weights/types: per-hop sample metadata (optional use)
    """

    def __init__(self, graph, fanouts: Sequence[int], edge_types=None,
                 with_features: bool = True, **kw):
        super().__init__(graph, **kw)
        self.fanouts = list(fanouts)
        self.edge_types = edge_types
        self.with_features = with_features

    def __call__(self, roots: np.ndarray) -> Dict:
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        ids, w, t = self.graph.sample_fanout(
            roots, self.fanouts, edge_types=self.edge_types,
            default_id=self.default_id)
        all_ids = [roots] + ids
        batch = {"ids": all_ids, "weights": w, "types": t}
        if self.with_features and self.feature_ids:
            batch["layers"] = [self.features(i) for i in all_ids]
        return batch


class WholeDataFlow(DataFlow):
    """Full 1-hop closure as an edge_index batch (reference WholeDataFlow
    whole_dataflow.py:26; also serves GCNDataFlow's full-neighbor mode).

    Returns the batch nodes plus ALL their neighbors, deduplicated, with a
    local edge_index. Shapes vary with the closure size — pad_to_multiple
    rounds table/edge sizes up so jit recompiles are bounded (bucketing).
    """

    def __init__(self, graph, edge_types=None, hops: int = 1,
                 pad_to_multiple: int = 256, **kw):
        super().__init__(graph, **kw)
        self.edge_types = edge_types
        self.hops = hops
        self.pad = pad_to_multiple

    def __call__(self, roots: np.ndarray) -> Dict:
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        frontier = roots
        nodes = [roots]
        src_rows: List[np.ndarray] = []
        dst_rows: List[np.ndarray] = []
        edges_src: List[np.ndarray] = []
        edges_dst: List[np.ndarray] = []
        for _ in range(self.hops):
            off, nbr, w, t = self.graph.get_full_neighbor(
                frontier, edge_types=self.edge_types)
            counts = np.diff(off).astype(np.int64)
            e_dst = np.repeat(frontier, counts)
            edges_src.append(nbr)
            edges_dst.append(e_dst)
            frontier = np.unique(nbr)
            nodes.append(frontier)
        node_table = np.unique(np.concatenate(nodes))
        # np.unique returns sorted ids → local rows via binary search
        src = np.concatenate(edges_src) if edges_src else np.zeros(0, np.uint64)
        dst = np.concatenate(edges_dst) if edges_dst else np.zeros(0, np.uint64)
        src_idx = np.searchsorted(node_table, src).astype(np.int32)
        dst_idx = np.searchsorted(node_table, dst).astype(np.int32)
        root_idx = np.searchsorted(node_table, roots).astype(np.int32)
        n_real = len(node_table)
        # pad table and edges to bucket boundaries for bounded recompiles
        n_pad = -len(node_table) % self.pad
        e_pad = -len(src_idx) % self.pad
        node_table = np.concatenate(
            [node_table, np.full(n_pad, self.default_id, np.uint64)])
        pad_row = len(node_table) - 1 if n_pad else 0
        src_idx = np.concatenate([src_idx, np.full(e_pad, pad_row, np.int32)])
        dst_idx = np.concatenate([dst_idx, np.full(e_pad, pad_row, np.int32)])
        batch = {
            "nodes": node_table,
            "edge_index": np.stack([src_idx, dst_idx]).astype(np.int32),
            "root_index": root_idx,
            "n_real_nodes": n_real,
            "n_real_edges": len(src),
        }
        if self.feature_ids:
            batch["x"] = self.features(node_table)
        return batch


class FullBatchDataFlow(DataFlow):
    """Whole-graph batches (Cora-scale transductive training): the node
    table and edge_index are the entire graph, built once and cached;
    per-step only root_index varies. The reference's GCN examples train
    this way through GCNDataFlow's full-neighbor mode."""

    def __init__(self, graph, edge_types=None, **kw):
        super().__init__(graph, **kw)
        self.edge_types = edge_types
        self._static: Optional[Dict] = None

    def _build_static(self) -> Dict:
        nodes = np.sort(self.graph.all_node_ids())
        off, nbr, w, t = self.graph.get_full_neighbor(
            nodes, edge_types=self.edge_types)
        counts = np.diff(off).astype(np.int64)
        src_ids = nbr
        dst_ids = np.repeat(nodes, counts)
        src_idx = np.searchsorted(nodes, src_ids).astype(np.int32)
        dst_idx = np.searchsorted(nodes, dst_ids).astype(np.int32)
        static = {
            "nodes": nodes,
            "edge_index": np.stack([src_idx, dst_idx]),
            "edge_weight": w.astype(np.float32),
            "edge_type": t.astype(np.int32),
        }
        if self.feature_ids:
            static["x"] = self.features(nodes)
        return static

    def __call__(self, roots: np.ndarray) -> Dict:
        if self._static is None:
            self._static = self._build_static()
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        batch = dict(self._static)
        batch["root_index"] = np.searchsorted(
            self._static["nodes"], roots).astype(np.int32)
        return batch


class LayerwiseDataFlow(DataFlow):
    """LADIES-style layerwise batches (reference layerwise_dataflow.py:26):
    per-layer importance-sampled pools + dense inter-pool adjacency."""

    def __init__(self, graph, layer_sizes: Sequence[int], edge_types=None,
                 sample: bool = True, **kw):
        """sample=False expands exact 1-hop closures instead of sampled
        pools — FastGCN's standard eval mode (train with importance
        sampling, evaluate with the full propagation matrix)."""
        super().__init__(graph, **kw)
        self.layer_sizes = list(layer_sizes)
        self.edge_types = edge_types
        self.sample = sample

    def _dense_adj(self, rows: np.ndarray, cols: np.ndarray) -> np.ndarray:
        """Row-normalized dense adjacency [len(rows), len(cols)] of
        Â = A + I restricted to the sampled pool (FastGCN/LADIES use the
        self-loop-augmented GCN propagation matrix — without the diagonal
        a root whose neighbors missed the pool gets a zero embedding).

        Vectorized: each (edge, matching-col) pair is expanded via
        searchsorted ranges over the sorted col array — duplicate pool
        columns each receive the edge weight, and edge writes land in
        edge order (later parallel edges overwrite earlier, matching
        the original per-edge loop)."""
        rows = np.asarray(rows, np.uint64)
        cols_arr = np.asarray(cols, np.uint64)
        order = np.argsort(cols_arr, kind="stable")
        sc = cols_arr[order]
        adj = np.zeros((len(rows), len(cols_arr)), dtype=np.float32)
        off, nbr, w, _ = self.graph.get_full_neighbor(
            rows, edge_types=self.edge_types)

        def expand(ids, per_id_row):
            """(row, col, run-length) triples for every position of
            each id in the sorted col array."""
            lo = np.searchsorted(sc, ids)
            hi = np.searchsorted(sc, ids, side="right")
            cnt = (hi - lo).astype(np.int64)
            total = int(cnt.sum())
            if total == 0:
                return (np.empty(0, np.int64), np.empty(0, np.int64),
                        np.empty(0, np.int64))
            rep = np.repeat(np.arange(len(ids)), cnt)
            pos_in_run = np.arange(total) - np.repeat(
                np.cumsum(cnt) - cnt, cnt)
            cpos = order[np.repeat(lo, cnt) + pos_in_run]
            return per_id_row[rep], cpos, rep

        edge_row = np.repeat(np.arange(len(rows)),
                             np.diff(off).astype(np.int64))
        er, ec, eidx = expand(nbr, edge_row)
        adj[er, ec] = w[eidx]
        sr, scol, _ = expand(rows, np.arange(len(rows)))
        # (row, col) pairs cannot repeat (distinct positions per sorted
        # run, one run per row), so plain fancy += is exact — and faster
        # than an unbuffered np.add.at scatter
        adj[sr, scol] += 1.0
        norm = adj.sum(axis=1, keepdims=True)
        return adj / np.maximum(norm, 1e-12)

    def __call__(self, roots: np.ndarray) -> Dict:
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        levels = [roots]
        if self.sample:
            pools = self.graph.sample_layerwise(
                roots, self.layer_sizes, edge_types=self.edge_types,
                default_id=self.default_id)
            # LADIES-style connectivity guarantee: each level's pool also
            # contains the previous level's nodes, so self-loops always
            # have a column to land on (reference layerwise_dataflow.py
            # unions the batch into the sampled layer).
            for p in pools:
                levels.append(np.concatenate([levels[-1], p]))
        else:
            for _ in self.layer_sizes:
                _, nbr, _, _ = self.graph.get_full_neighbor(
                    levels[-1], edge_types=self.edge_types)
                levels.append(np.unique(np.concatenate([levels[-1], nbr])))
        adjs = [self._dense_adj(levels[i], levels[i + 1])
                for i in range(len(levels) - 1)]
        batch = {"ids": levels, "adjs": adjs}
        if self.feature_ids:
            batch["layers"] = [self.features(i) for i in levels]
        return batch


class FastGCNDataFlow(LayerwiseDataFlow):
    """FastGCN = layerwise sampling with per-layer independent pools
    (reference fastgcn via LayerwiseEachDataFlow); the engine's layerwise
    sampler already importance-samples per layer, so this shares the
    implementation with distinct default layer sizes."""


class RelationDataFlow(DataFlow):
    """Per-edge-type fanout batches for relational models (reference
    relation_dataflow.py:25): one fanout per relation, stacked."""

    def __init__(self, graph, fanout: int, num_relations: int, **kw):
        super().__init__(graph, **kw)
        self.fanout = fanout
        self.num_relations = num_relations

    def __call__(self, roots: np.ndarray) -> Dict:
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        per_rel_ids = []
        per_rel_w = []
        for r in range(self.num_relations):
            nb, w, _ = self.graph.sample_neighbor(
                roots, self.fanout, edge_types=[r], default_id=self.default_id)
            per_rel_ids.append(nb)
            per_rel_w.append(w)
        batch = {
            "ids": roots,
            "nbr_ids": np.stack(per_rel_ids),   # [R, B, K]
            "nbr_weights": np.stack(per_rel_w),
        }
        if self.feature_ids:
            batch["x"] = self.features(roots)
            batch["nbr_x"] = np.stack(
                [self.features(i.ravel()).reshape(len(roots), self.fanout, -1)
                 for i in per_rel_ids])
        return batch
