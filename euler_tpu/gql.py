"""GQL query interface: gremlin-style strings against the graph engine.

Capability parity with the reference's euler.Query/QueryProxy surface
(euler/client/query.h:33, query_proxy.h:39 — SURVEY.md §2.1) and the
`initialize_graph` remote/local mode switch (tf_euler/python/euler_ops/
base.py:37). A `Query` object targets either an embedded in-process graph
(local mode: compile → fuse → execute on the host thread pool) or a set of
remote graph shards (distribute mode: compile → split/REMOTE/merge over
framed-TCP RPC), transparently to the caller::

    q = Query.local(engine, index_spec="price:range_index")
    out = q.run("sampleN(0, 64).has(price gt 3).values(f).as(feat)",
                )
    ids = out["feat:1"]

    server = start_service(data_dir, shard_idx=0, shard_num=2, port=9190)
    q = Query.remote("hosts:127.0.0.1:9190,127.0.0.1:9191")

Supported chain calls (see euler_tpu/core/cc/gql.h for the grammar):
v, e, gl, sampleN, sampleE, sampleNWithTypes, sampleGL, graphNodes,
sampleNB, sampleLNB, getNB/outV, getRNB/inV, getSortedNB, getTopKNB,
values, udf, label, has, hasLabel, hasKey, hasId, orderBy, limit, as.
"""

from __future__ import annotations

import ctypes
import os
import threading
import weakref
from typing import Dict, Optional

import numpy as np

from euler_tpu.core import lib as _libmod
from euler_tpu.core.lib import EngineError, check

__all__ = ["Query", "GraphService", "start_service", "compile_debug",
           "register_udf", "udf_cache_stats", "udf_cache_clear",
           "udf_cache_set_capacity", "edge_types_str", "wal_stats",
           "push_ownership", "server_trace_hist", "server_trace_spans",
           "server_trace_chrome", "store_stats", "cold_read_quantile"]


def edge_types_str(edge_types) -> str:
    """GQL edge-type argument convention: None/empty → "*" (all types),
    else colon-joined ids — the single definition shared by the remote
    client and the conditioned ops facade."""
    if edge_types is None:
        return "*"
    return ":".join(str(int(t)) for t in edge_types) or "*"

def _note_unexpected(site: str, exc: BaseException) -> None:
    """Count an exception that a best-effort site (a __del__, the UDF
    trampoline) must swallow but did NOT expect — on the obs registry
    (gql_unexpected_errors_total{site=}), so it is visible on /metrics
    instead of vanishing. Never raises: these sites run during GC and
    interpreter teardown, where even the import can fail."""
    try:
        from euler_tpu import obs

        obs.default_registry().counter(
            "gql_unexpected_errors_total",
            "unexpected exceptions swallowed at best-effort gql sites",
            ("site",)).labels(site=site).inc()
    except Exception:
        pass  # interpreter teardown: nothing left to report into


_DTYPES = {
    0: np.uint64,
    1: np.int64,
    2: np.int32,
    3: np.float32,
    4: np.uint8,
}
_DTYPE_CODES = {
    np.dtype(np.uint64): 0,
    np.dtype(np.int64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.float32): 3,
    np.dtype(np.uint8): 4,
}


class Query:
    """A query proxy bound to a local engine or a remote shard set."""

    def __init__(self, lib, handle: int, mode: str = "local"):
        self._lib = lib
        self._h = handle
        self._mode = mode  # "local" | "distribute" — explain() renders it
        # guards _h for stats()/close(): a /metrics scrape thread polls
        # stats() via the bind_obs collector while the owner may be
        # close()ing — without the lock that is a use-after-free on the
        # native handle. run() stays lock-free (owner-thread hot path).
        self._mu = threading.Lock()

    @classmethod
    def local(cls, engine, index_spec: str = "", seed: int = 0) -> "Query":
        """Embedded mode over a GraphEngine (euler_tpu.graph.GraphEngine)."""
        lib = _libmod.load()
        h = lib.etq_new_local(engine.h, index_spec.encode(), seed)
        if h == 0:
            raise EngineError(lib.etg_last_error().decode())
        return cls(lib, h)

    @classmethod
    def remote(cls, endpoints: str, seed: int = 0,
               mode: str = "distribute") -> "Query":
        """Distribute mode. endpoints: "hosts:h:p,h:p" or "dir:/registry"."""
        lib = _libmod.load()
        h = lib.etq_new_remote(endpoints.encode(), seed, mode.encode())
        if h == 0:
            raise EngineError(lib.etg_last_error().decode())
        return cls(lib, h, mode=mode)

    def run(self, gremlin: str,
            inputs: Optional[Dict[str, np.ndarray]] = None,
            deadline_ms: Optional[float] = None,
            trace: Optional[tuple] = None
            ) -> Dict[str, np.ndarray]:
        """Execute a chain; returns alias outputs ("name:i") + terminals.

        deadline_ms: remaining per-call budget to PROPAGATE to remote
        shards (v2 frames carry it; a shard sheds a request whose
        budget expired before dispatch — counted deadline_shed, never a
        silent partial). Does not bound the call locally; local proxies
        and v1 peers ignore it.

        trace: (trace_id, parent_span_id) wire trace context to stamp
        into every REMOTE sub-call's v2 frame (hello-negotiated
        kFeatTrace) — the shard records its queue/decode/execute/
        serialize breakdown under it, so a merged chrome trace stitches
        server time beneath the client span. None (or a 0 trace id)
        stamps nothing: the wire stays byte-identical."""
        lib = self._lib
        eh = lib.etq_exec_new(self._h)
        if eh == 0:
            raise EngineError(lib.etg_last_error().decode())
        if deadline_ms is not None and deadline_ms > 0:
            # per-thread handoff, consumed by the native run below; the
            # finally clears it so a failed run can't leak the budget
            # into the next deadline-less call on this thread
            lib.etg_set_call_deadline_ms(float(deadline_ms))
        traced = trace is not None and int(trace[0]) != 0
        if traced:
            lib.etg_set_call_trace(int(trace[0]) & (2 ** 64 - 1),
                                   int(trace[1]) & (2 ** 64 - 1))
        try:
            for name, arr in (inputs or {}).items():
                a = np.ascontiguousarray(arr)
                if a.dtype not in _DTYPE_CODES:
                    if np.issubdtype(a.dtype, np.integer):
                        a = a.astype(np.int64)
                    else:
                        a = a.astype(np.float32)
                dims = np.array(a.shape or (1,), dtype=np.int64)
                check(lib, lib.etq_exec_add_input(
                    eh, name.encode(), _DTYPE_CODES[a.dtype], dims.size,
                    dims.ctypes.data_as(_libmod.c_i64p),
                    a.ctypes.data_as(ctypes.c_void_p)))
            check(lib, lib.etq_exec_run(eh, gremlin.encode()))
            out: Dict[str, np.ndarray] = {}
            n = lib.etq_exec_output_count(eh)
            for i in range(n):
                name = lib.etq_exec_output_name(eh, i).decode()
                dt = ctypes.c_int32()
                rank = ctypes.c_int32()
                numel = ctypes.c_int64()
                check(lib, lib.etq_exec_output_info(
                    eh, i, ctypes.byref(dt), ctypes.byref(rank),
                    ctypes.byref(numel)))
                dims = np.zeros(max(rank.value, 1), dtype=np.int64)
                check(lib, lib.etq_exec_output_dims(
                    eh, i, dims.ctypes.data_as(_libmod.c_i64p)))
                dtype = _DTYPES[dt.value]
                arr = np.empty(int(numel.value), dtype=dtype)
                ptr = lib.etq_exec_output_data(eh, i)
                if arr.size and ptr:
                    ctypes.memmove(arr.ctypes.data, ptr,
                                   arr.size * arr.itemsize)
                out[name] = arr.reshape(dims[:rank.value]
                                        if rank.value else ())
            return out
        finally:
            if deadline_ms is not None and deadline_ms > 0:
                lib.etg_set_call_deadline_ms(0.0)
            if traced:
                lib.etg_set_call_trace(0, 0)
            lib.etq_exec_free(eh)

    # -- streaming deltas --------------------------------------------------
    def epoch(self) -> int:
        """Observed graph epoch: exact for local proxies; for remote
        proxies the max epoch seen on any shard reply (v2 mux frames
        piggyback it — call delta_since for an active refresh)."""
        e = self._lib.etq_epoch(self._h)
        if e < 0:
            raise EngineError(self._lib.etg_last_error().decode())
        return int(e)

    def apply_delta(self, node_ids=None, node_types=None,
                    node_weights=None, edge_src=None, edge_dst=None,
                    edge_types=None, edge_weights=None) -> int:
        """Batched delta through this proxy: local mode swaps the bound
        graph handle's snapshot; distribute mode broadcasts the delta
        to every shard (each applies the rows it hash-owns and bumps
        its epoch). Returns the new epoch."""
        from euler_tpu.graph.api import _delta_arrays

        nid, nt, nw, es, ed, et, ew = _delta_arrays(
            node_ids, node_types, node_weights, edge_src, edge_dst,
            edge_types, edge_weights)
        out_epoch = ctypes.c_int64()
        check(self._lib, self._lib.etq_apply_delta(
            self._h, nid.size,
            nid.ctypes.data_as(_libmod.c_u64p),
            nt.ctypes.data_as(_libmod.c_i32p),
            nw.ctypes.data_as(_libmod.c_f32p), es.size,
            es.ctypes.data_as(_libmod.c_u64p),
            ed.ctypes.data_as(_libmod.c_u64p),
            et.ctypes.data_as(_libmod.c_i32p),
            ew.ctypes.data_as(_libmod.c_f32p), ctypes.byref(out_epoch)))
        return int(out_epoch.value)

    # -- elastic fleet (ownership maps; remote proxies) --------------------
    def set_ownership(self, spec: str) -> None:
        """Install the epoch-versioned ownership map this client routes
        with (registry-published spec, e.g. "e3-P4-0.1.2.2+3"). Splits
        then place ids by the map's owner lists (p2c over replicated
        partitions' owners) and every request is stamped with the map
        epoch so a shard on a newer map refuses it explicitly ("stale
        ownership map") instead of serving a misrouted read."""
        check(self._lib, self._lib.etq_set_ownership(self._h,
                                                     spec.encode()))

    def ownership_epoch(self) -> int:
        """Installed ownership-map epoch (0 = none / local proxy)."""
        e = self._lib.etq_ownership_epoch(self._h)
        if e < 0:
            raise EngineError(self._lib.etg_last_error().decode())
        return int(e)

    def shard_num(self) -> int:
        """Shard count this proxy was built against (1 for local)."""
        n = self._lib.etq_shard_num(self._h)
        if n < 0:
            raise EngineError(self._lib.etg_last_error().decode())
        return int(n)

    def shard_stats(self):
        """(requests, rows) per-shard uint64 arrays since proxy init.
        Rows (split-routed ids) are the hot-shard detection signal —
        the distribute rewrite fires one REMOTE per shard per query
        regardless, so request counts alone cannot see skew."""
        n = self.shard_num()
        reqs = np.zeros(max(n, 1), dtype=np.uint64)
        rows = np.zeros(max(n, 1), dtype=np.uint64)
        got = self._lib.etq_shard_stats(
            self._h, reqs.ctypes.data_as(_libmod.c_u64p),
            rows.ctypes.data_as(_libmod.c_u64p), int(reqs.size))
        if got < 0:
            raise EngineError(self._lib.etg_last_error().decode())
        return reqs[:got], rows[:got]

    def delta_since(self, from_epoch: int):
        """(epoch, covered, dirty_ids) — union over shards in remote
        mode; covered=False when any shard's bounded history no longer
        reaches from_epoch (treat everything as dirty)."""
        lib = self._lib
        res = lib.etres_new()
        try:
            out_epoch = ctypes.c_int64()
            covered = ctypes.c_int32()
            check(lib, lib.etq_delta_since(self._h, int(from_epoch), res,
                                           ctypes.byref(out_epoch),
                                           ctypes.byref(covered)))
            n = lib.etres_u64_len(res)
            ids = (np.ctypeslib.as_array(lib.etres_u64(res), (n,)).copy()
                   if n else np.zeros(0, dtype=np.uint64))
        finally:
            lib.etres_free(res)
        return int(out_epoch.value), bool(covered.value), ids

    def dump_index(self, directory: str) -> None:
        """Persist the local-mode index to `directory` (reference:
        serialized Index/ dir, index_manager.h:34,54). Reload later with
        Query.local(engine, index_spec="load:<directory>") — or in
        start_service — instead of rebuilding from columns."""
        check(self._lib, self._lib.etq_index_dump(self._h,
                                                  directory.encode()))

    def explain(self, gremlin: str) -> str:
        """Render what this proxy registers for `gremlin` and what a
        server's prepare-time optimizer turns that registration into:
        a "-- as registered --" DAG (the proxy's compile mode) followed
        by a "-- server optimized --" block whose header carries the
        per-pass rewrite counts (fuse/pushdown/dedup) and the
        determinism verdict that gates the result-reuse / coalescing
        fast paths. Distribute-mode note: shards optimize each REMOTE
        sub-plan they receive, so the local-form optimized block is the
        per-shard view. Pure client-side compile — nothing executes."""
        lib = self._lib
        shard_num = max(int(lib.etq_shard_num(self._h)), 1)
        mode = self._mode if shard_num > 1 else "local"

        def _probe(stage: int, m: str, n_shards: int) -> str:
            n = lib.etq_compile_debug2(gremlin.encode(), n_shards,
                                       n_shards, m.encode(), stage,
                                       None, 0)
            if n < 0:
                raise EngineError(lib.etg_last_error().decode())
            buf = ctypes.create_string_buffer(int(n) + 1)
            lib.etq_compile_debug2(gremlin.encode(), n_shards, n_shards,
                                   m.encode(), stage, buf, n + 1)
            return buf.value.decode()

        registered = _probe(0, mode, shard_num)
        # the optimizer runs on the plan a SHARD receives — local form
        optimized = _probe(1, "local", 1)
        return ("-- as registered (mode=%s, shards=%d) --\n%s"
                "-- server optimized --\n%s"
                % (mode, shard_num, registered, optimized))

    def stats(self) -> dict:
        """Per-proxy query counters: queries, errors, total_us, last_us
        (aux parity: engine-side query timing)."""
        import numpy as np

        out = np.zeros(4, dtype=np.uint64)
        with self._mu:
            if not self._h:
                raise EngineError("query proxy is closed")
            check(self._lib, self._lib.etq_stats(
                self._h, out.ctypes.data_as(_libmod.c_u64p)))
        return {"queries": int(out[0]), "errors": int(out[1]),
                "total_us": int(out[2]), "last_us": int(out[3])}

    def bind_obs(self, name: str) -> None:
        """Bridge this proxy's ENGINE-SIDE stats() counters into
        euler_tpu.obs gauges (gql_proxy_*{proxy=name}), refreshed at
        every registry scrape/snapshot by a collector. The collector
        holds only a weakref: a collected or close()d proxy drops off
        the next scrape instead of pinning the native handle."""
        from euler_tpu import obs

        reg = obs.default_registry()
        gauges = {
            k: reg.gauge(f"gql_proxy_{k}",
                         f"engine-side query proxy {k}",
                         ("proxy",)).labels(proxy=name)
            for k in ("queries", "errors", "total_us", "last_us")}
        ref = weakref.ref(self)

        def _collect():
            q = ref()
            if q is None or not q._h:
                return False  # proxy gone: collector self-removes
            try:
                st = q.stats()
            except EngineError:  # closed between the check and the call
                return False
            for k, v in st.items():
                g = gauges.get(k)
                if g is not None:
                    g.set(v)

        reg.add_collector(_collect)
        _ensure_udf_cache_obs()

    def close(self) -> None:
        with self._mu:
            if self._h:
                self._lib.etq_free(self._h)
                self._h = 0

    def __del__(self):  # best-effort
        try:
            self.close()
        except (EngineError, OSError, AttributeError, TypeError):
            # expected at interpreter teardown: the ctypes lib / module
            # globals may already be torn down under this object
            pass
        except Exception as e:
            _note_unexpected("query_del", e)


class GraphService:
    """A serving graph shard (reference euler.start(), python_api.cc:29)."""

    def __init__(self, lib, handle: int):
        self._lib = lib
        self._h = handle

    @property
    def port(self) -> int:
        return self._lib.ets_port(self._h)

    @property
    def epoch(self) -> int:
        """The served graph's current epoch (recovery-rejoin checks)."""
        return int(self._lib.ets_epoch(self._h))

    # -- elastic fleet -----------------------------------------------------
    def set_ownership(self, spec: str) -> int:
        """Install an epoch-versioned ownership map on this shard: the
        flip after which requests routed on an older map are refused
        ("stale ownership map", counted), deltas filter by the map's
        owner lists, and — when the shard is durable — the spec is
        persisted beside the WAL so crash recovery replays under it.
        Returns the installed map epoch (the flip_fleet contract —
        push_ownership returns the same for wire pushes)."""
        check(self._lib, self._lib.ets_set_ownership(self._h,
                                                     spec.encode()))
        return self.map_epoch

    @property
    def map_epoch(self) -> int:
        """Installed ownership-map epoch (0 = none)."""
        return int(self._lib.ets_map_epoch(self._h))

    def plan_debug(self) -> str:
        """Dump this shard's shared prepared-plan store: one block per
        registered plan — id, generation, determinism verdict, the
        prepare-time optimizer's per-pass rewrite counts, the DAG that
        actually executes, and (when rewritten) the verbatim form the
        client registered. The server half of Query.explain()."""
        lib = self._lib
        n = lib.ets_plan_debug(self._h, None, 0)
        if n < 0:
            raise EngineError(lib.etg_last_error().decode())
        buf = ctypes.create_string_buffer(int(n) + 1)
        lib.ets_plan_debug(self._h, buf, n + 1)
        return buf.value.decode()

    def stop(self) -> None:
        if self._h:
            self._lib.ets_stop(self._h)
            self._h = 0

    def __del__(self):
        try:
            self.stop()
        except (EngineError, OSError, AttributeError, TypeError):
            pass  # teardown-order races (see Query.__del__)
        except Exception as e:
            _note_unexpected("graph_service_del", e)


# ---------------------------------------------------------------------------
# Server-side timing breakdown (cross-process tracing; etg_server_trace_*)
# ---------------------------------------------------------------------------
# Axis names — order must match rpc.h ServerTraceStats::VerbSlot and the
# phase constants in rpc.cc's kExecute dispatch.
_TRACE_VERBS = ("execute", "apply_delta", "get_delta", "get_delta_log",
                "set_ownership", "meta")
_TRACE_PHASES = ("queue", "decode", "execute", "serialize")
# log2-µs bucket bounds: 1µs, 2µs, ... 2^23µs (~8.4s); index 24 is the
# overflow bucket (mirrors ServerTraceStats::kTraceBuckets).
_TRACE_BOUNDS_US = tuple(float(1 << i) for i in range(24))
# ring-record flag bits (ServerTraceRecord.flags)
TRACE_FLAG_DEADLINE_SHED = 1
TRACE_FLAG_STALE_MAP_SHED = 2
TRACE_FLAG_ERROR = 4


def server_trace_hist(verb: str = "execute",
                      phase: str = "queue") -> dict:
    """One native per-verb/per-phase server timing histogram (always
    on — no negotiation, no Python in the measurement path): the
    queue-wait / decode / execute / serialize breakdown every request
    through this process's GraphServers lands in. Returns {count,
    sum_us, buckets: [[le_us, count], ...]} with raw (non-cumulative)
    per-bucket counts; non-"execute" verbs record queue + execute
    only."""
    lib = _libmod.load()
    out = np.zeros(27, dtype=np.uint64)
    check(lib, lib.etg_server_trace_hist(
        _TRACE_VERBS.index(verb), _TRACE_PHASES.index(phase),
        out.ctypes.data_as(_libmod.c_u64p)))
    counts = [int(v) for v in out[2:]]
    return {"count": int(out[0]), "sum_us": int(out[1]),
            "buckets": [[le, c] for le, c in
                        zip(list(_TRACE_BOUNDS_US) + ["+Inf"], counts)]}


def server_phase_quantile(verb: str = "execute", phase: str = "decode",
                          q: float = 0.99, baseline: dict = None):
    """Bucket-interpolated quantile (ms) of one native server phase
    histogram — the counted ruler the wire-path work is judged by
    (accept.py's decode-phase gate, bench_host --mode wire). With
    `baseline` (a prior server_trace_hist snapshot of the SAME
    verb/phase), the quantile is computed over the DELTA since that
    snapshot, so an A/B leg reads only its own requests. None when the
    (delta) histogram is empty."""
    from euler_tpu.obs.metrics import bucket_quantile

    h = server_trace_hist(verb, phase)
    counts = [c for _, c in h["buckets"]]
    if baseline is not None:
        base = [c for _, c in baseline["buckets"]]
        counts = [max(c - b, 0) for c, b in zip(counts, base)]
    if sum(counts) == 0:
        return None
    v = bucket_quantile(counts, _TRACE_BOUNDS_US, q)
    return None if v is None else v / 1000.0


def server_trace_spans() -> list:
    """Drain the bounded server-side span ring: one dict per request
    that carried a wire trace context (kFeatTrace), with the
    queue/decode/execute/serialize breakdown in µs, the client's
    trace/parent-span ids, and the server-minted span id. Read-and-
    clear — the harness dumps once per run."""
    lib = _libmod.load()
    res = lib.etres_new()
    try:
        check(lib, lib.etg_server_trace_dump(res))
        n = lib.etres_u64_len(res)
        flat = (np.ctypeslib.as_array(lib.etres_u64(res), (n,)).copy()
                if n else np.zeros(0, dtype=np.uint64))
    finally:
        lib.etres_free(res)
    out = []
    for i in range(0, flat.size, 10):
        r = flat[i:i + 10]
        out.append({
            "trace_id": int(r[0]), "parent_span": int(r[1]),
            "span_id": int(r[2]), "verb": int(r[3]), "flags": int(r[4]),
            "start_unix_us": int(r[5]), "queue_us": int(r[6]),
            "decode_us": int(r[7]), "exec_us": int(r[8]),
            "serialize_us": int(r[9]),
        })
    return out


def server_trace_chrome(path: str, spans: Optional[list] = None) -> str:
    """Export the server-side span ring (drained, unless `spans` from a
    prior server_trace_spans() call is given) as chrome://tracing JSON:
    per request one "server:execute" parent span with its queue_wait /
    decode / execute / serialize children laid out sequentially, each
    request on its own chrome tid so concurrent requests never
    corrupt nesting. args carry trace_id / parent_span (the CLIENT
    span) / span_id, so tools/trace_dump.py --merge stitches these
    under the client's graph_rpc spans on one timeline.
    otherData.epoch_unix anchors ts=0 on the wall clock, the same
    convention Tracer.chrome_trace uses."""
    import json as _json
    import os as _os
    import tempfile as _tempfile

    if spans is None:
        spans = server_trace_spans()
    epoch_us = min((s["start_unix_us"] for s in spans), default=0)
    pid = _os.getpid()
    events = []
    for s in spans:
        base = s["start_unix_us"] - epoch_us
        tid = s["span_id"] & 0xFFFFFFFF
        args = {"trace_id": s["trace_id"], "parent_span": s["parent_span"],
                "span_id": s["span_id"], "flags": s["flags"]}
        total = (s["queue_us"] + s["decode_us"] + s["exec_us"]
                 + s["serialize_us"])
        name = _TRACE_VERBS[0] if s["verb"] == 0 else f"verb{s['verb']}"
        events.append({"name": f"server:{name}", "ph": "X", "cat": "srv",
                       "ts": base, "dur": total, "pid": pid, "tid": tid,
                       "args": args})
        off = 0
        for phase, key in (("queue_wait", "queue_us"),
                           ("decode", "decode_us"),
                           ("execute", "exec_us"),
                           ("serialize", "serialize_us")):
            if s[key] == 0 and phase != "queue_wait":
                continue
            events.append({"name": phase, "ph": "X", "cat": "srv",
                           "ts": base + off, "dur": s[key], "pid": pid,
                           "tid": tid, "args": {"trace_id": s["trace_id"]}})
            off += s[key]
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": {"epoch_unix": epoch_us / 1e6,
                           "exporter": "euler_tpu.gql.server_trace"}}
    d = _os.path.dirname(_os.path.abspath(path)) or "."
    fd, tmp = _tempfile.mkstemp(
        prefix=_os.path.basename(path) + ".", suffix=".tmp", dir=d)
    with _os.fdopen(fd, "w") as f:
        _json.dump(trace, f)
    _os.replace(tmp, path)
    return path


_server_trace_obs_done = False
_server_trace_obs_mu = threading.Lock()


def _ensure_server_trace_obs() -> None:
    """Bridge the native per-verb server timing histograms into obs
    gauges (the etg_rpc_stats → rpc_* pattern), once per process, on
    the first start_service: a /metrics scrape of one shard process
    then shows queue-wait and execute quantiles measured entirely in
    the native layer. Per (verb, phase): graph_server_phase_us_count /
    _sum, and bucket-interpolated p50/p99/p999 as
    graph_server_phase_ms_quantile{verb,phase,q}."""
    global _server_trace_obs_done
    with _server_trace_obs_mu:
        if _server_trace_obs_done:
            return
        from euler_tpu import obs as _obs

        reg = _obs.default_registry()
        g_count = reg.gauge(
            "graph_server_phase_us_count",
            "server-side per-request phase observations (native)",
            ("verb", "phase"))
        g_sum = reg.gauge(
            "graph_server_phase_us_sum",
            "server-side per-request phase time total, µs (native)",
            ("verb", "phase"))
        g_q = reg.gauge(
            "graph_server_phase_ms_quantile",
            "server-side phase latency quantile, ms "
            "(bucket-interpolated from the native log2-µs histogram)",
            ("verb", "phase", "q"))

        from euler_tpu.obs.metrics import bucket_quantile

        def _collect():
            for verb in _TRACE_VERBS:
                for phase in _TRACE_PHASES:
                    if verb != "execute" and phase in ("decode",
                                                       "serialize"):
                        continue  # never observed for these verbs
                    h = server_trace_hist(verb, phase)
                    if h["count"] == 0:
                        continue
                    g_count.labels(verb=verb, phase=phase).set(h["count"])
                    g_sum.labels(verb=verb, phase=phase).set(h["sum_us"])
                    counts = [c for _, c in h["buckets"]]
                    for q in (0.5, 0.99, 0.999):
                        v = bucket_quantile(counts, _TRACE_BOUNDS_US, q)
                        if v is not None:
                            g_q.labels(verb=verb, phase=phase,
                                       q=str(q)).set(v / 1000.0)

        reg.add_collector(_collect)
        _obs.register_health(
            "graph_server_trace",
            lambda: {"execute_queue": server_trace_hist("execute", "queue")
                     ["count"],
                     "execute_exec": server_trace_hist("execute", "execute")
                     ["count"]})
        # flag only after every registration succeeded (wal-obs pattern)
        _server_trace_obs_done = True


# native durability counter layout (etg_wal_stats) — order must match
# capi.cc. `degraded` is a gauge counting the process's degraded wal
# INSTANCES (shards currently refusing deltas because their log is
# unwritable); everything else is a monotonic counter.
_WAL_STAT_KEYS = (
    "appends", "fsyncs", "replayed_records", "compactions",
    "catchup_deltas", "refused", "torn_records", "degraded")

_wal_obs_done = False
_wal_obs_mu = threading.Lock()


def wal_stats() -> dict:
    """Process-global write-ahead-log durability counters: records
    appended/fsynced, records replayed at recovery, snapshot
    compactions, deltas applied via peer anti-entropy catch-up, deltas
    refused while degraded, torn/corrupt records dropped at replay, and
    the degraded gauge. Benches snapshot before/after a leg and diff."""
    lib = _libmod.load()
    out = np.zeros(len(_WAL_STAT_KEYS), dtype=np.uint64)
    lib.etg_wal_stats(out.ctypes.data_as(_libmod.c_u64p))
    return {k: int(v) for k, v in zip(_WAL_STAT_KEYS, out)}


def _ensure_wal_obs() -> None:
    """Mirror the native durability counters into obs gauges
    (wal_appends_total, wal_fsyncs_total, wal_replayed_records_total,
    wal_compactions_total, wal_recovery_catchup_deltas_total,
    wal_refused_total, wal_torn_records_total, wal_degraded) and expose
    them on /healthz via a "graph_wal" health provider — once per
    process, first durable start_service."""
    global _wal_obs_done
    with _wal_obs_mu:
        if _wal_obs_done:
            return
        from euler_tpu import obs as _obs

        reg = _obs.default_registry()
        names = {
            "appends": "wal_appends_total",
            "fsyncs": "wal_fsyncs_total",
            "replayed_records": "wal_replayed_records_total",
            "compactions": "wal_compactions_total",
            "catchup_deltas": "wal_recovery_catchup_deltas_total",
            "refused": "wal_refused_total",
            "torn_records": "wal_torn_records_total",
            "degraded": "wal_degraded",
        }
        gauges = {
            k: reg.gauge(n, f"graph shard write-ahead log {k} "
                            "(process-global, native counter mirror)")
            for k, n in names.items()}

        def _collect():
            for k, v in wal_stats().items():
                gauges[k].set(v)

        reg.add_collector(_collect)
        _obs.register_health("graph_wal", wal_stats)
        # only after every registration succeeded: a raise above leaves
        # the flag unset so the next durable start retries instead of
        # permanently serving without wal observability
        _wal_obs_done = True


# native out-of-core tier counter layout (etg_store_stats) — order must
# match store.h kStoreStatSlots. Slots 10..34 are the cold-read log2-µs
# histogram buckets (the _TRACE_BOUNDS_US convention + overflow).
_STORE_STAT_KEYS = (
    "hot_hits", "cold_reads", "page_in", "page_out", "resident_bytes",
    "mapped_bytes", "hot_pinned_bytes", "attaches", "cold_n",
    "cold_sum_us")

_store_obs_done = False
_store_obs_mu = threading.Lock()


def store_stats() -> dict:
    """Process-global out-of-core storage-tier counters (store.h):
    hot-set hits vs cold row reads, mincore-observed page_in/page_out
    and resident bytes across every live mmap'd graph, hot-set pinned
    bytes, attach count, and the cold-read page-in latency histogram
    under "cold_buckets" ([[le_us, count], ...] raw per-bucket counts).
    All zeros when no graph is attached. Benches snapshot before/after
    a leg and diff."""
    lib = _libmod.load()
    out = np.zeros(10 + 25, dtype=np.uint64)
    lib.etg_store_stats(out.ctypes.data_as(_libmod.c_u64p))
    d = {k: int(v) for k, v in zip(_STORE_STAT_KEYS, out)}
    d["cold_buckets"] = [
        [le, int(c)] for le, c in
        zip(list(_TRACE_BOUNDS_US) + ["+Inf"], out[10:])]
    return d


def cold_read_quantile(q: float = 0.999, baseline: dict = None):
    """Bucket-interpolated quantile (ms) of the cold-read page-in
    latency histogram — the counted bound on the out-of-core tier's
    miss penalty (bench_host --mode outcore's p999 gate). With
    `baseline` (a prior store_stats snapshot), computes over the delta
    since it. None when the (delta) histogram is empty."""
    from euler_tpu.obs.metrics import bucket_quantile

    counts = [c for _, c in store_stats()["cold_buckets"]]
    if baseline is not None:
        base = [c for _, c in baseline["cold_buckets"]]
        counts = [max(c - b, 0) for c, b in zip(counts, base)]
    if sum(counts) == 0:
        return None
    v = bucket_quantile(counts, _TRACE_BOUNDS_US, q)
    return None if v is None else v / 1000.0


def _process_rss_bytes() -> int:
    """This process's resident set size, from /proc/self/status VmRSS
    (kB). 0 where /proc is unavailable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def _ensure_store_obs() -> None:
    """Mirror the out-of-core tier counters into obs gauges — the
    residency pair the 10×-RAM claim is judged by on /metrics
    (process_rss_bytes vs graph_storage_resident_bytes +
    graph_storage_mapped_bytes), plus hot/cold accounting — and expose
    a "graph_storage" health provider. Once per process, first
    storage="mmap" start_service (or explicit call)."""
    global _store_obs_done
    with _store_obs_mu:
        if _store_obs_done:
            return
        from euler_tpu import obs as _obs

        reg = _obs.default_registry()
        names = {
            "hot_hits": "graph_storage_hot_hits_total",
            "cold_reads": "graph_storage_cold_reads_total",
            "page_in": "graph_storage_page_in_total",
            "page_out": "graph_storage_page_out_total",
            "resident_bytes": "graph_storage_resident_bytes",
            "mapped_bytes": "graph_storage_mapped_bytes",
            "hot_pinned_bytes": "graph_storage_hot_pinned_bytes",
        }
        gauges = {
            k: reg.gauge(n, f"out-of-core graph storage tier {k} "
                            "(process-global, native counter mirror)")
            for k, n in names.items()}
        rss = reg.gauge(
            "process_rss_bytes",
            "process resident set size (/proc/self/status VmRSS) — "
            "read against graph_storage_resident_bytes to see how much "
            "of the mapped graph the kernel is holding in RAM")

        def _collect():
            s = store_stats()
            for k in names:
                gauges[k].set(s[k])
            rss.set(_process_rss_bytes())

        reg.add_collector(_collect)
        _obs.register_health("graph_storage", store_stats)
        _store_obs_done = True


def start_service(data_dir: str, shard_idx: int = 0, shard_num: int = 1,
                  port: int = 0, registry_dir: str = "",
                  host: str = "127.0.0.1", index_spec: str = "",
                  wal_dir: str = "", wal_fsync: str = "always",
                  wal_compact_bytes: int = 64 << 20,
                  catchup: bool = True, storage: str = None,
                  hot_bytes: int = None) -> GraphService:
    """Load shard `shard_idx`/`shard_num` from data_dir and serve it.

    registry_dir: where the shard registers for discovery — a shared
    directory path (or "dir:/path"), or "tcp:<host>:<port>" pointing at
    a registry server (start_registry) for clusters with no shared
    filesystem (the reference's ZooKeeper role).

    wal_dir: non-empty makes the shard DURABLE — every accepted delta
    is appended to a checksummed write-ahead log before the snapshot
    swap, and a restart with the same wal_dir recovers snapshot+WAL to
    the pre-crash epoch, then (catchup=True, registry given) closes any
    remaining gap from a peer's retained delta log before registering
    for traffic. An unwritable wal_dir degrades gracefully: reads
    serve, every delta is refused with an explicit status (counted,
    `wal_degraded` on /healthz).
    wal_fsync: "always" fsyncs each append (survives power loss);
    "never" rides the page cache (survives process death/SIGKILL only).
    wal_compact_bytes: once the log exceeds this, the snapshot is
    re-dumped (atomic temp+rename) and the log truncated; <= 0 disables
    compaction.

    storage: "ram" (default) serves from the heap; "mmap" serves from
    the out-of-core columnar tier — the graph's big columns are mmap'd
    from a columnar store file (written beside the data files on first
    start, and by every WAL compaction thereafter), with `hot_bytes` of
    hub-first hot set pinned in RAM. Reads are byte-identical to the
    RAM engine; the page cache absorbs everything beyond the hot set,
    so the shard can serve graphs far larger than RAM at a counted
    cold-read penalty (store_stats() / cold_read_quantile()). Both
    default from the ETG_STORAGE / ETG_HOT_BYTES environment (so
    launchers flip a fleet without code changes)."""
    lib = _libmod.load()
    fsync_map = {"always": 1, "never": 0}
    if wal_fsync not in fsync_map:
        raise ValueError(
            f"wal_fsync must be one of {sorted(fsync_map)}, got "
            f"{wal_fsync!r}")
    if storage is None:
        storage = os.environ.get("ETG_STORAGE", "ram")
    storage_map = {"ram": 0, "mmap": 1}
    if storage not in storage_map:
        raise ValueError(
            f"storage must be one of {sorted(storage_map)}, got "
            f"{storage!r}")
    if hot_bytes is None:
        hot_bytes = int(os.environ.get("ETG_HOT_BYTES", "0"))
    if wal_dir:
        _ensure_wal_obs()
    if storage == "mmap":
        _ensure_store_obs()
    # every serving shard process exposes its native timing breakdown
    # (queue-wait/execute quantiles) on /metrics — no opt-in needed
    _ensure_server_trace_obs()
    h = lib.ets_start3(data_dir.encode(), shard_idx, shard_num, port,
                       registry_dir.encode(), host.encode(),
                       index_spec.encode(), wal_dir.encode(),
                       fsync_map[wal_fsync], int(wal_compact_bytes),
                       1 if catchup else 0, storage_map[storage],
                       int(hot_bytes))
    if h == 0:
        raise EngineError(lib.etg_last_error().decode())
    return GraphService(lib, h)


class RegistryService:
    """A TCP registry server (ZK-role discovery without a shared FS):
    shards heartbeat named entries; clients and monitors list them with
    ages. Use "tcp:<host>:<port>" as registry_dir / endpoints."""

    def __init__(self, lib, handle: int):
        self._lib = lib
        self._h = handle

    @property
    def port(self) -> int:
        return self._lib.etr_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.etr_stop(self._h)
            self._h = 0

    def __del__(self):
        try:
            self.stop()
        except (EngineError, OSError, AttributeError, TypeError):
            pass  # teardown-order races (see Query.__del__)
        except Exception as e:
            _note_unexpected("registry_service_del", e)


def start_registry(port: int = 0) -> RegistryService:
    """Start a registry server (port 0 → ephemeral)."""
    lib = _libmod.load()
    h = lib.etr_start(port)
    if h == 0:
        raise EngineError(lib.etg_last_error().decode())
    return RegistryService(lib, h)


def push_ownership(host: str, port: int, spec: str) -> int:
    """Push an ownership-map spec to one graph shard over the
    kSetOwnership admin verb (the elastic driver's flip for servers it
    does not hold an in-process handle to — e.g. subprocess shards).
    Returns the installed map epoch."""
    lib = _libmod.load()
    out = ctypes.c_int64()
    check(lib, lib.etg_push_ownership(host.encode(), int(port),
                                      spec.encode(), ctypes.byref(out)))
    return int(out.value)


def scan_registry(spec: str):
    """List a registry's shard entries: {shard: (host, port, age_ms)}.
    spec = directory path, "dir:/path", or "tcp:host:port"."""
    lib = _libmod.load()
    buf = ctypes.create_string_buffer(1 << 16)
    n = lib.etr_scan(spec.encode(), buf, len(buf))
    if n < 0:
        raise EngineError(lib.etg_last_error().decode())
    if n >= len(buf):  # truncated: re-scan with the reported size
        buf = ctypes.create_string_buffer(n + 1)
        n = lib.etr_scan(spec.encode(), buf, len(buf))
        if n < 0:
            raise EngineError(lib.etg_last_error().decode())
    out = {}
    for line in buf.value.decode().splitlines():
        idx, host, port, age = line.split(",")
        out[int(idx)] = (host, int(port), int(age))
    return out


# ctypes callbacks must outlive the engine; keyed by name so
# re-registration replaces (matching the registry's last-wins rule)
_UDF_CALLBACKS: Dict[str, object] = {}

_UDF_CBTYPE = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.POINTER(ctypes.c_double), ctypes.c_int64,   # params
    _libmod.c_u64p, ctypes.c_int64,                    # offsets, n_rows
    _libmod.c_f32p, ctypes.c_int64,                    # values, n_vals
    ctypes.c_void_p)                                   # out builder


def register_udf(name: str, fn) -> None:
    """Register a custom value-UDF callable from GQL `udf(name, feat)`
    (reference udf.h:33-68 UDF registration, here via ctypes so no
    recompilation is needed).

    fn(params, offsets, values) -> (out_offsets, out_values):
      params  float64 [P] — numeric params from "udf(name:p1:p2, feat)"
      offsets uint64 [n+1], values float32 [offsets[-1]] — ragged rows
    Both outputs are converted with np.asarray; out_offsets must have
    one more entry than output rows.

    Note: in distribute mode the UDF executes on the shard SERVERS —
    register it in each server process as well.

    PURITY: dense-feature UDF results are cached (UdfResultCache; see
    udf_cache_stats) keyed on the graph + spec + ids, so fn MUST be a
    pure function of (params, offsets, values). Re-registering a name
    invalidates all cached results; a deliberately stateful or random
    UDF should disable the cache with udf_cache_set_capacity(0).
    """
    lib = _libmod.load()
    _ensure_udf_cache_obs()  # local-mode UDF users get the gauges too

    @_UDF_CBTYPE
    def cb(params, n_params, offs, n_rows, vals, n_vals, out):
        try:
            p = np.ctypeslib.as_array(params, (n_params,)) if n_params \
                else np.zeros(0)
            o = np.ctypeslib.as_array(offs, (n_rows + 1,))
            v = np.ctypeslib.as_array(vals, (n_vals,)) if n_vals \
                else np.zeros(0, np.float32)
            out_o, out_v = fn(p.copy(), o.copy(), v.copy())
            out_o = np.ascontiguousarray(out_o, dtype=np.uint64)
            out_v = np.ascontiguousarray(out_v, dtype=np.float32)
            if out_o.size == 0 or out_o[0] != 0 or out_o[-1] != out_v.size:
                raise ValueError(
                    f"udf {name!r}: offsets must start at 0 and end at "
                    f"len(values) ({out_o[-1] if out_o.size else '?'} != "
                    f"{out_v.size})")
            lib.et_udf_emit(out, out_o.ctypes.data_as(_libmod.c_u64p),
                            out_o.size,
                            out_v.ctypes.data_as(_libmod.c_f32p),
                            out_v.size)
            return 0
        except (ValueError, TypeError):
            # malformed UDF output / non-convertible arrays: the
            # expected failure mode — rc=1 surfaces it as a query error
            return 1
        except Exception as e:
            # a genuinely unexpected bug in the user fn (or this
            # trampoline) must not vanish behind the same rc=1: count
            # it where /metrics can see it, then fail the query
            _note_unexpected("udf_cb", e)
            return 1

    _UDF_CALLBACKS[name] = cb
    lib.etg_register_udf(name.encode(), ctypes.cast(cb, ctypes.c_void_p))


def udf_cache_stats() -> dict:
    """UDF result-cache counters (reference UdfCache, udf.h:33-68):
    {'hits', 'misses', 'entries', 'bytes', 'epoch_evictions'}. Cached
    results are keyed on the graph SNAPSHOT's uid + registry generation
    + spec + fid + ids, so entries never go stale — a streaming delta
    swaps in a new snapshot (new uid) and the old snapshot's entries
    are dropped at the bump (epoch_evictions counts them, mirrored as
    udf_cache_epoch_evictions_total); re-registering any UDF orphans
    old entries, and eviction is size-bounded LRU."""
    lib = _libmod.load()
    h = ctypes.c_uint64()
    m = ctypes.c_uint64()
    e = ctypes.c_uint64()
    b = ctypes.c_uint64()
    lib.etg_udf_cache_stats(ctypes.byref(h), ctypes.byref(m),
                            ctypes.byref(e), ctypes.byref(b))
    return {"hits": h.value, "misses": m.value, "entries": e.value,
            "bytes": b.value,
            "epoch_evictions": int(lib.etg_udf_cache_epoch_evictions())}


_udf_obs_once = threading.Lock()
_udf_obs_done = False


def _ensure_udf_cache_obs() -> None:
    """Register (once per process) the collector mirroring the native
    UDF result-cache counters into gql_udf_cache_* gauges. Called from
    bind_obs — i.e. only after the native lib is known to be loaded, so
    a /metrics scrape never triggers a first-time lib build."""
    global _udf_obs_done
    with _udf_obs_once:
        if _udf_obs_done:
            return
        _udf_obs_done = True
    from euler_tpu import obs

    reg = obs.default_registry()
    gauges = {k: reg.gauge(f"gql_udf_cache_{k}",
                           f"UDF result-cache {k} (see udf_cache_stats)")
              for k in ("hits", "misses", "entries", "bytes")}
    # epoch-bump invalidation count (streaming deltas) keeps the
    # counter-style *_total name the satellite dashboards expect
    gauges["epoch_evictions"] = reg.gauge(
        "udf_cache_epoch_evictions_total",
        "UDF result-cache entries dropped by graph epoch bumps")

    def _collect():
        for k, v in udf_cache_stats().items():
            g = gauges.get(k)
            if g is not None:
                g.set(v)

    reg.add_collector(_collect)


def udf_cache_clear() -> None:
    """Drop every cached UDF result (testing / memory pressure)."""
    _libmod.load().etg_udf_cache_clear()


def udf_cache_set_capacity(num_bytes: int) -> None:
    """Resize the UDF result cache (default 64MB; 0 disables caching)."""
    _libmod.load().etg_udf_cache_set_capacity(num_bytes)


def compile_debug(gremlin: str, shard_num: int = 1, partition_num: int = 1,
                  mode: str = "local") -> str:
    """Compile and render the optimized DAG (golden structure tests)."""
    lib = _libmod.load()
    n = lib.etq_compile_debug(gremlin.encode(), shard_num, partition_num,
                              mode.encode(), None, 0)
    if n < 0:
        raise EngineError(lib.etg_last_error().decode())
    buf = ctypes.create_string_buffer(int(n) + 1)
    lib.etq_compile_debug(gremlin.encode(), shard_num, partition_num,
                          mode.encode(), buf, n + 1)
    return buf.value.decode()
