"""Graph data prep: JSON → partitioned binary graph.

Parity: euler/tools/generate_euler_data.py:28-50 (EulerGenerator =
json2meta + json2partdat) — accepts the same graph.json schema as the
reference (nodes: id/type/weight/features[{name,type,value}], edges:
src/dst/type/weight/features) and writes this framework's binary layout
(meta.bin + part_p.dat, format in euler_tpu/core/cc/io.h). Partition
assignment: hash(node_id) % num_partitions; an edge lives in its source
node's partition (reference json2partdat behavior).

Usage:
  python -m euler_tpu.tools.generate_data graph.json out_dir 2
"""

from __future__ import annotations

import json
import struct
import sys
from collections import defaultdict
from typing import Dict, List

MAGIC_PART = b"ETP1"
MAGIC_META = b"ETM1"
VERSION = 1

KIND_DENSE, KIND_SPARSE, KIND_BINARY = 0, 1, 2
_KIND_BY_NAME = {"dense": KIND_DENSE, "float": KIND_DENSE,
                 "sparse": KIND_SPARSE, "uint64": KIND_SPARSE,
                 "binary": KIND_BINARY, "string": KIND_BINARY}


def _feature_registry(items: List[dict], reg: Dict[str, dict]) -> None:
    for obj in items:
        for f in obj.get("features", []):
            name = f["name"]
            kind = _KIND_BY_NAME.get(f.get("type", "dense"), KIND_DENSE)
            if name not in reg:
                reg[name] = {"id": len(reg), "kind": kind, "dim": 0}
            if kind == KIND_DENSE:
                reg[name]["dim"] = max(reg[name]["dim"],
                                       len(f.get("value", [])))
            elif kind == KIND_SPARSE:
                reg[name]["dim"] = max(reg[name]["dim"],
                                       len(f.get("value", [])))


def _pack_str(s: str) -> bytes:
    raw = s.encode()
    return struct.pack("<I", len(raw)) + raw


def _pack_feats(feats: List[dict], reg: Dict[str, dict]) -> bytes:
    dense, sparse, binary = [], [], []
    for f in feats:
        info = reg[f["name"]]
        fid = info["id"]
        val = f.get("value", [])
        if info["kind"] == KIND_DENSE:
            dense.append((fid, [float(v) for v in val]))
        elif info["kind"] == KIND_SPARSE:
            sparse.append((fid, [int(v) for v in val]))
        else:
            raw = val if isinstance(val, str) else "".join(map(str, val))
            binary.append((fid, raw.encode()))
    out = [struct.pack("<H", len(dense))]
    for fid, v in dense:
        out.append(struct.pack("<HI", fid, len(v)))
        out.append(struct.pack(f"<{len(v)}f", *v))
    out.append(struct.pack("<H", len(sparse)))
    for fid, v in sparse:
        out.append(struct.pack("<HI", fid, len(v)))
        out.append(struct.pack(f"<{len(v)}Q", *v))
    out.append(struct.pack("<H", len(binary)))
    for fid, raw in binary:
        out.append(struct.pack("<HI", fid, len(raw)))
        out.append(raw)
    return b"".join(out)


def convert(json_path: str, out_dir: str, num_partitions: int = 1) -> dict:
    import os

    os.makedirs(out_dir, exist_ok=True)
    with open(json_path) as f:
        g = json.load(f)
    nodes = g.get("nodes", [])
    edges = g.get("edges", [])

    node_reg: Dict[str, dict] = {}
    edge_reg: Dict[str, dict] = {}
    _feature_registry(nodes, node_reg)
    _feature_registry(edges, edge_reg)

    # type name → id maps (types may be ints already or strings)
    def type_id(val, table: Dict) -> int:
        key = str(val)
        if key not in table:
            table[key] = len(table)
        return table[key]

    node_types: Dict[str, int] = {}
    edge_types: Dict[str, int] = {}

    def node_id(val) -> int:
        # string ids hash to u64 (reference parity: the json tools map
        # string node ids through py_hash64, euler/util/python_api.cc)
        if isinstance(val, str) and not val.lstrip("-").isdigit():
            from euler_tpu.utils import hash64

            return hash64(val)
        return int(val)

    part_nodes = defaultdict(list)
    part_edges = defaultdict(list)
    for nd in nodes:
        nid = node_id(nd["id"])
        p = nid % num_partitions
        rec = struct.pack("<Qif", nid, type_id(nd.get("type", 0), node_types),
                          float(nd.get("weight", 1.0)))
        rec += _pack_feats(nd.get("features", []), node_reg)
        part_nodes[p].append(rec)
    for ed in edges:
        src = node_id(ed.get("src", ed.get("src_id", 0)))
        dst = node_id(ed.get("dst", ed.get("dst_id", 0)))
        p = src % num_partitions
        rec = struct.pack("<QQif", src, dst,
                          type_id(ed.get("type", 0), edge_types),
                          float(ed.get("weight", 1.0)))
        rec += _pack_feats(ed.get("features", []), edge_reg)
        part_edges[p].append(rec)

    for p in range(num_partitions):
        with open(os.path.join(out_dir, f"part_{p}.dat"), "wb") as f:
            f.write(MAGIC_PART)
            f.write(struct.pack("<I", VERSION))
            f.write(struct.pack("<Q", len(part_nodes[p])))
            for rec in part_nodes[p]:
                f.write(rec)
            f.write(struct.pack("<Q", len(part_edges[p])))
            for rec in part_edges[p]:
                f.write(rec)

    # meta.bin
    nt = max(len(node_types), 1)
    et = max(len(edge_types), 1)
    with open(os.path.join(out_dir, "meta.bin"), "wb") as f:
        f.write(MAGIC_META)
        f.write(struct.pack("<IIII", VERSION, nt, et, num_partitions))
        f.write(struct.pack("<QQ", len(nodes), len(edges)))
        f.write(_pack_str(g.get("name", "graph")))
        names = sorted(node_types, key=node_types.get) or ["0"]
        f.write(struct.pack("<I", len(names)))
        for n in names:
            f.write(_pack_str(n))
        names = sorted(edge_types, key=edge_types.get) or ["0"]
        f.write(struct.pack("<I", len(names)))
        for n in names:
            f.write(_pack_str(n))
        for reg in (node_reg, edge_reg):
            items = sorted(reg.items(), key=lambda kv: kv[1]["id"])
            f.write(struct.pack("<I", len(items)))
            for name, info in items:
                f.write(_pack_str(name))
                f.write(struct.pack("<iq", info["kind"], info["dim"]))
    return {"nodes": len(nodes), "edges": len(edges),
            "partitions": num_partitions,
            "node_features": len(node_reg), "edge_features": len(edge_reg)}


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        return 1
    stats = convert(argv[0], argv[1],
                    int(argv[2]) if len(argv) > 2 else 1)
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
