"""Interactive GQL console for a live graph cluster or a local dump.

Parity: euler/tools/remote_console/remote_console.cc — the linenoise CLI
that issues gremlin to a running cluster and pretty-prints results.

Usage:
  python -m euler_tpu.tools.console --endpoints hosts:127.0.0.1:9190
  python -m euler_tpu.tools.console --endpoints dir:/srv/registry
  python -m euler_tpu.tools.console --data /path/to/dump      # embedded
  python -m euler_tpu.tools.console --endpoints ... -q 'sampleN(-1, 4).as(n)'

Console commands:
  let <name> u64|i32|f32 <v1,v2,...>   bind an input tensor
  inputs                                list bound inputs
  <gremlin>                             run it (e.g. v(roots).getNB(*).as(nb))
  help | quit
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

_DTYPES = {"u64": np.uint64, "i32": np.int32, "f32": np.float32}


def _print_outputs(out: dict) -> None:
    for name in sorted(out):
        v = out[name]
        with np.printoptions(threshold=40, edgeitems=8):
            print(f"  {name}: {v.dtype}{list(v.shape)} = {v}")


def run_console(query, one_shot: str = "") -> int:
    inputs: dict = {}
    if one_shot:
        try:
            _print_outputs(query.run(one_shot, inputs))
            return 0
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    try:
        import readline  # noqa: F401  (line editing + history)
    except ImportError:
        pass
    print("euler_tpu console — 'help' for commands, 'quit' to exit")
    while True:
        try:
            line = input("gql> ").strip()
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not line:
            continue
        if line in ("quit", "exit"):
            return 0
        if line == "help":
            print(__doc__)
            continue
        if line == "inputs":
            for k, v in inputs.items():
                print(f"  {k}: {v.dtype}{list(v.shape)}")
            continue
        if line.startswith("let "):
            try:
                _, name, dt, vals = line.split(None, 3)
                inputs[name] = np.array(
                    [float(x) if dt == "f32" else int(x)
                     for x in vals.replace(",", " ").split()],
                    dtype=_DTYPES[dt])
                print(f"  {name}: {inputs[name].dtype}{list(inputs[name].shape)}")
            except (ValueError, KeyError) as e:
                print(f"  bad let (let <name> u64|i32|f32 <v,...>): {e}")
            continue
        try:
            _print_outputs(query.run(line, inputs))
        except Exception as e:  # engine errors surface as messages
            print(f"  error: {e}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--endpoints", default="",
                    help="hosts:h:p,... or dir:/registry (remote mode)")
    ap.add_argument("--mode", default="distribute",
                    choices=["distribute", "graph_partition"])
    ap.add_argument("--data", default="", help="local dump dir (embedded mode)")
    ap.add_argument("--index", default="", help="index spec for embedded mode")
    ap.add_argument("-q", "--query", default="", help="run one query and exit")
    args = ap.parse_args(argv)

    from euler_tpu.gql import Query

    if args.endpoints:
        q = Query.remote(args.endpoints, mode=args.mode)
    elif args.data:
        from euler_tpu.graph import GraphEngine

        engine = GraphEngine.load(args.data)
        q = Query.local(engine, index_spec=args.index)
    else:
        ap.error("need --endpoints or --data")
    try:
        return run_console(q, args.query)
    finally:
        q.close()


if __name__ == "__main__":
    sys.exit(main())
