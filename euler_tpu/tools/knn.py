"""KNN retrieval over inferred embeddings.

Parity: knn/knn.py (faiss IVFFlat over embedding_*.npy / ids_*.npy,
knn.py:36-76). faiss isn't assumed present; the same IVF structure
(coarse k-means quantizer + per-list scan with nprobe) is implemented in
numpy, with a brute-force fallback for small corpora.

Usage:
  python -m euler_tpu.tools.knn model_dir --query_ids 1,2,3 --k 10
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


class IVFFlatIndex:
    """Inverted-file index: k-means coarse centroids, exact scan inside
    the nprobe nearest lists (metric: inner product)."""

    def __init__(self, nlist: int = 64, nprobe: int = 8, iters: int = 10,
                 seed: int = 0):
        self.nlist = nlist
        self.nprobe = min(nprobe, nlist)
        self.iters = iters
        self.seed = seed
        self.centroids = None
        self.lists = None
        self.data = None
        self.ids = None

    def train_add(self, data: np.ndarray, ids: np.ndarray) -> None:
        n = data.shape[0]
        rng = np.random.default_rng(self.seed)
        k = min(self.nlist, max(1, n // 4))
        self.nlist = k
        self.nprobe = min(self.nprobe, k)
        centroids = data[rng.choice(n, k, replace=False)].copy()
        for _ in range(self.iters):
            assign = np.argmax(data @ centroids.T, axis=1)
            for c in range(k):
                members = data[assign == c]
                if len(members):
                    centroids[c] = members.mean(axis=0)
        assign = np.argmax(data @ centroids.T, axis=1)
        self.centroids = centroids
        self.lists = [np.where(assign == c)[0] for c in range(k)]
        self.data = data
        self.ids = ids

    def state_dict(self) -> dict:
        """Array-only serialization of the trained clustering (no data/
        ids payload — the serving bundle stores those once): centroids,
        the per-point list assignment, and nprobe. Rebuild against the
        same data/ids with from_state."""
        if self.centroids is None:
            raise ValueError("index not trained (call train_add first)")
        assign = np.empty(len(self.data), dtype=np.int64)
        for c, members in enumerate(self.lists):
            assign[members] = c
        return {"centroids": np.asarray(self.centroids, np.float32),
                "assign": assign,
                "nprobe": np.asarray(self.nprobe, np.int64)}

    @classmethod
    def from_state(cls, state: dict, data: np.ndarray,
                   ids: np.ndarray) -> "IVFFlatIndex":
        """Reconstruct a trained index from state_dict() output plus the
        original (data, ids) arrays — search results are identical to
        the index that produced the state."""
        centroids = np.asarray(state["centroids"], np.float32)
        assign = np.asarray(state["assign"], np.int64)
        if assign.shape[0] != data.shape[0]:
            raise ValueError(
                f"index state assigns {assign.shape[0]} points but data "
                f"has {data.shape[0]} rows")
        idx = cls(nlist=centroids.shape[0], nprobe=int(state["nprobe"]))
        idx.centroids = centroids
        idx.lists = [np.where(assign == c)[0]
                     for c in range(centroids.shape[0])]
        idx.data = np.asarray(data, np.float32)
        idx.ids = np.asarray(ids)
        return idx

    def search(self, queries: np.ndarray, k: int):
        if self.centroids is None:
            raise ValueError("index not trained (call train_add first)")
        # nprobe may have been set past nlist (or nlist shrank in
        # train_add): probing every list is the correct degenerate case
        nprobe = min(self.nprobe, len(self.lists))
        sims_c = queries @ self.centroids.T               # [Q, nlist]
        probe = np.argsort(-sims_c, axis=1)[:, :nprobe]
        out_ids = np.zeros((len(queries), k), dtype=self.ids.dtype)
        out_sims = np.full((len(queries), k), -np.inf, np.float32)
        for qi, q in enumerate(queries):
            cand = np.concatenate([self.lists[c] for c in probe[qi]]) \
                if len(probe[qi]) else np.arange(len(self.data))
            if len(cand) == 0:
                cand = np.arange(len(self.data))
            sims = self.data[cand] @ q
            top = np.argsort(-sims, kind="stable")[:k]
            take = cand[top]
            out_ids[qi, :len(take)] = self.ids[take]
            out_sims[qi, :len(take)] = sims[top]
        return out_ids, out_sims


def brute_force(data, ids, queries, k):
    """Exact top-k by inner product under the TOTAL order (-sim, row):
    ties break toward the lower row index, exactly like a stable
    descending sort. That makes the result well-defined under ties (a
    zero query vector ties every row at 0.0) and is what lets a
    sharded fleet's merged top-k be byte-identical to this reference:
    per-shard top-k under the same order, merged in shard order,
    resolves ties in exactly the same global row order.

    Implementation: fold -0.0 to +0.0 (bit order == value order for
    finite floats after that) and argpartition the float sims — the
    fast path. A row where the k-th value TIES values left outside the
    partition is ambiguous (partition picks ties arbitrarily); only
    those rows rerun under a composite uint64 (descending-sim bits |
    row) key, which encodes the total order exactly. Random float sims
    essentially never tie, so the composite pass normally touches only
    degenerate rows (zero queries). O(n + k log k) per query instead
    of a full stable sort of the corpus (measured ~8x the GEMM)."""
    sims = (queries @ data.T) + 0.0        # -0.0 -> +0.0, else unchanged
    n = sims.shape[1]
    k = min(int(k), n)
    if sims.dtype != np.float32 or n == 0 or k <= 0:
        top = np.argsort(-sims, axis=1, kind="stable")[:, :k]
        return ids[top], np.take_along_axis(sims, top, axis=1)
    if k >= n:
        top = np.argsort(_desc_keys(sims), axis=1)
    else:
        cand = np.argpartition(-sims, k - 1, axis=1)[:, :k]
        cvals = np.take_along_axis(sims, cand, axis=1)
        ck = _desc_keys(cvals, rows=cand)
        top = np.take_along_axis(cand, np.argsort(ck, axis=1), axis=1)
        bound = cvals.min(axis=1)          # smallest selected sim
        n_eq_all = np.count_nonzero(sims == bound[:, None], axis=1)
        n_eq_sel = np.count_nonzero(cvals == bound[:, None], axis=1)
        bad = n_eq_all != n_eq_sel         # a boundary tie leaked out
        if bad.any():
            key = _desc_keys(sims[bad])
            sub = np.argpartition(key, k - 1, axis=1)[:, :k]
            sk = np.take_along_axis(key, sub, axis=1)
            top[bad] = np.take_along_axis(
                sub, np.argsort(sk, axis=1), axis=1)
    return ids[top], np.take_along_axis(sims, top, axis=1)


def _desc_keys(sims: np.ndarray, rows=None) -> np.ndarray:
    """uint64 sort keys realizing the (-sim, row) total order: monotone
    float32->uint32 bit map, inverted for descending, row index in the
    low word as the tie-break. `rows` supplies explicit row indices for
    a candidate subset (defaults to 0..n-1)."""
    bits = sims.view(np.uint32)
    asc = np.where(bits >> 31, ~bits, bits | np.uint32(0x80000000))
    if rows is None:
        rows = np.arange(sims.shape[1], dtype=np.uint64)
    return ((~asc).astype(np.uint64) << np.uint64(32)) \
        | np.asarray(rows, dtype=np.uint64)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("model_dir")
    ap.add_argument("--query_ids", default="")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--nlist", type=int, default=64)
    ap.add_argument("--nprobe", type=int, default=8)
    ap.add_argument("--brute", action="store_true")
    args = ap.parse_args(argv)

    emb = np.load(os.path.join(args.model_dir, "embedding_0.npy"))
    ids_path = os.path.join(args.model_dir, "ids_0.npy")
    ids = (np.load(ids_path) if os.path.exists(ids_path)
           else np.arange(len(emb), dtype=np.uint64))
    if args.query_ids:
        qids = np.array([int(v) for v in args.query_ids.split(",")],
                        dtype=ids.dtype)
        rows = np.searchsorted(np.sort(ids), qids)
        order = np.argsort(ids)
        queries = emb[order[rows.clip(0, len(ids) - 1)]]
    else:
        qids = ids[:5]
        queries = emb[:5]
    if args.brute or len(emb) < 1000:
        out_ids, sims = brute_force(emb, ids, queries, args.k)
    else:
        index = IVFFlatIndex(args.nlist, args.nprobe)
        index.train_add(emb, ids)
        out_ids, sims = index.search(queries, args.k)
    for qi, qid in enumerate(qids):
        print(json.dumps({"query": int(qid),
                          "neighbors": out_ids[qi].tolist(),
                          "scores": [round(float(s), 4) for s in sims[qi]]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
