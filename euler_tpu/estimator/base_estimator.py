"""Training drivers: train / evaluate / infer / train_and_evaluate.

Parity: euler_estimator/python/base_estimator.py:27-180 (BaseEstimator on
tf.estimator: train loop with LoggingTensorHook + ProfilerHook, evaluate,
infer writing embedding_*.npy / ids_*.npy, checkpointing to model_dir).

TPU-first redesign: a functional train loop — flax TrainState + optax,
one jitted train_step (donate-argnums on state so HBM buffers are
reused), orbax checkpointing, jax.profiler for the profiling hook, and an
optional jax.sharding.Mesh for SPMD data parallelism (batch sharded over
the 'data' axis; parameters replicated — see euler_tpu.parallel for the
embedding-sharded variant).

The model contract is ModelOutput (embedding, loss, metric_name, metric);
input_fn is a host-side iterator of numpy batch dicts with STATIC shapes.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from euler_tpu import obs as _obs
from euler_tpu.utils import optimizers as opt_lib

# per-process estimator numbering: the label value distinguishing N
# estimators' children on the shared estimator_* metrics
_EST_IDS = itertools.count()


class TrainState(train_state.TrainState):
    """TrainState + mutable variable collections (scalable-encoder caches)
    + the nonfinite-guard skip counter (device scalar so the guarded step
    stays a single jitted dispatch)."""

    extra_vars: Dict[str, Any] = None
    skipped_steps: Any = None


def _to_device_tree(batch: Dict, max_id: int = 0) -> Dict:
    """numpy batch → jnp pytree. uint64 id arrays become int32 rows
    (bucketized by max_id+1 when provided) because TPU jit runs with x64
    disabled; all other arrays pass through."""

    def conv(v):
        if isinstance(v, np.ndarray) and v.dtype == np.uint64:
            if max_id > 0:
                v = (v % np.uint64(max_id + 1))
            return v.astype(np.int32)
        return v

    return jax.tree_util.tree_map(conv, batch)


def _merged(batch: Dict, static_batch: Dict) -> Dict:
    return {**batch, **static_batch} if static_batch else batch


def _last_finite(vals) -> float:
    """Most recent finite scalar in `vals` (NaN when none): run
    summaries report the last REAL loss, not a guard-skipped step's
    NaN."""
    for v in reversed(vals):
        f = float(v)
        if np.isfinite(f):
            return f
    return float("nan")


def _match_placement(new_tree, like_tree):
    """Re-place each restored leaf with the CURRENT leaf's sharding:
    snapshot/checkpoint restores go through host numpy, which would
    silently replicate a deliberately sharded leaf (e.g. a
    shard_act_cache'd activation cache) — re-inflating per-chip memory
    by mp with no error."""
    def place(new, like):
        sh = getattr(like, "sharding", None)
        if sh is not None:
            try:
                return jax.device_put(new, sh)
            except Exception:  # shape changed / mesh gone: plain array
                pass
        return jnp.asarray(new)

    try:
        return jax.tree_util.tree_map(place, new_tree, like_tree)
    except ValueError:  # tree structures differ (e.g. fresh collection)
        return jax.tree_util.tree_map(jnp.asarray, new_tree)


class BaseEstimator:
    """Drives a flax model with the ModelOutput contract.

    params dict (mirrors the reference's params into estimators):
      optimizer: name (default 'adam'), learning_rate, batch_size,
      log_steps, checkpoint_steps, max_id (for id bucketization),
      profiling (bool).
    """

    def __init__(self, model, params: Dict, model_dir: Optional[str] = None,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.model = model
        self.params_cfg = dict(params or {})
        self.model_dir = model_dir
        self.mesh = mesh
        self.tx = opt_lib.get(
            self.params_cfg.get("optimizer", "adam"),
            self.params_cfg.get("learning_rate", 0.01),
            weight_decay=float(self.params_cfg.get("weight_decay", 0.0)),
        )
        self.max_id = int(self.params_cfg.get("max_id", 0))
        # >1 → lax.scan over that many host batches per device dispatch
        # (the TPUEstimator iterations_per_loop idea): amortizes dispatch
        # and host↔device round-trip latency, which dominates when the
        # chip sits behind a network tunnel
        self.steps_per_loop = int(self.params_cfg.get("steps_per_loop", 1))
        self.log_steps = int(self.params_cfg.get("log_steps", 20))
        self.ckpt_steps = int(self.params_cfg.get("checkpoint_steps", 1000))
        self.profiling = bool(self.params_cfg.get("profiling", False))
        # nonfinite guard: a batch whose loss is NaN/Inf must not poison
        # the donated params/opt_state — the step skips the update and
        # counts it (see _make_one_step). Default ON; set
        # nonfinite_guard=False to trade the (tiny) lax.cond for raw
        # speed on trusted data.
        self.nonfinite_guard = bool(
            self.params_cfg.get("nonfinite_guard", True))
        # resilient input path: transient input-pipeline failures (a
        # flaky graph service) are retried with backoff; past the
        # retries, up to skip_batch_budget batches may be abandoned
        # (counted) before the error is treated as unrecoverable — at
        # which point an emergency checkpoint is written and the error
        # re-raises.
        self.input_retries = int(self.params_cfg.get("input_retries", 3))
        self.input_backoff_s = float(
            self.params_cfg.get("input_backoff_s", 0.1))
        self._skip_budget = int(
            self.params_cfg.get("skip_batch_budget", 0))
        # multi-worker host feeder (ISSUE 4): feeder_workers > 1 wraps
        # train()'s input stream in a ParallelPrefetcher — K sampler
        # threads feeding an ordered bounded queue. Estimators whose
        # batches are independent (NodeEstimator host mode) expose a
        # thread-safe per-batch factory so sampling itself runs in
        # parallel; otherwise only transform/prefetch overlap. Batch
        # ORDER stays deterministic per feeder, but which random roots
        # land in which position is not bit-reproducible vs serial.
        self.feeder_workers = int(self.params_cfg.get("feeder_workers", 0))
        self.feeder_depth = int(
            self.params_cfg.get("feeder_depth", 0)) or None
        # partitioned device-table tier (opt-in knobs, ISSUE 6): callers
        # that build the feature store from estimator params read these —
        # table_partition = K mesh shards for the feature table (0/1 =
        # replicated), hub_cache_frac = fraction of highest-degree rows
        # replicated on every chip in front of the partition
        # (PartitionedFeatureStore). Validated here so a typo'd config
        # fails at construction, not after a day of training.
        self.table_partition = int(self.params_cfg.get("table_partition", 0))
        if self.table_partition < 0:
            raise ValueError(
                f"table_partition must be >= 0, got {self.table_partition}")
        self.hub_cache_frac = float(
            self.params_cfg.get("hub_cache_frac", 0.0))
        if not 0.0 <= self.hub_cache_frac < 1.0:
            raise ValueError(
                f"hub_cache_frac must be in [0, 1), got "
                f"{self.hub_cache_frac}")
        if self.hub_cache_frac > 0 and self.table_partition <= 1:
            raise ValueError(
                "hub_cache_frac needs a partitioned table "
                "(table_partition >= 2): a replicated table has no "
                "remote leg for the hub cache to absorb")
        self._live_feeder = None
        self._input_factory = None
        # input-path counters live on the obs registry (children labeled
        # by estimator instance); input_health / health() are VIEWS over
        # them — the same numbers a /metrics scrape reports
        self._obs_name = f"estimator{next(_EST_IDS)}"
        reg = _obs.default_registry()
        lab = {"estimator": self._obs_name}
        self._ctr_input_failures = reg.counter(
            "estimator_input_failures_total",
            "input batches that raised", ("estimator",)).labels(**lab)
        self._ctr_input_retries = reg.counter(
            "estimator_input_retries_total",
            "input-pipeline retry sleeps", ("estimator",)).labels(**lab)
        self._ctr_skipped_batches = reg.counter(
            "estimator_skipped_batches_total",
            "input batches abandoned under skip_batch_budget",
            ("estimator",)).labels(**lab)
        self._hist_input_wait = reg.histogram(
            "estimator_input_wait_ms",
            "per-step host wait for the next batch (sampling + RPC + "
            "host→device conversion)", ("estimator",)).labels(**lab)
        self._hist_device_step = reg.histogram(
            "estimator_device_step_ms",
            "per-step train-step dispatch", ("estimator",)).labels(**lab)
        self._hist_hook = reg.histogram(
            "estimator_hook_ms",
            "per-step logging/checkpoint hooks", ("estimator",)
        ).labels(**lab)
        self._g_steps_per_sec = reg.gauge(
            "estimator_steps_per_sec", "train-loop throughput",
            ("estimator",)).labels(**lab)
        self._g_skipped_steps = reg.gauge(
            "estimator_skipped_steps",
            "nonfinite-guard skipped device steps",
            ("estimator",)).labels(**lab)
        self._g_global_step = reg.gauge(
            "estimator_global_step", "last reported global step",
            ("estimator",)).labels(**lab)
        # non-counter health fields (strings / one-shot markers) stay
        # instance-side; the input_health view merges them back in
        self._input_meta: Dict[str, Any] = {
            "emergency_checkpoint_step": None, "last_input_error": None}
        _obs.register_health(self._obs_name, self.health)
        self.state: Optional[TrainState] = None
        self._train_step = None
        self._train_loop = None
        self._eval_step = None
        self._ckpt_mgr = None
        # device-resident arrays merged into every batch (e.g. a
        # DeviceFeatureStore table): same jax.Array object each step, so
        # jit sees a cached on-device arg — no per-step transfer
        self.static_batch: Dict[str, Any] = {}
        # called with this estimator right before every interleaved and
        # final evaluation in train_and_evaluate (e.g. a full-coverage
        # activation-cache refresh, models/graphsage.refresh_act_cache)
        self.pre_eval_hook = None

    # -- setup -------------------------------------------------------------
    def _init_state(self, batch: Dict, rng=None) -> None:
        rng = rng if rng is not None else jax.random.key(
            int(self.params_cfg.get("seed", 0)))
        variables = self.model.init(rng, batch)
        params = variables.pop("params")
        self.state = TrainState.create(
            apply_fn=self.model.apply, params=params, tx=self.tx,
            extra_vars=dict(variables),
            skipped_steps=jnp.zeros((), jnp.int32),
        )

    def _make_one_step(self):
        """The single SGD step shared by the per-step jit and the scanned
        loop — one definition so the two dispatch paths cannot drift."""
        mutable_keys = [k for k in (self.state.extra_vars or {})]
        dropout_key = jax.random.key(
            int(self.params_cfg.get("seed", 0)) + 1)

        def one_step(state: TrainState, batch):
            # per-step dropout rng; eval applies without rngs → dropout
            # layers run deterministic there
            rngs = {"dropout": jax.random.fold_in(dropout_key, state.step)}

            def loss_fn(p):
                variables = {"params": p, **(state.extra_vars or {})}
                if mutable_keys:
                    out, new_vars = state.apply_fn(
                        variables, batch, mutable=mutable_keys, rngs=rngs)
                else:
                    out = state.apply_fn(variables, batch, rngs=rngs)
                    new_vars = {}
                return out.loss, (out, new_vars)

            (loss, (out, new_vars)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)

            def apply_update(_):
                s2 = state.apply_gradients(grads=grads)
                if new_vars:
                    s2 = s2.replace(extra_vars=dict(new_vars))
                return s2

            def skip_update(_):
                # bad batch: keep params/opt_state/extra_vars, advance
                # the step (so dropout rng / schedules move on) and
                # count the skip — the donated buffers survive intact
                return state.replace(
                    step=state.step + 1,
                    skipped_steps=state.skipped_steps + 1)

            if self.nonfinite_guard and state.skipped_steps is not None:
                # guard the GRADS too: overflow in the backward pass can
                # yield NaN grads under a finite loss, which would poison
                # the donated params with skipped_steps still reading 0
                ok = jnp.isfinite(loss)
                for g in jax.tree_util.tree_leaves(grads):
                    ok &= jnp.all(jnp.isfinite(g))
                state = jax.lax.cond(ok, apply_update, skip_update, None)
            else:
                state = apply_update(None)
            return state, loss, out.metric

        return one_step

    def _build_train_step(self):
        train_step = self._make_one_step()

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            data = NamedSharding(self.mesh, P("data"))
            self._data_sharding = data
            train_step = jax.jit(
                train_step,
                donate_argnums=(0,),
            )
        else:
            train_step = jax.jit(train_step, donate_argnums=(0,))
        return train_step

    def _build_train_loop(self):
        """K steps per dispatch: scan the single-step body over a batch
        pytree stacked on axis 0. static_batch rides as an explicit arg
        so the feature table isn't baked into the jaxpr as a constant."""
        one_step = self._make_one_step()

        def train_loop(state: TrainState, batches, static_batch):
            def body(s, b):
                s, loss, metric = one_step(s, _merged(b, static_batch))
                return s, (loss, metric)

            state, (losses, metrics) = jax.lax.scan(body, state, batches)
            return state, losses, metrics

        return jax.jit(train_loop, donate_argnums=(0,))

    def _build_eval_step(self):
        def eval_step(state: TrainState, batch):
            variables = {"params": state.params, **(state.extra_vars or {})}
            out = state.apply_fn(variables, batch)
            return out.loss, out.metric, out.embedding

        return jax.jit(eval_step)

    def _checkpoint_manager(self):
        if self._ckpt_mgr is None and self.model_dir:
            import orbax.checkpoint as ocp

            path = os.path.abspath(os.path.join(self.model_dir, "checkpoints"))
            os.makedirs(path, exist_ok=True)
            self._ckpt_mgr = ocp.CheckpointManager(
                path, options=ocp.CheckpointManagerOptions(max_to_keep=3))
        return self._ckpt_mgr

    def save_checkpoint(self, step: int) -> None:
        mgr = self._checkpoint_manager()
        if mgr is None:
            return
        import orbax.checkpoint as ocp

        payload = {"params": self.state.params,
                   "opt_state": self.state.opt_state,
                   "extra_vars": self.state.extra_vars or {},
                   # persisted explicitly (not only as the checkpoint
                   # label) so a resumed run restarts at the right step
                   # instead of 0 and re-overwriting earlier checkpoints
                   "step": int(self.state.step)}
        mgr.save(step, args=ocp.args.StandardSave(payload))

    def finalize_checkpoints(self) -> None:
        """Block until async orbax saves commit — called at the end of
        every train path so a process exiting right after train() never
        leaves a half-written checkpoint (observed as futures-after-
        shutdown errors at exit). Mid-training saves stay async."""
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait_until_finished()

    def restore_checkpoint(self) -> Optional[int]:
        mgr = self._checkpoint_manager()
        if mgr is None or mgr.latest_step() is None:
            return None
        import orbax.checkpoint as ocp

        step = mgr.latest_step()
        payload = {"params": self.state.params,
                   "opt_state": self.state.opt_state,
                   "extra_vars": self.state.extra_vars or {},
                   "step": int(self.state.step)}
        try:
            restored = mgr.restore(step,
                                   args=ocp.args.StandardRestore(payload))
        except Exception as first_err:
            # pre-step-persisting checkpoint layout: retry without the
            # step entry and fall back to the checkpoint label. If the
            # legacy-layout retry ALSO fails, the checkpoint is broken
            # for some other reason — re-raise the ORIGINAL error so the
            # real diagnosis isn't masked by a missing-key complaint.
            payload.pop("step")
            try:
                restored = mgr.restore(
                    step, args=ocp.args.StandardRestore(payload))
            except Exception:
                raise first_err
        resume_step = int(restored.get("step", step))
        self.state = self.state.replace(
            params=restored["params"], opt_state=restored["opt_state"],
            step=jnp.asarray(resume_step, dtype=jnp.int32),
            extra_vars=_match_placement(restored.get("extra_vars") or {},
                                        self.state.extra_vars or {}))
        return resume_step

    # -- resilient input path ----------------------------------------------
    def _skipped_steps(self) -> int:
        """Nonfinite-guard skip count from device state (0 pre-init)."""
        if self.state is None or self.state.skipped_steps is None:
            return 0
        return int(jax.device_get(self.state.skipped_steps))

    @property
    def input_health(self) -> Dict[str, Any]:
        """Input-path counters — a compatibility VIEW over this
        estimator's obs registry children (plus the instance-side
        last-error / emergency-checkpoint markers); mutate the counters
        through the registry children, not this dict."""
        return {
            "input_failures": int(self._ctr_input_failures.value),
            "input_retries": int(self._ctr_input_retries.value),
            "skipped_batches": int(self._ctr_skipped_batches.value),
            **self._input_meta,
        }

    def health(self) -> Dict[str, Any]:
        """Input-path + train-step degradation counters, merged with the
        graph client's health() when the estimator's graph exposes one —
        a single surface for 'did this run degrade?'.

        skipped_steps comes from the obs GAUGE (refreshed by the train
        thread at every log hook and at train()'s end), NOT from device
        state: health() runs on the /healthz scrape thread, and a
        device_get there could touch buffers the in-flight train step
        has already donated. Mid-train the value is at most one log
        window stale; after train() it is exact."""
        out = dict(self.input_health)
        out["skipped_steps"] = int(self._g_skipped_steps.value)
        graph_health = getattr(getattr(self, "graph", None), "health", None)
        if callable(graph_health):
            out["graph"] = graph_health()
        # partitioned feature-store tier (NodeEstimator feature_store=
        # PartitionedFeatureStore): degree stats + the hub-cache
        # hit/miss and gather-leg counters, same pattern as the client
        # cache's cache_stats()
        store_stats = getattr(getattr(self, "feature_store", None),
                              "cache_stats", None)
        if callable(store_stats):
            out["feature_store"] = store_stats()
        return out

    def _phase(self, name: str, hist):
        """Span + histogram for one train-loop phase (input_wait /
        device_step / hook). obs.timed_span never swallows exceptions —
        a StopIteration from the input iterator propagates to the
        loops' break handlers unchanged."""
        return _obs.timed_span(name, hist, estimator=self._obs_name)

    def _emergency_checkpoint(self, err: BaseException) -> None:
        """Best-effort checkpoint before an unrecoverable input error
        re-raises — the run dies, the progress doesn't. Never masks the
        original error."""
        if self.state is None:
            return
        step = int(self.state.step)
        try:
            self.save_checkpoint(step)
            self.finalize_checkpoints()
            if self.model_dir:
                self._input_meta["emergency_checkpoint_step"] = step
                print(f"emergency checkpoint at step {step} before "
                      f"re-raising input error: {err}", flush=True)
        except Exception as ce:  # pragma: no cover - disk-full etc.
            print(f"emergency checkpoint failed ({ce}); "
                  f"re-raising original input error", flush=True)

    # -- multi-worker feeder -----------------------------------------------
    def _train_batch_factory(self):
        """Thread-safe zero-arg one-batch callable for the multi-worker
        feeder, or None when the input stream must stay serialized
        (subclass hook — see NodeEstimator)."""
        return None

    def _wrap_feeder(self, input_fn, use_factory: bool = True):
        """ParallelPrefetcher over the train input: the subclass batch
        factory when one exists AND the caller passed the estimator's
        own train_input_fn (parallel sampling); a CUSTOM input_fn's
        stream is never substituted — it wraps with serialized next()
        so its schedule (e.g. a chaos kill script) is preserved."""
        from euler_tpu.estimator.prefetch import ParallelPrefetcher

        src = self._train_batch_factory() if use_factory else None
        if src is None:
            src = input_fn() if callable(input_fn) else input_fn
        f = ParallelPrefetcher(src, workers=self.feeder_workers,
                               depth=self.feeder_depth,
                               name=f"{self._obs_name}_train")
        self._live_feeder = f
        return f

    def _close_live_feeder(self) -> None:
        f, self._live_feeder = self._live_feeder, None
        if f is not None:
            f.close()

    def _next_input(self, it):
        """next(it) with transient-failure retry (exponential backoff)
        and the skip-batch budget. Returns (raw_batch, it) — the
        iterator may have been recreated from the train input_fn after a
        failure (a generator that raised is dead). StopIteration passes
        through; an unrecoverable error checkpoints then re-raises.

        Contract: retry/skip assumes input_fn() returns a STATELESS
        (infinite random-sampler) stream — the estimator convention; all
        built-in input_fns qualify — because recreation restarts the
        stream. A finite deterministic stream would replay its head on
        every recreation, so pass those as plain iterators instead: with
        no factory every input failure is treated as unrecoverable
        (emergency checkpoint + re-raise), never silently replayed."""
        attempts = 0
        while True:
            try:
                return next(it), it
            except StopIteration:
                raise
            except Exception as e:
                from euler_tpu.graph.remote import retryable_error

                # retry needs a recreatable source: a generator that
                # raised is dead (next() would yield StopIteration and
                # silently END training) — without the input_fn factory
                # every failure is unrecoverable. A RESILIENT feeder
                # (ParallelPrefetcher) survives its own errors, so it
                # is retryable even when passed as a bare iterator.
                transient = ((self._input_factory is not None
                              or getattr(it, "resilient", False))
                             and (retryable_error(e)
                                  or isinstance(e, OSError)))
                self._ctr_input_failures.inc()
                self._input_meta["last_input_error"] = str(e)
                if not transient:
                    self._emergency_checkpoint(e)
                    raise
                if attempts < self.input_retries:
                    attempts += 1
                    self._ctr_input_retries.inc()
                    with _obs.span("input_retry_backoff",
                                   estimator=self._obs_name,
                                   attempt=attempts):
                        time.sleep(min(
                            self.input_backoff_s * (2 ** (attempts - 1)),
                            2.0))
                elif self._skip_budget > 0:
                    # retries exhausted for this batch: abandon it and
                    # move on (countable degraded event, not a job kill)
                    self._skip_budget -= 1
                    self._ctr_skipped_batches.inc()
                    attempts = 0
                else:
                    self._emergency_checkpoint(e)
                    raise
                if self._input_factory is not None and not getattr(
                        it, "resilient", False):
                    # the raised iter is dead — close it first (a feeder
                    # holds worker threads; a generator's close() is a
                    # no-op) then recreate. A resilient feeder
                    # (ParallelPrefetcher) delivers the error in-stream
                    # and keeps producing: just call next() again.
                    closer = getattr(it, "close", None)
                    if callable(closer):
                        try:
                            closer()
                        except Exception:
                            pass
                    it = self._input_factory()

    # -- drivers -----------------------------------------------------------
    def train(self, input_fn: Callable[[], Iterator[Dict]],
              max_steps: int = 1000) -> Dict[str, float]:
        if self.feeder_workers > 1 and callable(input_fn):
            # multi-worker feeder: K sampler threads over the input
            # stream; it owns worker threads, so train() reclaims it on
            # every exit path and recreation-on-failure rebuilds it
            use_factory = input_fn == getattr(self, "train_input_fn",
                                              None)
            it = self._wrap_feeder(input_fn, use_factory)
            self._input_factory = lambda: self._wrap_feeder(input_fn,
                                                            use_factory)
            try:
                return self._train_impl(it, max_steps)
            finally:
                self._close_live_feeder()
        it = input_fn() if callable(input_fn) else input_fn
        self._input_factory = input_fn if callable(input_fn) else None
        return self._train_impl(it, max_steps)

    def _train_impl(self, it, max_steps: int) -> Dict[str, float]:
        with self._phase("input_wait", self._hist_input_wait):
            raw0, it = self._next_input(it)
            raw_first = _to_device_tree(raw0, self.max_id)
        first = _merged(raw_first, self.static_batch)
        if self.state is None:
            self._init_state(first)
            self.restore_checkpoint()
        if self._train_step is None:
            self._train_step = self._build_train_step()
        if self.profiling and self.model_dir:
            jax.profiler.start_trace(os.path.join(self.model_dir, "prof"))
        if self.steps_per_loop > 1:
            # pass the UNMERGED batch: the looped path stacks raw batches
            # and merges static_batch inside the scanned body
            return self._run_looped(it, raw_first, max_steps)
        step = int(self.state.step)
        start_step = step
        losses, metrics = [], []
        # monotonic everywhere in the loop: an NTP step during a long
        # run must not corrupt rates (same bug class PR 2 fixed in
        # FileBarrier.wait)
        t0 = time.monotonic()
        batch = first
        last_log = t0
        while step < max_steps:
            with _obs.span("train_step", estimator=self._obs_name,
                           step=step):
                with self._phase("device_step", self._hist_device_step):
                    self.state, loss, metric = self._train_step(
                        self.state, _merged(batch, self.static_batch))
                step += 1
                losses.append(loss)
                metrics.append(metric)
                do_log = step % self.log_steps == 0
                do_ckpt = self.ckpt_steps and step % self.ckpt_steps == 0
                if do_log or do_ckpt:
                    with self._phase("hook", self._hist_hook):
                        if do_log:
                            # nanmean: a guard-skipped step's NaN
                            # loss/metric must not turn the whole
                            # window's log line into nan
                            lv = float(jnp.nanmean(jnp.stack(
                                losses[-self.log_steps:])))
                            mv = float(jnp.nanmean(jnp.stack(
                                metrics[-self.log_steps:])))
                            now = time.monotonic()
                            rate = self.log_steps / max(now - last_log,
                                                        1e-9)
                            last_log = now
                            self._g_steps_per_sec.set(rate)
                            # train thread owns the state buffers here
                            # (between dispatches) — safe sync point to
                            # refresh the gauge health() reads
                            self._g_skipped_steps.set(
                                self._skipped_steps())
                            print(f"step {step}: loss={lv:.4f} "
                                  f"metric={mv:.4f} "
                                  f"({rate:.1f} steps/s)", flush=True)
                        if do_ckpt:
                            self.save_checkpoint(step)
                if step < max_steps:
                    try:
                        with self._phase("input_wait",
                                         self._hist_input_wait):
                            raw, it = self._next_input(it)
                            batch = _to_device_tree(raw, self.max_id)
                    except StopIteration:
                        break
        if self.ckpt_steps:
            self.save_checkpoint(step)
        self.finalize_checkpoints()
        if self.profiling and self.model_dir:
            jax.profiler.stop_trace()
        rate = (step - start_step) / max(time.monotonic() - t0, 1e-9)
        skipped = self._skipped_steps()
        self._g_steps_per_sec.set(rate)
        self._g_skipped_steps.set(skipped)
        self._g_global_step.set(step)
        return {
            # guard-skipped steps report NaN loss/metric; exclude them
            # from the summary so one bad batch doesn't blank the run's
            # headline numbers (the skip itself is in skipped_steps)
            "loss": _last_finite(losses),
            "metric": float(jnp.nanmean(jnp.stack(metrics)))
            if metrics else 0.0,
            "steps_per_sec": rate,
            "global_step": step,
            "skipped_steps": skipped,
            "skipped_batches": self.input_health["skipped_batches"],
        }

    def _run_looped(self, it, first: Dict, max_steps: int) -> Dict[str, float]:
        """steps_per_loop > 1 train path: full K-step windows dispatch as
        one scanned device call; a tail shorter than K falls back to the
        single-step function (no partial-scan recompile)."""
        K = self.steps_per_loop
        step = int(self.state.step)
        start_step = step
        loop_losses, loop_metrics = [], []
        last_loss = float("nan")
        t0 = time.monotonic()
        last_log = t0
        logged_at = step
        buf = [first]
        exhausted = False

        def stack(*xs):
            if isinstance(xs[0], np.ndarray):
                return np.stack(xs)
            return jnp.stack(xs)

        while step < max_steps:
            want = min(K, max_steps - step)
            if len(buf) < want and not exhausted:
                with self._phase("input_wait", self._hist_input_wait):
                    while len(buf) < want and not exhausted:
                        try:
                            raw, it = self._next_input(it)
                            buf.append(_to_device_tree(raw, self.max_id))
                        except StopIteration:
                            exhausted = True
            if not buf:
                break
            if len(buf) == K:
                if self._train_loop is None:
                    self._train_loop = self._build_train_loop()
                stacked = jax.tree_util.tree_map(stack, *buf)
                with self._phase("device_step", self._hist_device_step):
                    self.state, l_arr, m_arr = self._train_loop(
                        self.state, stacked, self.static_batch)
                # nanmean / last-finite: guard-skipped steps inside the
                # scanned window report NaN and must not poison the
                # window aggregate or the reported final loss
                loop_losses.append((jnp.nanmean(l_arr), K))
                loop_metrics.append((jnp.nanmean(m_arr), K))
                fin = np.asarray(l_arr)
                fin = fin[np.isfinite(fin)]
                if fin.size:
                    last_loss = float(fin[-1])
                done = K
            else:
                # tail shorter than K: single-step dispatches (the jit
                # was built in train() before this path was entered)
                for b in buf:
                    with self._phase("device_step",
                                     self._hist_device_step):
                        self.state, l, m = self._train_step(
                            self.state, _merged(b, self.static_batch))
                    loop_losses.append((l, 1))
                    loop_metrics.append((m, 1))
                    if np.isfinite(float(l)):
                        last_loss = float(l)
                done = len(buf)
            prev = step
            step += done
            buf = []
            do_log = step - logged_at >= self.log_steps
            do_ckpt = self.ckpt_steps and \
                step // self.ckpt_steps > prev // self.ckpt_steps
            if do_log or do_ckpt:
                with self._phase("hook", self._hist_hook):
                    if do_log:
                        now = time.monotonic()
                        rate = (step - logged_at) / max(now - last_log,
                                                        1e-9)
                        self._g_steps_per_sec.set(rate)
                        self._g_skipped_steps.set(self._skipped_steps())
                        print(f"step {step}: "
                              f"loss={float(loop_losses[-1][0]):.4f} "
                              f"metric={float(loop_metrics[-1][0]):.4f} "
                              f"({rate:.1f} steps/s)", flush=True)
                        last_log, logged_at = now, step
                    if do_ckpt:
                        self.save_checkpoint(step)
            if exhausted:
                break
        if self.ckpt_steps:
            self.save_checkpoint(step)
        self.finalize_checkpoints()
        if self.profiling and self.model_dir:
            jax.profiler.stop_trace()
        # step-weighted mean so the reported train metric matches what
        # the same run would report with steps_per_loop=1; NaN entries
        # (guard-skipped steps / all-skipped windows) drop out with
        # their weight
        if loop_metrics:
            w = np.asarray([c for _, c in loop_metrics], np.float64)
            vals = np.asarray([float(v) for v, _ in loop_metrics])
            keep = np.isfinite(vals)
            metric = float(np.dot(vals[keep], w[keep] / w[keep].sum())) \
                if keep.any() else float("nan")
        else:
            metric = 0.0
        rate = (step - start_step) / max(time.monotonic() - t0, 1e-9)
        skipped = self._skipped_steps()
        self._g_steps_per_sec.set(rate)
        self._g_skipped_steps.set(skipped)
        self._g_global_step.set(step)
        return {
            "loss": float(last_loss),
            "metric": metric,
            "steps_per_sec": rate,
            "global_step": step,
            "skipped_steps": skipped,
            "skipped_batches": self.input_health["skipped_batches"],
        }

    def evaluate(self, input_fn, steps: int = 100) -> Dict[str, float]:
        it = input_fn() if callable(input_fn) else input_fn
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        losses, metrics, weights = [], [], []
        for _ in range(steps):
            try:
                raw = next(it)
            except StopIteration:
                break
            batch = _to_device_tree(raw, self.max_id)
            if self.state is None:
                self._init_state(_merged(batch, self.static_batch))
                self.restore_checkpoint()
                self._eval_step = self._build_eval_step()
            loss, metric, _ = self._eval_step(
                self.state, _merged(batch, self.static_batch))
            losses.append(float(loss))
            metrics.append(float(metric))
            # masked batches (graph packing / node eval sweeps) report
            # per-batch means over n_real entries; weight them so a short
            # final sweep batch doesn't count like a full one
            mask = None
            if isinstance(raw, dict):
                mask = raw.get("graph_mask")
                if mask is None:
                    mask = raw.get("metric_mask")
            weights.append(float(np.sum(mask)) if mask is not None else 1.0)
        if not losses:
            return {"loss": float("nan"), "metric": float("nan")}
        w = np.asarray(weights)
        w = w / w.sum()
        return {"loss": float(np.dot(losses, w)),
                "metric": float(np.dot(metrics, w))}

    def infer(self, input_fn, steps: int = 100,
              id_key: str = "infer_ids") -> Dict[str, str]:
        """Writes embedding_0.npy / ids_0.npy under model_dir (parity:
        reference infer artifacts base_estimator.py:157-180)."""
        it = input_fn() if callable(input_fn) else input_fn
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        embs, ids = [], []
        for _ in range(steps):
            try:
                raw = next(it)
            except StopIteration:
                break
            batch = _to_device_tree(raw, self.max_id)
            if self.state is None:
                self._init_state(_merged(batch, self.static_batch))
                self.restore_checkpoint()
                self._eval_step = self._build_eval_step()
            _, _, emb = self._eval_step(
                self.state, _merged(batch, self.static_batch))
            embs.append(np.asarray(emb))
            key = id_key if id_key in raw else ("ids" if "ids" in raw else None)
            if key is not None:
                v = raw[key]
                v = v[0] if isinstance(v, list) else v
                ids.append(np.asarray(v).ravel()[: emb.shape[0]])
        out_dir = self.model_dir or "."
        os.makedirs(out_dir, exist_ok=True)
        emb_path = os.path.join(out_dir, "embedding_0.npy")
        np.save(emb_path, np.concatenate(embs) if embs else np.zeros((0,)))
        id_path = os.path.join(out_dir, "ids_0.npy")
        if ids:
            np.save(id_path, np.concatenate(ids))
        return {"embedding": emb_path, "ids": id_path}

    def export_bundle(self, out_dir: str, input_fn=None,
                      steps: int = 1_000_000, nlist: int = 64,
                      nprobe: int = 8, index: bool = True,
                      shards: int = 1, version: Optional[str] = None,
                      extra_meta: Optional[Dict[str, Any]] = None):
        """Export a versioned serving bundle (euler_tpu.serving): the
        trained parameter pytree, the full node-embedding matrix from a
        batched `embed_all` inference pass over `input_fn` (default:
        this estimator's infer_input_fn sweep), and an IVFFlat index
        over it — everything the InferenceServer needs, checksummed in
        a manifest so corruption is detected at load. `shards > 1`
        writes the partitioned fleet layout instead (contiguous 1/N row
        shards, per-shard IVFFlat, one manifest) for a sharded serving
        fleet; `version` stamps the bundle_version the hot-swap
        protocol reports (default: the training step). Returns the
        ModelBundle (already written to out_dir)."""
        import dataclasses

        import jax.tree_util as jtu

        from euler_tpu.serving.export import ModelBundle, embed_all
        from euler_tpu.tools.knn import IVFFlatIndex

        ids, emb = embed_all(self, input_fn, steps)
        leaves = jtu.tree_flatten_with_path(self.state.params)[0]
        params = {jtu.keystr(path): np.asarray(jax.device_get(leaf))
                  for path, leaf in leaves}
        spec: Dict[str, Any] = {"model_class": type(self.model).__name__}
        if dataclasses.is_dataclass(self.model):
            for f in dataclasses.fields(self.model):
                if f.name in ("parent", "name"):
                    continue
                v = getattr(self.model, f.name, None)
                if isinstance(v, (str, int, float, bool)) or v is None:
                    spec[f.name] = v
        meta = {"global_step": int(self.state.step), **(extra_meta or {})}
        if version is not None:
            meta["bundle_version"] = str(version)
        index_state = None
        if index and shards == 1 and len(ids) >= 2:
            # the global index only serves the unsharded layout;
            # save_sharded trains one per shard instead
            idx = IVFFlatIndex(nlist=nlist, nprobe=nprobe)
            idx.train_add(emb, ids)
            index_state = idx.state_dict()
        bundle = ModelBundle(params, emb, ids, index_state, spec, meta)
        if shards > 1:
            bundle.save_sharded(out_dir, shards, nlist=nlist,
                                nprobe=nprobe, index=index)
        else:
            bundle.save(out_dir)
        return bundle

    def train_and_evaluate(self, train_input_fn, eval_input_fn,
                           max_steps: int = 1000,
                           eval_steps: int = 50,
                           eval_every: int = 0,
                           keep_best: bool = False) -> Dict[str, float]:
        """Train with optional interleaved evaluation.

        eval_every > 0 evaluates on eval_input_fn every that many train
        steps (the reference's tf.estimator.train_and_evaluate interleaves
        the same way); keep_best additionally snapshots the parameters at
        the best interleaved eval metric and restores them before the
        final evaluation — the standard early-stopping protocol for the
        citation benchmarks, whose small train splits overfit long before
        a fixed step budget ends.
        """
        if eval_every <= 0:
            train_res = self.train(train_input_fn, max_steps)
            if self.pre_eval_hook:
                self.pre_eval_hook(self)
            eval_res = self.evaluate(eval_input_fn, eval_steps)
            return {**{f"train_{k}": v for k, v in train_res.items()},
                    **{f"eval_{k}": v for k, v in eval_res.items()}}

        owned_feeder = self.feeder_workers > 1 and callable(train_input_fn)
        if owned_feeder:
            # one feeder spans every train segment (segments pass it as
            # a bare iterator, so train() doesn't wrap or close it)
            it = self._wrap_feeder(
                train_input_fn,
                train_input_fn == getattr(self, "train_input_fn", None))
        else:
            it = train_input_fn() if callable(train_input_fn) \
                else train_input_fn
        best_metric, best_step, best_snap = -float("inf"), 0, None
        train_res: Dict[str, float] = {}
        step = 0
        # segments checkpoint once at the end (at the restored-best
        # weights), not once per segment
        saved_ckpt_steps, self.ckpt_steps = self.ckpt_steps, 0
        try:
            while step < max_steps:
                target = min(step + eval_every, max_steps)
                try:
                    seg = self.train(it, max_steps=target)
                except StopIteration:
                    break  # train iterator exhausted at a segment edge
                train_res = seg
                step = seg["global_step"]
                if self.pre_eval_hook:
                    self.pre_eval_hook(self)
                ev = self.evaluate(eval_input_fn, eval_steps)
                m = ev["metric"]
                if keep_best and (best_snap is None or m > best_metric):
                    best_metric, best_step = m, step
                    best_snap = jax.device_get(
                        {"params": self.state.params,
                         "extra_vars": self.state.extra_vars or {}})
                if step < target:
                    break  # train iterator exhausted mid-segment
        finally:
            self.ckpt_steps = saved_ckpt_steps
            if owned_feeder:
                self._close_live_feeder()
        if keep_best and best_snap is not None:
            self.state = self.state.replace(
                params=jax.tree_util.tree_map(jnp.asarray,
                                              best_snap["params"]),
                extra_vars=_match_placement(
                    best_snap["extra_vars"],
                    self.state.extra_vars or {}) or {})
        if self.ckpt_steps and self.state is not None:
            self.save_checkpoint(step)  # disk matches the reported weights
            self.finalize_checkpoints()
        if self.pre_eval_hook:
            # the restored-best snapshot's cache was refreshed before
            # its eval, but keep_best=False (or a first-segment
            # StopIteration) reaches here without any refresh at all
            self.pre_eval_hook(self)
        eval_res = self.evaluate(eval_input_fn, eval_steps)
        out = {**{f"train_{k}": v for k, v in train_res.items()},
               **{f"eval_{k}": v for k, v in eval_res.items()}}
        if keep_best:
            out["best_step"] = best_step
        return out
