"""Training drivers: train / evaluate / infer / train_and_evaluate.

Parity: euler_estimator/python/base_estimator.py:27-180 (BaseEstimator on
tf.estimator: train loop with LoggingTensorHook + ProfilerHook, evaluate,
infer writing embedding_*.npy / ids_*.npy, checkpointing to model_dir).

TPU-first redesign: a functional train loop — flax TrainState + optax,
one jitted train_step (donate-argnums on state so HBM buffers are
reused), orbax checkpointing, jax.profiler for the profiling hook, and an
optional jax.sharding.Mesh for SPMD data parallelism (batch sharded over
the 'data' axis; parameters replicated — see euler_tpu.parallel for the
embedding-sharded variant).

The model contract is ModelOutput (embedding, loss, metric_name, metric);
input_fn is a host-side iterator of numpy batch dicts with STATIC shapes.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from euler_tpu.utils import optimizers as opt_lib


class TrainState(train_state.TrainState):
    """TrainState + mutable variable collections (scalable-encoder caches)."""

    extra_vars: Dict[str, Any] = None


def _to_device_tree(batch: Dict, max_id: int = 0) -> Dict:
    """numpy batch → jnp pytree. uint64 id arrays become int32 rows
    (bucketized by max_id+1 when provided) because TPU jit runs with x64
    disabled; all other arrays pass through."""

    def conv(v):
        if isinstance(v, np.ndarray) and v.dtype == np.uint64:
            if max_id > 0:
                v = (v % np.uint64(max_id + 1))
            return v.astype(np.int32)
        return v

    return jax.tree_util.tree_map(conv, batch)


def _merged(batch: Dict, static_batch: Dict) -> Dict:
    return {**batch, **static_batch} if static_batch else batch


def _match_placement(new_tree, like_tree):
    """Re-place each restored leaf with the CURRENT leaf's sharding:
    snapshot/checkpoint restores go through host numpy, which would
    silently replicate a deliberately sharded leaf (e.g. a
    shard_act_cache'd activation cache) — re-inflating per-chip memory
    by mp with no error."""
    def place(new, like):
        sh = getattr(like, "sharding", None)
        if sh is not None:
            try:
                return jax.device_put(new, sh)
            except Exception:  # shape changed / mesh gone: plain array
                pass
        return jnp.asarray(new)

    try:
        return jax.tree_util.tree_map(place, new_tree, like_tree)
    except ValueError:  # tree structures differ (e.g. fresh collection)
        return jax.tree_util.tree_map(jnp.asarray, new_tree)


class BaseEstimator:
    """Drives a flax model with the ModelOutput contract.

    params dict (mirrors the reference's params into estimators):
      optimizer: name (default 'adam'), learning_rate, batch_size,
      log_steps, checkpoint_steps, max_id (for id bucketization),
      profiling (bool).
    """

    def __init__(self, model, params: Dict, model_dir: Optional[str] = None,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.model = model
        self.params_cfg = dict(params or {})
        self.model_dir = model_dir
        self.mesh = mesh
        self.tx = opt_lib.get(
            self.params_cfg.get("optimizer", "adam"),
            self.params_cfg.get("learning_rate", 0.01),
            weight_decay=float(self.params_cfg.get("weight_decay", 0.0)),
        )
        self.max_id = int(self.params_cfg.get("max_id", 0))
        # >1 → lax.scan over that many host batches per device dispatch
        # (the TPUEstimator iterations_per_loop idea): amortizes dispatch
        # and host↔device round-trip latency, which dominates when the
        # chip sits behind a network tunnel
        self.steps_per_loop = int(self.params_cfg.get("steps_per_loop", 1))
        self.log_steps = int(self.params_cfg.get("log_steps", 20))
        self.ckpt_steps = int(self.params_cfg.get("checkpoint_steps", 1000))
        self.profiling = bool(self.params_cfg.get("profiling", False))
        self.state: Optional[TrainState] = None
        self._train_step = None
        self._train_loop = None
        self._eval_step = None
        self._ckpt_mgr = None
        # device-resident arrays merged into every batch (e.g. a
        # DeviceFeatureStore table): same jax.Array object each step, so
        # jit sees a cached on-device arg — no per-step transfer
        self.static_batch: Dict[str, Any] = {}
        # called with this estimator right before every interleaved and
        # final evaluation in train_and_evaluate (e.g. a full-coverage
        # activation-cache refresh, models/graphsage.refresh_act_cache)
        self.pre_eval_hook = None

    # -- setup -------------------------------------------------------------
    def _init_state(self, batch: Dict, rng=None) -> None:
        rng = rng if rng is not None else jax.random.key(
            int(self.params_cfg.get("seed", 0)))
        variables = self.model.init(rng, batch)
        params = variables.pop("params")
        self.state = TrainState.create(
            apply_fn=self.model.apply, params=params, tx=self.tx,
            extra_vars=dict(variables),
        )

    def _make_one_step(self):
        """The single SGD step shared by the per-step jit and the scanned
        loop — one definition so the two dispatch paths cannot drift."""
        mutable_keys = [k for k in (self.state.extra_vars or {})]
        dropout_key = jax.random.key(
            int(self.params_cfg.get("seed", 0)) + 1)

        def one_step(state: TrainState, batch):
            # per-step dropout rng; eval applies without rngs → dropout
            # layers run deterministic there
            rngs = {"dropout": jax.random.fold_in(dropout_key, state.step)}

            def loss_fn(p):
                variables = {"params": p, **(state.extra_vars or {})}
                if mutable_keys:
                    out, new_vars = state.apply_fn(
                        variables, batch, mutable=mutable_keys, rngs=rngs)
                else:
                    out = state.apply_fn(variables, batch, rngs=rngs)
                    new_vars = {}
                return out.loss, (out, new_vars)

            (loss, (out, new_vars)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            state = state.apply_gradients(grads=grads)
            if new_vars:
                state = state.replace(extra_vars=dict(new_vars))
            return state, loss, out.metric

        return one_step

    def _build_train_step(self):
        train_step = self._make_one_step()

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            data = NamedSharding(self.mesh, P("data"))
            self._data_sharding = data
            train_step = jax.jit(
                train_step,
                donate_argnums=(0,),
            )
        else:
            train_step = jax.jit(train_step, donate_argnums=(0,))
        return train_step

    def _build_train_loop(self):
        """K steps per dispatch: scan the single-step body over a batch
        pytree stacked on axis 0. static_batch rides as an explicit arg
        so the feature table isn't baked into the jaxpr as a constant."""
        one_step = self._make_one_step()

        def train_loop(state: TrainState, batches, static_batch):
            def body(s, b):
                s, loss, metric = one_step(s, _merged(b, static_batch))
                return s, (loss, metric)

            state, (losses, metrics) = jax.lax.scan(body, state, batches)
            return state, losses, metrics

        return jax.jit(train_loop, donate_argnums=(0,))

    def _build_eval_step(self):
        def eval_step(state: TrainState, batch):
            variables = {"params": state.params, **(state.extra_vars or {})}
            out = state.apply_fn(variables, batch)
            return out.loss, out.metric, out.embedding

        return jax.jit(eval_step)

    def _checkpoint_manager(self):
        if self._ckpt_mgr is None and self.model_dir:
            import orbax.checkpoint as ocp

            path = os.path.abspath(os.path.join(self.model_dir, "checkpoints"))
            os.makedirs(path, exist_ok=True)
            self._ckpt_mgr = ocp.CheckpointManager(
                path, options=ocp.CheckpointManagerOptions(max_to_keep=3))
        return self._ckpt_mgr

    def save_checkpoint(self, step: int) -> None:
        mgr = self._checkpoint_manager()
        if mgr is None:
            return
        import orbax.checkpoint as ocp

        payload = {"params": self.state.params,
                   "opt_state": self.state.opt_state,
                   "extra_vars": self.state.extra_vars or {}}
        mgr.save(step, args=ocp.args.StandardSave(payload))

    def finalize_checkpoints(self) -> None:
        """Block until async orbax saves commit — called at the end of
        every train path so a process exiting right after train() never
        leaves a half-written checkpoint (observed as futures-after-
        shutdown errors at exit). Mid-training saves stay async."""
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait_until_finished()

    def restore_checkpoint(self) -> Optional[int]:
        mgr = self._checkpoint_manager()
        if mgr is None or mgr.latest_step() is None:
            return None
        import orbax.checkpoint as ocp

        step = mgr.latest_step()
        payload = {"params": self.state.params,
                   "opt_state": self.state.opt_state,
                   "extra_vars": self.state.extra_vars or {}}
        restored = mgr.restore(step, args=ocp.args.StandardRestore(payload))
        self.state = self.state.replace(
            params=restored["params"], opt_state=restored["opt_state"],
            extra_vars=_match_placement(restored.get("extra_vars") or {},
                                        self.state.extra_vars or {}))
        return step

    # -- drivers -----------------------------------------------------------
    def train(self, input_fn: Callable[[], Iterator[Dict]],
              max_steps: int = 1000) -> Dict[str, float]:
        it = input_fn() if callable(input_fn) else input_fn
        raw_first = _to_device_tree(next(it), self.max_id)
        first = _merged(raw_first, self.static_batch)
        if self.state is None:
            self._init_state(first)
            self.restore_checkpoint()
        if self._train_step is None:
            self._train_step = self._build_train_step()
        if self.profiling and self.model_dir:
            jax.profiler.start_trace(os.path.join(self.model_dir, "prof"))
        if self.steps_per_loop > 1:
            # pass the UNMERGED batch: the looped path stacks raw batches
            # and merges static_batch inside the scanned body
            return self._run_looped(it, raw_first, max_steps)
        step = int(self.state.step)
        start_step = step
        losses, metrics = [], []
        t0 = time.time()
        batch = first
        last_log = t0
        while step < max_steps:
            self.state, loss, metric = self._train_step(
                self.state, _merged(batch, self.static_batch))
            step += 1
            losses.append(loss)
            metrics.append(metric)
            if step % self.log_steps == 0:
                lv = float(jnp.mean(jnp.stack(losses[-self.log_steps:])))
                mv = float(jnp.mean(jnp.stack(metrics[-self.log_steps:])))
                now = time.time()
                rate = self.log_steps / max(now - last_log, 1e-9)
                last_log = now
                print(f"step {step}: loss={lv:.4f} metric={mv:.4f} "
                      f"({rate:.1f} steps/s)", flush=True)
            if self.ckpt_steps and step % self.ckpt_steps == 0:
                self.save_checkpoint(step)
            if step < max_steps:
                try:
                    batch = _to_device_tree(next(it), self.max_id)
                except StopIteration:
                    break
        if self.ckpt_steps:
            self.save_checkpoint(step)
        self.finalize_checkpoints()
        if self.profiling and self.model_dir:
            jax.profiler.stop_trace()
        return {
            "loss": float(losses[-1]) if losses else float("nan"),
            "metric": float(jnp.mean(jnp.stack(metrics))) if metrics else 0.0,
            "steps_per_sec": (step - start_step) / max(time.time() - t0, 1e-9),
            "global_step": step,
        }

    def _run_looped(self, it, first: Dict, max_steps: int) -> Dict[str, float]:
        """steps_per_loop > 1 train path: full K-step windows dispatch as
        one scanned device call; a tail shorter than K falls back to the
        single-step function (no partial-scan recompile)."""
        K = self.steps_per_loop
        step = int(self.state.step)
        start_step = step
        loop_losses, loop_metrics = [], []
        last_loss = float("nan")
        t0 = time.time()
        last_log = t0
        logged_at = step
        buf = [first]
        exhausted = False

        def stack(*xs):
            if isinstance(xs[0], np.ndarray):
                return np.stack(xs)
            return jnp.stack(xs)

        while step < max_steps:
            want = min(K, max_steps - step)
            while len(buf) < want and not exhausted:
                try:
                    buf.append(_to_device_tree(next(it), self.max_id))
                except StopIteration:
                    exhausted = True
            if not buf:
                break
            if len(buf) == K:
                if self._train_loop is None:
                    self._train_loop = self._build_train_loop()
                stacked = jax.tree_util.tree_map(stack, *buf)
                self.state, l_arr, m_arr = self._train_loop(
                    self.state, stacked, self.static_batch)
                loop_losses.append((jnp.mean(l_arr), K))
                loop_metrics.append((jnp.mean(m_arr), K))
                last_loss = l_arr[-1]
                done = K
            else:
                # tail shorter than K: single-step dispatches (the jit
                # was built in train() before this path was entered)
                for b in buf:
                    self.state, last_loss, m = self._train_step(
                        self.state, _merged(b, self.static_batch))
                    loop_losses.append((last_loss, 1))
                    loop_metrics.append((m, 1))
                done = len(buf)
            prev = step
            step += done
            buf = []
            if step - logged_at >= self.log_steps:
                now = time.time()
                rate = (step - logged_at) / max(now - last_log, 1e-9)
                print(f"step {step}: loss={float(loop_losses[-1][0]):.4f} "
                      f"metric={float(loop_metrics[-1][0]):.4f} "
                      f"({rate:.1f} steps/s)", flush=True)
                last_log, logged_at = now, step
            if self.ckpt_steps and \
                    step // self.ckpt_steps > prev // self.ckpt_steps:
                self.save_checkpoint(step)
            if exhausted:
                break
        if self.ckpt_steps:
            self.save_checkpoint(step)
        self.finalize_checkpoints()
        if self.profiling and self.model_dir:
            jax.profiler.stop_trace()
        # step-weighted mean so the reported train metric matches what
        # the same run would report with steps_per_loop=1
        if loop_metrics:
            w = np.asarray([c for _, c in loop_metrics], np.float64)
            vals = np.asarray([float(v) for v, _ in loop_metrics])
            metric = float(np.dot(vals, w / w.sum()))
        else:
            metric = 0.0
        return {
            "loss": float(last_loss),
            "metric": metric,
            "steps_per_sec": (step - start_step) / max(time.time() - t0, 1e-9),
            "global_step": step,
        }

    def evaluate(self, input_fn, steps: int = 100) -> Dict[str, float]:
        it = input_fn() if callable(input_fn) else input_fn
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        losses, metrics, weights = [], [], []
        for _ in range(steps):
            try:
                raw = next(it)
            except StopIteration:
                break
            batch = _to_device_tree(raw, self.max_id)
            if self.state is None:
                self._init_state(_merged(batch, self.static_batch))
                self.restore_checkpoint()
                self._eval_step = self._build_eval_step()
            loss, metric, _ = self._eval_step(
                self.state, _merged(batch, self.static_batch))
            losses.append(float(loss))
            metrics.append(float(metric))
            # masked batches (graph packing / node eval sweeps) report
            # per-batch means over n_real entries; weight them so a short
            # final sweep batch doesn't count like a full one
            mask = None
            if isinstance(raw, dict):
                mask = raw.get("graph_mask")
                if mask is None:
                    mask = raw.get("metric_mask")
            weights.append(float(np.sum(mask)) if mask is not None else 1.0)
        if not losses:
            return {"loss": float("nan"), "metric": float("nan")}
        w = np.asarray(weights)
        w = w / w.sum()
        return {"loss": float(np.dot(losses, w)),
                "metric": float(np.dot(metrics, w))}

    def infer(self, input_fn, steps: int = 100,
              id_key: str = "infer_ids") -> Dict[str, str]:
        """Writes embedding_0.npy / ids_0.npy under model_dir (parity:
        reference infer artifacts base_estimator.py:157-180)."""
        it = input_fn() if callable(input_fn) else input_fn
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        embs, ids = [], []
        for _ in range(steps):
            try:
                raw = next(it)
            except StopIteration:
                break
            batch = _to_device_tree(raw, self.max_id)
            if self.state is None:
                self._init_state(_merged(batch, self.static_batch))
                self.restore_checkpoint()
                self._eval_step = self._build_eval_step()
            _, _, emb = self._eval_step(
                self.state, _merged(batch, self.static_batch))
            embs.append(np.asarray(emb))
            key = id_key if id_key in raw else ("ids" if "ids" in raw else None)
            if key is not None:
                v = raw[key]
                v = v[0] if isinstance(v, list) else v
                ids.append(np.asarray(v).ravel()[: emb.shape[0]])
        out_dir = self.model_dir or "."
        os.makedirs(out_dir, exist_ok=True)
        emb_path = os.path.join(out_dir, "embedding_0.npy")
        np.save(emb_path, np.concatenate(embs) if embs else np.zeros((0,)))
        id_path = os.path.join(out_dir, "ids_0.npy")
        if ids:
            np.save(id_path, np.concatenate(ids))
        return {"embedding": emb_path, "ids": id_path}

    def train_and_evaluate(self, train_input_fn, eval_input_fn,
                           max_steps: int = 1000,
                           eval_steps: int = 50,
                           eval_every: int = 0,
                           keep_best: bool = False) -> Dict[str, float]:
        """Train with optional interleaved evaluation.

        eval_every > 0 evaluates on eval_input_fn every that many train
        steps (the reference's tf.estimator.train_and_evaluate interleaves
        the same way); keep_best additionally snapshots the parameters at
        the best interleaved eval metric and restores them before the
        final evaluation — the standard early-stopping protocol for the
        citation benchmarks, whose small train splits overfit long before
        a fixed step budget ends.
        """
        if eval_every <= 0:
            train_res = self.train(train_input_fn, max_steps)
            if self.pre_eval_hook:
                self.pre_eval_hook(self)
            eval_res = self.evaluate(eval_input_fn, eval_steps)
            return {**{f"train_{k}": v for k, v in train_res.items()},
                    **{f"eval_{k}": v for k, v in eval_res.items()}}

        it = train_input_fn() if callable(train_input_fn) else train_input_fn
        best_metric, best_step, best_snap = -float("inf"), 0, None
        train_res: Dict[str, float] = {}
        step = 0
        # segments checkpoint once at the end (at the restored-best
        # weights), not once per segment
        saved_ckpt_steps, self.ckpt_steps = self.ckpt_steps, 0
        try:
            while step < max_steps:
                target = min(step + eval_every, max_steps)
                try:
                    seg = self.train(it, max_steps=target)
                except StopIteration:
                    break  # train iterator exhausted at a segment edge
                train_res = seg
                step = seg["global_step"]
                if self.pre_eval_hook:
                    self.pre_eval_hook(self)
                ev = self.evaluate(eval_input_fn, eval_steps)
                m = ev["metric"]
                if keep_best and (best_snap is None or m > best_metric):
                    best_metric, best_step = m, step
                    best_snap = jax.device_get(
                        {"params": self.state.params,
                         "extra_vars": self.state.extra_vars or {}})
                if step < target:
                    break  # train iterator exhausted mid-segment
        finally:
            self.ckpt_steps = saved_ckpt_steps
        if keep_best and best_snap is not None:
            self.state = self.state.replace(
                params=jax.tree_util.tree_map(jnp.asarray,
                                              best_snap["params"]),
                extra_vars=_match_placement(
                    best_snap["extra_vars"],
                    self.state.extra_vars or {}) or {})
        if self.ckpt_steps and self.state is not None:
            self.save_checkpoint(step)  # disk matches the reported weights
            self.finalize_checkpoints()
        if self.pre_eval_hook:
            # the restored-best snapshot's cache was refreshed before
            # its eval, but keep_best=False (or a first-segment
            # StopIteration) reaches here without any refresh at all
            self.pre_eval_hook(self)
        eval_res = self.evaluate(eval_input_fn, eval_steps)
        out = {**{f"train_{k}": v for k, v in train_res.items()},
               **{f"eval_{k}": v for k, v in eval_res.items()}}
        if keep_best:
            out["best_step"] = best_step
        return out
