"""Background batch prefetchers: overlap host-side graph sampling with
device compute (the role of the reference's async TF queue runners /
one-RPC fanout amortization, SURVEY.md §7 hard part (b)).

Two shapes:

  * Prefetcher — one producer thread keeping `depth` batches ready
    ahead of a consumer (the original single-worker overlap).
  * ParallelPrefetcher — K worker threads each independently producing
    batches from a thread-safe source, delivered strictly IN TICKET
    ORDER through a bounded reorder buffer: the multi-worker feeder
    mode BaseEstimator enables with params["feeder_workers"] (ISSUE 4
    — the host feeder, not the device step, is the measured ceiling of
    every host-fed path).

Both are context managers and MUST be close()d (or abandoned only via
`with`): an abandoned consumer used to leak a daemon thread blocked on
q.put forever.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Callable, Iterator, Optional, Union

_FEEDER_IDS = itertools.count()


class Prefetcher:
    """Wraps an iterator; a daemon thread keeps `depth` batches ready."""

    _STOP = object()

    def __init__(self, it: Iterator, depth: int = 2, transform=None):
        """transform (optional) runs on each batch IN the prefetch thread —
        pass jax.device_put to overlap host→device transfer with device
        compute, not just graph sampling."""
        self._it = it
        self._transform = transform
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._err = None
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for item in self._it:
                if self._transform is not None:
                    item = self._transform(item)
                # bounded put that can be interrupted: close() sets the
                # flag and drains, so a producer parked on a full queue
                # always wakes up and exits instead of leaking
                while not self._closed.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._closed.is_set():
                    return
        except Exception as e:  # surfaced on next()
            self._err = e
        finally:
            while not self._closed.is_set():
                try:
                    self._q.put(self._STOP, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        item = self._q.get()
        if item is self._STOP:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer thread and reclaim it: sentinel + drain.
        Safe to call more than once; next() afterwards raises
        StopIteration."""
        self._closed.set()
        while self._thread.is_alive():
            try:  # free a producer parked in put()
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(0.05)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ParallelPrefetcher:
    """K sampler threads → ordered bounded queue → optional transform.

    source is either
      * a zero-arg callable producing ONE batch per call — it must be
        thread-safe; workers call it concurrently (genuinely parallel
        sampling; NodeEstimator._train_batch_factory provides one), or
      * an iterator — next() is serialized under a lock, so only the
        transform and queue depth overlap (the safe fallback for
        stateful generators).

    Delivery is strictly in ticket order: worker k claims sequence
    numbers under a lock and parks results in a bounded reorder buffer
    (`depth` outstanding tickets), so the consumer sees the same batch
    order as a single-threaded feeder over the same source. A batch
    that RAISES delivers its error at its sequence position and the
    stream then CONTINUES — the estimator's resilient input path can
    retry without tearing the feeder down. StopIteration from an
    iterator source ends the stream.

    Reports feeder_queue_depth{feeder=...} (ready batches waiting) and
    feeder_batches_total through euler_tpu.obs.
    """

    # a raised batch does NOT kill the stream — the estimator's input
    # retry path checks this instead of recreating the iterator
    resilient = True

    def __init__(self, source: Union[Callable, Iterator],
                 workers: int = 4, depth: Optional[int] = None,
                 transform=None, name: Optional[str] = None):
        from euler_tpu import obs as _obs

        self._transform = transform
        if callable(source):
            self._pull = source
            self._pull_mu = None
        else:
            it = iter(source)
            # iterator mode: ticket claim + next(it) must be ONE
            # critical section — claiming first and pulling under a
            # separate lock lets a later ticket receive an earlier
            # item (order broken) and, at end-of-stream, park "end"
            # BEFORE the real final batch (batch silently dropped)
            self._pull = lambda: next(it)
            self._pull_mu = threading.Lock()
        self.workers = max(int(workers), 1)
        self._depth = max(int(depth) if depth else 2 * self.workers,
                          self.workers)
        self._cond = threading.Condition()
        self._next_ticket = 0      # next sequence a worker claims
        self._next_out = 0         # next sequence the consumer emits
        self._ready = {}           # seq -> (kind, payload)
        self._closed = False
        self._ended = False        # iterator source exhausted
        self._name = name or f"feeder{next(_FEEDER_IDS)}"
        reg = _obs.default_registry()
        lab = {"feeder": self._name}
        self._g_depth = reg.gauge(
            "feeder_queue_depth",
            "ready batches parked in the reorder buffer",
            ("feeder",)).labels(**lab)
        self._ctr_batches = reg.counter(
            "feeder_batches_total", "batches produced by feeder workers",
            ("feeder",)).labels(**lab)
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"euler-{self._name}-{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    def _claim(self):
        """Next ticket number, honoring the backlog bound; None when
        closed/ended."""
        with self._cond:
            while (not self._closed and not self._ended
                   and self._next_ticket - self._next_out
                   >= self._depth):
                self._cond.wait(0.1)
            if self._closed or self._ended:
                return None
            seq = self._next_ticket
            self._next_ticket += 1
            return seq

    def _claim_and_pull(self):
        """(seq, result) — factory mode claims then pulls concurrently;
        iterator mode does both under the pull lock so ticket order ==
        source order (and "end" is provably the LAST ticket)."""
        if self._pull_mu is None:
            seq = self._claim()
            if seq is None:
                return None, None
        else:
            self._pull_mu.acquire()
        try:
            if self._pull_mu is not None:
                seq = self._claim()
                if seq is None:
                    return None, None
            try:
                return seq, ("ok", self._pull())
            except StopIteration:
                return seq, ("end", None)
            except BaseException as e:   # delivered in-order, once
                return seq, ("err", e)
        finally:
            if self._pull_mu is not None:
                self._pull_mu.release()

    def _work(self):
        while True:
            seq, res = self._claim_and_pull()
            if seq is None:
                return
            # transform stays OUTSIDE the pull lock: in iterator mode
            # it is the part that actually parallelizes
            if res[0] == "ok" and self._transform is not None:
                try:
                    res = ("ok", self._transform(res[1]))
                except BaseException as e:
                    res = ("err", e)
            with self._cond:
                if self._closed:
                    return
                self._ready[seq] = res
                self._g_depth.set(len(self._ready))
                self._cond.notify_all()
                if res[0] == "end":
                    self._ended = True
                    return

    def __iter__(self):
        return self

    def __next__(self):
        with self._cond:
            while True:
                if self._closed:
                    raise StopIteration
                res = self._ready.pop(self._next_out, None)
                if res is None:
                    if self._ended and self._next_out >= self._next_ticket:
                        raise StopIteration
                    self._cond.wait(0.1)
                    continue
                self._next_out += 1
                self._g_depth.set(len(self._ready))
                self._cond.notify_all()
                kind, payload = res
                if kind == "ok":
                    self._ctr_batches.inc()
                    return payload
                if kind == "end":
                    # workers past the end parked "end" too; everything
                    # after the first is equivalent
                    raise StopIteration
                raise payload            # kind == "err": stream continues

    def close(self) -> None:
        """Stop all workers and reclaim their threads. Idempotent;
        next() afterwards raises StopIteration."""
        with self._cond:
            self._closed = True
            self._ready.clear()
            self._g_depth.set(0)
            self._cond.notify_all()
        for t in self._threads:
            t.join(5.0)

    def __enter__(self) -> "ParallelPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_feeder(source, workers: int = 0, depth: Optional[int] = None,
                transform=None):
    """The one constructor the tools share: workers > 1 → a
    ParallelPrefetcher over `source` (an iterator, or a thread-safe
    zero-arg BATCH factory); workers <= 1 → the single-thread
    Prefetcher (a callable source is looped as a batch factory)."""
    if workers and workers > 1:
        return ParallelPrefetcher(source, workers=workers, depth=depth,
                                  transform=transform)
    it = iter(source, object()) if callable(source) else source
    return Prefetcher(it, depth=depth or 2, transform=transform)
