"""Background batch prefetcher: overlaps host-side graph sampling with
device compute (the role of the reference's async TF queue runners /
one-RPC fanout amortization, SURVEY.md §7 hard part (b))."""

from __future__ import annotations

import queue
import threading
from typing import Iterator


class Prefetcher:
    """Wraps an iterator; a daemon thread keeps `depth` batches ready."""

    _STOP = object()

    def __init__(self, it: Iterator, depth: int = 2, transform=None):
        """transform (optional) runs on each batch IN the prefetch thread —
        pass jax.device_put to overlap host→device transfer with device
        compute, not just graph sampling."""
        self._it = it
        self._transform = transform
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._err = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for item in self._it:
                if self._transform is not None:
                    item = self._transform(item)
                self._q.put(item)
        except Exception as e:  # surfaced on next()
            self._err = e
        finally:
            self._q.put(self._STOP)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._STOP:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
