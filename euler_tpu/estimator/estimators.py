"""Concrete estimators: Node / Edge / Graph / Gae / Sample.

Parity: euler_estimator/python/{node,edge,graph,gae,sample}_estimator.py —
each wires an input_fn (root sampling from the graph engine) to the
BaseEstimator loop. Splits follow the reference dataset convention: node
type encodes the split (train/val/test), labels live in a dense feature.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from euler_tpu.estimator.base_estimator import BaseEstimator
from euler_tpu.graph import GraphEngine


class NodeEstimator(BaseEstimator):
    """Supervised node classification (reference node_estimator.py:31-50)."""

    def __init__(self, model, params: Dict, graph: GraphEngine, dataflow,
                 label_fid="label", label_dim: Optional[int] = None,
                 model_dir=None, mesh=None, feature_store=None,
                 eval_dataflow=None, device_sampler=None,
                 eval_via_flow: bool = False):
        """feature_store: optional DeviceFeatureStore — batches then carry
        int32 'rows' into the device-resident table instead of shipping
        feature arrays, and the table rides self.static_batch.
        eval_dataflow: optional flow for evaluate/infer (e.g. FastGCN
        trains on sampled pools but evaluates full-adjacency).
        device_sampler: optional DeviceNeighborTable (requires
        feature_store) — neighbor sampling moves into the jitted step;
        batches carry only root rows + a sample seed, and the model must
        read nbr_table/cum_table (e.g. DeviceSampledGraphSage).
        eval_via_flow: with device_sampler, route eval/infer batches
        through the HOST eval_dataflow instead of the in-jit sampler —
        for protocols whose eval geometry differs from training (e.g.
        FastGCN trains on sampled pools but evaluates exact 1-hop
        closures); the model must then also accept the host batch.
        Compile-cost caveat: host layerwise batches have data-dependent
        level sizes (np.unique closures), so each distinct eval batch
        geometry jit-compiles a fresh eval step — fine for the FastGCN
        protocol's few fixed eval sets, but unbounded compile churn on
        large/varied eval sets (bucket or pad closure sizes if eval
        throughput ever matters)."""
        super().__init__(model, params, model_dir, mesh)
        self.graph = graph
        self.dataflow = dataflow
        self.eval_dataflow = eval_dataflow or dataflow
        self.label_fid = label_fid
        self.label_dim = label_dim
        self.batch_size = int(params.get("batch_size", 32))
        self.train_node_type = int(params.get("train_node_type", 0))
        self.eval_node_type = int(params.get("eval_node_type", 1))
        self.infer_node_type = int(params.get("infer_node_type", -1))
        self.feature_store = feature_store
        self.device_sampler = device_sampler
        self.eval_via_flow = bool(eval_via_flow)
        if self.eval_via_flow and device_sampler is None:
            raise ValueError("eval_via_flow only applies with a "
                             "device_sampler (host mode already "
                             "evaluates through the flow)")
        if self.eval_via_flow and self.eval_dataflow is None:
            raise ValueError("eval_via_flow needs an eval_dataflow (or "
                             "dataflow) to build the host eval batches")
        if device_sampler is not None and feature_store is None:
            raise ValueError("device_sampler requires a feature_store")
        # independent per-phase device-sampler RNG streams (advisor r2:
        # one shared counter made training draws depend on how many
        # interleaved evals had run — eval cadence broke step-for-step
        # reproducibility)
        self._seed_counters = {0: 0, 1: 0}
        if feature_store is not None:
            self.static_batch["feature_table"] = feature_store.features
            if getattr(feature_store, "feature_scale", None) is not None:
                # int8-quantized table: models dequantize after gather
                self.static_batch["feature_scale"] = \
                    feature_store.feature_scale
            if getattr(feature_store, "labels", None) is not None:
                self.static_batch["label_table"] = feature_store.labels
            if getattr(feature_store, "hub_size", 0) > 0:
                # PartitionedFeatureStore: the replicated hot-row tier —
                # gather_feature_rows routes feature reads cache-first
                # whenever this key is present
                self.static_batch["hub_cache"] = feature_store.hub_cache
        if device_sampler is not None:
            self.static_batch.update(device_sampler.tables)

    def _node_batch(self, roots, flow, stream: int = 0) -> Dict:
        """One batch for the given roots through whichever input path is
        configured (device sampler / feature store / host arrays)."""
        store = self.feature_store
        if self.device_sampler is not None:
            if self.eval_via_flow and stream == 1:
                # eval keeps the HOST protocol: the flow's full batch
                # geometry (layers/adjs/...) rides to the device as-is,
                # labels fetched host-side (the label table is keyed by
                # rows the host batch doesn't carry)
                batch = flow(roots)
                batch["labels"] = self.graph.get_dense_feature(
                    roots, self.label_fid,
                    self.label_dim if self.label_dim else None)
                batch["infer_ids"] = roots
                return batch
            # on-device sampling: the host's whole contribution is
            # root rows + a seed (the model draws the fanout in-jit)
            return self._sampler_batch(roots, stream)
        batch = flow(roots)
        if store is not None:
            # rows replace ids/weights/types AND (with a label table)
            # the host label fetch — the device step sees only int32
            # rows, everything else gathers from HBM-resident tables
            rows = [store.lookup(i) for i in batch["ids"]]
            batch = {"rows": rows, "infer_ids": roots}
            if getattr(store, "observe_batch", None) is not None:
                # partitioned store: count this batch's gather split
                # (local/cached/remote) — host mode carries EVERY hop's
                # rows, so the counters cover the full fanout
                for r in rows:
                    store.observe_batch(r)
            if getattr(store, "labels", None) is None:
                batch["labels"] = self.graph.get_dense_feature(
                    roots, self.label_fid,
                    self.label_dim if self.label_dim else None)
        else:
            batch["labels"] = self.graph.get_dense_feature(
                roots, self.label_fid,
                self.label_dim if self.label_dim else None)
            batch["infer_ids"] = roots
        return batch

    def _batches(self, node_type: int, flow=None,
                 stream: int = 0) -> Iterator[Dict]:
        flow = flow or self.dataflow
        while True:
            roots = self.graph.sample_node(self.batch_size, node_type)
            yield self._node_batch(roots, flow, stream)

    def _sampler_batch(self, roots, stream: int = 0) -> Dict:
        """Device-sampler batch: root rows + a per-batch seed; labels via
        the device table when present, host fetch otherwise (mirrors the
        store path's fallback). stream 0 = train, 1 = eval/infer — the
        high seed bit separates them so eval cadence never shifts the
        training sample sequence."""
        self._seed_counters[stream] += 1
        seed = np.uint32((stream << 31) | self._seed_counters[stream])
        root_rows = self.feature_store.lookup(roots)
        if getattr(self.feature_store, "observe_batch", None) is not None:
            # device-sampler mode draws hop rows in-jit, so host-side
            # accounting covers the roots; the full-fanout split is
            # measured by tools/bench_host.py --mode table
            self.feature_store.observe_batch(root_rows)
        batch = {"rows": [root_rows],
                 "sample_seed": seed,
                 "infer_ids": roots}
        if getattr(self.feature_store, "labels", None) is None:
            batch["labels"] = self.graph.get_dense_feature(
                roots, self.label_fid,
                self.label_dim if self.label_dim else None)
        return batch

    def train_input_fn(self):
        return self._batches(self.train_node_type)

    def _train_batch_factory(self):
        """Thread-safe per-call train batch builder for the multi-worker
        feeder (params["feeder_workers"] > 1): every batch is an
        independent root draw + flow expansion + label fetch, so K
        workers can build K batches concurrently against the graph
        service. Device-sampler mode returns None — its per-batch seed
        stream is ordered, and parallel claims would decouple seed
        order from batch order — so the feeder falls back to
        serialized next()."""
        if self.device_sampler is not None:
            return None
        flow = self.dataflow

        def one_batch():
            roots = self.graph.sample_node(self.batch_size,
                                           self.train_node_type)
            return self._node_batch(roots, flow)

        return one_batch

    def eval_input_fn(self):
        return self._batches(self.eval_node_type, flow=self.eval_dataflow,
                             stream=1)

    def split_ids(self, node_type: int) -> np.ndarray:
        """All node ids of a split (node type), engine order."""
        ids = self.graph.all_node_ids()
        if node_type < 0:
            return ids
        return ids[self.graph.get_node_type(ids) == node_type]

    def eval_sweep_steps(self, node_type: Optional[int] = None) -> int:
        n = len(self.split_ids(
            self.eval_node_type if node_type is None else node_type))
        return max((n + self.batch_size - 1) // self.batch_size, 1)

    def eval_sweep_input_fn(self, node_type: Optional[int] = None,
                            flow=None) -> Iterator[Dict]:
        """Deterministic pass over a split: every node EXACTLY once. For
        accuracy-decomposable metrics (single-label micro-F1 ==
        accuracy) the n_real-weighted batch mean IS the exact full-split
        value; for true multilabel micro-F1 it is the standard per-batch
        average (micro-F1 doesn't decompose over batches), still free of
        sampling noise. The final chunk pads to the static batch shape
        with a metric_mask zeroing the padded rows out of loss and
        metric (SuperviseModel honors it; advisor r2: unmasked
        repeat-pads double-count)."""
        ids = self.split_ids(
            self.eval_node_type if node_type is None else node_type)
        flow = flow or self.eval_dataflow

        def gen():
            for i in range(0, len(ids), self.batch_size):
                chunk = ids[i:i + self.batch_size]
                n_real = len(chunk)
                if n_real < self.batch_size:
                    chunk = np.concatenate([
                        chunk,
                        np.full(self.batch_size - n_real, chunk[-1],
                                np.uint64)])
                batch = self._node_batch(chunk, flow, stream=1)
                mask = np.zeros(self.batch_size, np.float32)
                mask[:n_real] = 1.0
                batch["metric_mask"] = mask
                yield batch

        return gen()

    def infer_input_fn(self):
        """Deterministic sweep over all nodes (padded final batch)."""
        ids = self.graph.all_node_ids()
        if self.infer_node_type >= 0:
            ids = ids[self.graph.get_node_type(ids) == self.infer_node_type]

        def gen():
            for i in range(0, len(ids), self.batch_size):
                chunk = ids[i:i + self.batch_size]
                if len(chunk) < self.batch_size:
                    chunk = np.concatenate([
                        chunk,
                        np.full(self.batch_size - len(chunk), chunk[-1],
                                np.uint64)])
                # stream 1: inference must not advance the train seed
                # counter (mid-training infer would shift all subsequent
                # training draws)
                yield self._node_batch(chunk, self.eval_dataflow, stream=1)

        return gen()


class EdgeEstimator(BaseEstimator):
    """Unsupervised link-based training (reference edge_estimator.py):
    positive edges sampled from the graph; negatives sampled globally."""

    def __init__(self, model, params: Dict, graph: GraphEngine,
                 dataflow=None, model_dir=None, mesh=None):
        super().__init__(model, params, model_dir, mesh)
        self.graph = graph
        self.dataflow = dataflow
        self.batch_size = int(params.get("batch_size", 32))
        self.num_negs = int(params.get("num_negs", 5))
        self.edge_type = int(params.get("train_edge_type", -1))
        self.neg_node_type = int(params.get("neg_node_type", -1))

    def _batches(self) -> Iterator[Dict]:
        while True:
            src, dst, _ = self.graph.sample_edge(self.batch_size,
                                                 self.edge_type)
            negs = self.graph.sample_node(
                self.batch_size * self.num_negs, self.neg_node_type
            ).reshape(self.batch_size, self.num_negs)
            batch = self.dataflow(src) if self.dataflow else {}
            batch.update({"ids": src if self.dataflow is None else batch.get("ids", src),
                          "src": src, "pos": dst, "negs": negs,
                          "infer_ids": src})
            yield batch

    def train_input_fn(self):
        return self._batches()

    def eval_input_fn(self):
        return self._batches()


class GraphEstimator(BaseEstimator):
    """Whole-graph classification batches (reference graph_estimator.py):
    each step packs `num_graphs` small graphs into one node table."""

    def __init__(self, model, params: Dict, graphs, labels,
                 model_dir=None, mesh=None):
        """graphs: list of dicts {x [n,D], edge_index [2,e]}; labels [G]."""
        super().__init__(model, params, model_dir, mesh)
        self.graphs = graphs
        self.labels = np.asarray(labels)
        self.num_graphs = int(params.get("num_graphs", 16))
        self.max_nodes = int(params.get("max_nodes", 0)) or max(
            g["x"].shape[0] for g in graphs) * self.num_graphs
        self.max_edges = int(params.get("max_edges", 0)) or max(
            g["edge_index"].shape[1] for g in graphs) * self.num_graphs
        self.rng = np.random.default_rng(int(params.get("seed", 0)))

    def _pack(self, idxs, n_real: Optional[int] = None) -> Dict:
        """Pack `num_graphs` graphs into one static-shape batch; entries
        past n_real are shape padding, masked out of loss and metric."""
        n_real = len(idxs) if n_real is None else n_real
        xs, eis, gi, labels = [], [], [], []
        offset = 0
        for slot, gidx in enumerate(idxs):
            g = self.graphs[gidx]
            n = g["x"].shape[0]
            xs.append(g["x"])
            eis.append(g["edge_index"] + offset)
            gi.append(np.full(n, slot, np.int32))
            labels.append(self.labels[gidx])
            offset += n
        x = np.concatenate(xs).astype(np.float32)
        ei = np.concatenate(eis, axis=1).astype(np.int32)
        gi = np.concatenate(gi)
        mask = np.zeros(len(idxs), np.float32)
        mask[:n_real] = 1.0
        # pad to static shapes: dummy nodes attach to an extra sink row
        n_pad = self.max_nodes - x.shape[0]
        e_pad = self.max_edges - ei.shape[1]
        if n_pad > 0:
            x = np.concatenate([x, np.zeros((n_pad, x.shape[1]), np.float32)])
            gi = np.concatenate([gi, np.full(n_pad, len(idxs) - 1, np.int32)])
        if e_pad > 0:
            sink = self.max_nodes - 1
            ei = np.concatenate(
                [ei, np.full((2, e_pad), sink, np.int32)], axis=1)
        return {"x": x, "edge_index": ei, "graph_index": gi,
                "labels": np.asarray(labels), "graph_mask": mask}

    def _batches(self, idx_pool) -> Iterator[Dict]:
        while True:
            idxs = self.rng.choice(idx_pool, self.num_graphs, replace=True)
            yield self._pack(idxs)

    def train_input_fn(self):
        split = self.params_cfg.get("train_indices")
        pool = np.asarray(split) if split is not None else np.arange(
            len(self.graphs))
        return self._batches(pool)

    def eval_input_fn(self):
        """Deterministic sweep: every eval graph exactly once per pass
        (random-with-replacement batches made the eval metric noisy
        enough to defeat best-checkpoint selection on small pools).
        Callers must pass evaluate() steps >= ceil(pool / num_graphs) or
        the tail of the pool is never seen — run_graph_model sizes
        eval_steps from the pool for exactly this reason."""
        split = self.params_cfg.get("eval_indices")
        pool = np.asarray(split) if split is not None else np.arange(
            len(self.graphs))

        def gen():
            for i in range(0, len(pool), self.num_graphs):
                chunk = pool[i:i + self.num_graphs]
                n_real = len(chunk)
                if n_real < self.num_graphs:
                    chunk = np.concatenate(
                        [chunk,
                         np.repeat(chunk[-1], self.num_graphs - n_real)])
                yield self._pack(chunk, n_real)

        return gen()


class GaeEstimator(BaseEstimator):
    """Graph auto-encoder batches (reference gae_estimator.py): node-table
    closure + positive edges + sampled negative pairs."""

    def __init__(self, model, params: Dict, graph: GraphEngine, dataflow,
                 model_dir=None, mesh=None):
        super().__init__(model, params, model_dir, mesh)
        self.graph = graph
        self.dataflow = dataflow
        self.batch_size = int(params.get("batch_size", 32))
        self.num_pos = int(params.get("num_pos", 64))
        self.rng = np.random.default_rng(int(params.get("seed", 0)))

    def _batches(self) -> Iterator[Dict]:
        while True:
            roots = self.graph.sample_node(self.batch_size, -1)
            batch = self.dataflow(roots)
            # positives are REAL edges of this batch's subgraph: sample
            # columns of its edge_index (rows already index the node
            # table). Globally sampled edges would mostly fall outside
            # the closure and train the decoder on noise.
            ei = batch["edge_index"]
            cols = self.rng.integers(0, ei.shape[1], self.num_pos)
            pos_src = ei[0][cols]
            pos_dst = ei[1][cols]
            neg_src = self.rng.integers(0, batch["n_real_nodes"], self.num_pos)
            neg_dst = self.rng.integers(0, batch["n_real_nodes"], self.num_pos)
            batch.update({
                "pos_src": pos_src.astype(np.int32),
                "pos_dst": pos_dst.astype(np.int32),
                "neg_src": neg_src.astype(np.int32),
                "neg_dst": neg_dst.astype(np.int32),
                "infer_ids": roots,
            })
            yield batch

    def train_input_fn(self):
        return self._batches()

    def eval_input_fn(self):
        return self._batches()


class SampleEstimator(BaseEstimator):
    """Line-oriented sample files (reference sample_estimator.py:
    TextLine inputs of "src dst label"-style records)."""

    def __init__(self, model, params: Dict, sample_file: str, parse_fn,
                 model_dir=None, mesh=None):
        super().__init__(model, params, model_dir, mesh)
        self.sample_file = sample_file
        self.parse_fn = parse_fn
        self.batch_size = int(params.get("batch_size", 32))

    def _batches(self) -> Iterator[Dict]:
        while True:
            with open(self.sample_file) as f:
                lines = []
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    lines.append(line)
                    if len(lines) == self.batch_size:
                        yield self.parse_fn(lines)
                        lines = []

    def train_input_fn(self):
        return self._batches()

    def eval_input_fn(self):
        return self._batches()
