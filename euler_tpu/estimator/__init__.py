from euler_tpu.estimator.base_estimator import BaseEstimator, TrainState  # noqa: F401
from euler_tpu.estimator.estimators import (  # noqa: F401
    EdgeEstimator,
    GaeEstimator,
    GraphEstimator,
    NodeEstimator,
    SampleEstimator,
)
from euler_tpu.estimator.streaming import StreamingDriver  # noqa: F401
