"""Continuous-learning loop over a mutating graph (streaming deltas).

The reference system was deployed on live e-commerce graphs: nodes and
edges keep arriving while training and serving run (the TF-GNN
production train→export→serve loop, arxiv 2207.03522). The engine-side
pieces are a graph epoch + batched ``apply_delta`` (O(delta) dirty-set
bookkeeping, RCU snapshot swap); this module composes the loop END TO
END on top of them:

    driver = StreamingDriver(estimator, engine,
                             device_table=table,        # optional
                             caches=[cached_engine],    # optional
                             serving_client=client,     # optional
                             export_dir="/bundles")
    driver.apply_delta(node_ids=new_ids, edge_src=s, edge_dst=d)
    driver.fine_tune(steps=50)
    driver.export_and_swap()      # fresh bundle → rolling fleet swap

After ``export_and_swap`` returns, a kNN query against the serving
fleet reflects nodes that did not exist at train start — the ROADMAP
item-3 acceptance. Every maintenance step is COUNTED, never assumed:
cache invalidation via ``cache_epoch_{evicted,retained}_total``, alias
patching via ``alias_rows_{patched,rebuilt}_total``, and the driver's
own ``streaming_{deltas,exports,swaps}_total``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterable, Optional

from euler_tpu import obs as _obs


class StreamingDriver:
    """Composes delta apply → derived-state maintenance → fine-tune →
    export → fleet hot-swap, with one stats dict per step.

    estimator: a BaseEstimator (used for fine_tune / export_bundle).
    engine: the graph engine deltas go through. If it is itself a
      CachedGraphEngine the cache invalidates inline; additional caches
      (other clients' wrappers in-process) go in `caches`.
    device_table: a DeviceNeighborTable to patch per dirty row
      (replicated split layout; alias tables patch with it).
    serving_client: a ServingClient whose fleet export_and_swap()
      promotes fresh bundles into.
    export_dir: where versioned bundles land (one subdir per version).
    """

    def __init__(self, estimator, engine, device_table=None,
                 caches: Iterable = (), serving_client=None,
                 export_dir: Optional[str] = None, shards: int = 1):
        self.estimator = estimator
        self.engine = engine
        self.device_table = device_table
        self.caches = list(caches)
        self.serving_client = serving_client
        self.export_dir = export_dir
        self.shards = int(shards)
        self._exports = 0
        reg = _obs.default_registry()
        self._ctr = {
            k: reg.counter(f"streaming_{k}_total", h)
            for k, h in (
                ("deltas", "graph deltas applied through StreamingDriver"),
                ("exports", "bundles exported by StreamingDriver"),
                ("swaps", "serving-fleet hot-swaps by StreamingDriver"),
                ("deltas_refused", "graph deltas refused by a degraded "
                                   "shard (write-ahead log unwritable)"),
            )}
        self._g_epoch = reg.gauge(
            "streaming_graph_epoch",
            "graph epoch after the driver's last delta")

    # -- the loop's steps --------------------------------------------------
    def apply_delta(self, **delta) -> Dict[str, Any]:
        """Apply one batched delta mid-train and maintain every piece of
        derived state O(delta): the engine swaps in the new snapshot
        (epoch bump), wrapped caches evict exactly the dirty ids, and
        the device neighbor/alias tables patch only the dirty rows.
        Returns {epoch, dirty, table, caches}."""
        from euler_tpu.core.lib import EngineError
        from euler_tpu.graph.api import delta_dirty_ids

        try:
            epoch = self.engine.apply_delta(**delta)
        except EngineError as e:
            # a durable shard with an unwritable WAL refuses deltas
            # rather than diverging from its log — count the explicit
            # status so dashboards see the degrade, then surface it
            if "wal" in str(e).lower():
                self._ctr["deltas_refused"].inc()
            raise
        dirty = delta_dirty_ids(**delta)
        self._ctr["deltas"].inc()
        self._g_epoch.set(epoch)
        table_stats = None
        if self.device_table is not None:
            # patch against the post-delta engine (row identity is
            # append-only, so only dirty rows re-derive)
            table_stats = self.device_table.patch_rows(
                self._graph_view(), dirty)
        cache_stats = []
        for cache in self.caches:
            # out-of-band caches reconcile from the engine's dirty
            # history (the engine wrapper, if any, already did inline)
            maybe = getattr(cache, "maybe_invalidate", None)
            if callable(maybe):
                maybe()
                stats = getattr(cache, "cache_stats", None)
                cache_stats.append(stats() if callable(stats) else None)
        return {"epoch": epoch, "dirty": int(dirty.size),
                "table": table_stats, "caches": cache_stats}

    def _graph_view(self):
        """The object device-table patching queries (node_rows /
        get_full_neighbor): the engine itself, unwrapped from chaos or
        cache layers so a patch never trips fault injection."""
        eng = self.engine
        seen = set()
        while id(eng) not in seen:
            seen.add(id(eng))
            inner = getattr(eng, "_engine", None)
            if inner is None:
                break
            eng = inner
        return eng

    def fine_tune(self, steps: int, input_fn=None) -> Dict[str, float]:
        """Continue training for `steps` MORE steps on the post-delta
        graph (the estimator's own train loop — resilient input path,
        chaos machinery and all). BaseEstimator.train's max_steps is an
        ABSOLUTE global-step bound, so offset from the current step —
        passing `steps` raw would silently no-op after any prior
        training. Default input_fn: the estimator's train_input_fn."""
        fn = input_fn if input_fn is not None else \
            self.estimator.train_input_fn
        state = self.estimator.state            # None before first train
        target = (int(state.step) if state is not None else 0) + int(steps)
        return self.estimator.train(fn, max_steps=target)

    def export_and_swap(self, version: Optional[str] = None,
                        **export_kw) -> Dict[str, Any]:
        """Export a fresh versioned bundle of the CURRENT params +
        embeddings (new nodes included — embed_all sweeps the post-delta
        graph) and roll it through the serving fleet with the
        zero-downtime hot-swap. Without a serving_client the export
        still happens (pull-based deployments)."""
        if self.export_dir is None:
            raise ValueError("StreamingDriver needs export_dir to export")
        self._exports += 1
        version = version if version is not None else \
            f"stream{self._exports}-{int(time.time())}"
        out_dir = os.path.join(self.export_dir, str(version))
        self.estimator.export_bundle(out_dir, shards=self.shards,
                                     version=version, **export_kw)
        self._ctr["exports"].inc()
        swap = None
        if self.serving_client is not None:
            swap = self.serving_client.swap_fleet(out_dir)
            self._ctr["swaps"].inc()
        return {"version": version, "bundle_dir": out_dir, "swap": swap}

    def round(self, delta: Dict[str, Any], steps: int,
              train_input_fn=None, version: Optional[str] = None,
              **export_kw) -> Dict[str, Any]:
        """One full continuous-learning round: delta → fine-tune →
        export → swap. Served kNN reflects the delta's new nodes within
        this one export period. export_kw forwards to export_bundle
        (input_fn= there selects the inference sweep — it must cover
        the post-delta id set for new nodes to enter the index)."""
        out = {"delta": self.apply_delta(**delta)}
        out["train"] = self.fine_tune(steps, input_fn=train_input_fn)
        out.update(self.export_and_swap(version=version, **export_kw))
        return out
