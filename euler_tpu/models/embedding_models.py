"""Random-walk / proximity embedding models: DeepWalk, LINE, Node2Vec.

Parity: examples/deepwalk, examples/line (skip-gram over walks; LINE
first+second order proximity). Training batches come from
walk_ops.random_walk + gen_pair (DeepWalk) or sample_edge (LINE).
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.utils import metrics as M
from euler_tpu.utils.layers import Embedding

Array = jax.Array


class DeepWalk(nn.Module):
    """Skip-gram with negative sampling. batch: src [B], pos [B], negs
    [B, N] (pairs from gen_pair; negatives sampled globally)."""

    max_id: int = 0
    dim: int = 128

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = Embedding(self.max_id + 1, self.dim, name="emb")
        ctx = Embedding(self.max_id + 1, self.dim, name="ctx")
        src = emb(batch["src"])                       # [B, D]
        pos = ctx(batch["pos"])                       # [B, D]
        negs = ctx(batch["negs"])                     # [B, N, D]
        pos_logit = (src * pos).sum(-1, keepdims=True)
        neg_logit = jnp.einsum("bd,bnd->bn", src, negs)
        loss = (
            optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean()
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        return ModelOutput(src, loss, "mrr", M.mrr(scores))


Node2Vec = DeepWalk  # same model; the walk's p/q bias differs (walk_ops)


class LINE(nn.Module):
    """LINE (1st/2nd order). batch: src [B], pos [B], negs [B, N].
    order=1 shares one table; order=2 uses a context table."""

    max_id: int = 0
    dim: int = 128
    order: int = 2

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = Embedding(self.max_id + 1, self.dim, name="emb")
        ctx = emb if self.order == 1 else Embedding(
            self.max_id + 1, self.dim, name="ctx")
        src = emb(batch["src"])
        pos = ctx(batch["pos"])
        negs = ctx(batch["negs"])
        pos_logit = (src * pos).sum(-1, keepdims=True)
        neg_logit = jnp.einsum("bd,bnd->bn", src, negs)
        loss = (
            optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean()
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        return ModelOutput(src, loss, "mrr", M.mrr(scores))
