"""Random-walk / proximity embedding models: DeepWalk, LINE, Node2Vec.

Parity: examples/deepwalk, examples/line (skip-gram over walks; LINE
first+second order proximity). Training batches come from
walk_ops.random_walk + gen_pair (DeepWalk) or sample_edge (LINE).
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.utils import metrics as M
from euler_tpu.utils.layers import Embedding

Array = jax.Array


class DeepWalk(nn.Module):
    """Skip-gram with negative sampling. batch: src [B], pos [B], negs
    [B, N] (pairs from gen_pair; negatives sampled globally)."""

    max_id: int = 0
    dim: int = 128

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = Embedding(self.max_id + 1, self.dim, name="emb")
        ctx = Embedding(self.max_id + 1, self.dim, name="ctx")
        src = emb(batch["src"])                       # [B, D]
        pos = ctx(batch["pos"])                       # [B, D]
        negs = ctx(batch["negs"])                     # [B, N, D]
        pos_logit = (src * pos).sum(-1, keepdims=True)
        neg_logit = jnp.einsum("bd,bnd->bn", src, negs)
        loss = (
            optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean()
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        return ModelOutput(src, loss, "mrr", M.mrr(scores))


Node2Vec = DeepWalk  # same model; the walk's p/q bias differs (walk_ops)


class DeviceSampledSkipGram(nn.Module):
    """DeepWalk / node2vec / LINE with the ENTIRE input pipeline on
    device: walks (device_walk.walk_rows over the HBM neighbor table),
    skip-gram pair generation, and weighted negative sampling all run
    inside the jitted step — the host ships only root rows + a seed.

    Covers the reference walk family (random_walk_op.cc + gen_pair_op.cc
    + the global negative sampler): walk_len/window give DeepWalk; p,q
    give node2vec's second-order bias; walk_len=1 with window (0,1) and
    share_context=True is LINE first-order (order-2 = separate ctx
    table, the default). Pairs touching pad_row (dead-end walks) are
    masked out of loss and metric — strictly cleaner than the host
    path's default-id pairs.

    batch: rows=[roots [B]], sample_seed, nbr_table, cum_table,
    neg_rows, neg_cum (DeviceNodeSampler.tables).
    """

    num_rows: int = 0           # feature-table rows N (pad_row == N)
    dim: int = 128
    walk_len: int = 5
    left_win: int = 1
    right_win: int = 1
    num_negs: int = 5
    p: float = 1.0
    q: float = 1.0
    share_context: bool = False
    # set to the mesh when nbr/cum are row-sharded over 'model'
    # (shard_rows=True): walk-table reads then route through the
    # masked-take+psum gather instead of a local take (which GSPMD would
    # otherwise turn into a full-table all-gather per hop). The
    # negative-sampler tables stay replicated (O(N) scalars).
    table_mesh: Any = None
    # unit-weight tables (DeviceNeighborTable.uniform_rows): p=q=1 walk
    # draws become one neighbor-row gather each, no cum-row read
    # (replicated tables only; the node2vec biased path keeps cum)
    uniform_sampling: bool = False

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        from euler_tpu.parallel.device_sampler import (
            is_model_sharded, make_table_gather,
        )
        from euler_tpu.parallel.device_walk import (
            gen_pair_rows, sample_global_rows, walk_rows,
        )

        roots = batch["rows"][0]
        pad = self.num_rows
        key = jax.random.fold_in(jax.random.key(23), batch["sample_seed"])
        kw, kn = jax.random.split(key)
        tg = make_table_gather(self.table_mesh) \
            if is_model_sharded(self.table_mesh) else None
        atab = batch.get("alias_table") if tg is None else None
        walks = walk_rows(batch["nbr_table"], batch["cum_table"], roots,
                          self.walk_len, kw, p=self.p, q=self.q,
                          gather=tg,
                          uniform=self.uniform_sampling and tg is None
                          and atab is None,
                          alias_table=atab)
        pairs = gen_pair_rows(walks, self.left_win, self.right_win)
        flat = pairs.reshape(-1, 2)                    # [B*P, 2]
        src_r, pos_r = flat[:, 0], flat[:, 1]
        negs_r = sample_global_rows(batch["neg_rows"], batch["neg_cum"],
                                    kn, (flat.shape[0], self.num_negs))
        emb = Embedding(self.num_rows + 1, self.dim, name="emb")
        ctx = emb if self.share_context else Embedding(
            self.num_rows + 1, self.dim, name="ctx")
        src = emb(src_r)
        pos = ctx(pos_r)
        negs = ctx(negs_r)
        pos_logit = (src * pos).sum(-1, keepdims=True)
        neg_logit = jnp.einsum("bd,bnd->bn", src, negs)
        valid = ((src_r != pad) & (pos_r != pad)).astype(jnp.float32)
        loss = (
            M.masked_mean(optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean(-1), valid)
            + M.masked_mean(optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean(-1), valid)
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        ranks = 1.0 + (scores[:, 1:] >= scores[:, :1]).sum(
            axis=1).astype(jnp.float32)
        mrr = M.masked_mean(1.0 / ranks, valid)
        return ModelOutput(emb(roots), loss, "mrr", mrr)


class LINE(nn.Module):
    """LINE (1st/2nd order). batch: src [B], pos [B], negs [B, N].
    order=1 shares one table; order=2 uses a context table."""

    max_id: int = 0
    dim: int = 128
    order: int = 2

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = Embedding(self.max_id + 1, self.dim, name="emb")
        ctx = emb if self.order == 1 else Embedding(
            self.max_id + 1, self.dim, name="ctx")
        src = emb(batch["src"])
        pos = ctx(batch["pos"])
        negs = ctx(batch["negs"])
        pos_logit = (src * pos).sum(-1, keepdims=True)
        neg_logit = jnp.einsum("bd,bnd->bn", src, negs)
        loss = (
            optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean()
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        return ModelOutput(src, loss, "mrr", M.mrr(scores))
