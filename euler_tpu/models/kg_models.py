"""Knowledge-graph embedding models: TransE/H/R/D, DistMult, RGCN scorer.

Parity: examples/TransX (TransE/TransH/TransR/TransD), examples/distmult,
examples/rgcn. Batches: positive triples (h [B], r [B], t [B]) + corrupted
entities (neg_t [B, N] and/or neg_h [B, N]); margin ranking loss; MRR/hits
metrics over the candidate set.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.utils import metrics as M
from euler_tpu.utils.layers import Embedding

Array = jax.Array


class _KGBase(nn.Module):
    """Shared: entity/relation tables, margin loss, rank metrics."""

    num_entities: int = 0
    num_relations: int = 0
    dim: int = 64
    margin: float = 1.0
    norm_ord: int = 1

    def build_tables(self) -> Dict[str, nn.Module]:
        """Create this scorer's parameter modules ONCE (flax compact:
        module instances must be created once and reused across calls)."""
        return {"rel": Embedding(self.num_relations, self.dim, name="rel")}

    def score(self, tables: Dict[str, nn.Module], h: Array, r_idx: Array,
              t: Array, h_ids: Array, t_ids: Array) -> Array:
        """Higher = more plausible. h/t: [..., D] entity embeddings;
        r_idx/h_ids/t_ids: [...] index arrays (models needing extra
        per-entity parameters look them up by id)."""
        raise NotImplementedError

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        h_ids, t_ids, r = batch["h"], batch["t"], batch["r"]
        neg_t_ids = batch["neg_t"]
        ent = Embedding(self.num_entities, self.dim, name="ent")
        tables = self.build_tables()
        h = ent(h_ids)                                 # [B, D]
        t = ent(t_ids)
        neg_t = ent(neg_t_ids)                         # [B, N, D]
        pos = self.score(tables, h, r, t, h_ids, t_ids)[:, None]
        neg = self.score(tables, h[:, None, :], r[:, None], neg_t,
                         h_ids[:, None], neg_t_ids)     # [B, N]
        loss = jnp.maximum(0.0, self.margin - pos + neg).mean()
        scores = jnp.concatenate([pos, neg], axis=1)
        return ModelOutput(h, loss, "mrr", M.mrr(scores))


class TransE(_KGBase):
    """score = -||h + r - t||."""

    def score(self, tables, h, r_idx, t, h_ids=None, t_ids=None):
        r = tables["rel"](r_idx)
        return -jnp.linalg.norm(h + r - t, ord=self.norm_ord, axis=-1)


class TransH(_KGBase):
    """Project h,t onto relation hyperplane (normal w_r) then translate."""

    def build_tables(self):
        return {"rel": Embedding(self.num_relations, self.dim, name="rel"),
                "norm": Embedding(self.num_relations, self.dim, name="norm")}

    def score(self, tables, h, r_idx, t, h_ids=None, t_ids=None):
        r = tables["rel"](r_idx)
        w = tables["norm"](r_idx)
        w = w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True), 1e-12)
        h_p = h - (h * w).sum(-1, keepdims=True) * w
        t_p = t - (t * w).sum(-1, keepdims=True) * w
        return -jnp.linalg.norm(h_p + r - t_p, ord=self.norm_ord, axis=-1)


class TransR(_KGBase):
    """Relation-specific projection matrix M_r."""

    def build_tables(self):
        return {"rel": Embedding(self.num_relations, self.dim, name="rel"),
                "proj": Embedding(self.num_relations, self.dim * self.dim,
                                  name="proj")}

    def score(self, tables, h, r_idx, t, h_ids=None, t_ids=None):
        r = tables["rel"](r_idx)
        m = tables["proj"](r_idx)
        m = m.reshape(*r_idx.shape, self.dim, self.dim)
        h_p = jnp.einsum("...d,...de->...e", h, m)
        t_p = jnp.einsum("...d,...de->...e", t, m)
        return -jnp.linalg.norm(h_p + r - t_p, ord=self.norm_ord, axis=-1)


class TransD(_KGBase):
    """Dynamic rank-1 projection: h_p = h + (w_h·h) w_r (per entity and
    relation projection vectors)."""

    def build_tables(self):
        return {"rel": Embedding(self.num_relations, self.dim, name="rel"),
                "rel_p": Embedding(self.num_relations, self.dim,
                                   name="rel_p"),
                "ent_p": Embedding(self.num_entities, self.dim,
                                   name="ent_p")}

    def score(self, tables, h, r_idx, t, h_ids=None, t_ids=None):
        r = tables["rel"](r_idx)
        w_r = tables["rel_p"](r_idx)
        ent_p = tables["ent_p"]
        w_h = ent_p(h_ids)
        w_t = ent_p(t_ids)
        h_p = h + (w_h * h).sum(-1, keepdims=True) * w_r
        t_p = t + (w_t * t).sum(-1, keepdims=True) * w_r
        return -jnp.linalg.norm(h_p + r - t_p, ord=self.norm_ord, axis=-1)


class DistMult(_KGBase):
    """score = <h, r, t> trilinear."""

    def score(self, tables, h, r_idx, t, h_ids=None, t_ids=None):
        r = tables["rel"](r_idx)
        return (h * r * t).sum(-1)
