"""GraphSAGE models — the framework's flagship (bench.py drives these).

Parity: examples/graphsage (SupervisedGraphSage / UnsupervisedGraphSage /
ScalableSage) over the dense fanout path (SURVEY.md §2.3 encoders).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax

from euler_tpu.mp_utils.base import SuperviseModel, UnsuperviseModel
from euler_tpu.parallel.sharded_embedding import ShardedEmbedding
from euler_tpu.utils.encoders import SageEncoder, ScalableSageEncoder, ShallowEncoder

Array = jax.Array


def gather_feature_rows(batch: Dict[str, Any], rows, gather=None):
    """table[rows] for each hop's rows, honoring an int8-quantized
    table: when the batch carries 'feature_scale'
    (DeviceFeatureStore(quantize='int8')), the gathered int8 rows are
    dequantized by the per-column scale — the multiply fuses into the
    consumer, and the gather itself moves half the HBM bytes.

    A 'hub_cache' batch key (PartitionedFeatureStore: the replicated
    top-degree rows of a mesh-partitioned table) routes every feature
    gather CACHE-FIRST: rows below the cache height are served from
    the local replica and only the cold tail reaches `gather` (the
    cross-shard exchange), with hub positions masked to the trailing
    zero row — dequant applies after the combine, so int8 routing is
    byte-exact too."""
    from euler_tpu.parallel.feature_store import dequantize_rows

    table = batch["feature_table"]
    take = gather or (lambda t, r: jax.numpy.take(t, r, axis=0))
    hub = batch.get("hub_cache")
    if hub is not None:
        from euler_tpu.parallel.partitioned_store import hub_routed_take

        take = hub_routed_take(take, hub)
    scale = batch.get("feature_scale")
    if scale is None:
        return [take(table, r) for r in rows]
    return [dequantize_rows(take(table, r), scale) for r in rows]


def _fanout_layers(batch: Dict[str, Any]):
    """Per-hop feature arrays from either batch geometry:
      'layers'               — features shipped from the host (engine path)
      'rows' + 'feature_table' — int32 rows gathered from a device-resident
                               table (DeviceFeatureStore path; the gather
                               runs in-jit, so only ~0.7MB of rows crosses
                               the host↔device link per step)."""
    layers = batch.get("layers")
    if layers is not None:
        return layers
    return gather_feature_rows(batch, batch["rows"])


class SupervisedGraphSage(SuperviseModel):
    """Fanout batch {'layers': [x0..xL]} (or rows + device feature table)
    → SageEncoder → logits."""

    dim: int = 32
    fanouts: Sequence[int] = (10, 10)
    aggregator: str = "mean"

    def embed(self, batch: Dict[str, Any]) -> Array:
        return SageEncoder(self.dim, tuple(self.fanouts), self.aggregator,
                           name="encoder")(_fanout_layers(batch))


class UnsupervisedGraphSage(UnsuperviseModel):
    """Fanout batch + pos/negs ids → sage embedding vs context table."""

    fanouts: Sequence[int] = (10, 10)
    aggregator: str = "mean"

    def embed(self, batch: Dict[str, Any]) -> Array:
        return SageEncoder(self.dim, tuple(self.fanouts), self.aggregator,
                           concat=False, name="encoder")(_fanout_layers(batch))


class _GatherEncode(nn.Module):
    """gather + encode as ONE module — the single encoder dispatch for
    DeviceSampledGraphSage (every config shares this param tree, so the
    remat toggle never invalidates a checkpoint). Wrapped in nn.remat
    when remat=True: that puts gather+encode under one jax.checkpoint
    boundary, so the backward pass RE-GATHERS the per-hop feature
    layers instead of keeping them alive — at the canonical products
    shape the hop-2 layer alone is ~1GB bf16, the allocation that makes
    batch 65536 OOM. Residuals kept are only the HBM tables (already
    resident) and the int32 rows."""

    dim: int
    fanouts: tuple
    aggregator: str
    encoder: str
    gather: Any = None  # make_table_gather closure for sharded tables

    @nn.compact
    def __call__(self, table, scale, rows):
        from euler_tpu.utils.encoders import GCNEncoder, GenieEncoder

        batch = {"feature_table": table}
        if scale is not None:
            batch["feature_scale"] = scale
        layers = gather_feature_rows(batch, rows, gather=self.gather)
        if self.encoder == "gcn":
            return GCNEncoder(self.dim, self.fanouts, name="enc")(layers)
        if self.encoder == "genie":
            return GenieEncoder(self.dim, self.fanouts,
                                name="enc")(layers)
        return SageEncoder(self.dim, self.fanouts, self.aggregator,
                           name="enc")(layers)


class DeviceSampledGraphSage(SuperviseModel):
    """A fanout model whose sampling runs ON DEVICE (DeviceNeighborTable):
    the batch carries only root rows + a sample seed; neighbor sampling,
    feature gather, and label lookup all read HBM-resident tables inside
    the jitted step. The TPU-first configuration bench.py measures —
    the host feeder drops out of the critical path entirely. encoder
    picks any fanout-layer encoder ('sage', 'gcn' or 'genie' — all
    consume the per-hop feature list the on-device sampler produces)."""

    dim: int = 32
    fanouts: Sequence[int] = (10, 10)
    aggregator: str = "mean"
    encoder: str = "sage"
    # remat: recompute gather+encode in the backward pass
    # (_RematGatherEncode) — unlocks batches whose per-hop feature
    # layers don't fit HBM twice. Replicated tables only.
    remat: bool = False
    # uniform_sampling: the table's rows are unit-weight
    # (DeviceNeighborTable.uniform_rows — unweighted graphs) → each hop
    # is ONE neighbor-row gather, no cum-row read. Applies on the
    # replicated split-table path only (fused/row-sharded layouts keep
    # the weighted draw); distribution-identical on such tables.
    uniform_sampling: bool = False

    def embed(self, batch: Dict[str, Any]) -> Array:
        from euler_tpu.parallel.device_sampler import (
            is_model_sharded, make_table_gather, sample_fanout_rows,
            sample_fanout_rows_fused,
        )

        roots = batch["rows"][0]
        key = jax.random.fold_in(jax.random.key(17), batch["sample_seed"])
        # table_mesh set → tables are row-sharded over 'model' and every
        # read goes through the masked-take + psum gather; None → the
        # replicated local-take fast path
        gather = make_table_gather(self.table_mesh)
        sharded = is_model_sharded(self.table_mesh)
        if batch.get("nbrcum_table") is not None:
            # fused [N+1, 2C] layout (DeviceNeighborTable(fused=True)):
            # one row gather per hop instead of cum + neighbor gathers.
            # Composes with row-sharded tables: the gather becomes one
            # masked-take+psum per hop (half the split-sharded path's)
            rows = sample_fanout_rows_fused(batch["nbrcum_table"], roots,
                                            tuple(self.fanouts), key,
                                            gather=gather if sharded
                                            else None)
        else:
            # alias_table in the batch (DeviceNeighborTable(alias=True))
            # selects the O(1) alias draw; it subsumes the uniform
            # shortcut, so presence wins over uniform_sampling
            atab = batch.get("alias_table") if not sharded else None
            rows = sample_fanout_rows(
                batch["nbr_table"], batch["cum_table"],
                roots, tuple(self.fanouts), key,
                gather=gather if sharded else None,
                uniform=(self.uniform_sampling and not sharded
                         and atab is None),
                alias_table=atab)
        if self.encoder not in ("sage", "gcn", "genie"):
            raise ValueError(
                f"DeviceSampledGraphSage.encoder must be 'sage', 'gcn' "
                f"or 'genie', got {self.encoder!r}")
        if self.remat and sharded:
            raise ValueError(
                "DeviceSampledGraphSage(remat=True) supports "
                "replicated tables only (the re-gather would nest "
                "shard_map inside jax.checkpoint)")
        mod_cls = nn.remat(_GatherEncode) if self.remat else _GatherEncode
        mod = mod_cls(self.dim, tuple(self.fanouts), self.aggregator,
                      self.encoder, gather=gather if sharded else None,
                      name="encoder")
        return mod(batch["feature_table"], batch.get("feature_scale"),
                   rows)


class DeviceSampledScalableSage(SuperviseModel):
    """Historical-activation GraphSAGE with sampling AND the activation
    cache ON DEVICE — the in-jit re-application of the reference's
    ScalableGCN/ScalableSage insight (tf_euler/python/utils/encoders.py
    :294,629, there a host-side TF variable store).

    Structural fix for the products-scale bottleneck (PERF.md): the
    canonical 2-hop fanout gathers ~B·k1·k2 random feature rows per
    step (~5M at batch 32768, fanouts [15,10]) — the dominant HBM cost.
    This model samples ONE hop, gathers raw features for roots + hop-1
    neighbors only (B + B·k rows), and reads deeper-layer neighbor
    activations from an HBM cache [N+1, dim] carried in the train
    state's 'cache' collection (donated each step → XLA updates it in
    place). Per-step gather bytes drop ~10× at the canonical shape;
    staleness is the documented ScalableGCN tradeoff, pinned by the
    graphsage-dev-cache quality row in RESULTS.md.

    Eval applies with the cache frozen (read-only), same protocol as
    the reference's store-based eval."""

    dim: int = 32
    fanout: int = 10          # neighbors sampled per node (single hop)
    num_layers: int = 2       # model depth; layers >0 read the cache
    max_id: int = 0           # cache rows - 1 == feature-table rows - 1
    cache_dtype: Any = None   # None → float32; jnp.bfloat16 at scale
    store_decay: float = 0.9  # EMA weight on the old cached activation
    encoder: str = "sage"     # 'sage' (concat) or 'gcn' (mean-combine),
    # the reference's two scalable variants (encoders.py:294,629)
    uniform_sampling: bool = False  # as DeviceSampledGraphSage

    def embed(self, batch: Dict[str, Any]) -> Array:
        import jax.numpy as jnp

        from euler_tpu.parallel.device_sampler import (
            is_model_sharded, make_table_gather, sample_hop,
            sample_hop_fused,
        )

        roots = batch["rows"][0]
        b = roots.shape[0]
        key = jax.random.fold_in(jax.random.key(17), batch["sample_seed"])
        gather = make_table_gather(self.table_mesh)
        tg = gather if is_model_sharded(self.table_mesh) else None
        if batch.get("nbrcum_table") is not None:
            nbr = sample_hop_fused(batch["nbrcum_table"], roots,
                                   int(self.fanout), key, tg)
        else:
            atab = batch.get("alias_table") if tg is None else None
            nbr = sample_hop(batch["nbr_table"], batch["cum_table"],
                             roots, int(self.fanout), key, tg,
                             uniform=self.uniform_sampling
                             and tg is None and atab is None,
                             alias_table=atab)
        x, nbr_x = gather_feature_rows(batch, [roots, nbr], gather=gather)
        if self.encoder == "gcn":
            from euler_tpu.utils.encoders import ScalableGCNEncoder
            enc_cls = ScalableGCNEncoder
        elif self.encoder == "sage":
            enc_cls = ScalableSageEncoder
        else:
            raise ValueError(
                f"DeviceSampledScalableSage.encoder must be 'sage' or "
                f"'gcn', got {self.encoder!r}")
        enc = enc_cls(
            self.dim, int(self.num_layers), int(self.max_id),
            store_decay=self.store_decay,
            cache_dtype=self.cache_dtype or jnp.float32, name="encoder")
        return enc(roots, x, nbr.reshape(b, int(self.fanout)),
                   nbr_x.reshape(b, int(self.fanout), x.shape[-1]))


def shard_act_cache(est, mesh, axis: str = "model"):
    """Re-place the estimator's activation cache row-sharded over the
    mesh's model axis (per-chip cache bytes 1/mp — the same capacity
    lever row-sharded graph tables get from placement.put_row_sharded).
    GSPMD keeps the sharding through the jitted train step (the cache
    update is a row scatter, so each chip only writes its slice;
    pinned by tests/test_parallel.py::test_act_cache_row_sharded).
    Call once after the first train step (or any state init)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from euler_tpu.parallel.device_sampler import is_model_sharded

    if not is_model_sharded(mesh, axis):
        return
    state = est.state
    if state is None:
        # calling before the state exists is a caller bug that would
        # silently forfeit the 1/mp memory lever at scale
        raise ValueError(
            "shard_act_cache: estimator state not initialized — run at "
            "least one train step before sharding the cache")
    if "cache" not in (state.extra_vars or {}):
        return  # model carries no activation cache: legitimate no-op
    sh = NamedSharding(mesh, P(axis, None))
    cache = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sh), state.extra_vars["cache"])
    est.state = state.replace(
        extra_vars={**state.extra_vars, "cache": cache})


def refresh_act_cache(est, n_rows=None, chunk: int = 8192, seed: int = 1):
    """Full-coverage refresh of a DeviceSampledScalableSage estimator's
    activation cache: run the model forward over EVERY table row in
    chunks with the cache mutable, so nodes outside the train split get
    populated entries too (first writes land at full scale —
    encoders._ema_update). This is the structural fix for the config's
    quality gap on small-train-split data: plain training only ever
    writes cache rows for train roots, so eval-time neighbor reads hit
    zeros. Install as `est.pre_eval_hook = refresh_act_cache` (the
    reference's analog is its periodic full-graph store refresh in the
    ScalableGCN training loop, tf_euler/python/utils/encoders.py:294).

    The trailing pad row is excluded and re-zeroed: padded neighbor
    slots must keep aggregating zeros, not relu(bias)."""
    import numpy as np

    state = est.state
    if not (state and state.extra_vars
            and "cache" in (state.extra_vars or {})):
        return
    cache = state.extra_vars["cache"]
    if n_rows is None:
        n_rows = int(est.static_batch["feature_table"].shape[0])
    live = n_rows - 1  # rows 0..live-1 are real nodes; row live is pad
    chunk = max(1, min(chunk, live))

    upd = getattr(est, "_act_cache_upd", None)
    if upd is None:
        # memoized on the estimator: a fresh jax.jit wrapper per call
        # would recompile at every pre-eval refresh. Capture ONLY
        # apply_fn (a constant holding no arrays) — closing over the
        # whole TrainState would pin the first call's params+opt_state
        # copy in device memory for the estimator's lifetime
        apply_fn = state.apply_fn

        @jax.jit
        def upd(params, cache, batch):
            _, new = apply_fn({"params": params, "cache": cache},
                              batch, mutable=["cache"])
            return new["cache"]

        est._act_cache_upd = upd

    import jax.numpy as jnp

    base = dict(est.static_batch)
    for i, lo in enumerate(range(0, live, chunk)):
        rows = np.arange(lo, lo + chunk, dtype=np.int32)
        rows = np.minimum(rows, live - 1)  # tail clamps to a real row
        batch = {**base, "rows": [jnp.asarray(rows)],
                 "sample_seed": np.uint32(seed * 1_000_003 + i)}
        cache = upd(state.params, cache, batch)
    cache = jax.tree_util.tree_map(
        lambda a: a.at[live].set(jnp.zeros((), a.dtype)), cache)
    est.state = state.replace(
        extra_vars={**state.extra_vars, "cache": cache})


class DeviceSampledLayerwiseGCN(SuperviseModel):
    """FastGCN/LADIES with sampling ON DEVICE: per-layer importance
    pools, dense inter-pool adjacency, and feature gathers all run
    in-jit over the HBM tables (parallel/device_layerwise.py); the host
    ships root rows + a seed. Reference topology: API_SAMPLE_L
    (sample_layer_op.cc:74) + LayerwiseDataFlow on the host."""

    dim: int = 32
    layer_sizes: Sequence[int] = (128, 128)
    # per-layer input dropout inside LayerEncoder (the standard FastGCN
    # setup) — distinct from SuperviseModel.dropout, which the base
    # class applies once to the final embedding
    layer_dropout: float = 0.0

    def embed(self, batch: Dict[str, Any]) -> Array:
        from euler_tpu.parallel.device_layerwise import sample_layerwise_rows
        from euler_tpu.utils.encoders import LayerEncoder

        if batch.get("adjs") is not None:
            # host-built layerwise batch (NodeEstimator eval_via_flow):
            # the FastGCN protocol evaluates on exact 1-hop closures, so
            # eval geometry arrives from the host flow pre-assembled
            return LayerEncoder(self.dim, dropout=self.layer_dropout,
                                name="encoder")(batch["layers"],
                                                batch["adjs"])
        if batch.get("nbrcum_table") is not None:
            raise ValueError(
                "DeviceSampledLayerwiseGCN needs the split nbr/cum "
                "tables (pool weights come from the cum rows) — build "
                "DeviceNeighborTable with fused=False")
        from euler_tpu.parallel.device_sampler import is_model_sharded

        if is_model_sharded(self.table_mesh):
            raise NotImplementedError(
                "row-sharded tables are not supported for device "
                "layerwise sampling (top-k pooling needs the full "
                "candidate slot set) — use replicated tables "
                "(shard_rows=False)")
        roots = batch["rows"][0]
        key = jax.random.fold_in(jax.random.key(31), batch["sample_seed"])
        levels, adjs = sample_layerwise_rows(
            batch["nbr_table"], batch["cum_table"], roots,
            tuple(self.layer_sizes), key,
            alias_table=batch.get("alias_table"))
        layers = gather_feature_rows(batch, levels)
        return LayerEncoder(self.dim, dropout=self.layer_dropout,
                            name="encoder")(layers, adjs)


class DeviceSampledUnsupervisedSage(nn.Module):
    """Unsupervised GraphSAGE fully on device: the fanout embedding AND
    the positive/negative context pipeline run in-jit. Positives are one
    weighted neighbor draw per root (the reference's SamplePosWithTypes
    role, solution/samplers.py); negatives draw from the HBM node-weight
    sampler (DeviceNodeSampler). The host ships only root rows + a seed.
    Pairs whose positive lands on pad_row (isolated roots) are masked
    out of loss and metric."""

    num_rows: int = 0
    dim: int = 32
    fanouts: Sequence[int] = (10, 10)
    aggregator: str = "mean"
    num_negs: int = 5
    # set to the mesh when the nbr/cum (or fused) + feature tables are
    # row-sharded over 'model' (shard_rows=True): every table read then
    # goes through the masked-take+psum gather. The negative-sampler
    # tables (neg_rows/neg_cum) stay replicated — they are O(N) scalars,
    # not O(N·C)/O(N·D) rows.
    table_mesh: Any = None
    uniform_sampling: bool = False  # as DeviceSampledGraphSage

    @nn.compact
    def __call__(self, batch: Dict[str, Any]):
        import jax.numpy as jnp
        import optax

        from euler_tpu.mp_utils.base import ModelOutput
        from euler_tpu.parallel.device_sampler import (
            is_model_sharded, make_table_gather, sample_fanout_rows,
            sample_fanout_rows_fused, sample_hop, sample_hop_fused,
        )
        from euler_tpu.parallel.device_walk import sample_global_rows
        from euler_tpu.utils import metrics as M
        from euler_tpu.utils.layers import Embedding

        roots = batch["rows"][0]
        pad = self.num_rows
        key = jax.random.fold_in(jax.random.key(29), batch["sample_seed"])
        kf, kp, kn = jax.random.split(key, 3)
        gather = make_table_gather(self.table_mesh)
        tg = gather if is_model_sharded(self.table_mesh) else None
        fused_tab = batch.get("nbrcum_table")
        if fused_tab is not None:
            rows = sample_fanout_rows_fused(fused_tab, roots,
                                            tuple(self.fanouts), kf,
                                            gather=tg)
        else:
            atab = batch.get("alias_table") if tg is None else None
            unif = self.uniform_sampling and tg is None and atab is None
            rows = sample_fanout_rows(batch["nbr_table"],
                                      batch["cum_table"],
                                      roots, tuple(self.fanouts), kf,
                                      gather=tg, uniform=unif,
                                      alias_table=atab)
        layers = gather_feature_rows(batch, rows, gather=gather)
        emb = SageEncoder(self.dim, tuple(self.fanouts), self.aggregator,
                          concat=False, name="encoder")(layers)   # [B, D]
        if fused_tab is not None:
            pos_r = sample_hop_fused(fused_tab, roots, 1, kp, tg)  # [B]
        else:
            pos_r = sample_hop(batch["nbr_table"], batch["cum_table"],
                               roots, 1, kp, gather=tg,
                               uniform=self.uniform_sampling
                               and tg is None and atab is None,
                               alias_table=atab)                  # [B]
        negs_r = sample_global_rows(batch["neg_rows"], batch["neg_cum"],
                                    kn, (roots.shape[0], self.num_negs))
        ctx = Embedding(self.num_rows + 1, self.dim, name="ctx_emb")
        pos = ctx(pos_r)                                          # [B, D]
        negs = ctx(negs_r)                                        # [B, N, D]
        pos_logit = (emb * pos).sum(-1, keepdims=True)
        neg_logit = jnp.einsum("bd,bnd->bn", emb, negs)
        valid = (pos_r != pad).astype(jnp.float32)
        loss = (
            M.masked_mean(optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean(-1), valid)
            + M.masked_mean(optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean(-1), valid)
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        ranks = 1.0 + (scores[:, 1:] >= scores[:, :1]).sum(
            axis=1).astype(jnp.float32)
        mrr = M.masked_mean(1.0 / ranks, valid)
        return ModelOutput(emb, loss, "mrr", mrr)


class ShardedSupervisedGraphSage(SuperviseModel):
    """GraphSAGE with an id-embedding input sharded across the mesh's
    'model' axis — the multi-chip flagship: feature = concat(sharded id
    embedding, dense features). Exercises DP (batch) + embedding MP in one
    step, the SURVEY §2.4 mapping."""

    dim: int = 32
    fanouts: Sequence[int] = (10, 10)
    aggregator: str = "mean"
    max_id: int = 0
    id_dim: int = 16

    def embed(self, batch: Dict[str, Any]) -> Array:
        emb = ShardedEmbedding(self.max_id + 1, self.id_dim, name="id_emb")
        layers = []
        for ids, x in zip(batch["ids"], batch["layers"]):
            e = emb(ids)
            layers.append(jax.numpy.concatenate([x, e], axis=-1))
        return SageEncoder(self.dim, tuple(self.fanouts), self.aggregator,
                           name="encoder")(layers)


class ScalableGraphSage(SuperviseModel):
    """1-hop sampling + historical activation caches (reference
    ScalableSageEncoder). Run with mutable=['cache']."""

    dim: int = 32
    num_layers: int = 2
    max_id: int = 0

    def embed(self, batch: Dict[str, Any]) -> Array:
        enc = ScalableSageEncoder(self.dim, self.num_layers, self.max_id,
                                  name="encoder")
        ids = batch["ids"][0]
        x = batch["layers"][0]
        nbr_ids = batch["ids"][1].reshape(ids.shape[0], -1)
        nbr_x = batch["layers"][1].reshape(ids.shape[0], -1, x.shape[-1])
        return enc(ids, x, nbr_ids, nbr_x)
