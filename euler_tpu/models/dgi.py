"""Deep Graph Infomax. Parity: examples/dgi.

Encoder embeddings vs corrupted (feature-shuffled) embeddings scored
against the graph summary by a bilinear discriminator.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.mp_utils.base_gnn import BaseGNNNet
from euler_tpu.utils import metrics as M

Array = jax.Array


class DGI(nn.Module):
    """batch: x/edge_index (+ x_corrupt: row-shuffled features, built by
    the feeder)."""

    conv_name: str = "gcn"
    dim: int = 64
    num_layers: int = 1

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        net = BaseGNNNet(self.conv_name, self.dim, self.num_layers,
                         name="encoder")
        sub = dict(batch)
        sub.pop("root_index", None)
        # paper: h = PReLU(GCN(x)); only the SUMMARY goes through a
        # sigmoid — squashing the embeddings themselves destroys the
        # linear separability the downstream probe relies on
        act = nn.PReLU()
        h_real = act(net(sub))
        sub_c = dict(sub)
        sub_c["x"] = batch["x_corrupt"]
        h_fake = act(net(sub_c))
        summary = nn.sigmoid(h_real.mean(axis=0))
        w = self.param("disc", nn.initializers.glorot_uniform(),
                       (self.dim, self.dim))
        real_logit = h_real @ w @ summary
        fake_logit = h_fake @ w @ summary
        loss = (
            optax.sigmoid_binary_cross_entropy(
                real_logit, jnp.ones_like(real_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                fake_logit, jnp.zeros_like(fake_logit)).mean()
        )
        scores = jnp.concatenate([real_logit, fake_logit])
        labels = jnp.concatenate(
            [jnp.ones_like(real_logit), jnp.zeros_like(fake_logit)])
        return ModelOutput(h_real, loss, "auc", M.auc(scores, labels))
