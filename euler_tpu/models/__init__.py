from euler_tpu.models.dgi import DGI  # noqa: F401
from euler_tpu.models.embedding_models import (  # noqa: F401
    LINE,
    DeepWalk,
    DeviceSampledSkipGram,
    Node2Vec,
)
from euler_tpu.models.graphsage import (  # noqa: F401
    ScalableGraphSage,
    DeviceSampledGraphSage,
    DeviceSampledLayerwiseGCN,
    DeviceSampledScalableSage,
    DeviceSampledUnsupervisedSage,
    ShardedSupervisedGraphSage,
    SupervisedGraphSage,
    UnsupervisedGraphSage,
)
from euler_tpu.models.kg_models import (  # noqa: F401
    DistMult,
    TransD,
    TransE,
    TransH,
    TransR,
)
