"""Remote graph client: the GraphEngine batch API served by a shard
cluster over GQL.

Parity: the reference's TF custom kernels build a GQL string per op and
run it through QueryProxy against remote shards (SURVEY.md §3.3,
tf_euler/kernels/sample_fanout_op.cc:36-48 — the chained
".sampleNB().as(nb_i)" one-round-trip fanout). Here the same idea backs
the GraphEngine surface the dataflows/estimators consume, so a trainer
switches from embedded to cluster mode by swapping the graph object:

    remote = RemoteGraphEngine("hosts:127.0.0.1:9190,127.0.0.1:9191")
    flow = FanoutDataFlow(remote, [10, 10], feature_ids=["feature"])
    est = NodeEstimator(model, params, remote, flow, ...)

Every sample_fanout call is ONE query (compile-cached server-side plan,
split/REMOTE/merge per shard) — the host-side feeding pattern the
reference's whole design exists to amortize.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from euler_tpu.core.lib import EngineError
from euler_tpu.gql import Query


class RemoteGraphEngine:
    """GraphEngine-compatible batch sampling/feature API over a remote
    Query proxy (distribute or graph_partition mode)."""

    def __init__(self, endpoints: str, seed: int = 0,
                 mode: str = "distribute",
                 retry_deadline_s: float = 30.0):
        """retry_deadline_s: failover budget. A query that fails (shard
        died mid-call, RpcChannel exhausted its in-channel retries) is
        retried until this deadline — the registry monitor swaps the
        replacement shard's endpoint in live, so a restarted shard
        becomes visible within its heartbeat interval and the retry
        succeeds without rebuilding the engine. 0 disables (one
        attempt). Reference semantics: rpc_client.h:46 retry counter +
        ZK watch re-resolution."""
        self.query = Query.remote(endpoints, seed=seed, mode=mode)
        self.retry_deadline_s = float(retry_deadline_s)
        # host-side rng for the client-computed node2vec bias; seed=0 →
        # fresh entropy (matching the engine's seed convention)
        self._rng = np.random.default_rng(seed if seed else None)

    def _run(self, gql: str, feed=None):
        """query.run with shard-failover retry (see retry_deadline_s)."""
        deadline = time.monotonic() + self.retry_deadline_s
        while True:
            try:
                return self.query.run(gql, feed)
            except EngineError as e:
                # only transport failures are retryable (a dead/restarting
                # shard surfaces as "rpc to H:P failed after retries");
                # semantic errors (unknown feature, parse) raise at once
                if "failed after retries" not in str(e) \
                        or time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)

    # -- root sampling -----------------------------------------------------
    def sample_node(self, count: int, node_type: int = -1) -> np.ndarray:
        out = self._run(f"sampleN({node_type}, {count}).as(n)")
        return out["n:0"].astype(np.uint64).ravel()

    def sample_edge(self, count: int, edge_type: int = -1):
        out = self._run(f"sampleE({edge_type}, {count}).as(e)")
        return (out["e:0"].astype(np.uint64), out["e:1"].astype(np.uint64),
                out["e:2"].astype(np.int32))

    def sample_node_with_types(self, types) -> np.ndarray:
        """One weighted node draw per requested type (reference
        SampleNWithTypes) via the sampleNWithTypes verb."""
        types = np.ascontiguousarray(types, dtype=np.int32).ravel()
        out = self._run("sampleNWithTypes(t).as(n)", {"t": types})
        return out["n:0"].astype(np.uint64).ravel()

    # -- traversal ---------------------------------------------------------
    @staticmethod
    def _et(edge_types) -> str:
        from euler_tpu.gql import edge_types_str

        return edge_types_str(edge_types)

    def sample_fanout(self, roots, counts: Sequence[int], edge_types=None,
                      default_id: int = 0):
        """Multi-hop expansion in ONE round trip (reference
        sample_fanout_op.cc:36-48). Returns (ids_per_hop, w_per_hop,
        t_per_hop) with hop i arrays of shape [n·prod(counts[:i+1])]."""
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        if edge_types is not None and len(edge_types) > 0 and isinstance(
                edge_types[0], (list, tuple, np.ndarray)):
            if len(edge_types) != len(counts):
                raise ValueError(
                    f"per-hop edge_types has {len(edge_types)} entries, "
                    f"expected {len(counts)} (one per hop)")
            per_hop = [self._et(h) for h in edge_types]
        else:
            per_hop = [self._et(edge_types)] * len(counts)
        q = "v(r)"
        for i, k in enumerate(counts):
            q += f".sampleNB({per_hop[i]}, {int(k)}, {default_id}).as(h{i})"
        out = self._run(q, {"r": roots})
        ids = [out[f"h{i}:1"].astype(np.uint64) for i in range(len(counts))]
        w = [out[f"h{i}:2"].astype(np.float32) for i in range(len(counts))]
        t = [out[f"h{i}:3"].astype(np.int32) for i in range(len(counts))]
        return ids, w, t

    def sample_neighbor(self, ids, count: int, edge_types=None,
                        default_id: int = 0):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(
            f"v(r).sampleNB({self._et(edge_types)}, {count}, "
            f"{default_id}).as(nb)", {"r": ids})
        n = ids.size
        return (out["nb:1"].reshape(n, count).astype(np.uint64),
                out["nb:2"].reshape(n, count).astype(np.float32),
                out["nb:3"].reshape(n, count).astype(np.int32))

    def get_full_neighbor(self, ids, edge_types=None,
                          sorted_by_id: bool = False,
                          in_edges: bool = False):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        verb = "getRNB" if in_edges else (
            "getSortedNB" if sorted_by_id else "getNB")
        out = self._run(
            f"v(r).{verb}({self._et(edge_types)}).as(nb)", {"r": ids})
        idx = out["nb:0"].reshape(-1, 2)
        offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
        return (offsets, out["nb:1"].astype(np.uint64),
                out["nb:2"].astype(np.float32), out["nb:3"].astype(np.int32))

    def get_neighbor_edges(self, ids, edge_types=None):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(
            f"v(r).outE({self._et(edge_types)}).as(e)", {"r": ids})
        idx = out["e:0"].reshape(-1, 2)
        offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
        return (offsets, out["e:1"].astype(np.uint64),
                out["e:2"].astype(np.uint64), out["e:3"].astype(np.int32),
                out["e:4"].astype(np.float32))

    def sample_layerwise(self, roots, layer_sizes: Sequence[int],
                         edge_types=None, default_id: int = 0,
                         weight_func: str = ""):
        """LADIES pools from the cluster via one sampleLNB query
        (reference SampleNeighborLayerwiseWithAdj → API_SAMPLE_L).
        weight_func '' or 'sqrt' (hub-dampening, reference
        local_sample_layer_op.cc:94). Note: in distribute mode sqrt is
        applied to each shard's partial accumulation (the reference's
        distributed semantics too) — see POOL_MERGE in
        kernels_dist.cc."""
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        sizes = ":".join(str(int(s)) for s in layer_sizes)
        wf = f", {weight_func}" if weight_func else ""
        out = self._run(
            f"v(r).sampleLNB({self._et(edge_types)}, {sizes}, "
            f"{default_id}{wf}).as(l)", {"r": roots})
        return [out[f"l:{i}"].astype(np.uint64)
                for i in range(len(layer_sizes))]

    def random_walk(self, roots, walk_len: int, p: float = 1.0,
                    q: float = 1.0, edge_types=None,
                    default_id: int = 0) -> np.ndarray:
        """[n, walk_len+1] walks against the cluster. The unbiased case
        is ONE chained-sampleNB round trip; node2vec bias (p/q) falls
        back to per-step neighbor queries with client-side reweighting —
        the reference's random_walk_op.cc:70-110 approach."""
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        n = roots.size
        et = self._et(edge_types)
        out = np.zeros((n, walk_len + 1), dtype=np.uint64)
        out[:, 0] = roots
        if p == 1.0 and q == 1.0:
            gql = "v(r)" + "".join(
                f".sampleNB({et}, 1, {default_id}).as(s{i})"
                for i in range(walk_len))
            res = self._run(gql, {"r": roots})
            for i in range(walk_len):
                out[:, i + 1] = res[f"s{i}:1"].astype(np.uint64)
            return out
        rng = self._rng
        prev = np.zeros(n, dtype=np.uint64)
        cur = roots.copy()
        # neighbor lists of `prev` are the previous step's `cur` lists —
        # cache them instead of refetching (halves the per-step RPCs)
        poff = np.zeros(n + 1, dtype=np.int64)
        pnbr = np.zeros(0, dtype=np.uint64)
        for step in range(walk_len):
            off, nbr, w, _ = self.get_full_neighbor(cur,
                                                    edge_types=edge_types)
            off = off.astype(np.int64)
            nxt = np.full(n, default_id, dtype=np.uint64)
            for i in range(n):
                b, e = off[i], off[i + 1]
                if e <= b:
                    continue
                cand = nbr[b:e]
                wt = w[b:e].astype(np.float64).copy()
                prev_nb = set(pnbr[poff[i]:poff[i + 1]].tolist())
                for j, x in enumerate(cand):
                    if x == prev[i]:
                        wt[j] /= p        # return edge
                    elif int(x) not in prev_nb:
                        wt[j] /= q        # outward edge
                s = wt.sum()
                if s <= 0:
                    continue
                nxt[i] = cand[rng.choice(e - b, p=wt / s)]
            prev, cur = cur, nxt
            poff, pnbr = off, nbr
            out[:, step + 1] = cur
        return out

    # -- features ----------------------------------------------------------
    def _dense_from_values(self, out, n: int, names, dims, single: bool):
        """Decode a values() query's (idx, vals) pairs into dense [n, d]
        arrays. Rows can be ragged (graph_partition mode returns EMPTY
        rows for ids a shard doesn't own) — scatter by the idx offsets
        instead of a flat reshape, zero-filling misses like the embedded
        engine does. Shared by the node and edge dense getters."""
        outs = []
        dim_list = ([dims] if single else list(dims)) if dims is not None \
            else [None] * len(names)
        for i, want in enumerate(dim_list):
            idx = out[f"f:{2 * i}"].reshape(-1, 2).astype(np.int64)
            vals = out[f"f:{2 * i + 1}"].astype(np.float32)
            lens = idx[:, 1] - idx[:, 0]
            dim = int(want) if want is not None else int(lens.max(initial=0))
            # fast path (the distribute-mode norm): every row complete
            # and laid out contiguously → one reshape, no Python loop
            # on the feeder path
            if (idx.shape[0] == n and vals.size == n * dim
                    and (lens == dim).all()
                    and (idx[:, 0] == np.arange(n) * dim).all()):
                outs.append(vals.reshape(n, dim))
                continue
            arr = np.zeros((n, dim), dtype=np.float32)
            for r in range(min(n, idx.shape[0])):
                m = min(int(lens[r]), dim)
                arr[r, :m] = vals[idx[r, 0]:idx[r, 0] + m]
            outs.append(arr)
        return outs[0] if single else outs

    def get_dense_feature(self, ids, fids, dims=None):
        """[n, dim] float32 per fid; mirrors GraphEngine.get_dense_feature
        (single name → single array, list → list)."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        single = not isinstance(fids, (list, tuple, np.ndarray))
        names = [fids] if single else list(fids)
        q = "v(r).values(" + ", ".join(str(n) for n in names) + ").as(f)"
        out = self._run(q, {"r": ids})
        return self._dense_from_values(out, ids.size, names, dims, single)

    @staticmethod
    def _csr_result(out, tag: str, dtype):
        """(offsets[n+1], values) from a values() query's (idx, vals)
        pair — the CSR convention the embedded engine's sparse/binary
        getters return."""
        idx = out[f"{tag}:0"].reshape(-1, 2).astype(np.int64)
        offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
        return offsets, out[f"{tag}:1"].astype(dtype)

    def get_sparse_feature(self, ids, fid) -> tuple:
        """(offsets[n+1], u64 values) CSR; mirrors
        GraphEngine.get_sparse_feature over the cluster."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(f"v(r).values({fid}).as(p)", {"r": ids})
        return self._csr_result(out, "p", np.uint64)

    def get_binary_feature(self, ids, fid) -> tuple:
        """(offsets[n+1], bytes) CSR of raw per-node byte strings."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(f"v(r).values({fid}).as(p)", {"r": ids})
        offs, vals = self._csr_result(out, "p", np.uint8)
        return offs, vals.tobytes()

    def get_edge_dense_feature(self, src, dst, types, fids, dims=None):
        """[n, dim] float32 per fid for (src, dst, type) edge triples."""
        feed = {"batch:0": np.ascontiguousarray(src, np.uint64).ravel(),
                "batch:1": np.ascontiguousarray(dst, np.uint64).ravel(),
                "batch:2": np.ascontiguousarray(types, np.int32).ravel()}
        single = not isinstance(fids, (list, tuple, np.ndarray))
        names = [fids] if single else list(fids)
        q = "e(batch).values(" + ", ".join(str(n) for n in names) + ").as(f)"
        out = self._run(q, feed)
        return self._dense_from_values(out, feed["batch:0"].size, names,
                                       dims, single)

    def get_edge_sparse_feature(self, src, dst, types, fid) -> tuple:
        feed = {"batch:0": np.ascontiguousarray(src, np.uint64).ravel(),
                "batch:1": np.ascontiguousarray(dst, np.uint64).ravel(),
                "batch:2": np.ascontiguousarray(types, np.int32).ravel()}
        out = self._run(f"e(batch).values({fid}).as(p)", feed)
        return self._csr_result(out, "p", np.uint64)

    def get_edge_binary_feature(self, src, dst, types, fid) -> tuple:
        """(offsets[n+1], bytes): per-edge raw byte strings over the
        cluster (reference GetEdgeBinaryFeature)."""
        feed = {"batch:0": np.ascontiguousarray(src, np.uint64).ravel(),
                "batch:1": np.ascontiguousarray(dst, np.uint64).ravel(),
                "batch:2": np.ascontiguousarray(types, np.int32).ravel()}
        out = self._run(f"e(batch).values({fid}).as(p)", feed)
        offs, vals = self._csr_result(out, "p", np.uint8)
        return offs, vals.tobytes()

    def get_node_type(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run("v(r).label().as(t)", {"r": ids})
        return out["t:0"].astype(np.int32)

    def type_id(self, name_or_id, edge: bool = False) -> int:
        """Cluster clients resolve numeric ids/strings only — type NAME
        metadata lives in the shards' local meta and is not served over
        the wire; resolve names against a local GraphEngine (or extend
        the meta RPC) if needed."""
        if isinstance(name_or_id, (int, np.integer)):
            return int(name_or_id)
        s = str(name_or_id)
        try:
            return int(s)
        except ValueError:
            raise KeyError(
                f"RemoteGraphEngine cannot resolve type NAME {s!r}; "
                "pass the integer type id (names resolve on embedded "
                "engines via GraphEngine.type_id)")

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self.query.close()
