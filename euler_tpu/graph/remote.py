"""Remote graph client: the GraphEngine batch API served by a shard
cluster over GQL.

Parity: the reference's TF custom kernels build a GQL string per op and
run it through QueryProxy against remote shards (SURVEY.md §3.3,
tf_euler/kernels/sample_fanout_op.cc:36-48 — the chained
".sampleNB().as(nb_i)" one-round-trip fanout). Here the same idea backs
the GraphEngine surface the dataflows/estimators consume, so a trainer
switches from embedded to cluster mode by swapping the graph object:

    remote = RemoteGraphEngine("hosts:127.0.0.1:9190,127.0.0.1:9191")
    flow = FanoutDataFlow(remote, [10, 10], feature_ids=["feature"])
    est = NodeEstimator(model, params, remote, flow, ...)

Every sample_fanout call is ONE query (compile-cached server-side plan,
split/REMOTE/merge per shard) — the host-side feeding pattern the
reference's whole design exists to amortize. With pool_size > 0 the
engine additionally runs the pipelined client (graph/pipeline.py):
submit() futures, and large id sets fanned out as concurrent chunks
instead of one blocking query at a time.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import threading
import time
import weakref
from typing import Optional, Sequence

import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.core.lib import EngineError
from euler_tpu.gql import Query

# process-wide engine numbering: the per-instance label value on the
# shared graph_rpc_* metrics (euler_tpu.obs), so N engines in one
# process report side by side and health() stays an exact per-engine
# view
_ENGINE_IDS = itertools.count()

# health() counter keys ↔ registry counters (one definition: the compat
# view iterates this, so view and bookkeeping cannot drift)
_RPC_COUNTERS = {
    "calls": "graph rpc calls issued (before any retry)",
    "retries": "retry sleep cycles taken on transport failures",
    "failovers": "calls that failed then succeeded on a retry",
    "degraded": "default_id-padded results served (degrade=True)",
    "deadline_exhausted": "calls that ran out of retry budget",
    "health_merge_errors": "proxy stats() failures during health()",
    # elastic fleet: a shard refused the call because it was routed on
    # a superseded ownership map — the engine refreshed the registry-
    # published map (rebuilding its proxies when the fleet grew) and
    # retried; never a silent misroute
    "stale_map_retries": "calls refused as stale-map, refreshed + retried",
    "ownership_refreshes": "ownership-map refreshes applied",
}

# Error-text markers for failures worth retrying: transport-level faults
# (a dead/restarting shard surfaces as "rpc to H:P failed after retries"
# — the ONLY transport error string the C++ client emits from a query; a
# chaos layer injects "chaos:"-prefixed transport errors; a thread-timed
# attempt reports "timeout"). Deliberately NARROW: bare words like
# "connect"/"send"/"recv" would misclassify semantic errors whose
# message merely mentions them (e.g. a feature named "last_send_time"),
# and with degrade=True a misclassified PERMANENT error would train on
# padding forever. Semantic errors (parse failure, unknown feature)
# never match — retrying those only re-fails.
_TRANSPORT_MARKERS = (
    "failed after retries",
    "timeout",
    "timed out",
    "connection reset",
    "reset by peer",
    "connection refused",
    "broken pipe",
    "unavailable",
    "chaos:",
    # a shard dropped the request because its PROPAGATED deadline
    # expired in the dispatch queue — transport-shaped (the caller's
    # budget decides whether another attempt is worth it)
    "deadline shed",
    # a shard refused the request as routed on a superseded ownership
    # map — retryable AFTER the engine refreshes the published map
    # (_run_wire hooks exactly this marker to refresh before retrying)
    "stale ownership map",
)


def retryable_error(exc: BaseException) -> bool:
    """True when the failure is transport-shaped (worth retrying against
    the same or a re-resolved endpoint); False for semantic errors that
    would fail identically on every attempt."""
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if not isinstance(exc, EngineError):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _TRANSPORT_MARKERS)


# ---------------------------------------------------------------------------
# RPC transport config (protocol v2 mux + adaptive frame compression)
# ---------------------------------------------------------------------------
# Native client-edge counter layout (etg_rpc_stats) — order must match
# capi.cc. *_raw is the pre-compression payload view of the same frames,
# so bytes_received_raw / bytes_received is the reply compression ratio.
_RPC_STAT_KEYS = (
    "round_trips", "bytes_sent", "bytes_received", "bytes_sent_raw",
    "bytes_received_raw", "connections_opened", "compressed_frames_sent",
    "compressed_frames_received", "mux_calls", "v1_calls",
    "hello_fallbacks", "inflight",
    # tail-latency machinery: deadline_shed is SERVER-edge (loopback
    # tests see both edges in one process), the rest client-edge
    "deadline_propagated", "deadline_shed", "hedge_fired", "hedge_won",
    "hedge_wasted",
    # elastic fleet: stale_map_shed is SERVER-edge (requests refused as
    # routed on a superseded ownership map); replica_hedge_* count
    # ClientManager's cross-replica races (hedge_replicas)
    "stale_map_shed", "replica_hedge_fired", "replica_hedge_won",
    "replica_hedge_wasted",
    # cross-process tracing: kExecute requests stamped with a wire
    # trace context (zero with tracing off / against pre-trace peers —
    # the wire-identity pins read exactly this)
    "trace_propagated",
    # prepared query plans (wire path): registered/hits/misses/
    # invalidated are SERVER-edge plan-cache accounting (a miss or an
    # ownership-flip invalidation is always an explicit status the
    # client answers by re-preparing); fallbacks is CLIENT-edge — a
    # prepared call that went out as a classic full-plan frame
    "prepared_registered", "prepared_hits", "prepared_misses",
    "prepared_invalidated", "prepared_fallbacks",
    # prepare-time plan optimizer + deterministic fast paths (all
    # SERVER-edge): plan_optimized counts registrations the optimizer
    # rewrote, plan_rewrites_* the per-pass rewrite totals
    # (fuse/pushdown/dedup; epoch = per-epoch distribute re-derivations
    # on a generation-bumped re-registration); coalesced_requests rode a
    # neighbor's identical execute, coalesce_batches answered > 1
    # request; reuse_hits/misses/invalidated account the bounded
    # deterministic result-reuse window (invalidated = entries purged on
    # a graph-epoch or ownership-map bump — staleness is structurally
    # impossible, every bump empties the window)
    "plan_optimized", "plan_rewrites_fuse", "plan_rewrites_pushdown",
    "plan_rewrites_dedup", "plan_rewrites_epoch", "coalesced_requests",
    "coalesce_batches", "reuse_hits", "reuse_misses", "reuse_invalidated")

# Last config applied through configure_rpc (the native side has no
# getter). RemoteGraphEngine reads `mux` to default pool_shared.
_RPC_CONFIG = {"mux": False, "connections": 1, "compress_threshold": 0,
               "max_inflight": 256, "hedge_delay_ms": 0.0, "p2c": False,
               "hedge_replicas": False, "prepared": False,
               "plan_cache": 64, "deflate_reuse": True,
               "plan_optimize": True, "coalesce_window_us": 0,
               "reuse_window": 0}
_rpc_mu = threading.Lock()
_rpc_env_applied = False
_rpc_obs_done = False


def configure_rpc(mux=None, connections=None, compress_threshold=None,
                  max_inflight=None, hedge_delay_ms=None,
                  p2c=None, hedge_replicas=None, prepared=None,
                  plan_cache=None, deflate_reuse=None, plan_optimize=None,
                  coalesce_window_us=None, reuse_window=None) -> dict:
    """Set the PROCESS-GLOBAL graph-RPC transport knobs; returns the
    resulting config. None leaves a knob unchanged. Applies to engines
    (native channels) built AFTER the call — except hedge_delay_ms and
    p2c, which live channels read per call.

    mux: one v2 connection carries many in-flight requests (correlation-
      id frames, demux reader) instead of one blocking fd per concurrent
      call; v1 servers are detected at the hello and served classic
      framing. connections: mux connections per shard endpoint.
    compress_threshold: > 0 zlib-1-deflates frame bodies >= this many
      bytes when the peer negotiated it (a frame that would not shrink
      is sent raw — adaptive per frame). max_inflight: per-connection
      in-flight cap (client blocks / server bounds dispatch past it).
    hedge_delay_ms: > 0 fires a HEDGE for a mux kExecute whose reply is
      this late — same request on a second mux connection, first reply
      wins, loser cancelled by request_id (hedge_fired/won/wasted
      counters). Needs connections >= 2. 0 disables (the byte-identical
      pre-hedging path). RemoteGraphEngine(hedge=True) keeps this
      ADAPTIVE off the observed latency histogram. p2c: power-of-two-
      choices mux connection selection off (inflight, EWMA latency)
      instead of blind rotation.
    hedge_replicas: additionally race a straggling kExecute across
      graph-shard REPLICAS — when the installed ownership map lists
      another shard whose owned partitions cover the target's, the same
      request fires at it past the hedge delay; first reply wins,
      counted replica_hedge_fired/won/wasted. Needs an ownership map
      with multi-owner partitions (elastic rebalancing) and
      hedge_delay_ms > 0. The explicitly-deferred PR 11 item: graph
      shards had no replicas until the elastic fleet.
    prepared: prepared query plans (the read-hot-path wire saver, needs
      mux): each distinct kExecute plan (inner DAG + output names)
      registers ONCE per connection keyed by its content hash, then
      steady-state requests ship only the feed tensors stamped with the
      plan id — request bytes and server decode time stop paying for
      the plan a training loop repeats thousands of times. An unknown /
      evicted / ownership-flip-invalidated id is an explicit counted
      miss status (prepared_misses / prepared_invalidated) the client
      answers by re-preparing; pre-feature peers and prepared-off calls
      are byte-identical to today (prepared_fallbacks counts full-frame
      sends). plan_cache: server-side per-connection LRU bound on
      decoded plans. deflate_reuse: reuse one zlib deflate state per
      connection writer (deflateReset per frame, identical bytes)
      instead of a per-frame init; off restores compress2 for A/B.
    plan_optimize: run the server's prepare-time plan optimizer on every
      kPrepare registration (sub-plan dedup, filter/post-process
      pushdown, whole-plan fusion) — the optimized form executes, the
      wire and the results are byte-identical (default ON; off keeps
      the registered plan verbatim for A/B). coalesce_window_us: > 0
      lets a DETERMINISTIC prepared execute wait up to this long for
      identical requests (same plan id, graph snapshot and feed bytes,
      across connections) and answers them all from ONE execution
      (coalesced_requests / coalesce_batches). reuse_window: > 0 keeps
      that many deterministic results server-side keyed (plan, graph
      uid, feed bytes) — an identical request inside the window skips
      decode AND execute entirely (reuse_hits); every graph-epoch or
      ownership bump purges the window (reuse_invalidated), so a stale
      reply is impossible. Both default 0 = off, byte-identical wire."""
    from euler_tpu.core import lib as _lib

    lib = _lib.load()
    with _rpc_mu:
        if mux is not None:
            _RPC_CONFIG["mux"] = bool(mux)
        if connections is not None:
            _RPC_CONFIG["connections"] = max(int(connections), 1)
        if compress_threshold is not None:
            _RPC_CONFIG["compress_threshold"] = max(
                int(compress_threshold), 0)
        if max_inflight is not None:
            _RPC_CONFIG["max_inflight"] = max(int(max_inflight), 1)
        if hedge_delay_ms is not None:
            _RPC_CONFIG["hedge_delay_ms"] = max(float(hedge_delay_ms), 0.0)
        if p2c is not None:
            _RPC_CONFIG["p2c"] = bool(p2c)
        if hedge_replicas is not None:
            _RPC_CONFIG["hedge_replicas"] = bool(hedge_replicas)
        if prepared is not None:
            _RPC_CONFIG["prepared"] = bool(prepared)
        if plan_cache is not None:
            _RPC_CONFIG["plan_cache"] = max(int(plan_cache), 1)
        if deflate_reuse is not None:
            _RPC_CONFIG["deflate_reuse"] = bool(deflate_reuse)
        if plan_optimize is not None:
            _RPC_CONFIG["plan_optimize"] = bool(plan_optimize)
        if coalesce_window_us is not None:
            _RPC_CONFIG["coalesce_window_us"] = max(
                int(coalesce_window_us), 0)
        if reuse_window is not None:
            _RPC_CONFIG["reuse_window"] = max(int(reuse_window), 0)
        lib.etg_rpc_config(
            -1 if mux is None else int(bool(mux)),
            0 if connections is None else max(int(connections), 1),
            -1 if compress_threshold is None else max(
                int(compress_threshold), 0),
            0 if max_inflight is None else max(int(max_inflight), 1),
            -1 if hedge_delay_ms is None else max(
                int(float(hedge_delay_ms) * 1000.0), 0),
            -1 if p2c is None else int(bool(p2c)),
            -1 if hedge_replicas is None else int(bool(hedge_replicas)),
            -1 if prepared is None else int(bool(prepared)),
            0 if plan_cache is None else max(int(plan_cache), 1),
            -1 if deflate_reuse is None else int(bool(deflate_reuse)),
            -1 if plan_optimize is None else int(bool(plan_optimize)),
            -1 if coalesce_window_us is None else max(
                int(coalesce_window_us), 0),
            -1 if reuse_window is None else max(int(reuse_window), 0))
        return dict(_RPC_CONFIG)


def configure_rpc_from_env() -> dict:
    """Apply EULER_TPU_RPC_{MUX,CONNS,COMPRESS,MAX_INFLIGHT} once per
    process (idempotent; explicit configure_rpc calls win afterwards).
    Called by RemoteGraphEngine construction so `EULER_TPU_RPC_MUX=1
    python train.py` flips a whole job without code changes."""
    import os

    global _rpc_env_applied
    with _rpc_mu:
        if _rpc_env_applied:
            return dict(_RPC_CONFIG)
    kw = {}
    if os.environ.get("EULER_TPU_RPC_MUX"):
        kw["mux"] = os.environ["EULER_TPU_RPC_MUX"] not in ("0", "")
    if os.environ.get("EULER_TPU_RPC_CONNS"):
        kw["connections"] = int(os.environ["EULER_TPU_RPC_CONNS"])
    if os.environ.get("EULER_TPU_RPC_COMPRESS"):
        kw["compress_threshold"] = int(os.environ["EULER_TPU_RPC_COMPRESS"])
    if os.environ.get("EULER_TPU_RPC_MAX_INFLIGHT"):
        kw["max_inflight"] = int(os.environ["EULER_TPU_RPC_MAX_INFLIGHT"])
    if os.environ.get("EULER_TPU_RPC_HEDGE_MS"):
        kw["hedge_delay_ms"] = float(os.environ["EULER_TPU_RPC_HEDGE_MS"])
    if os.environ.get("EULER_TPU_RPC_P2C"):
        kw["p2c"] = os.environ["EULER_TPU_RPC_P2C"] not in ("0", "")
    if os.environ.get("EULER_TPU_RPC_HEDGE_REPLICAS"):
        kw["hedge_replicas"] = (
            os.environ["EULER_TPU_RPC_HEDGE_REPLICAS"] not in ("0", ""))
    if os.environ.get("EULER_TPU_RPC_PREPARED"):
        kw["prepared"] = os.environ["EULER_TPU_RPC_PREPARED"] not in (
            "0", "")
    if os.environ.get("EULER_TPU_RPC_PLAN_CACHE"):
        kw["plan_cache"] = int(os.environ["EULER_TPU_RPC_PLAN_CACHE"])
    if os.environ.get("EULER_TPU_RPC_DEFLATE_REUSE"):
        kw["deflate_reuse"] = os.environ[
            "EULER_TPU_RPC_DEFLATE_REUSE"] not in ("0", "")
    if os.environ.get("EULER_TPU_RPC_PLAN_OPTIMIZE"):
        kw["plan_optimize"] = os.environ[
            "EULER_TPU_RPC_PLAN_OPTIMIZE"] not in ("0", "")
    if os.environ.get("EULER_TPU_RPC_COALESCE_US"):
        kw["coalesce_window_us"] = int(
            os.environ["EULER_TPU_RPC_COALESCE_US"])
    if os.environ.get("EULER_TPU_RPC_REUSE_WINDOW"):
        kw["reuse_window"] = int(os.environ["EULER_TPU_RPC_REUSE_WINDOW"])
    # apply BEFORE publishing the applied flag: a concurrently
    # constructing engine must never observe applied=True while the env
    # config has not reached the native side yet (it would build its
    # channels un-muxed for life). Racing duplicates of configure_rpc
    # are idempotent, so two first-callers applying is harmless.
    out = configure_rpc(**kw) if kw else dict(_RPC_CONFIG)
    with _rpc_mu:
        _rpc_env_applied = True
    return out


def rpc_transport_stats() -> dict:
    """Client-edge transport counters (process-global, cumulative):
    round_trips, wire bytes sent/received, the pre-compression *_raw
    views, connections_opened, compressed frame counts, mux vs v1 call
    split, hello fallbacks, and the in-flight gauge. Benches snapshot
    before/after a leg and diff."""
    from euler_tpu.core import lib as _lib

    lib = _lib.load()
    out = np.zeros(len(_RPC_STAT_KEYS), dtype=np.uint64)
    lib.etg_rpc_stats(out.ctypes.data_as(_lib.c_u64p))
    return {k: int(v) for k, v in zip(_RPC_STAT_KEYS, out)}


def _ensure_rpc_obs() -> None:
    """Mirror the native transport counters into obs gauges
    (rpc_round_trips_total, rpc_bytes_{sent,received}[_raw]_total,
    rpc_inflight, ...) via a registry collector — once per process, and
    only after the native lib is known loaded (a /metrics scrape must
    never trigger a first-time build)."""
    global _rpc_obs_done
    with _rpc_mu:
        if _rpc_obs_done:
            return
        _rpc_obs_done = True
    reg = _obs.default_registry()
    gauges = {
        k: reg.gauge(
            f"rpc_{k}" if k == "inflight" else f"rpc_{k}_total",
            f"graph rpc transport {k} (client edge, process-global)")
        for k in _RPC_STAT_KEYS}

    def _collect():
        for k, v in rpc_transport_stats().items():
            gauges[k].set(v)

    reg.add_collector(_collect)


class RetryDeadlineExceeded(EngineError):
    """A retryable call ran out of its deadline/attempt budget. Carries
    the last underlying error text; degrade-mode sampling queries catch
    exactly this (semantic errors raise as plain EngineError at once)."""


@dataclasses.dataclass
class RetryPolicy:
    """Backoff/deadline policy for remote graph calls.

    deadline_s: total per-call budget across retries (0 → one attempt).
    base_backoff_s / max_backoff_s: exponential backoff with FULL jitter —
      sleep ~ U(0, min(max_backoff_s, base_backoff_s * 2^(attempt-1))),
      the AWS-style decorrelation that avoids retry stampedes when every
      trainer host sees the same shard die.
    call_timeout_s: per-ATTEMPT bound. The graph-query RPC channels use
      blocking sockets (long merges may stream for a while), so a black-
      holed connection would otherwise hang forever; > 0 runs each
      attempt on a worker thread and abandons it past the bound (the
      abandoned attempt unblocks when its socket dies; close() reaps).
      None/0 keeps the plain blocking call. Caveat: an abandoned attempt
      still occupies an engine executor thread until its socket dies, so
      during a SUSTAINED black-hole even non-timed calls may stall
      behind a saturated executor — full recovery needs the dead
      endpoint's connections to actually drop (they do when the shard
      process restarts or the network heals with RST/FIN), after which
      the parked attempts drain and the pool frees itself.
    max_attempts: hard attempt cap inside the deadline (0 → unlimited).
    """

    deadline_s: float = 30.0
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    call_timeout_s: Optional[float] = None
    max_attempts: int = 0

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter backoff for retry `attempt` (1-based)."""
        hi = min(self.max_backoff_s,
                 self.base_backoff_s * (2 ** max(attempt - 1, 0)))
        return rng.uniform(0.0, max(hi, 0.0))


class RemoteGraphEngine:
    """GraphEngine-compatible batch sampling/feature API over a remote
    Query proxy (distribute or graph_partition mode)."""

    def __init__(self, endpoints: str, seed: int = 0,
                 mode: str = "distribute",
                 retry_deadline_s: float = 30.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 degrade: bool = False,
                 pool_size: int = 0,
                 pool_handles: Optional[int] = None,
                 pool_shared: Optional[bool] = None,
                 dedup: bool = False,
                 chunk_size: int = 4096,
                 hedge: bool = False,
                 hedge_quantile: float = 0.95,
                 hedge_min_ms: float = 1.0,
                 hedge_max_ms: float = 250.0,
                 deadline_propagation: bool = False,
                 ownership_refresh_s: float = 0.0):
        """retry_deadline_s: failover budget. A query that fails (shard
        died mid-call, RpcChannel exhausted its in-channel retries) is
        retried under RetryPolicy (exponential backoff, full jitter)
        until this deadline — the registry monitor swaps the
        replacement shard's endpoint in live, so a restarted shard
        becomes visible within its heartbeat interval and the retry
        succeeds without rebuilding the engine. 0 disables (one
        attempt). Reference semantics: rpc_client.h:46 retry counter +
        ZK watch re-resolution.

        retry_policy: full control over backoff/deadline/per-attempt
        timeout; overrides retry_deadline_s when given.

        degrade: opt-in graceful degradation — a SAMPLING query that
        exhausts its retry deadline returns default_id-padded,
        correctly-shaped results and counts the event in health()
        ["degraded"] instead of raising mid-epoch (the TF-GNN
        "countable degraded batches" production posture). Feature
        getters never degrade (silent zeros would corrupt training
        data without a trace).

        pool_size: > 0 enables the pipelined RPC client — pool_size
        worker threads over `pool_handles` (default pool_size) pooled
        query handles, exposing submit(gql, feed) -> Future and
        turning large-id-set batch calls (sample_fanout /
        sample_neighbor / get_full_neighbor / get_dense_feature) into
        concurrent per-chunk queries merged in order. Each pooled call
        still runs the full RetryPolicy/degrade machinery and
        `graph_rpc` span. 0 (default) keeps the serial one-query-at-a-
        time client.

        pool_shared: pooled query handles are SHARED by the workers
        (concurrent run() on one handle, round-robin) instead of checked
        out exclusively — the mux-transport shape: N logical in-flight
        queries over pool_handles handles (default 1) and a small fixed
        wire-connection count, instead of one fd per in-flight call.
        None (default) auto-enables exactly when the process-global mux
        transport is on (configure_rpc). Concurrent draws on a shared
        handle stay distinct (each execution takes a fresh nonce).

        dedup: in-flight request dedup — concurrent IDENTICAL
        deterministic queries (same verb + ids; feature/neighbor reads)
        coalesce onto ONE wire call, counted as rpc_dedup_hits_total.
        Sampling verbs are never coalesced. Results are byte-identical
        to independent calls (followers receive copies).

        chunk_size: id-set size above which a pooled engine splits a
        batch call into concurrent chunks (ignored without a pool).

        hedge: adaptive straggler hedging on the mux transport — a
        kExecute whose reply exceeds the hedge delay fires the SAME
        request on a second mux connection; first reply wins, the loser
        is cancelled by request_id (its late reply discarded at the
        demux reader). The delay ADAPTS: every 64 calls it is recomputed
        as the hedge_quantile of this engine's observed per-attempt
        latency histogram (graph_rpc_attempt_ms — no retries/backoff),
        clamped to [hedge_min_ms, hedge_max_ms] (the max is
        also the cold-start delay before any data). Process-global knob
        (configure_rpc) — the LAST engine to refresh wins, which is the
        right behavior for the normal one-engine-per-process case.
        Requires mux with connections >= 2; hedging off is byte-
        identical to the pre-hedging wire. Sampling semantics: both
        legs carry identical bytes, so a hedged sampling query returns
        one of two draws of the same distribution.

        deadline_propagation: stamp each attempt's REMAINING retry
        budget into the v2 request frames (hello-negotiated) so a shard
        sheds queued work that can no longer make it — counted
        deadline_shed server-side, never a silent partial. v1 peers are
        byte-unchanged; off (default) stamps nothing.

        ownership_refresh_s: > 0 enables elastic-fleet routing — the
        engine TTL-caches the registry-published epoch-versioned
        ownership map (PR 8 client-cache pattern): on the call path it
        re-fetches at most every this-many seconds, installs newer maps
        into its native proxies (splits then place ids by the map's
        owner lists, p2c over replicated partitions), REBUILDS the
        proxies when the fleet grew (a live 2→4 split), and every
        request is stamped with the map epoch so a flipped shard
        refuses stale-map reads explicitly — which this engine answers
        by a forced refresh + retry (counted stale_map_retries; zero
        silent misroutes). Needs registry endpoints ("dir:"/"tcp:");
        0 (default) keeps the static hash-routed fleet."""
        configure_rpc_from_env()  # before the native channels are built
        if ownership_refresh_s and ownership_refresh_s > 0 \
                and not _RPC_CONFIG["mux"]:
            # elastic routing NEEDS the v2 mux transport: the stale-map
            # protection rides the hello-negotiated map-epoch request
            # prefix, which the classic v1 framing cannot carry — an
            # unstamped request would be served silently by a flipped
            # shard. Forced here, before the channels are built.
            configure_rpc(mux=True)
        self.query = Query.remote(endpoints, seed=seed, mode=mode)
        # elastic fleet: TTL-cached registry-published ownership map
        self._endpoints = endpoints
        self._seed = seed
        self._mode = mode
        self.ownership_refresh_s = float(ownership_refresh_s)
        self._omap_mu = threading.Lock()
        # serializes fetch+install+rebuild: two threads hitting the
        # stale-map path at once must not both rebuild (the second
        # would close the first's freshly built pipeline)
        self._omap_refresh_mu = threading.Lock()
        self._omap_epoch = 0
        self._omap_spec: Optional[str] = None
        self._omap_next_check = 0.0
        # proxies/pipelines retired by a fleet-growth rebuild: kept
        # alive (not closed) because in-flight calls on other threads —
        # including the rebuild trigger itself, when it fires on a
        # pooled worker — may still hold them; engine.close() closes
        # them once
        self._retired_proxies: list = []
        self._retired_pipelines: list = []
        self.retry = retry_policy or RetryPolicy(
            deadline_s=float(retry_deadline_s))
        # tail-latency knobs (ISSUE 12): adaptive hedging + deadline
        # propagation — both opt-in, both no-ops on the wire when off
        self.hedge = bool(hedge)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_max_ms = float(hedge_max_ms)
        self.deadline_propagation = bool(deadline_propagation)
        self._hedge_calls = 0  # refresh cadence counter (under _health_mu)
        if self.hedge:
            # arm at the conservative cold-start delay; the histogram
            # takes over from the first refresh
            configure_rpc(hedge_delay_ms=self.hedge_max_ms)
        self.retry_deadline_s = self.retry.deadline_s  # back-compat alias
        self.degrade = bool(degrade)
        # host-side rng for the client-computed node2vec bias; seed=0 →
        # fresh entropy (matching the engine's seed convention)
        self._rng = np.random.default_rng(seed if seed else None)
        self._backoff_rng = random.Random(seed ^ 0x5EED if seed else None)
        self._health_mu = threading.Lock()
        # counters live on the obs registry (labeled by engine instance);
        # health() is a VIEW over them — no parallel bookkeeping
        self._obs_name = f"remote{next(_ENGINE_IDS)}"
        reg = _obs.default_registry()
        lab = {"engine": self._obs_name}
        self._ctr = {
            k: reg.counter(f"graph_rpc_{k}_total", h,
                           ("engine",)).labels(**lab)
            for k, h in _RPC_COUNTERS.items()}
        self._ctr_backoff_s = reg.counter(
            "graph_rpc_backoff_seconds_total",
            "seconds slept in retry backoff", ("engine",)).labels(**lab)
        self._hist_call_ms = reg.histogram(
            "graph_rpc_ms", "end-to-end graph rpc latency incl. retries",
            ("engine",)).labels(**lab)
        # per-ATTEMPT wire latency (no retries, no backoff sleeps):
        # the adaptive hedge delay reads its quantiles here — the
        # end-to-end histogram above would inflate the delay with
        # backoff exactly when stragglers/failures abound
        self._hist_attempt_ms = reg.histogram(
            "graph_rpc_attempt_ms",
            "single-attempt graph rpc wire latency (hedge-delay signal)",
            ("engine",)).labels(**lab)
        self._last_error: Optional[str] = None
        # elastic fleet observability: the installed map epoch and the
        # per-shard request counters (hot-shard detection feeds off
        # graph_shard_requests_total at every scrape)
        self._g_map_epoch = reg.gauge(
            "graph_ownership_epoch",
            "installed ownership-map epoch (0 = hash routing)",
            ("engine",)).labels(**lab)
        self._g_shard_reqs = reg.gauge(
            "graph_shard_requests_total",
            "kExecute requests issued per graph shard (client edge)",
            ("engine", "shard"))
        self._g_shard_rows = reg.gauge(
            "graph_shard_rows_total",
            "split-routed ids per graph shard (client edge — the "
            "hot-shard detection signal)", ("engine", "shard"))
        eng_ref = weakref.ref(self)
        obs_name = self._obs_name
        g_reqs, g_rows = self._g_shard_reqs, self._g_shard_rows

        def _collect_shards():
            eng = eng_ref()
            if eng is None:
                return False  # engine gone: collector self-removes
            try:
                reqs, rows = eng.query.shard_stats()
            except (EngineError, OSError):
                return None  # closed/unavailable; keep the collector
            for s in range(len(reqs)):
                g_reqs.labels(engine=obs_name, shard=str(s)).set(
                    int(reqs[s]))
                g_rows.labels(engine=obs_name, shard=str(s)).set(
                    int(rows[s]))

        reg.add_collector(_collect_shards)
        _obs.register_health(self._obs_name, self.health)
        self.query.bind_obs(self._obs_name)
        self._strays: list = []  # abandoned timed-out attempt threads
        _ensure_rpc_obs()
        # in-flight dedup: deterministic sub-queries coalesce onto one
        # wire call (graph/pipeline.py); None keeps every call 1:1
        self._dedup = None
        if dedup:
            from euler_tpu.graph.pipeline import InflightDedup

            self._dedup = InflightDedup(self._obs_name)
        # pipelined client (ISSUE 4): per-engine worker pool + pooled
        # query handles; None keeps the serial path byte-identical
        self.chunk_size = int(chunk_size)
        self.pipeline = None
        self._pipeline_args = None
        if pool_size and pool_size > 0:
            from euler_tpu.graph.pipeline import PipelinedClient

            shared = (_RPC_CONFIG["mux"] if pool_shared is None
                      else bool(pool_shared))
            # retained for proxy rebuilds after a fleet-growth refresh
            self._pipeline_args = dict(workers=int(pool_size),
                                       handles=pool_handles,
                                       shared=shared)
            self.pipeline = PipelinedClient(
                self, endpoints, seed, mode, **self._pipeline_args)
        if self.ownership_refresh_s > 0:
            # best-effort initial install (the fleet may predate maps);
            # runs after the pipeline exists so pooled handles get it
            try:
                self.refresh_ownership(force=True)
            except (EngineError, OSError, ValueError):
                pass

    # -- health / retry machinery ------------------------------------------
    def health(self) -> dict:
        """Counter surface for ops/bench artifacts: calls, retries (sleep
        cycles), failovers (calls that failed then succeeded on retry),
        degraded (padded results served), deadline_exhausted, last_error,
        plus the proxy's own query/error totals. A compatibility VIEW
        over this engine's euler_tpu.obs registry children — the same
        numbers a /metrics scrape reports, by construction."""
        out = {k: int(self._ctr[k].value) for k in
               ("calls", "retries", "failovers", "degraded",
                "deadline_exhausted", "stale_map_retries",
                "ownership_refreshes")}
        with self._health_mu:
            out["last_error"] = self._last_error
        try:
            out.update({f"proxy_{k}": v
                        for k, v in self.query.stats().items()
                        if k in ("queries", "errors")})
        except (EngineError, OSError):
            # closed / stats unavailable — the merge failure is COUNTED
            # (it was silently swallowed pre-obs), local counters still
            # serve
            self._ctr["health_merge_errors"].inc()
        out["health_merge_errors"] = int(
            self._ctr["health_merge_errors"].value)
        return out

    def _bump(self, key: str, n: int = 1) -> None:
        self._ctr[key].inc(n)

    # bound on live abandoned attempt threads: past this, timed attempts
    # fail fast instead of spawning — a long black-holed outage with
    # degrade=True must not accumulate threads/sockets without limit
    _MAX_STRAYS = 32

    def _attempt(self, gql: str, feed, query=None, deadline_ms=None,
                 trace=None):
        """One query attempt, bounded by retry.call_timeout_s when set
        (the RPC sockets block, so a black-holed connection can only be
        escaped by abandoning the attempt thread). `query` selects a
        pooled handle; None uses the engine's own. deadline_ms and the
        wire trace context ride to the shards inside the v2 frames
        (Query.run)."""
        query = query if query is not None else self.query
        t = self.retry.call_timeout_s
        t_att = time.monotonic()
        if not t or t <= 0:
            out = query.run(gql, feed, deadline_ms=deadline_ms,
                            trace=trace)
            self._hist_attempt_ms.observe(
                (time.monotonic() - t_att) * 1000.0)
            return out
        with self._health_mu:
            # reap strays that have since unblocked; refuse to grow past
            # the cap ("timeout" marker keeps this retryable/degradable)
            self._strays = [th for th in self._strays if th.is_alive()]
            if len(self._strays) >= self._MAX_STRAYS:
                raise EngineError(
                    f"graph rpc attempt timeout: {len(self._strays)} "
                    "abandoned in-flight attempts already parked "
                    "(endpoint black-holed?); refusing to spawn more")
        box = {}

        def work():
            try:
                box["out"] = query.run(gql, feed, deadline_ms=deadline_ms,
                                       trace=trace)
            except BaseException as e:  # surfaced on join below
                box["err"] = e

        th = threading.Thread(target=work, daemon=True)
        th.start()
        th.join(t)
        if th.is_alive():
            with self._health_mu:
                self._strays.append(th)
            raise EngineError(
                f"graph rpc attempt timeout after {t:.3f}s "
                "(in-flight attempt abandoned)")
        if "err" in box:
            raise box["err"]
        self._hist_attempt_ms.observe((time.monotonic() - t_att) * 1000.0)
        return box["out"]

    def _run(self, gql: str, feed=None, query=None):
        """_run_wire with in-flight dedup in front when enabled:
        concurrent identical DETERMINISTIC queries (never sampling
        verbs) coalesce onto one wire call; followers get byte-
        identical copies of the leader's result."""
        if self._dedup is not None:
            return self._dedup.run(
                gql, feed, lambda: self._run_wire(gql, feed, query))
        return self._run_wire(gql, feed, query)

    def _run_wire(self, gql: str, feed=None, query=None):
        """query.run under RetryPolicy: retryable (transport) failures
        back off with full jitter until the deadline; semantic errors
        raise at once; an exhausted budget raises
        RetryDeadlineExceeded. The whole call (retries + backoff
        included) runs under a `graph_rpc` span and lands in the
        graph_rpc_ms histogram, success or raise. `query` runs the
        attempts on a pooled handle (the pipelined client's workers);
        default is the engine's own handle."""
        pol = self.retry
        self._bump("calls")
        if self.ownership_refresh_s > 0:
            # TTL tick: within the TTL this is one lock + compare; past
            # it, one registry fetch amortized over the window
            try:
                self.refresh_ownership()
            except (EngineError, OSError, ValueError):
                pass  # stale map still routes; the shard-side check
                # + forced refresh below stay the correctness backstop
        with _obs.timed_span("graph_rpc", self._hist_call_ms,
                             engine=self._obs_name, gql=gql[:80]) as sp:
            deadline = time.monotonic() + max(pol.deadline_s, 0.0)
            attempt = 0
            # wire trace context: every attempt (and every hedge leg the
            # native layer fires) carries THIS span's (trace_id,
            # span_id), so the shards' timing breakdowns stitch under
            # the graph_rpc span in a merged chrome trace. 0 when
            # tracing is disabled — nothing is stamped on the wire.
            wire_trace = (sp.trace_id, sp.span_id)
            while True:
                try:
                    dl_ms = None
                    if self.deadline_propagation:
                        # each attempt ships the budget REMAINING now —
                        # a shard sheds it once it can no longer make it
                        dl_ms = max(
                            (deadline - time.monotonic()) * 1000.0, 1.0)
                    out = self._attempt(gql, feed, query,
                                        deadline_ms=dl_ms,
                                        trace=wire_trace)
                    if attempt:
                        # the call came back after ≥1 transport failure:
                        # the shard (or its replacement channel)
                        # recovered
                        self._bump("failovers")
                    sp.set(attempts=attempt + 1)
                    if self.hedge:
                        self._maybe_refresh_hedge()
                    return out
                except EngineError as e:
                    if not retryable_error(e):
                        raise
                    if "stale ownership map" in str(e).lower():
                        # the shard flipped to a newer map than this
                        # request was split with: refresh NOW (forced)
                        # so the retry routes on the fresh map — the
                        # split/merge plan re-runs from scratch
                        self._bump("stale_map_retries")
                        try:
                            self.refresh_ownership(force=True)
                        except (EngineError, OSError, ValueError):
                            pass  # retry anyway; backoff paces us
                        # a POOLED handle may now be retired (fleet-
                        # growth rebuild): it can never adopt the wider
                        # map, so every retry on it would be refused —
                        # re-point the remaining attempts at the
                        # engine's fresh proxy
                        if query is not None and query is not self.query:
                            query = None
                    attempt += 1
                    with self._health_mu:
                        self._last_error = str(e)
                    now = time.monotonic()
                    exhausted = (now >= deadline
                                 or (pol.max_attempts
                                     and attempt >= pol.max_attempts))
                    if exhausted:
                        self._bump("deadline_exhausted")
                        sp.set(attempts=attempt, exhausted=True)
                        raise RetryDeadlineExceeded(
                            f"graph rpc gave up after {attempt} "
                            f"attempt(s) ({pol.deadline_s:.1f}s "
                            f"deadline): {e}") from e
                    self._bump("retries")
                    sleep = min(
                        pol.backoff_s(attempt, self._backoff_rng),
                        max(deadline - now, 0.0))
                    with _obs.span("graph_rpc_backoff",
                                   engine=self._obs_name,
                                   attempt=attempt):
                        time.sleep(sleep)
                    self._ctr_backoff_s.inc(sleep)

    def _note_degraded(self) -> None:
        self._bump("degraded")

    # -- elastic fleet: ownership-map cache / refresh ----------------------
    def ownership_epoch(self) -> int:
        """Installed ownership-map epoch (0 = hash routing)."""
        return self.query.ownership_epoch()

    def shard_traffic(self):
        """(requests, rows) per-shard uint64 arrays since the current
        proxy was built. ROWS (split-routed ids) are the hot-shard
        signal — the distribute rewrite fires one REMOTE per shard per
        query regardless, so requests alone cannot see skew. Mirrored
        as graph_shard_{requests,rows}_total{engine=,shard=} gauges."""
        return self.query.shard_stats()


    def _registry_endpoints(self) -> Optional[str]:
        return (self._endpoints
                if self._endpoints.startswith(("dir:", "tcp:")) else None)

    def refresh_ownership(self, force: bool = False) -> int:
        """TTL-cached ownership-map refresh (PR 8 client-cache
        pattern): fetch the registry-published map, and when it is
        newer than the installed one push it into the native proxies —
        REBUILDING them first when the map references a grown fleet
        (live split). force=True skips the TTL (the stale-map retry
        path). Returns the installed epoch. No-op without registry
        endpoints or a published map."""
        registry = self._registry_endpoints()
        if registry is None:
            return 0
        now = time.monotonic()
        with self._omap_mu:
            if not force and now < self._omap_next_check:
                return self._omap_epoch
            # claim the slot before the fetch so concurrent callers
            # don't stampede the registry
            self._omap_next_check = now + max(self.ownership_refresh_s,
                                              0.5)
        from euler_tpu.graph import elastic

        # ONE refresh at a time: concurrent stale-map retries must not
        # both rebuild the proxies (the loser would close the winner's
        # fresh pipeline); late arrivals re-check the epoch inside and
        # return the already-installed map
        with self._omap_refresh_mu:
            m = elastic.fetch_map(registry)
            if m is None:
                return 0
            with self._omap_mu:
                if m.map_epoch <= self._omap_epoch:
                    return self._omap_epoch
            if m.shard_num != self.query.shard_num():
                # the fleet grew (or shrank): these proxies were built
                # against the wrong channel set — rebuild from discovery
                self._rebuild_proxies()
            spec = m.encode()
            self.query.set_ownership(spec)
            if self.pipeline is not None:
                self.pipeline.set_ownership(spec)
            with self._omap_mu:
                self._omap_epoch = m.map_epoch
                self._omap_spec = spec
        self._bump("ownership_refreshes")
        self._g_map_epoch.set(m.map_epoch)
        return m.map_epoch

    def _rebuild_proxies(self) -> None:
        """Swap in fresh native proxies discovered from the registry
        (new shard count after a live split). The retired proxies AND
        the retired pipeline are kept alive, not closed: this can run
        ON one of the old pipeline's own worker threads (the stale-map
        retry path), where close() would try to join the current
        thread, and cancelling the old pool's queued futures would
        fail calls that are mid-retry. Old workers drain naturally —
        their in-flight calls re-point at the fresh proxy (the
        stale-map hook in _run_wire) — and everything retired is
        closed with the engine."""
        fresh = Query.remote(self._endpoints, seed=self._seed,
                             mode=self._mode)
        old, self.query = self.query, fresh
        self._retired_proxies.append(old)
        self.query.bind_obs(self._obs_name)
        if self.pipeline is not None:
            from euler_tpu.graph.pipeline import PipelinedClient

            old_pipe = self.pipeline
            self.pipeline = PipelinedClient(
                self, self._endpoints, self._seed, self._mode,
                **self._pipeline_args)
            self._retired_pipelines.append(old_pipe)

    # -- adaptive hedging --------------------------------------------------
    _HEDGE_REFRESH_CALLS = 64

    def _maybe_refresh_hedge(self) -> None:
        """Every _HEDGE_REFRESH_CALLS successful calls, recompute the
        process-global hedge delay as the hedge_quantile of THIS
        engine's per-attempt latency histogram (bucket-interpolated),
        clamped to [hedge_min_ms, hedge_max_ms] — the adaptive
        percentile the straggler detector fires at."""
        with self._health_mu:
            self._hedge_calls += 1
            if self._hedge_calls % self._HEDGE_REFRESH_CALLS:
                return
        self.update_hedge_delay()

    def update_hedge_delay(self) -> float:
        """Force one adaptive-hedge-delay refresh; returns the applied
        delay in ms (also pushed into the process-global RpcConfig)."""
        q = self._hist_attempt_ms.quantile(self.hedge_quantile)
        delay = self.hedge_max_ms if q is None else min(
            max(float(q), self.hedge_min_ms), self.hedge_max_ms)
        configure_rpc(hedge_delay_ms=delay)
        return delay

    # -- pipelined submission / chunked intra-batch fan-out ----------------
    def submit(self, gql: str, feed=None):
        """Future-returning query submission. With a pool (pool_size>0)
        the call queues to the worker pool and runs on a pooled handle;
        without one it executes synchronously and returns an already-
        completed Future — one surface either way."""
        pipe = self.pipeline
        if pipe is not None:
            try:
                return pipe.submit(gql, feed)
            except RuntimeError:
                # the pipeline was closed under us by a fleet-growth
                # proxy rebuild: fall through to the synchronous path
                # for this call (the rebuilt pipeline serves the next)
                pass
        from concurrent.futures import Future

        fut = Future()
        try:
            fut.set_result(self._run(gql, feed))
        except BaseException as e:
            fut.set_exception(e)
        return fut

    def _id_chunks(self, n: int):
        """[(lo, hi)] chunk bounds when the pipelined client should fan
        an id set out concurrently; None → serial single call (no pool,
        chunking disabled, or the set is small enough already)."""
        c = self.chunk_size
        if self.pipeline is None or c <= 0 or n <= c:
            return None
        return [(i, min(i + c, n)) for i in range(0, n, c)]

    def _chunk_results(self, chunks, submit_chunk, can_degrade=True):
        """Submit every chunk, then collect results IN CHUNK ORDER. With
        degrade=True a degradable (sampling) chunk that exhausts its
        retry deadline yields None (the caller pads exactly that id
        range); otherwise the first failure raises after all futures
        were issued — in-flight siblings finish on their workers and
        are dropped. can_degrade=False for verbs that never degrade
        (feature/neighbor getters), matching the serial path."""
        futs = [submit_chunk(a, b) for a, b in chunks]
        outs = []
        for f in futs:
            try:
                outs.append(f.result())
            except RetryDeadlineExceeded:
                if not (can_degrade and self.degrade):
                    raise
                self._note_degraded()
                outs.append(None)
        return outs

    # -- root sampling -----------------------------------------------------
    def sample_node(self, count: int, node_type: int = -1) -> np.ndarray:
        out = self._run(f"sampleN({node_type}, {count}).as(n)")
        return out["n:0"].astype(np.uint64).ravel()

    def sample_edge(self, count: int, edge_type: int = -1):
        out = self._run(f"sampleE({edge_type}, {count}).as(e)")
        return (out["e:0"].astype(np.uint64), out["e:1"].astype(np.uint64),
                out["e:2"].astype(np.int32))

    def sample_node_with_types(self, types) -> np.ndarray:
        """One weighted node draw per requested type (reference
        SampleNWithTypes) via the sampleNWithTypes verb."""
        types = np.ascontiguousarray(types, dtype=np.int32).ravel()
        out = self._run("sampleNWithTypes(t).as(n)", {"t": types})
        return out["n:0"].astype(np.uint64).ravel()

    # -- traversal ---------------------------------------------------------
    @staticmethod
    def _et(edge_types) -> str:
        from euler_tpu.gql import edge_types_str

        return edge_types_str(edge_types)

    def sample_fanout(self, roots, counts: Sequence[int], edge_types=None,
                      default_id: int = 0):
        """Multi-hop expansion in ONE round trip (reference
        sample_fanout_op.cc:36-48). Returns (ids_per_hop, w_per_hop,
        t_per_hop) with hop i arrays of shape [n·prod(counts[:i+1])]."""
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        if edge_types is not None and len(edge_types) > 0 and isinstance(
                edge_types[0], (list, tuple, np.ndarray)):
            if len(edge_types) != len(counts):
                raise ValueError(
                    f"per-hop edge_types has {len(edge_types)} entries, "
                    f"expected {len(counts)} (one per hop)")
            per_hop = [self._et(h) for h in edge_types]
        else:
            per_hop = [self._et(edge_types)] * len(counts)
        q = "v(r)"
        for i, k in enumerate(counts):
            q += f".sampleNB({per_hop[i]}, {int(k)}, {default_id}).as(h{i})"
        chunks = self._id_chunks(roots.size)
        if chunks is None:
            try:
                out = self._run(q, {"r": roots})
            except RetryDeadlineExceeded:
                if not self.degrade:
                    raise
                self._note_degraded()
                ids, w, t = [], [], []
                m = roots.size
                for k in counts:
                    m *= int(k)
                    ids.append(np.full(m, default_id, np.uint64))
                    w.append(np.zeros(m, np.float32))
                    t.append(np.full(m, -1, np.int32))
                return ids, w, t
            ids = [out[f"h{i}:1"].astype(np.uint64)
                   for i in range(len(counts))]
            w = [out[f"h{i}:2"].astype(np.float32)
                 for i in range(len(counts))]
            t = [out[f"h{i}:3"].astype(np.int32)
                 for i in range(len(counts))]
            return ids, w, t
        # concurrent fan-out: hop arrays are root-major, so per-chunk
        # hop arrays concatenate into exactly the unchunked layout
        outs = self._chunk_results(
            chunks, lambda a, b: self.submit(q, {"r": roots[a:b]}))
        ids, w, t = [], [], []
        mult = 1
        for i, k in enumerate(counts):
            mult *= int(k)
            pi, pw, pt = [], [], []
            for (a, b), out in zip(chunks, outs):
                m = (b - a) * mult
                if out is None:          # this chunk degraded: pad it
                    pi.append(np.full(m, default_id, np.uint64))
                    pw.append(np.zeros(m, np.float32))
                    pt.append(np.full(m, -1, np.int32))
                else:
                    pi.append(out[f"h{i}:1"].astype(np.uint64))
                    pw.append(out[f"h{i}:2"].astype(np.float32))
                    pt.append(out[f"h{i}:3"].astype(np.int32))
            ids.append(np.concatenate(pi))
            w.append(np.concatenate(pw))
            t.append(np.concatenate(pt))
        return ids, w, t

    def sample_neighbor(self, ids, count: int, edge_types=None,
                        default_id: int = 0):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        n = ids.size
        gql = (f"v(r).sampleNB({self._et(edge_types)}, {count}, "
               f"{default_id}).as(nb)")
        chunks = self._id_chunks(n)
        if chunks is None:
            try:
                out = self._run(gql, {"r": ids})
            except RetryDeadlineExceeded:
                if not self.degrade:
                    raise
                self._note_degraded()
                return (np.full((n, count), default_id, np.uint64),
                        np.zeros((n, count), np.float32),
                        np.full((n, count), -1, np.int32))
            return (out["nb:1"].reshape(n, count).astype(np.uint64),
                    out["nb:2"].reshape(n, count).astype(np.float32),
                    out["nb:3"].reshape(n, count).astype(np.int32))
        outs = self._chunk_results(
            chunks, lambda a, b: self.submit(gql, {"r": ids[a:b]}))
        nb = np.full((n, count), default_id, np.uint64)
        w = np.zeros((n, count), np.float32)
        t = np.full((n, count), -1, np.int32)
        for (a, b), out in zip(chunks, outs):
            if out is None:
                continue                 # degraded chunk keeps padding
            m = b - a
            nb[a:b] = out["nb:1"].reshape(m, count).astype(np.uint64)
            w[a:b] = out["nb:2"].reshape(m, count).astype(np.float32)
            t[a:b] = out["nb:3"].reshape(m, count).astype(np.int32)
        return nb, w, t

    def get_full_neighbor(self, ids, edge_types=None,
                          sorted_by_id: bool = False,
                          in_edges: bool = False):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        verb = "getRNB" if in_edges else (
            "getSortedNB" if sorted_by_id else "getNB")
        gql = f"v(r).{verb}({self._et(edge_types)}).as(nb)"
        chunks = self._id_chunks(ids.size)
        if chunks is None:
            out = self._run(gql, {"r": ids})
            idx = out["nb:0"].reshape(-1, 2)
            offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
            return (offsets, out["nb:1"].astype(np.uint64),
                    out["nb:2"].astype(np.float32),
                    out["nb:3"].astype(np.int32))
        # neighbor getters never degrade, so a failed chunk raises
        outs = self._chunk_results(
            chunks, lambda a, b: self.submit(gql, {"r": ids[a:b]}),
            can_degrade=False)
        offs, nbrs, ws, ts = [np.zeros(1, np.int64)], [], [], []
        base = 0
        for out in outs:
            idx = out["nb:0"].reshape(-1, 2).astype(np.int64)
            offs.append(idx[:, 1] + base)
            base += int(idx[-1, 1]) if idx.size else 0
            nbrs.append(out["nb:1"].astype(np.uint64))
            ws.append(out["nb:2"].astype(np.float32))
            ts.append(out["nb:3"].astype(np.int32))
        return (np.concatenate(offs).astype(np.uint64),
                np.concatenate(nbrs), np.concatenate(ws),
                np.concatenate(ts))

    def get_neighbor_edges(self, ids, edge_types=None):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(
            f"v(r).outE({self._et(edge_types)}).as(e)", {"r": ids})
        idx = out["e:0"].reshape(-1, 2)
        offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
        return (offsets, out["e:1"].astype(np.uint64),
                out["e:2"].astype(np.uint64), out["e:3"].astype(np.int32),
                out["e:4"].astype(np.float32))

    def sample_layerwise(self, roots, layer_sizes: Sequence[int],
                         edge_types=None, default_id: int = 0,
                         weight_func: str = ""):
        """LADIES pools from the cluster via one sampleLNB query
        (reference SampleNeighborLayerwiseWithAdj → API_SAMPLE_L).
        weight_func '' or 'sqrt' (hub-dampening, reference
        local_sample_layer_op.cc:94). Note: in distribute mode sqrt is
        applied to each shard's partial accumulation (the reference's
        distributed semantics too) — see POOL_MERGE in
        kernels_dist.cc."""
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        sizes = ":".join(str(int(s)) for s in layer_sizes)
        wf = f", {weight_func}" if weight_func else ""
        try:
            out = self._run(
                f"v(r).sampleLNB({self._et(edge_types)}, {sizes}, "
                f"{default_id}{wf}).as(l)", {"r": roots})
        except RetryDeadlineExceeded:
            if not self.degrade:
                raise
            self._note_degraded()
            return [np.full(int(s), default_id, np.uint64)
                    for s in layer_sizes]
        return [out[f"l:{i}"].astype(np.uint64)
                for i in range(len(layer_sizes))]

    def random_walk(self, roots, walk_len: int, p: float = 1.0,
                    q: float = 1.0, edge_types=None,
                    default_id: int = 0) -> np.ndarray:
        """[n, walk_len+1] walks against the cluster. The unbiased case
        is ONE chained-sampleNB round trip; node2vec bias (p/q) falls
        back to per-step neighbor queries with client-side reweighting —
        the reference's random_walk_op.cc:70-110 approach."""
        roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
        n = roots.size
        et = self._et(edge_types)
        out = np.zeros((n, walk_len + 1), dtype=np.uint64)
        out[:, 0] = roots
        if p == 1.0 and q == 1.0:
            gql = "v(r)" + "".join(
                f".sampleNB({et}, 1, {default_id}).as(s{i})"
                for i in range(walk_len))
            try:
                res = self._run(gql, {"r": roots})
            except RetryDeadlineExceeded:
                if not self.degrade:
                    raise
                self._note_degraded()
                out[:, 1:] = default_id  # roots stay real; steps padded
                return out
            for i in range(walk_len):
                out[:, i + 1] = res[f"s{i}:1"].astype(np.uint64)
            return out
        rng = self._rng
        prev = np.zeros(n, dtype=np.uint64)
        cur = roots.copy()
        # neighbor lists of `prev` are the previous step's `cur` lists —
        # cache them instead of refetching (halves the per-step RPCs)
        poff = np.zeros(n + 1, dtype=np.int64)
        pnbr = np.zeros(0, dtype=np.uint64)
        for step in range(walk_len):
            try:
                off, nbr, w, _ = self.get_full_neighbor(
                    cur, edge_types=edge_types)
            except RetryDeadlineExceeded:
                if not self.degrade:
                    raise
                self._note_degraded()
                out[:, step + 1:] = default_id  # remaining steps padded
                return out
            off = off.astype(np.int64)
            nxt = self._biased_step(off, nbr, w, prev, poff, pnbr,
                                    p, q, default_id, rng)
            prev, cur = cur, nxt
            poff, pnbr = off, nbr
            out[:, step + 1] = cur
        return out

    @staticmethod
    def _biased_step(off, nbr, w, prev, poff, pnbr, p, q, default_id,
                     rng) -> np.ndarray:
        """One node2vec-biased walk step, fully vectorized (the per-node
        Python loop with a set() per row was the walk feeder's host
        ceiling): candidate weights are reweighted with numpy segment
        ops — the prev-neighbor membership test is a sorted-rank
        searchsorted over (row, id) composite keys, the draw a
        segment-sum + segmented inverse-CDF over one global cumsum.
        Distribution-identical to the loop (pinned by the seeded
        chi-squared test in tests/test_host_pipeline.py); rows with no
        candidates or zero total weight stay at default_id."""
        n = prev.size
        counts = off[1:] - off[:-1]
        nxt = np.full(n, default_id, dtype=np.uint64)
        if nbr.size == 0:
            return nxt
        seg = np.repeat(np.arange(n, dtype=np.int64), counts)
        wt = w.astype(np.float64)
        # return edge: candidate == the walk's previous node
        ret = nbr == prev[seg]
        # outward edge: candidate NOT adjacent to the previous node.
        # Sorted-membership: rank every id against the union of ids
        # seen this step, pack (row, rank) into one int64 key, and
        # binary-search the sorted prev-neighbor keys — no per-row set.
        uniq = np.unique(np.concatenate([nbr, pnbr]))
        stride = np.int64(uniq.size + 1)
        cand_key = seg * stride + np.searchsorted(uniq, nbr)
        pseg = np.repeat(np.arange(n, dtype=np.int64),
                         poff[1:] - poff[:-1])
        prev_key = np.sort(pseg * stride + np.searchsorted(uniq, pnbr))
        if prev_key.size:
            ins = np.minimum(np.searchsorted(prev_key, cand_key),
                             prev_key.size - 1)
            member = prev_key[ins] == cand_key
        else:
            member = np.zeros(nbr.size, dtype=bool)
        wt[ret] /= p
        wt[~ret & ~member] /= q
        # segment totals + segmented inverse-CDF draw on the global
        # cumulative sum: row i's draw lands in [off[i], off[i+1])
        s = np.bincount(seg, weights=wt, minlength=n)
        cum = np.cumsum(wt)
        start = np.concatenate([[0.0], cum])[off[:-1]]
        ok = s > 0
        u = rng.random(n) * s
        pos = np.searchsorted(cum, start + u, side="right")
        pos = np.minimum(pos, np.maximum(off[1:] - 1, 0))
        nxt[ok] = nbr[pos[ok]]
        return nxt

    # -- features ----------------------------------------------------------
    def _dense_from_values(self, out, n: int, names, dims, single: bool):
        """Decode a values() query's (idx, vals) pairs into dense [n, d]
        arrays. Rows can be ragged (graph_partition mode returns EMPTY
        rows for ids a shard doesn't own) — scatter by the idx offsets
        instead of a flat reshape, zero-filling misses like the embedded
        engine does. Shared by the node and edge dense getters."""
        outs = []
        dim_list = ([dims] if single else list(dims)) if dims is not None \
            else [None] * len(names)
        for i, want in enumerate(dim_list):
            idx = out[f"f:{2 * i}"].reshape(-1, 2).astype(np.int64)
            vals = out[f"f:{2 * i + 1}"].astype(np.float32)
            lens = idx[:, 1] - idx[:, 0]
            dim = int(want) if want is not None else int(lens.max(initial=0))
            # fast path (the distribute-mode norm): every row complete
            # and laid out contiguously → one reshape, no Python loop
            # on the feeder path
            if (idx.shape[0] == n and vals.size == n * dim
                    and (lens == dim).all()
                    and (idx[:, 0] == np.arange(n) * dim).all()):
                outs.append(vals.reshape(n, dim))
                continue
            # ragged slow path (graph_partition mode: shards return
            # EMPTY rows for ids they don't own): one repeat/scatter
            # pass instead of a per-row copy loop
            arr = np.zeros((n, dim), dtype=np.float32)
            k = min(n, idx.shape[0])
            cnt = np.minimum(lens[:k], dim).astype(np.int64)
            tot = int(cnt.sum())
            if tot:
                rows = np.repeat(np.arange(k), cnt)
                col = (np.arange(tot, dtype=np.int64)
                       - np.repeat(np.cumsum(cnt) - cnt, cnt))
                arr[rows, col] = vals[np.repeat(idx[:k, 0], cnt) + col]
            outs.append(arr)
        return outs[0] if single else outs

    def get_dense_feature(self, ids, fids, dims=None):
        """[n, dim] float32 per fid; mirrors GraphEngine.get_dense_feature
        (single name → single array, list → list)."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        single = not isinstance(fids, (list, tuple, np.ndarray))
        names = [fids] if single else list(fids)
        q = "v(r).values(" + ", ".join(str(n) for n in names) + ").as(f)"
        chunks = self._id_chunks(ids.size)
        if chunks is None:
            out = self._run(q, {"r": ids})
            return self._dense_from_values(out, ids.size, names, dims,
                                           single)
        outs = self._chunk_results(
            chunks, lambda a, b: self.submit(q, {"r": ids[a:b]}),
            can_degrade=False)
        # decode each chunk as a list, then merge per fid; with
        # dims=None a chunk's inferred width is its own rows' max, so
        # right-pad to the cross-chunk max — rows are zero-filled past
        # their length either way, byte-identical to the single query
        dim_list = None if dims is None else ([dims] if single
                                              else list(dims))
        per_chunk = [self._dense_from_values(out, b - a, names, dim_list,
                                             False)
                     for (a, b), out in zip(chunks, outs)]
        merged = []
        for i in range(len(names)):
            parts = [pc[i] for pc in per_chunk]
            width = max(p.shape[1] for p in parts)
            parts = [p if p.shape[1] == width else np.pad(
                p, ((0, 0), (0, width - p.shape[1]))) for p in parts]
            merged.append(np.concatenate(parts))
        return merged[0] if single else merged

    @staticmethod
    def _csr_result(out, tag: str, dtype):
        """(offsets[n+1], values) from a values() query's (idx, vals)
        pair — the CSR convention the embedded engine's sparse/binary
        getters return."""
        idx = out[f"{tag}:0"].reshape(-1, 2).astype(np.int64)
        offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
        return offsets, out[f"{tag}:1"].astype(dtype)

    def get_sparse_feature(self, ids, fid) -> tuple:
        """(offsets[n+1], u64 values) CSR; mirrors
        GraphEngine.get_sparse_feature over the cluster."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(f"v(r).values({fid}).as(p)", {"r": ids})
        return self._csr_result(out, "p", np.uint64)

    def get_binary_feature(self, ids, fid) -> tuple:
        """(offsets[n+1], bytes) CSR of raw per-node byte strings."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run(f"v(r).values({fid}).as(p)", {"r": ids})
        offs, vals = self._csr_result(out, "p", np.uint8)
        return offs, vals.tobytes()

    def get_edge_dense_feature(self, src, dst, types, fids, dims=None):
        """[n, dim] float32 per fid for (src, dst, type) edge triples."""
        feed = {"batch:0": np.ascontiguousarray(src, np.uint64).ravel(),
                "batch:1": np.ascontiguousarray(dst, np.uint64).ravel(),
                "batch:2": np.ascontiguousarray(types, np.int32).ravel()}
        single = not isinstance(fids, (list, tuple, np.ndarray))
        names = [fids] if single else list(fids)
        q = "e(batch).values(" + ", ".join(str(n) for n in names) + ").as(f)"
        out = self._run(q, feed)
        return self._dense_from_values(out, feed["batch:0"].size, names,
                                       dims, single)

    def get_edge_sparse_feature(self, src, dst, types, fid) -> tuple:
        feed = {"batch:0": np.ascontiguousarray(src, np.uint64).ravel(),
                "batch:1": np.ascontiguousarray(dst, np.uint64).ravel(),
                "batch:2": np.ascontiguousarray(types, np.int32).ravel()}
        out = self._run(f"e(batch).values({fid}).as(p)", feed)
        return self._csr_result(out, "p", np.uint64)

    def get_edge_binary_feature(self, src, dst, types, fid) -> tuple:
        """(offsets[n+1], bytes): per-edge raw byte strings over the
        cluster (reference GetEdgeBinaryFeature)."""
        feed = {"batch:0": np.ascontiguousarray(src, np.uint64).ravel(),
                "batch:1": np.ascontiguousarray(dst, np.uint64).ravel(),
                "batch:2": np.ascontiguousarray(types, np.int32).ravel()}
        out = self._run(f"e(batch).values({fid}).as(p)", feed)
        offs, vals = self._csr_result(out, "p", np.uint8)
        return offs, vals.tobytes()

    def get_node_type(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        out = self._run("v(r).label().as(t)", {"r": ids})
        return out["t:0"].astype(np.int32)

    # -- streaming deltas --------------------------------------------------
    def graph_epoch(self, refresh: bool = False) -> int:
        """Observed graph epoch. Passive by default: the max epoch seen
        on any shard reply (v2 mux frames carry it on every reply;
        without mux the value only moves when delta verbs run).
        refresh=True forces one kGetDelta round trip per shard so
        non-mux clients observe bumps made by OTHER clients."""
        if refresh:
            epoch, _, _ = self.delta_since(self.query.epoch())
            return epoch
        return self.query.epoch()

    def apply_delta(self, node_ids=None, node_types=None,
                    node_weights=None, edge_src=None, edge_dst=None,
                    edge_types=None, edge_weights=None) -> int:
        """Broadcast a batched delta to the cluster: every shard applies
        the rows it hash-owns through the builder machinery and swaps
        in a new snapshot (in-flight queries finish on the old one),
        then this client's routing meta refreshes so weight-
        proportional sampling reflects the post-delta graph. Returns
        the new (max) epoch. Not wrapped in RetryPolicy: the C++
        channel already retries transport failures, and a delta is
        idempotent per shard — re-issuing after a partial failure
        re-applies the same last-write-wins rows."""
        return self.query.apply_delta(
            node_ids=node_ids, node_types=node_types,
            node_weights=node_weights, edge_src=edge_src,
            edge_dst=edge_dst, edge_types=edge_types,
            edge_weights=edge_weights)

    def delta_since(self, from_epoch: int):
        """(epoch, covered, dirty_ids): union of the shards' dirty sets
        for epochs after from_epoch; covered=False → some shard's
        bounded history no longer reaches it (treat all ids dirty)."""
        return self.query.delta_since(int(from_epoch))

    def type_id(self, name_or_id, edge: bool = False) -> int:
        """Cluster clients resolve numeric ids/strings only — type NAME
        metadata lives in the shards' local meta and is not served over
        the wire; resolve names against a local GraphEngine (or extend
        the meta RPC) if needed."""
        if isinstance(name_or_id, (int, np.integer)):
            return int(name_or_id)
        s = str(name_or_id)
        try:
            return int(s)
        except ValueError:
            raise KeyError(
                f"RemoteGraphEngine cannot resolve type NAME {s!r}; "
                "pass the integer type id (names resolve on embedded "
                "engines via GraphEngine.type_id)")

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        _obs.unregister_health(self._obs_name)
        if self.pipeline is not None:
            # drain the worker pool first: pooled calls re-enter _run
            # and must not race the handle teardown below
            self.pipeline.close()
            self.pipeline = None
        # abandoned timed-out attempts still hold exec handles into the
        # query proxy; give them a moment to unblock (their sockets die
        # when the far end/proxy shuts down) and LEAK the proxy rather
        # than free it under a live thread
        with self._health_mu:
            strays, self._strays = self._strays, []
        deadline = time.monotonic() + 5.0
        for th in strays:
            th.join(max(deadline - time.monotonic(), 0.0))
        if any(th.is_alive() for th in strays):
            # leak: a stray thread still uses the handle (under the
            # query lock so a concurrent stats() scrape can't race the
            # zeroing)
            with self.query._mu:
                self.query._h = 0
            return
        self.query.close()
        # proxies/pipelines retired by fleet-growth rebuilds: closed
        # last — no new calls could reach them since the swap, and the
        # stray drain above bounded any in-flight ones
        for p in self._retired_pipelines:
            p.close()
        self._retired_pipelines.clear()
        for q in self._retired_proxies:
            q.close()
        self._retired_proxies.clear()
