from euler_tpu.graph.api import (  # noqa: F401
    BINARY,
    DENSE,
    SPARSE,
    EngineError,
    GraphBuilder,
    GraphEngine,
    seed,
)
from euler_tpu.graph.chaos import ChaosGraphEngine, ChaosPlan  # noqa: F401
from euler_tpu.graph.pipeline import (  # noqa: F401
    CachedGraphEngine,
    PipelinedClient,
)
from euler_tpu.graph.remote import (  # noqa: F401
    RemoteGraphEngine,
    RetryDeadlineExceeded,
    RetryPolicy,
    configure_rpc,
    retryable_error,
    rpc_transport_stats,
)
