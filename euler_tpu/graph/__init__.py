from euler_tpu.graph.api import (  # noqa: F401
    BINARY,
    DENSE,
    SPARSE,
    EngineError,
    GraphBuilder,
    GraphEngine,
    seed,
)
from euler_tpu.graph.remote import RemoteGraphEngine  # noqa: F401
