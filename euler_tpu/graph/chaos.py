"""Deterministic fault injection for graph engines (chaos harness).

The reference's production value is that training survives a flaky
sharded graph service; none of that is testable without a way to MAKE
the service flaky on demand. ChaosGraphEngine wraps any engine-shaped
object (embedded GraphEngine, RemoteGraphEngine, DataSet.engine) and
injects a seeded, reproducible schedule of the faults a real cluster
shows:

  * transport errors  — EngineError with the same "failed after
    retries" shape a dead shard produces, so retry classification in
    RemoteGraphEngine / BaseEstimator treats them identically;
  * added latency     — fixed + jittered per-call sleeps (slow shard);
  * truncated results — every ndarray in the result loses the back
    half of its leading axis (a shard answering partially);
  * shard flaps       — periodic down-windows measured in calls, the
    kill/restart cycle as seen from the client.

Schedules are pure functions of (seed, call index): two engines built
from the same plan inject the same faults at the same calls, so a chaos
test is exactly reproducible. For faults below the API boundary (RST,
stalls, black-holes against the real framed-TCP stack) use
tools/chaos_proxy.py instead.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import threading
import time
from typing import Tuple

from euler_tpu import obs as _obs
from euler_tpu.core.lib import EngineError

_CHAOS_IDS = itertools.count()


@dataclasses.dataclass
class ChaosPlan:
    """Seeded fault schedule. Rates are per intercepted call; explicit
    schedules (fail_calls / fail_from / flap_*) are deterministic in the
    0-based call index and win over the probabilistic rates."""

    seed: int = 0
    error_rate: float = 0.0          # P(transport error) per call
    latency_ms: float = 0.0          # fixed added latency per call
    latency_jitter_ms: float = 0.0   # + U(0, jitter)
    truncate_rate: float = 0.0       # P(result arrays truncated)
    flap_period: int = 0             # calls per flap cycle (0 = off)
    flap_down: int = 0               # first N calls of each cycle fail
    fail_calls: Tuple[int, ...] = () # exact call indices that fail
    fail_from: int = -1              # all calls >= this index fail (<0 off)


class ChaosGraphEngine:
    """Engine wrapper injecting the plan's faults at the call boundary.

    Everything not listed in CHAOS_METHODS (properties, close, type_id,
    ...) passes straight through to the wrapped engine."""

    CHAOS_METHODS = frozenset({
        "sample_node", "sample_edge", "sample_node_with_types",
        "sample_neighbor", "sample_fanout", "sample_layerwise",
        "get_full_neighbor", "get_neighbor_edges", "random_walk",
        "get_dense_feature", "get_sparse_feature", "get_binary_feature",
        "get_edge_dense_feature", "get_edge_sparse_feature",
        "get_edge_binary_feature", "get_node_type", "get_top_k_neighbor",
        "all_node_ids",
    })

    def __init__(self, engine, plan: ChaosPlan):
        self._engine = engine
        self.plan = plan
        self._rng = random.Random(plan.seed)
        self._mu = threading.Lock()
        self._calls = 0
        self._counters = {"errors": 0, "delayed": 0, "truncated": 0}
        # mirror injected faults onto the obs registry so chaos tests
        # can assert fault injection and observability agree on counts
        # (chaos_injected_total{engine=...,kind=error|delay|truncate})
        self._obs_name = f"chaos{next(_CHAOS_IDS)}"
        injected = _obs.default_registry().counter(
            "chaos_injected_total",
            "faults injected by ChaosGraphEngine",
            ("engine", "kind"))
        self._obs_kind = {
            k: injected.labels(engine=self._obs_name, kind=k)
            for k in ("error", "delay", "truncate")}

    # -- schedule ----------------------------------------------------------
    def _decide(self, idx: int):
        """(fail, delay_s, truncate) for call `idx`. Consumes the seeded
        rng in a fixed per-call order so the schedule is a pure function
        of (seed, idx) regardless of which methods are called."""
        p = self.plan
        fail = (idx in p.fail_calls
                or (p.fail_from >= 0 and idx >= p.fail_from)
                or (p.flap_period > 0 and (idx % p.flap_period)
                    < p.flap_down))
        r_err = self._rng.random()
        r_trunc = self._rng.random()
        r_jit = self._rng.random()
        fail = fail or (p.error_rate > 0 and r_err < p.error_rate)
        trunc = p.truncate_rate > 0 and r_trunc < p.truncate_rate
        delay = 0.0
        if p.latency_ms > 0 or p.latency_jitter_ms > 0:
            delay = (p.latency_ms + r_jit * p.latency_jitter_ms) / 1000.0
        return fail, delay, trunc

    @staticmethod
    def _truncate(result):
        """Drop the back half of every ndarray's leading axis — the shape
        a partially-answering shard produces. Recurses through nested
        tuples/lists (sample_fanout returns a tuple of LISTS of per-hop
        arrays) so no result shape silently escapes truncation."""
        import numpy as np

        def cut(v):
            if isinstance(v, np.ndarray) and v.ndim >= 1 and v.shape[0] > 1:
                return v[: v.shape[0] // 2]
            if isinstance(v, tuple):
                return tuple(cut(x) for x in v)
            if isinstance(v, list):
                return [cut(x) for x in v]
            return v

        return cut(result)

    # -- interception ------------------------------------------------------
    def __getattr__(self, name):
        attr = getattr(self._engine, name)
        if name not in self.CHAOS_METHODS or not callable(attr):
            return attr

        def chaotic(*args, **kwargs):
            with self._mu:
                idx = self._calls
                self._calls += 1
                fail, delay, trunc = self._decide(idx)
            if delay > 0:
                with self._mu:
                    self._counters["delayed"] += 1
                self._obs_kind["delay"].inc()
                time.sleep(delay)
            if fail:
                with self._mu:
                    self._counters["errors"] += 1
                self._obs_kind["error"].inc()
                raise EngineError(
                    f"chaos: rpc to shard failed after retries "
                    f"(injected at call {idx}, op {name})")
            out = attr(*args, **kwargs)
            if trunc:
                with self._mu:
                    self._counters["truncated"] += 1
                self._obs_kind["truncate"].inc()
                out = self._truncate(out)
            return out

        return chaotic

    # -- streaming deltas (explicit delegation) ----------------------------
    # The epoch/delta verbs are defined EXPLICITLY rather than left to
    # __getattr__: chaos interception must never apply to them (a fault-
    # injected apply_delta would diverge the wrapper's view of the epoch
    # from the engine's), and an engine lacking them must raise its own
    # AttributeError naming the engine — wrapper drift is pinned by
    # tests/test_streaming.py's delegation test.
    def graph_epoch(self, *args, **kwargs) -> int:
        return self._engine.graph_epoch(*args, **kwargs)

    def apply_delta(self, **delta) -> int:
        return self._engine.apply_delta(**delta)

    def delta_since(self, from_epoch: int):
        return self._engine.delta_since(from_epoch)

    # -- elastic fleet (explicit delegation, same contract as above) -------
    # Ownership-map maintenance is control-plane traffic: chaos must
    # never fault-inject a map refresh (a lost install would diverge
    # the wrapper's routing from the engine's).
    def refresh_ownership(self, force: bool = False) -> int:
        return self._engine.refresh_ownership(force=force)

    def ownership_epoch(self) -> int:
        return self._engine.ownership_epoch()

    def shard_traffic(self):
        return self._engine.shard_traffic()


    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        """Injected-fault counters: calls, errors, delayed, truncated."""
        with self._mu:
            return {"calls": self._calls, **self._counters}
