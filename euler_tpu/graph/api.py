"""Numpy-facing wrapper over the native graph engine.

This is the embedded (in-process) graph engine interface — capability
parity with the reference's local mode (euler/client/query_proxy.cc:160-190
`initialize_embedded_graph`) and the per-op C++ API surface
(euler/core/api/api.h:44-95). All ops are batch, take/return numpy arrays
with fixed shapes (padded with `default_id`) so results can be fed straight
into jax.device_put without ragged handling.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from euler_tpu.core import lib as _libmod
from euler_tpu.core.lib import EngineError, c_f32p, c_i32p, c_i64p, c_u64p

__all__ = ["GraphEngine", "GraphBuilder", "EngineError"]

DENSE, SPARSE, BINARY = 0, 1, 2


def _u64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.uint64)


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctype)


def _opt_types(edge_types) -> tuple:
    """Normalize an edge-type filter to (ptr, n). None/empty → all types."""
    if edge_types is None:
        return None, 0
    et = _i32(edge_types).ravel()
    if et.size == 0:
        return None, 0
    return et, et.size


class _Result:
    """RAII wrapper for the variable-size EtResult handle."""

    def __init__(self, lib):
        self._lib = lib
        self.h = lib.etres_new()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._lib.etres_free(self.h)

    def offsets(self) -> np.ndarray:
        n = self._lib.etres_offsets_len(self.h)
        if n == 0:
            return np.zeros(0, dtype=np.uint64)
        return np.ctypeslib.as_array(self._lib.etres_offsets(self.h), (n,)).copy()

    def u64(self) -> np.ndarray:
        n = self._lib.etres_u64_len(self.h)
        if n == 0:
            return np.zeros(0, dtype=np.uint64)
        return np.ctypeslib.as_array(self._lib.etres_u64(self.h), (n,)).copy()

    def f32(self) -> np.ndarray:
        n = self._lib.etres_f32_len(self.h)
        if n == 0:
            return np.zeros(0, dtype=np.float32)
        return np.ctypeslib.as_array(self._lib.etres_f32(self.h), (n,)).copy()

    def i32(self) -> np.ndarray:
        n = self._lib.etres_i32_len(self.h)
        if n == 0:
            return np.zeros(0, dtype=np.int32)
        return np.ctypeslib.as_array(self._lib.etres_i32(self.h), (n,)).copy()

    def bytes_(self) -> bytes:
        n = self._lib.etres_bytes_len(self.h)
        if n == 0:
            return b""
        return ctypes.string_at(self._lib.etres_bytes(self.h), n)


class GraphBuilder:
    """Accumulates nodes/edges/features, then .finalize() → GraphEngine."""

    def __init__(self):
        self._lib = _libmod.load()
        self.h = self._lib.etg_builder_new()
        self._feature_names: dict = {"node": {}, "edge": {}}

    def set_num_types(self, num_node_types: int, num_edge_types: int):
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_num_types(self.h, num_node_types, num_edge_types),
        )
        return self

    def set_type_name(self, type_id: int, name: str, edge: bool = False):
        """Name a node/edge type so training code can refer to it by
        name (reference type_ops get_node_type_id / get_edge_type_id;
        the json data-prep declares type names the same way). Unnamed
        types keep their numeric-string default."""
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_type_name(
                self.h, 1 if edge else 0, type_id, name.encode()),
        )
        return self

    def set_feature(self, fid: int, kind: int, dim: int, name: str = "", edge: bool = False):
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_feature(
                self.h, 1 if edge else 0, fid, kind, dim, name.encode()
            ),
        )
        self._feature_names["edge" if edge else "node"][name or str(fid)] = fid
        return self

    def add_nodes(self, ids, types=None, weights=None):
        ids = _u64(ids).ravel()
        n = ids.size
        tp = _ptr(_i32(types).ravel(), c_i32p) if types is not None else None
        wp = _ptr(_f32(weights).ravel(), c_f32p) if weights is not None else None
        _libmod.check(
            self._lib,
            self._lib.etg_builder_add_nodes(self.h, n, _ptr(ids, c_u64p), tp, wp),
        )
        return self

    def add_edges(self, src, dst, types=None, weights=None):
        src = _u64(src).ravel()
        dst = _u64(dst).ravel()
        n = src.size
        tp = _ptr(_i32(types).ravel(), c_i32p) if types is not None else None
        wp = _ptr(_f32(weights).ravel(), c_f32p) if weights is not None else None
        _libmod.check(
            self._lib,
            self._lib.etg_builder_add_edges(
                self.h, n, _ptr(src, c_u64p), _ptr(dst, c_u64p), tp, wp
            ),
        )
        return self

    def set_node_dense(self, ids, fid: int, values):
        ids = _u64(ids).ravel()
        values = _f32(values).reshape(ids.size, -1)
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_node_dense(
                self.h, _ptr(ids, c_u64p), ids.size, fid, values.shape[1],
                _ptr(values, c_f32p),
            ),
        )
        return self

    def set_node_sparse(self, ids, fid: int, offsets, values):
        ids = _u64(ids).ravel()
        offsets = _u64(offsets).ravel()
        values = _u64(values).ravel()
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_node_sparse(
                self.h, _ptr(ids, c_u64p), ids.size, fid,
                _ptr(offsets, c_u64p), _ptr(values, c_u64p),
            ),
        )
        return self

    def set_node_binary(self, node_id: int, fid: int, data: bytes):
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_node_binary(self.h, node_id, fid, data, len(data)),
        )
        return self

    def set_edge_binary(self, src: int, dst: int, etype: int, fid: int,
                        data: bytes):
        """Attach raw bytes to one edge (reference GetEdgeBinaryFeature
        storage side, tf_euler/kernels/get_edge_binary_feature_op.cc —
        there populated from the JSON 'binary_feature' edge block)."""
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_edge_binary(
                self.h, src, dst, etype, fid, data, len(data)),
        )
        return self

    def set_edge_dense(self, src, dst, types, fid: int, values):
        src = _u64(src).ravel()
        dst = _u64(dst).ravel()
        types = _i32(types if types is not None else np.zeros(src.size)).ravel()
        values = _f32(values).reshape(src.size, -1)
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_edge_dense(
                self.h, _ptr(src, c_u64p), _ptr(dst, c_u64p), _ptr(types, c_i32p),
                src.size, fid, values.shape[1], _ptr(values, c_f32p),
            ),
        )
        return self

    def set_edge_sparse(self, src: int, dst: int, etype: int, fid: int, values):
        values = _u64(values).ravel()
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_edge_sparse(
                self.h, src, dst, etype, fid, _ptr(values, c_u64p), values.size
            ),
        )
        return self

    def set_graph_labels(self, ids, labels) -> None:
        """Assign nodes to whole-graph labels (graph classification;
        reference graph_label batching). Label 0 = unlabeled."""
        ids = _u64(ids).ravel()
        labels = _u64(labels).ravel()
        _libmod.check(
            self._lib,
            self._lib.etg_builder_set_graph_labels(
                self.h, _ptr(ids, c_u64p), _ptr(labels, c_u64p), ids.size))

    def finalize(self, build_in_adjacency: bool = True) -> "GraphEngine":
        gh = self._lib.etg_builder_finalize(self.h, 1 if build_in_adjacency else 0)
        if gh < 0:
            raise EngineError(self._lib.etg_last_error().decode())
        self.h = None
        return GraphEngine(gh, feature_names=self._feature_names)


def _delta_arrays(node_ids, node_types, node_weights, edge_src, edge_dst,
                  edge_types, edge_weights):
    """Normalize a batched delta into contiguous arrays + validate the
    parallel lengths — one definition shared by the embedded and remote
    engines so both reject the same malformed deltas."""
    nid = _u64(node_ids if node_ids is not None else []).ravel()
    n = nid.size
    nt = _i32(node_types).ravel() if node_types is not None \
        else np.zeros(n, np.int32)
    nw = _f32(node_weights).ravel() if node_weights is not None \
        else np.ones(n, np.float32)
    es = _u64(edge_src if edge_src is not None else []).ravel()
    ed = _u64(edge_dst if edge_dst is not None else []).ravel()
    e = es.size
    et = _i32(edge_types).ravel() if edge_types is not None \
        else np.zeros(e, np.int32)
    ew = _f32(edge_weights).ravel() if edge_weights is not None \
        else np.ones(e, np.float32)
    if nt.size != n or nw.size != n:
        raise ValueError(
            f"delta node columns disagree: {n} ids, {nt.size} types, "
            f"{nw.size} weights")
    if ed.size != e or et.size != e or ew.size != e:
        raise ValueError(
            f"delta edge columns disagree: {e} src, {ed.size} dst, "
            f"{et.size} types, {ew.size} weights")
    if n == 0 and e == 0:
        raise ValueError("empty delta: nothing to apply")
    return nid, nt, nw, es, ed, et, ew


def delta_dirty_ids(node_ids=None, edge_src=None, edge_dst=None,
                    **_ignored) -> np.ndarray:
    """Sorted unique node ids a delta touches (nodes ∪ edge endpoints) —
    what the engine records as the epoch's dirty set. Callers that just
    issued the delta can invalidate locally from this instead of asking
    the engine (CachedGraphEngine.apply_delta does)."""
    parts = [np.asarray(a, dtype=np.uint64).ravel()
             for a in (node_ids, edge_src, edge_dst) if a is not None]
    if not parts:
        return np.zeros(0, dtype=np.uint64)
    return np.unique(np.concatenate(parts))


class GraphEngine:
    """In-process graph engine. Each finalized graph SNAPSHOT is
    immutable; apply_delta() builds and atomically swaps in a new
    snapshot behind this handle (graph_epoch() bumps, queries bound to
    the handle see it, in-flight readers finish on the old one)."""

    def __init__(self, handle: int, feature_names: Optional[dict] = None):
        self._lib = _libmod.load()
        self.h = handle
        self._feature_names = feature_names or {"node": {}, "edge": {}}
        if not self._feature_names["node"]:
            self._load_feature_names()

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def load(cls, directory: str, shard_idx: int = 0, shard_num: int = 1,
             data_type: int = 0, build_in_adjacency: bool = True) -> "GraphEngine":
        lib = _libmod.load()
        h = lib.etg_load(directory.encode(), shard_idx, shard_num, data_type,
                         1 if build_in_adjacency else 0)
        if h < 0:
            raise EngineError(lib.etg_last_error().decode())
        return cls(h)

    def dump(self, directory: str, num_partitions: int = 1,
             by_graph: bool = False) -> None:
        """by_graph=True partitions by graph label (whole graphs stay on
        one shard — the graph_partition serving mode)."""
        if "://" not in directory:  # remote urls (hdfs://) manage dirs
            import os

            os.makedirs(directory, exist_ok=True)
        _libmod.check(self._lib, self._lib.etg_dump(self.h, directory.encode(),
                                                    num_partitions,
                                                    1 if by_graph else 0))

    def close(self) -> None:
        if self.h is not None:
            self._lib.etg_free(self.h)
            self.h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _load_feature_names(self):
        for edge, key in ((0, "node"), (1, "edge")):
            n = (self._lib.etg_num_edge_features(self.h) if edge
                 else self._lib.etg_num_node_features(self.h))
            for fid in range(max(n, 0)):
                kind = ctypes.c_int32()
                dim = ctypes.c_int64()
                buf = ctypes.create_string_buffer(256)
                rc = self._lib.etg_feature_info(
                    self.h, edge, fid, ctypes.byref(kind), ctypes.byref(dim), buf, 256
                )
                if rc == 0:
                    name = buf.value.decode() or str(fid)
                    self._feature_names[key][name] = fid

    # -- introspection -----------------------------------------------------
    @property
    def node_count(self) -> int:
        return self._lib.etg_node_count(self.h)

    @property
    def edge_count(self) -> int:
        return self._lib.etg_edge_count(self.h)

    @property
    def num_node_types(self) -> int:
        return self._lib.etg_num_node_types(self.h)

    @property
    def num_edge_types(self) -> int:
        return self._lib.etg_num_edge_types(self.h)

    def feature_id(self, name, edge: bool = False) -> int:
        if isinstance(name, (int, np.integer)):
            return int(name)
        return self._feature_names["edge" if edge else "node"][name]

    def type_id(self, name_or_id, edge: bool = False) -> int:
        """Type name (or numeric string / int) → type id (reference
        type_ops). Raises KeyError for unknown names."""
        if isinstance(name_or_id, (int, np.integer)):
            return int(name_or_id)
        t = self._lib.etg_type_id(self.h, 1 if edge else 0,
                                  str(name_or_id).encode())
        if t < 0:
            kind = "edge" if edge else "node"
            raise KeyError(f"unknown {kind} type name: {name_or_id!r}")
        return int(t)

    def type_name(self, type_id: int, edge: bool = False) -> str:
        cap = 256
        while True:
            buf = ctypes.create_string_buffer(cap)
            _libmod.check(
                self._lib,
                self._lib.etg_type_name(self.h, 1 if edge else 0, type_id,
                                        buf, cap),
            )
            # snprintf truncates silently; a full buffer means retry
            # bigger so long names round-trip through type_id()
            if len(buf.value) < cap - 1:
                return buf.value.decode()
            cap *= 2

    def feature_dim(self, fid_or_name, edge: bool = False) -> int:
        fid = self.feature_id(fid_or_name, edge)
        kind = ctypes.c_int32()
        dim = ctypes.c_int64()
        _libmod.check(
            self._lib,
            self._lib.etg_feature_info(self.h, 1 if edge else 0, fid,
                                       ctypes.byref(kind), ctypes.byref(dim), None, 0),
        )
        return int(dim.value)

    def node_rows(self, ids, missing: int = 0) -> np.ndarray:
        """Batch u64 node id → int32 engine row (all_node_ids order);
        unknown ids map to `missing`. The fast path for device-resident
        feature-table training input (DeviceFeatureStore passes its zero
        pad row)."""
        ids = _u64(ids).ravel()
        out = np.zeros(ids.size, dtype=np.int32)
        _libmod.check(
            self._lib,
            self._lib.etg_node_rows(self.h, _ptr(ids, c_u64p), ids.size,
                                    missing, _ptr(out, c_i32p)))
        return out

    def all_node_ids(self) -> np.ndarray:
        out = np.zeros(self.node_count, dtype=np.uint64)
        _libmod.check(self._lib, self._lib.etg_all_node_ids(self.h, _ptr(out, c_u64p)))
        return out

    # -- streaming deltas --------------------------------------------------
    def graph_epoch(self) -> int:
        """Monotonic version stamp of the current snapshot (0 =
        as-finalized; each apply_delta bumps it)."""
        e = self._lib.etg_graph_epoch(self.h)
        if e < 0:
            raise EngineError(self._lib.etg_last_error().decode())
        return int(e)

    def apply_delta(self, node_ids=None, node_types=None,
                    node_weights=None, edge_src=None, edge_dst=None,
                    edge_types=None, edge_weights=None) -> int:
        """Apply a batched delta (add/update nodes and edges) and swap
        in the new immutable snapshot. Node rows are append-only (an
        existing node keeps its engine row; its type/weight update in
        place), an edge that already exists updates its weight, and new
        edges/nodes append — so derived row-indexed state (device
        feature/neighbor tables) stays valid for untouched rows and can
        be patched per dirty row. Returns the new epoch."""
        nid, nt, nw, es, ed, et, ew = _delta_arrays(
            node_ids, node_types, node_weights, edge_src, edge_dst,
            edge_types, edge_weights)
        out_epoch = ctypes.c_int64()
        _libmod.check(
            self._lib,
            self._lib.etg_apply_delta(
                self.h, nid.size, _ptr(nid, c_u64p), _ptr(nt, c_i32p),
                _ptr(nw, c_f32p), es.size, _ptr(es, c_u64p),
                _ptr(ed, c_u64p), _ptr(et, c_i32p), _ptr(ew, c_f32p),
                ctypes.byref(out_epoch)))
        return int(out_epoch.value)

    def delta_since(self, from_epoch: int):
        """(epoch, covered, dirty_ids): the sorted unique node ids
        touched by every delta after `from_epoch`. covered=False means
        the bounded per-epoch history no longer reaches from_epoch —
        the caller must treat EVERYTHING as dirty (full flush)."""
        out_epoch = ctypes.c_int64()
        covered = ctypes.c_int32()
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_delta_since(self.h, int(from_epoch), res.h,
                                          ctypes.byref(out_epoch),
                                          ctypes.byref(covered)))
            ids = res.u64()
        return int(out_epoch.value), bool(covered.value), ids

    def all_node_weights(self) -> np.ndarray:
        """Per-node weights in engine-row order (all_node_ids order) —
        backs device-resident weighted global sampling."""
        out = np.zeros(self.node_count, dtype=np.float32)
        _libmod.check(self._lib, self._lib.etg_all_node_weights(
            self.h, _ptr(out, c_f32p)))
        return out

    def node_weight_sums(self) -> np.ndarray:
        out = np.zeros(self.num_node_types, dtype=np.float32)
        _libmod.check(self._lib, self._lib.etg_node_weight_sums(self.h, _ptr(out, c_f32p)))
        return out

    def edge_weight_sums(self) -> np.ndarray:
        out = np.zeros(self.num_edge_types, dtype=np.float32)
        _libmod.check(self._lib, self._lib.etg_edge_weight_sums(self.h, _ptr(out, c_f32p)))
        return out

    # -- sampling ----------------------------------------------------------
    def sample_node(self, count: int, node_type: int = -1) -> np.ndarray:
        out = np.zeros(count, dtype=np.uint64)
        _libmod.check(
            self._lib, self._lib.etg_sample_node(self.h, node_type, count, _ptr(out, c_u64p))
        )
        return out

    def sample_node_with_types(self, types) -> np.ndarray:
        types = _i32(types).ravel()
        out = np.zeros(types.size, dtype=np.uint64)
        _libmod.check(
            self._lib,
            self._lib.etg_sample_node_with_types(
                self.h, _ptr(types, c_i32p), types.size, _ptr(out, c_u64p)
            ),
        )
        return out

    def sample_edge(self, count: int, edge_type: int = -1):
        src = np.zeros(count, dtype=np.uint64)
        dst = np.zeros(count, dtype=np.uint64)
        tp = np.zeros(count, dtype=np.int32)
        _libmod.check(
            self._lib,
            self._lib.etg_sample_edge(
                self.h, edge_type, count, _ptr(src, c_u64p), _ptr(dst, c_u64p),
                _ptr(tp, c_i32p),
            ),
        )
        return src, dst, tp

    def get_node_type(self, ids) -> np.ndarray:
        ids = _u64(ids).ravel()
        out = np.zeros(ids.size, dtype=np.int32)
        _libmod.check(
            self._lib,
            self._lib.etg_get_node_type(self.h, _ptr(ids, c_u64p), ids.size, _ptr(out, c_i32p)),
        )
        return out

    def sample_neighbor(self, ids, count: int, edge_types=None, default_id: int = 0,
                        in_edges: bool = False):
        ids = _u64(ids).ravel()
        n = ids.size
        et, n_et = _opt_types(edge_types)
        etp = _ptr(et, c_i32p) if et is not None else None
        out_ids = np.zeros((n, count), dtype=np.uint64)
        out_w = np.zeros((n, count), dtype=np.float32)
        out_t = np.zeros((n, count), dtype=np.int32)
        fn = self._lib.etg_sample_in_neighbor if in_edges else self._lib.etg_sample_neighbor
        _libmod.check(
            self._lib,
            fn(self.h, _ptr(ids, c_u64p), n, etp, n_et, count, default_id,
               _ptr(out_ids, c_u64p), _ptr(out_w, c_f32p), _ptr(out_t, c_i32p)),
        )
        return out_ids, out_w, out_t

    def get_top_k_neighbor(self, ids, k: int, edge_types=None, default_id: int = 0):
        ids = _u64(ids).ravel()
        n = ids.size
        et, n_et = _opt_types(edge_types)
        etp = _ptr(et, c_i32p) if et is not None else None
        out_ids = np.zeros((n, k), dtype=np.uint64)
        out_w = np.zeros((n, k), dtype=np.float32)
        out_t = np.zeros((n, k), dtype=np.int32)
        _libmod.check(
            self._lib,
            self._lib.etg_get_top_k_neighbor(
                self.h, _ptr(ids, c_u64p), n, etp, n_et, k, default_id,
                _ptr(out_ids, c_u64p), _ptr(out_w, c_f32p), _ptr(out_t, c_i32p)),
        )
        return out_ids, out_w, out_t

    def get_full_neighbor(self, ids, edge_types=None, sorted_by_id: bool = False,
                          in_edges: bool = False):
        """Returns (offsets[n+1], nbr_ids, weights, types) CSR arrays."""
        ids = _u64(ids).ravel()
        et, n_et = _opt_types(edge_types)
        etp = _ptr(et, c_i32p) if et is not None else None
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_get_full_neighbor(
                    self.h, _ptr(ids, c_u64p), ids.size, etp, n_et,
                    1 if sorted_by_id else 0, 1 if in_edges else 0, res.h),
            )
            return res.offsets(), res.u64(), res.f32(), res.i32()

    def get_neighbor_edges(self, ids, edge_types=None):
        """The *edges* to each node's out-neighbors (reference
        get_neighbor_edge_op.cc / GQL outE at gremlin.l:21).

        Returns (offsets[n+1], src, dst, types, weights): CSR arrays where
        row i's slice holds the (src=ids[i], dst, type) edge triples —
        directly chainable into get_edge_dense_feature and friends.
        """
        ids = _u64(ids).ravel()
        off, nb, w, t = self.get_full_neighbor(ids, edge_types=edge_types)
        src = np.repeat(ids, np.diff(off.astype(np.int64)))
        return off, src, nb, t, w

    @property
    def graph_label_count(self) -> int:
        return int(self._lib.etg_graph_label_count(self.h))

    def sample_graph_label(self, count: int) -> np.ndarray:
        """Uniform sample of whole-graph labels (reference
        SampleGraphLabel)."""
        out = np.zeros(count, dtype=np.uint64)
        _libmod.check(self._lib, self._lib.etg_sample_graph_label(
            self.h, count, _ptr(out, c_u64p)))
        return out

    def get_graph_by_label(self, labels):
        """(offsets[n+1], node_ids) CSR: the nodes of each labeled graph
        (reference GetGraphByLabel)."""
        labels = _u64(labels).ravel()
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_get_graph_by_label(
                    self.h, _ptr(labels, c_u64p), labels.size, res.h))
            return res.offsets(), res.u64()

    def sample_fanout(self, roots, counts: Sequence[int], edge_types=None,
                      default_id: int = 0):
        """Multi-hop expansion in one native call.

        Returns (ids_per_hop, weights_per_hop, types_per_hop); hop i arrays
        have shape [n_roots * prod(counts[:i+1])].
        """
        roots = _u64(roots).ravel()
        n = roots.size
        counts_arr = _i32(counts).ravel()
        n_hops = counts_arr.size
        # per-hop edge-type lists: edge_types is None | flat list (shared) |
        # list of per-hop lists
        if edge_types is None:
            et_flat, et_offsets = None, None
        else:
            if len(edge_types) > 0 and isinstance(
                    edge_types[0], (list, tuple, np.ndarray)):
                per_hop = [list(h) for h in edge_types]
                if len(per_hop) != n_hops:
                    raise ValueError(
                        f"per-hop edge_types has {len(per_hop)} entries, "
                        f"expected {n_hops} (one per hop)"
                    )
            else:
                per_hop = [list(edge_types)] * n_hops
            offs = [0]
            flat = []
            for hop_list in per_hop:
                flat.extend(hop_list)
                offs.append(len(flat))
            et_flat = _i32(flat) if flat else None
            et_offsets = np.asarray(offs, dtype=np.int64)
        sizes = []
        m = n
        for c in counts_arr:
            m *= int(c)
            sizes.append(m)
        ids_bufs = [np.zeros(s, dtype=np.uint64) for s in sizes]
        w_bufs = [np.zeros(s, dtype=np.float32) for s in sizes]
        t_bufs = [np.zeros(s, dtype=np.int32) for s in sizes]
        ids_ptrs = (c_u64p * n_hops)(*[_ptr(b, c_u64p) for b in ids_bufs])
        w_ptrs = (c_f32p * n_hops)(*[_ptr(b, c_f32p) for b in w_bufs])
        t_ptrs = (c_i32p * n_hops)(*[_ptr(b, c_i32p) for b in t_bufs])
        _libmod.check(
            self._lib,
            self._lib.etg_sample_fanout(
                self.h, _ptr(roots, c_u64p), n, _ptr(counts_arr, c_i32p), n_hops,
                _ptr(et_flat, c_i32p) if et_flat is not None else None,
                _ptr(et_offsets, c_i64p) if et_offsets is not None else None,
                default_id, ids_ptrs, w_ptrs, t_ptrs),
        )
        return ids_bufs, w_bufs, t_bufs

    def random_walk(self, roots, walk_len: int, p: float = 1.0, q: float = 1.0,
                    edge_types=None, default_id: int = 0) -> np.ndarray:
        roots = _u64(roots).ravel()
        et, n_et = _opt_types(edge_types)
        etp = _ptr(et, c_i32p) if et is not None else None
        out = np.zeros((roots.size, walk_len + 1), dtype=np.uint64)
        _libmod.check(
            self._lib,
            self._lib.etg_random_walk(
                self.h, _ptr(roots, c_u64p), roots.size, walk_len, p, q,
                default_id, etp, n_et, _ptr(out, c_u64p)),
        )
        return out

    def sample_layerwise(self, roots, layer_sizes: Sequence[int], edge_types=None,
                         default_id: int = 0, weight_func: str = ""):
        """weight_func '' (identity) or 'sqrt' — the reference's
        optional transform of the accumulated candidate weight before
        the draw (local_sample_layer_op.cc:94)."""
        roots = _u64(roots).ravel()
        sizes = _i32(layer_sizes).ravel()
        n_layers = sizes.size
        et, n_et = _opt_types(edge_types)
        etp = _ptr(et, c_i32p) if et is not None else None
        wf = {"": 0, "sqrt": 1}.get(weight_func)
        if wf is None:
            raise ValueError(
                f"weight_func must be '' or 'sqrt', got {weight_func!r}")
        bufs = [np.zeros(int(s), dtype=np.uint64) for s in sizes]
        ptrs = (c_u64p * n_layers)(*[_ptr(b, c_u64p) for b in bufs])
        _libmod.check(
            self._lib,
            self._lib.etg_sample_layerwise(
                self.h, _ptr(roots, c_u64p), roots.size, _ptr(sizes, c_i32p),
                n_layers, etp, n_et, default_id, wf, ptrs),
        )
        return bufs

    # -- features ----------------------------------------------------------
    def get_dense_feature(self, ids, fids, dims=None) -> list:
        """Returns [n, dim] float32 per fid (list), zero-filled for misses."""
        ids = _u64(ids).ravel()
        single = not isinstance(fids, (list, tuple, np.ndarray))
        fid_list = [fids] if single else list(fids)
        fid_list = [self.feature_id(f) for f in fid_list]
        if dims is None:
            dim_list = [self.feature_dim(f) for f in fid_list]
        else:
            dim_list = [dims] if single else list(dims)
        outs = []
        for fid, dim in zip(fid_list, dim_list):
            out = np.zeros((ids.size, dim), dtype=np.float32)
            _libmod.check(
                self._lib,
                self._lib.etg_get_dense_feature(
                    self.h, _ptr(ids, c_u64p), ids.size, fid, dim, _ptr(out, c_f32p)),
            )
            outs.append(out)
        return outs[0] if single else outs

    def get_sparse_feature(self, ids, fid) -> tuple:
        """Returns (offsets[n+1], values) CSR of uint64."""
        ids = _u64(ids).ravel()
        fid = self.feature_id(fid)
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_get_sparse_feature(self.h, _ptr(ids, c_u64p), ids.size, fid, res.h),
            )
            return res.offsets(), res.u64()

    def get_binary_feature(self, ids, fid) -> tuple:
        ids = _u64(ids).ravel()
        fid = self.feature_id(fid)
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_get_binary_feature(self.h, _ptr(ids, c_u64p), ids.size, fid, res.h),
            )
            return res.offsets(), res.bytes_()

    def get_edge_dense_feature(self, src, dst, types, fids, dims=None):
        src = _u64(src).ravel()
        dst = _u64(dst).ravel()
        types = _i32(types).ravel()
        single = not isinstance(fids, (list, tuple, np.ndarray))
        fid_list = [fids] if single else list(fids)
        fid_list = [self.feature_id(f, edge=True) for f in fid_list]
        if dims is None:
            dim_list = [self.feature_dim(f, edge=True) for f in fid_list]
        else:
            dim_list = [dims] if single else list(dims)
        outs = []
        for fid, dim in zip(fid_list, dim_list):
            out = np.zeros((src.size, dim), dtype=np.float32)
            _libmod.check(
                self._lib,
                self._lib.etg_get_edge_dense_feature(
                    self.h, _ptr(src, c_u64p), _ptr(dst, c_u64p), _ptr(types, c_i32p),
                    src.size, fid, dim, _ptr(out, c_f32p)),
            )
            outs.append(out)
        return outs[0] if single else outs

    def get_edge_sparse_feature(self, src, dst, types, fid) -> tuple:
        src = _u64(src).ravel()
        dst = _u64(dst).ravel()
        types = _i32(types).ravel()
        fid = self.feature_id(fid, edge=True)
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_get_edge_sparse_feature(
                    self.h, _ptr(src, c_u64p), _ptr(dst, c_u64p), _ptr(types, c_i32p),
                    src.size, fid, res.h),
            )
            return res.offsets(), res.u64()

    def get_edge_binary_feature(self, src, dst, types, fid) -> tuple:
        """Returns (offsets[n+1], bytes): per-edge raw byte strings, CSR
        (reference GetEdgeBinaryFeature, euler/core/api/api.h:44-95)."""
        src = _u64(src).ravel()
        dst = _u64(dst).ravel()
        types = _i32(types).ravel()
        fid = self.feature_id(fid, edge=True)
        with _Result(self._lib) as res:
            _libmod.check(
                self._lib,
                self._lib.etg_get_edge_binary_feature(
                    self.h, _ptr(src, c_u64p), _ptr(dst, c_u64p), _ptr(types, c_i32p),
                    src.size, fid, res.h),
            )
            return res.offsets(), res.bytes_()


def seed(value: int) -> None:
    """Seed the engine's RNG (current thread) for reproducible sampling."""
    _libmod.load().etg_seed(value)
