"""Elastic graph fleet: epoch-versioned ownership maps, live shard
splits, and hot-partition rebalancing.

The pre-elastic fleet fixes its shard count at start time and routes by
the implicit hash convention ``(id % P) % shard_num``; with the measured
hub_frac ≈ 0.996 degree skew that load is *predictably* unbalanced. This
module makes the topology a published, versioned datum instead:

  * **OwnershipMap** — partition → owner shard(s), ``map_epoch``-
    versioned. The Python class mirrors the native ``OwnershipMap``
    (graph.h) byte-for-byte through the shared spec string
    (``e<E>-P<pn>-0.1.2.2+3``), published in the discovery registry as
    an ``omap_<service>__<spec>`` entry — the same names-carry-the-data
    convention PR 8's serving entries use, invisible to the C shard
    scanner.
  * **Live split** — a new shard bootstraps from a peer's compacted
    snapshot + WAL (``clone_wal_dir``) and closes the tail gap through
    the PR 10 anti-entropy path (``kGetDeltaLog`` catch-up) before
    registering; the map then flips by epoch bump while reads keep
    serving. Flip ORDER is load-bearing (``flip_fleet``): registry
    first, surviving shards second — a stale client refused by a
    flipped shard finds the fresh map already published, so its retry
    lands correctly routed; a fresh client reaching a not-yet-flipped
    shard is safe because flips only shrink a surviving shard's owned
    set (the one-sided staleness check in rpc.cc documents this).
  * **Hot-partition rebalancing** — ``hottest_shard`` reads the
    per-shard request counters off the client (mirrored on the obs
    registry), ``add_replica`` lists an additional owner for the hot
    partition (the new owner must hold the rows: a split sibling that
    retained them, or a shard bootstrapped over them), and clients
    spread reads over the owner list (p2c in ID_SPLIT) with PR 11's
    hedging raceable across the replicas (``configure_rpc(
    hedge_replicas=True)``).

Nothing here starts processes: the test/bench owns its process
topology and composes these building blocks (see
``tools/bench_host.py --mode elastic`` and ``tests/test_elastic.py``).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
from typing import Dict, List, Optional, Sequence

__all__ = [
    "OwnershipMap", "publish_map", "fetch_map", "remove_map_entries",
    "flip_fleet", "clone_wal_dir", "hottest_shard", "map_entry_name",
]

_OMAP_PREFIX = "omap_"


@dataclasses.dataclass
class OwnershipMap:
    """Python mirror of the native OwnershipMap (euler_tpu/core/cc/
    graph.h): partition p is owned by ``owners[p]`` (primary first;
    extra owners are replicas holding the same rows). ``map_epoch`` 0
    is invalid here — the native side treats 0 as "no map"."""

    map_epoch: int
    partition_num: int
    owners: List[List[int]]

    @property
    def shard_num(self) -> int:
        """Fleet width: 1 + the highest shard index listed."""
        return 1 + max(max(os_) for os_ in self.owners)

    @classmethod
    def default(cls, partition_num: int, shard_num: int,
                epoch: int = 1) -> "OwnershipMap":
        """The hash convention as an explicit map: p → {p % shard_num}
        (partition_num raised to shard_num when smaller, matching
        ShardOf's placement modulus)."""
        p = max(int(partition_num), int(shard_num), 1)
        return cls(map_epoch=int(epoch), partition_num=p,
                   owners=[[q % int(shard_num)] for q in range(p)])

    def encode(self) -> str:
        body = ".".join("+".join(str(s) for s in os_)
                        for os_ in self.owners)
        return f"e{self.map_epoch}-P{self.partition_num}-{body}"

    @classmethod
    def decode(cls, spec: str) -> "OwnershipMap":
        try:
            head, pn, body = spec.split("-", 2)
            if not head.startswith("e") or not pn.startswith("P"):
                raise ValueError(spec)
            owners = [[int(s) for s in part.split("+")]
                      for part in body.split(".")]
            m = cls(map_epoch=int(head[1:]), partition_num=int(pn[1:]),
                    owners=owners)
        except (ValueError, IndexError) as e:
            raise ValueError(f"bad ownership spec {spec!r}") from e
        if m.map_epoch <= 0 or len(owners) != m.partition_num or not all(
                os_ for os_ in owners):
            raise ValueError(f"bad ownership spec {spec!r}")
        return m

    # -- topology algebra (every derived map bumps the epoch) --------------
    def split(self, new_shard_num: int) -> "OwnershipMap":
        """Re-spread single-owner partitions over a GROWN fleet by the
        hash convention at the new width: p → {p % new_shard_num}.
        Replicated partitions keep their extra owners only if those
        owners still hash-own them (a split is a clean re-spread; add
        replicas back afterwards via add_replica)."""
        n = int(new_shard_num)
        if n < self.shard_num:
            raise ValueError(
                f"split cannot shrink the fleet ({self.shard_num} -> {n})")
        return OwnershipMap(
            map_epoch=self.map_epoch + 1,
            partition_num=self.partition_num,
            owners=[[p % n] for p in range(self.partition_num)])

    def add_replica(self, partition: int, owner: int) -> "OwnershipMap":
        """List `owner` as an ADDITIONAL owner of `partition` (the
        rebalancing move). The caller is responsible for `owner`
        actually holding the partition's rows (split sibling that
        retained them, or a shard bootstrapped over them) — flip only
        after its catch-up reached the fleet epoch."""
        owners = [list(os_) for os_ in self.owners]
        if owner not in owners[partition]:
            owners[partition].append(owner)
        return OwnershipMap(map_epoch=self.map_epoch + 1,
                            partition_num=self.partition_num,
                            owners=owners)

    def owner_of(self, node_id: int) -> List[int]:
        return self.owners[int(node_id) % self.partition_num]


def map_entry_name(m: OwnershipMap, service: str = "graph") -> str:
    if "__" in service:
        raise ValueError(f"service name must not contain '__': {service!r}")
    return f"{_OMAP_PREFIX}{service}__{m.encode()}"


def publish_map(registry: str, m: OwnershipMap,
                service: str = "graph") -> str:
    """Publish `m` in the discovery registry (entry-name-carries-data,
    the PR 8 serving convention) and drop superseded omap entries.
    Returns the entry name. Publish BEFORE flipping any server: a
    stale client's refusal must find the fresh map here."""
    from euler_tpu.serving import wire

    name = map_entry_name(m, service)
    wire.registry_put(registry, name)
    prefix = f"{_OMAP_PREFIX}{service}__"
    for other in list(wire.registry_list(registry)):
        if other.startswith(prefix) and other != name:
            try:
                old = OwnershipMap.decode(other[len(prefix):])
            except ValueError:
                continue
            if old.map_epoch < m.map_epoch:
                wire.registry_remove(registry, other)
    return name


def fetch_map(registry: str,
              service: str = "graph") -> Optional[OwnershipMap]:
    """Highest-epoch published map, or None when the fleet has none
    (pre-elastic deployments: clients keep the hash convention)."""
    from euler_tpu.serving import wire

    prefix = f"{_OMAP_PREFIX}{service}__"
    best: Optional[OwnershipMap] = None
    for name in wire.registry_list(registry):
        if not name.startswith(prefix):
            continue
        try:
            m = OwnershipMap.decode(name[len(prefix):])
        except ValueError:
            continue
        if best is None or m.map_epoch > best.map_epoch:
            best = m
    return best


def remove_map_entries(registry: str, service: str = "graph") -> None:
    """Drop every published map entry (test teardown)."""
    from euler_tpu.serving import wire

    prefix = f"{_OMAP_PREFIX}{service}__"
    for name in list(wire.registry_list(registry)):
        if name.startswith(prefix):
            wire.registry_remove(registry, name)


def flip_fleet(registry: str, m: OwnershipMap, push_fns: Sequence,
               grow_push_fns: Sequence = (),
               service: str = "graph") -> List[int]:
    """The atomic-by-epoch topology flip, in the load-bearing order:

      1. flip every shard whose owned set GROWS under `m`
         (`grow_push_fns`: a replica-gaining sibling, a bootstrapped
         split shard not already flipped) — the one-sided stale-map
         check only makes newer-client-vs-older-shard safe when flips
         SHRINK the shard's owned set; a grown owner still filtering
         deltas under the old map while new-map clients read from it
         would silently miss that partition's mutations;
      2. publish `m` to the registry (stale clients refreshing after a
         refusal must find it);
      3. flip the remaining (shrinking/unchanged) shards via
         `push_fns` — in-process handles pass ``svc.set_ownership``,
         subprocess shards ``lambda spec: gql.push_ownership(host,
         port, spec)``.

    New shards should be started/bootstrapped BEFORE calling this.
    Returns the per-shard installed epochs, grow pushes first."""
    spec = m.encode()
    out = []
    for push in grow_push_fns:
        out.append(push(spec))
    publish_map(registry, m, service)
    for push in push_fns:
        out.append(push(spec))
    return out


def clone_wal_dir(src_wal_dir: str, dst_wal_dir: str) -> None:
    """Bootstrap a split shard's durable state from a peer: copy the
    peer's compacted snapshot + log generations + CURRENT/EPOCH into a
    fresh wal_dir. The new shard's RecoverShard then loads the
    snapshot and replays the log FILTERED BY ITS OWN identity (LoadShard
    and ApplyGraphDelta re-filter by shard_idx/shard_num), so a clone
    started as shard 2-of-4 keeps exactly the partitions it will own —
    the PR 10 anti-entropy path pointed at a split instead of a
    restart (kGetDeltaLog catch-up closes the tail the copy missed).

    The peer's OWNERSHIP spec is deliberately NOT copied: it describes
    the OLD topology, under which the new shard owns nothing — replay
    must fall back to the hash convention at the new fleet width until
    the driver pushes the post-split map."""
    if os.path.exists(dst_wal_dir) and os.listdir(dst_wal_dir):
        raise ValueError(f"clone target {dst_wal_dir!r} is not empty")
    os.makedirs(dst_wal_dir, exist_ok=True)
    for name in sorted(os.listdir(src_wal_dir)):
        if name == "OWNERSHIP" or name.endswith(".tmp"):
            continue
        src = os.path.join(src_wal_dir, name)
        dst = os.path.join(dst_wal_dir, name)
        if os.path.isdir(src):
            shutil.copytree(src, dst)
        else:
            shutil.copy2(src, dst)


def hottest_shard(counts: Dict[int, int]) -> tuple:
    """(shard, share) with the largest share — the rebalance trigger.
    Feed it ROUTED-ROW counts (``RemoteGraphEngine.shard_traffic()[1]``
    / the obs ``graph_shard_rows_total`` gauges): rows carry the skew;
    request counts are near-uniform because the distribute rewrite
    fires one REMOTE per shard per query."""
    total = sum(counts.values())
    if total <= 0:
        return -1, 0.0
    shard = max(counts, key=lambda s: counts[s])
    return shard, counts[shard] / total
