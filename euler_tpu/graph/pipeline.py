"""Parallel host input pipeline: pipelined RPC client + immutable-graph
client cache.

PERF.md's decomposition puts the host feeder at the top of every
host-fed path's cost: `RemoteGraphEngine` issued exactly ONE blocking
query at a time, and the feeder iterated serially. This module supplies
the two client-side halves of the fix (the third, the multi-worker
feeder, lives in estimator/prefetch.py):

  * HandlePool / PipelinedClient — a per-engine worker pool (N threads
    over M pooled native Query handles) with a
    ``submit(gql, feed) -> Future`` surface, so multiple queries are in
    flight against the shard cluster at once. Every worker call still
    runs through the OWNING engine's ``_run`` — the same RetryPolicy /
    degrade machinery and ``graph_rpc`` spans as the serial path, just
    against a pooled handle instead of the engine's own.

  * CachedGraphEngine — deterministic reads (``get_full_neighbor``
    rows, ``get_dense_feature`` rows) of a graph SNAPSHOT are served
    from a bounded client cache. The hit/miss partition is one
    vectorized searchsorted/take pass over sorted key arrays — never a
    per-id Python dict loop on the hot path — and only misses go over
    the wire. Sampling verbs are NEVER cached (a cached random draw
    would freeze the sampling distribution), and a result produced
    while the underlying engine degraded (default_id padding) is NEVER
    inserted (the poisoning guard). Streaming deltas (ISSUE 9) turned
    "the graph is frozen" into a CHECKED epoch contract: on an observed
    graph-epoch bump the cache evicts exactly the delta's dirty ids
    (full flush only past ``epoch_dirty_bound`` or a history gap), so
    warm state survives mutation instead of being flushed wholesale.

Everything reports through euler_tpu.obs:
``client_cache_{hits,misses,inserts,evicted_rows}_total{cache=...}`` +
``client_cache_bytes``, ``graph_pipeline_inflight`` /
``graph_pipeline_chunks_total`` and the ``graph_pipeline_chunk_ms``
submit-to-done latency histogram.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Tuple

import numpy as np

from euler_tpu import obs as _obs
from euler_tpu.core.lib import EngineError
from euler_tpu.gql import Query, edge_types_str

_CACHE_IDS = itertools.count()
_POOL_IDS = itertools.count()


def _ranges(counts: np.ndarray) -> np.ndarray:
    """[sum(counts)] position-within-row array for ragged rows of the
    given lengths — the shared repeat/cumsum gather idiom."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    return (np.arange(total, dtype=np.int64)
            - np.repeat(np.cumsum(counts) - counts, counts))


# ---------------------------------------------------------------------------
# pipelined RPC client
# ---------------------------------------------------------------------------

class HandlePool:
    """M pooled native Query handles over the same endpoints.

    Exclusive mode (default): handles check out for exclusive use per
    call (free-list queue; acquire blocks when all M are in flight).
    Concurrent run() on ONE handle is safe (verified under an 8-thread
    stress test, and the serial engine's timed-attempt strays already
    share its handle with retries) — the pool exists for CHANNEL
    parallelism (each handle owns its own connection set to the shards,
    so M handles keep M requests on the wire) and for distinct
    per-handle sampling seeds (concurrent draws must not replay one
    stream).

    Shared mode (``shared=True``, the mux-transport shape): acquire
    never blocks — callers round-robin over the M handles and run
    CONCURRENTLY on them, so N in-flight queries ride M handles (M is
    typically 1) whose mux connections carry them all; the wire fd
    count stops scaling with in-flight depth. Concurrent sampling draws
    on one shared handle stay distinct: every execution takes a fresh
    engine-side nonce, so streams never replay."""

    def __init__(self, endpoints: str, seed: int, mode: str, size: int,
                 shared: bool = False):
        self._q: queue.Queue = queue.Queue()
        self._shared = bool(shared)
        self._cv = threading.Condition()
        self._inflight = 0
        self._rr = 0
        self._handles = []
        for i in range(max(int(size), 1)):
            # distinct per-handle seeds: two concurrent sampling queries
            # on different handles must not replay the same draw stream
            h = Query.remote(endpoints, seed=(seed + i + 1) if seed else 0,
                             mode=mode)
            self._handles.append(h)
            self._q.put(h)
        self.size = len(self._handles)

    def acquire(self) -> Query:
        if self._shared:
            with self._cv:
                self._inflight += 1
                h = self._handles[self._rr % self.size]
                self._rr += 1
                return h
        return self._q.get()

    def release(self, h: Query) -> None:
        if self._shared:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()
            return
        self._q.put(h)

    def set_ownership(self, spec: str) -> None:
        """Install an ownership-map spec on EVERY pooled handle (the
        engine's map refresh must reach pooled channels too, or chunked
        fan-out would keep routing on the superseded map). Safe against
        concurrent run()s — the native install is atomic per handle."""
        for h in self._handles:
            h.set_ownership(spec)

    def close(self, timeout_s: float = 5.0) -> None:
        """Reclaim and close the handles. A handle parked under a live
        (black-holed) call past the timeout is LEAKED (handle zeroed,
        native memory intentionally not freed) rather than freed under
        a running thread — same policy as RemoteGraphEngine.close."""
        deadline = time.monotonic() + timeout_s
        if self._shared:
            with self._cv:
                while (self._inflight > 0
                       and time.monotonic() < deadline):
                    self._cv.wait(
                        max(min(deadline - time.monotonic(), 0.2), 0.01))
                drained = self._inflight == 0
            for h in self._handles:
                if drained:
                    h.close()
                else:
                    with h._mu:
                        h._h = 0  # leak: live calls still use the handle
            return
        reclaimed = []
        while len(reclaimed) < self.size:
            try:
                reclaimed.append(self._q.get(
                    timeout=max(deadline - time.monotonic(), 0.0)))
            except queue.Empty:
                break
        for h in reclaimed:
            h.close()
        for h in self._handles:
            if h not in reclaimed:
                with h._mu:
                    h._h = 0  # leak: still in use by an abandoned call


class PipelinedClient:
    """N worker threads draining a submit queue against M pooled query
    handles, on behalf of one RemoteGraphEngine. submit() returns a
    concurrent.futures.Future; the worker executes the engine's _run
    (retry/degrade/span machinery included) against a pooled handle."""

    def __init__(self, engine, endpoints: str, seed: int, mode: str,
                 workers: int, handles: Optional[int] = None,
                 shared: bool = False):
        """shared=True: the mux-transport shape — workers run
        CONCURRENTLY on `handles` (default 1) shared query handles, so
        in-flight depth comes from the workers while the wire fd count
        comes from the transport's mux connections, not from handle
        count. False (default): exclusive checkout, one handle per
        in-flight call (the PR-4 pool shape)."""
        self._engine = engine
        workers = max(int(workers), 1)
        self._handles = HandlePool(endpoints, seed, mode,
                                   handles or (1 if shared else workers),
                                   shared=shared)
        self._name = f"pipeline{next(_POOL_IDS)}"
        self._exec = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix=f"euler-{self._name}")
        self.workers = workers
        reg = _obs.default_registry()
        lab = {"engine": self._name}
        self._g_inflight = reg.gauge(
            "graph_pipeline_inflight",
            "pipelined graph rpc calls submitted but not completed",
            ("engine",)).labels(**lab)
        self._ctr_chunks = reg.counter(
            "graph_pipeline_chunks_total",
            "pipelined graph rpc submissions", ("engine",)).labels(**lab)
        self._hist_chunk_ms = reg.histogram(
            "graph_pipeline_chunk_ms",
            "submit-to-done latency per pipelined call (queue wait + "
            "rpc + retries)", ("engine",)).labels(**lab)
        self._closed = False

    def submit(self, gql: str, feed=None) -> Future:
        if self._closed:
            raise RuntimeError("PipelinedClient is closed")
        self._ctr_chunks.inc()
        self._g_inflight.inc()
        t_submit = time.monotonic()

        def call():
            try:
                h = self._handles.acquire()
                try:
                    return self._engine._run(gql, feed, query=h)
                finally:
                    self._handles.release(h)
            finally:
                self._g_inflight.dec()
                self._hist_chunk_ms.observe(
                    (time.monotonic() - t_submit) * 1000.0)

        return self._exec.submit(call)

    def set_ownership(self, spec: str) -> None:
        """Forward an ownership-map install to the pooled handles."""
        self._handles.set_ownership(spec)

    def close(self, timeout_s: float = 5.0) -> None:
        """Bounded shutdown mirroring the engine's stray policy: a
        worker parked on a black-holed socket must not hang close()
        forever — past the timeout its handle is leaked (by
        HandlePool.close) rather than freed under a live thread."""
        if self._closed:
            return
        self._closed = True
        deadline = time.monotonic() + timeout_s
        self._exec.shutdown(wait=False, cancel_futures=True)
        for t in list(getattr(self._exec, "_threads", ())):
            t.join(max(deadline - time.monotonic(), 0.0))
        self._handles.close(max(deadline - time.monotonic(), 0.1))


# ---------------------------------------------------------------------------
# in-flight request dedup
# ---------------------------------------------------------------------------

def deterministic_gql(gql: str) -> bool:
    """True when the query reads immutable graph state and two identical
    executions return identical bytes — the coalescing precondition.
    Every sampling verb starts with 'sample' (sampleN/sampleE/sampleNB/
    sampleLNB/sampleNWithTypes/sampleGL), so one marker refuses them
    all; udf() is excluded too (registered UDFs are REQUIRED pure for
    the result cache, but a stateful one would silently corrupt
    coalesced callers — refusing costs one wire call)."""
    return "sample" not in gql and "udf(" not in gql


class InflightDedup:
    """Coalesce concurrent IDENTICAL deterministic queries onto one wire
    call (e.g. overlapping feeder workers fetching the same feature
    rows). The first caller (leader) issues the call; callers that
    arrive with the same (gql, feed bytes) key while it is IN FLIGHT
    wait on the leader's future and receive byte-identical COPIES of
    its result (copies: callers may mutate returned arrays). The key
    holds the full feed bytes — no hash-collision coalescing. Entries
    leave the table the moment the leader finishes, so this never acts
    as a result cache (CachedGraphEngine is that, above this layer).
    Sampling verbs bypass entirely (see deterministic_gql): coalescing
    two draws would correlate their randomness.

    Counted on the obs registry: rpc_dedup_hits_total{engine=} (calls
    served from a leader's flight) / rpc_dedup_issued_total (leader
    flights that had at least the leader)."""

    def __init__(self, name: str):
        self._mu = threading.Lock()
        self._inflight: Dict[tuple, list] = {}  # key -> [Future, followers]
        reg = _obs.default_registry()
        lab = {"engine": name}
        self._ctr_hits = reg.counter(
            "rpc_dedup_hits_total",
            "calls coalesced onto an identical in-flight query",
            ("engine",)).labels(**lab)
        self._ctr_issued = reg.counter(
            "rpc_dedup_issued_total",
            "deduplicable queries that actually went to the wire",
            ("engine",)).labels(**lab)

    @staticmethod
    def _key(gql: str, feed) -> tuple:
        if not feed:
            return (gql,)
        items = []
        for k in sorted(feed):
            a = np.ascontiguousarray(feed[k])
            items.append((k, a.dtype.str, a.shape, a.tobytes()))
        return (gql, tuple(items))

    @staticmethod
    def _copy_result(out):
        if isinstance(out, dict):
            return {k: (np.array(v, copy=True)
                        if isinstance(v, np.ndarray) else v)
                    for k, v in out.items()}
        return out

    def run(self, gql: str, feed, fn):
        """fn() under dedup: leader executes, followers wait + copy."""
        if not deterministic_gql(gql):
            return fn()
        key = self._key(gql, feed)
        with self._mu:
            entry = self._inflight.get(key)
            leader = entry is None
            if leader:
                entry = [Future(), 0]
                self._inflight[key] = entry
            else:
                entry[1] += 1
        fut = entry[0]
        if not leader:
            self._ctr_hits.inc()
            return self._copy_result(fut.result())
        self._ctr_issued.inc()
        try:
            out = fn()
        except BaseException as e:
            with self._mu:
                self._inflight.pop(key, None)
            fut.set_exception(e)
            raise
        # drop the entry BEFORE completing the future: a caller arriving
        # after completion must issue its own call (in-flight dedup, not
        # a cache) and a waiter that joined in time still gets the result
        with self._mu:
            self._inflight.pop(key, None)
            followers = entry[1]
        fut.set_result(out)
        # followers copy from the future's pristine arrays AFTER this
        # return — the leader's caller may mutate its result, so when
        # anyone coalesced, hand the leader its own copy too
        return self._copy_result(out) if followers else out


# ---------------------------------------------------------------------------
# immutable-graph client cache
# ---------------------------------------------------------------------------

class _DenseStore:
    """Sorted-key store of fixed-width float32 rows (dense features).
    All operations are whole-array numpy passes."""

    __slots__ = ("keys", "vals", "gen", "width", "splits")

    def __init__(self):
        self.keys = np.zeros(0, dtype=np.uint64)
        self.vals = np.zeros((0, 0), dtype=np.float32)
        self.gen = np.zeros(0, dtype=np.int64)
        self.width = -1              # columns; -1 until first insert
        self.splits: Optional[Tuple[int, ...]] = None  # per-fid widths

    def lookup(self, ids: np.ndarray):
        """(hit_mask, store_rows) — store_rows valid where hit_mask."""
        if self.keys.size == 0:
            return np.zeros(ids.size, dtype=bool), None
        pos = np.searchsorted(self.keys, ids)
        pos = np.minimum(pos, self.keys.size - 1)
        hit = self.keys[pos] == ids
        return hit, pos

    def insert(self, ids: np.ndarray, rows: np.ndarray, gen: int) -> None:
        """Merge new (unique, absent) ids + rows, keeping keys sorted."""
        if self.width < 0:
            self.width = int(rows.shape[1])
        keys = np.concatenate([self.keys, ids])
        order = np.argsort(keys, kind="stable")
        self.keys = keys[order]
        self.vals = np.concatenate(
            [self.vals.reshape(-1, self.width),
             rows.astype(np.float32, copy=False)])[order]
        self.gen = np.concatenate(
            [self.gen, np.full(ids.size, gen, np.int64)])[order]

    def touch(self, rows: np.ndarray, gen: int) -> None:
        self.gen[rows] = gen

    def drop_oldest_half(self) -> int:
        if self.keys.size == 0:
            return 0
        cut = np.median(self.gen)
        keep = self.gen > cut
        if keep.all():                  # all gens equal: drop everything
            keep = np.zeros(self.keys.size, dtype=bool)
        dropped = int((~keep).sum())
        self.keys = self.keys[keep]
        self.vals = self.vals[keep]
        self.gen = self.gen[keep]
        return dropped

    def drop_ids(self, ids_sorted: np.ndarray) -> int:
        """Surgical epoch invalidation: evict exactly the rows whose key
        is in the (sorted unique) dirty set; every other row is
        retained warm. One searchsorted pass — O(n log d)."""
        if self.keys.size == 0 or ids_sorted.size == 0:
            return 0
        pos = np.searchsorted(ids_sorted, self.keys)
        pos = np.minimum(pos, ids_sorted.size - 1)
        keep = ids_sorted[pos] != self.keys
        dropped = int((~keep).sum())
        if dropped:
            self.keys = self.keys[keep]
            self.vals = self.vals[keep]
            self.gen = self.gen[keep]
        return dropped

    @property
    def nbytes(self) -> int:
        return int(self.keys.nbytes + self.vals.nbytes + self.gen.nbytes)

    @property
    def entries(self) -> int:
        return int(self.keys.size)


class _RaggedStore:
    """Sorted-key CSR store of ragged rows (full neighbor lists):
    keys[n] sorted, off[n+1], parallel value columns of length off[-1]
    (nbr uint64, w float32, t int32)."""

    __slots__ = ("keys", "off", "cols", "gen")

    def __init__(self):
        self.keys = np.zeros(0, dtype=np.uint64)
        self.off = np.zeros(1, dtype=np.int64)
        self.cols: Tuple[np.ndarray, ...] = (
            np.zeros(0, np.uint64), np.zeros(0, np.float32),
            np.zeros(0, np.int32))
        self.gen = np.zeros(0, dtype=np.int64)

    def lookup(self, ids: np.ndarray):
        if self.keys.size == 0:
            return np.zeros(ids.size, dtype=bool), None
        pos = np.searchsorted(self.keys, ids)
        pos = np.minimum(pos, self.keys.size - 1)
        hit = self.keys[pos] == ids
        return hit, pos

    def gather(self, rows: np.ndarray):
        """(counts, col_values...) for the given store rows, row-major —
        one repeat/take pass, no per-row loop."""
        counts = self.off[rows + 1] - self.off[rows]
        src = np.repeat(self.off[rows], counts) + _ranges(counts)
        return (counts,) + tuple(c[src] for c in self.cols)

    def insert(self, ids: np.ndarray, counts: np.ndarray, cols, gen: int):
        """Merge new (unique, absent) CSR rows; rebuilds the packed
        arrays with one argsort + gather pass."""
        old_counts = np.diff(self.off)
        all_keys = np.concatenate([self.keys, ids])
        all_counts = np.concatenate([old_counts, counts])
        starts = np.concatenate(
            [self.off[:-1], self.off[-1] + np.cumsum(counts) - counts])
        order = np.argsort(all_keys, kind="stable")
        cnt_o = all_counts[order]
        src = np.repeat(starts[order], cnt_o) + _ranges(cnt_o)
        flat = tuple(np.concatenate([old, new])[src]
                     for old, new in zip(self.cols, cols))
        self.keys = all_keys[order]
        self.off = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(cnt_o, dtype=np.int64)])
        self.cols = flat
        self.gen = np.concatenate(
            [self.gen, np.full(ids.size, gen, np.int64)])[order]

    def touch(self, rows: np.ndarray, gen: int) -> None:
        self.gen[rows] = gen

    def drop_oldest_half(self) -> int:
        if self.keys.size == 0:
            return 0
        cut = np.median(self.gen)
        keep = self.gen > cut
        if keep.all():
            keep = np.zeros(self.keys.size, dtype=bool)
        return self._drop_mask(keep)

    def drop_ids(self, ids_sorted: np.ndarray) -> int:
        """Surgical epoch invalidation (see _DenseStore.drop_ids)."""
        if self.keys.size == 0 or ids_sorted.size == 0:
            return 0
        pos = np.searchsorted(ids_sorted, self.keys)
        pos = np.minimum(pos, ids_sorted.size - 1)
        keep = ids_sorted[pos] != self.keys
        if keep.all():
            return 0
        return self._drop_mask(keep)

    def _drop_mask(self, keep: np.ndarray) -> int:
        dropped = int((~keep).sum())
        rows = np.flatnonzero(keep)
        counts, *cols = self.gather(rows)
        self.keys = self.keys[keep]
        self.off = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(counts, dtype=np.int64)])
        self.cols = tuple(cols)
        self.gen = self.gen[keep]
        return dropped

    @property
    def nbytes(self) -> int:
        return int(self.keys.nbytes + self.off.nbytes + self.gen.nbytes
                   + sum(c.nbytes for c in self.cols))

    @property
    def entries(self) -> int:
        return int(self.keys.size)


class CachedGraphEngine:
    """Bounded, thread-safe client cache over an engine-shaped object.

    Serves exactly the DETERMINISTIC reads of an immutable graph —
    ``get_full_neighbor`` (per edge_types/sorted/in_edges variant) and
    ``get_dense_feature`` (per fids/dims spec) — byte-identically to the
    wrapped engine; everything else (all sampling verbs, sparse/binary
    getters, lifecycle) passes straight through. Keyed lookups are one
    searchsorted/take pass over sorted uint64 key arrays; only misses
    (deduplicated) go over the wire.

    Poisoning guard: a fetch during which the underlying engine's
    ``degraded`` counter moved is NOT inserted — default_id padding must
    never become a permanent cache row. (Feature/neighbor getters never
    degrade today; the guard makes that a checked invariant rather than
    an assumption about remote.py's current shape.)

    Eviction: ``budget_bytes`` bounds the packed arrays; over budget the
    largest store drops its least-recently-used half (generation
    median) until under. stats()/health() are views over the
    client_cache_* obs registry counters by construction.
    """

    def __init__(self, engine, budget_bytes: int = 64 << 20,
                 name: Optional[str] = None,
                 epoch_dirty_bound: int = 262_144):
        """epoch_dirty_bound: max dirty-id set a graph-epoch bump is
        invalidated SURGICALLY from (evict only keys in the delta's
        dirty set); a bigger delta — or a history gap (covered=False) —
        falls back to the documented full flush. Counted either way:
        cache_epoch_{evicted,retained}_total / cache_epoch_flushes_total."""
        self._engine = engine
        self._budget = int(budget_bytes)
        self._mu = threading.RLock()
        self._gen = 0
        self._dense: Dict[tuple, _DenseStore] = {}
        self._ragged: Dict[tuple, _RaggedStore] = {}
        self._obs_name = name or f"cache{next(_CACHE_IDS)}"
        reg = _obs.default_registry()
        lab = {"cache": self._obs_name}
        self._ctr = {
            k: reg.counter(f"client_cache_{k}_total", h,
                           ("cache",)).labels(**lab)
            for k, h in (
                ("hits", "ids served from the client graph cache"),
                ("misses", "ids fetched over the wire"),
                ("inserts", "rows inserted into the client graph cache"),
                ("evicted_rows", "rows evicted under the byte budget"),
                ("poison_skips",
                 "fetches not cached because the engine degraded"),
            )}
        # streaming-delta invalidation accounting: evicted = rows whose
        # id was dirty, retained = warm rows that SURVIVED a bump (the
        # state a naive full flush would have destroyed), flushes =
        # bumps that fell back to a full flush (overflow / history gap)
        self._ctr_epoch = {
            k: reg.counter(f"cache_epoch_{k}_total", h,
                           ("cache",)).labels(**lab)
            for k, h in (
                ("evicted", "cache rows evicted by graph epoch bumps"),
                ("retained", "warm cache rows retained across epoch bumps"),
                ("flushes", "epoch bumps answered with a full flush"),
            )}
        self._g_bytes = reg.gauge(
            "client_cache_bytes", "packed client-cache array bytes",
            ("cache",)).labels(**lab)
        self._g_epoch = reg.gauge(
            "graph_epoch", "last graph epoch this cache reconciled to",
            ("cache",)).labels(**lab)
        self._dirty_bound = int(epoch_dirty_bound)
        # last epoch this cache reconciled to; None until the engine
        # exposes one (plain engine-shaped test doubles never do)
        self._observed_epoch: Optional[int] = None
        epoch_fn = getattr(engine, "graph_epoch", None)
        if callable(epoch_fn):
            try:
                self._observed_epoch = int(epoch_fn())
                self._g_epoch.set(self._observed_epoch)
            except (EngineError, OSError, AttributeError):
                # AttributeError: a delegating wrapper (ChaosGraphEngine)
                # always EXPOSES graph_epoch but raises when its inner
                # engine lacks it — that composition must keep working
                self._observed_epoch = None
        _obs.register_health(self._obs_name, self.cache_stats)

    # -- passthrough -------------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._engine, name)

    # -- introspection -----------------------------------------------------
    def cache_stats(self) -> dict:
        """{hits, misses, inserts, evicted_rows, poison_skips, bytes,
        entries, hit_rate} — a VIEW over the client_cache_* registry
        children (the same numbers a /metrics scrape reports)."""
        out = {k: int(c.value) for k, c in self._ctr.items()}
        out["bytes"] = int(self._g_bytes.value)
        with self._mu:
            out["entries"] = sum(
                s.entries for s in (*self._dense.values(),
                                    *self._ragged.values()))
            out["graph_epoch"] = self._observed_epoch
        for k, c in self._ctr_epoch.items():
            out[f"epoch_{k}"] = int(c.value)
        total = out["hits"] + out["misses"]
        out["hit_rate"] = out["hits"] / total if total else 0.0
        return out

    def health(self) -> dict:
        h = getattr(self._engine, "health", None)
        out = h() if callable(h) else {}
        out["cache"] = self.cache_stats()
        return out

    def clear_cache(self) -> None:
        with self._mu:
            self._dense.clear()
            self._ragged.clear()
            self._refresh_bytes()

    # -- elastic fleet (explicit delegation) -------------------------------
    # Ownership routing lives in the wrapped engine; the cache stays
    # VALID across map flips (ownership moves requests, not data — the
    # epoch-invalidation machinery below owns data coherence). Explicit
    # so an engine lacking the verbs raises its own AttributeError.
    def refresh_ownership(self, force: bool = False) -> int:
        return self._engine.refresh_ownership(force=force)

    def ownership_epoch(self) -> int:
        return self._engine.ownership_epoch()

    def shard_traffic(self):
        return self._engine.shard_traffic()


    # -- streaming-delta epoch coherence -----------------------------------
    def graph_epoch(self, *args, **kwargs) -> int:
        return self._engine.graph_epoch(*args, **kwargs)

    def delta_since(self, from_epoch: int):
        return self._engine.delta_since(from_epoch)

    def apply_delta(self, **delta) -> int:
        """Apply a delta through the wrapped engine, then invalidate
        THIS cache surgically from the delta itself — the dirty set is
        known locally (nodes ∪ edge endpoints), so the issuing client
        pays zero extra RPCs to stay coherent. If the engine's epoch
        jumped FURTHER than our own delta (another client applied
        in between), the local dirty set does not cover the gap —
        reconcile through the engine's history instead of silently
        skipping the intermediate epochs' dirty ids."""
        from euler_tpu.graph.api import delta_dirty_ids

        epoch = self._engine.apply_delta(**delta)
        dirty = delta_dirty_ids(**delta)
        gap = (self._observed_epoch is None
               or epoch != self._observed_epoch + 1)
        if gap:
            try:
                from_e = self._observed_epoch or 0
                e2, covered, hist = self._engine.delta_since(from_e)
                dirty = hist if covered else None
                epoch = max(epoch, e2)
            except (EngineError, OSError):
                dirty = None  # can't prove coverage → flush
        with self._mu:
            self._apply_dirty(dirty)
            self._observed_epoch = epoch
            self._g_epoch.set(epoch)
        return epoch

    def maybe_invalidate(self) -> None:
        """Reconcile the cache with the engine's current epoch. Called
        on every cached read (one native epoch poll, ~µs) and safe to
        call explicitly after an out-of-band delta. On a bump: evict
        only the dirty ids when the engine's history covers the gap and
        the set is under epoch_dirty_bound; otherwise the documented
        full-flush fallback. No epoch surface on the engine → no-op
        (the PR-4 immutable contract)."""
        if self._observed_epoch is None:
            return
        epoch_fn = getattr(self._engine, "graph_epoch", None)
        if not callable(epoch_fn):
            return
        try:
            cur = int(epoch_fn())
        except (EngineError, OSError, AttributeError):
            return
        if cur < self._observed_epoch:
            # epoch REGRESSION: the engine (a restarted shard) lost
            # deltas we already reconciled to — nothing can prove which
            # warm rows still match, so flush and re-anchor
            with self._mu:
                self._apply_dirty(None)
                self._observed_epoch = cur
                self._g_epoch.set(cur)
            return
        if cur == self._observed_epoch:
            return
        try:
            epoch, covered, dirty = self._engine.delta_since(
                self._observed_epoch)
        except (EngineError, OSError, AttributeError):
            return  # transient failure: retry at the next read
        with self._mu:
            if not covered:
                dirty = None  # history gap → everything is dirty
            self._apply_dirty(dirty)
            self._observed_epoch = max(epoch, cur)
            self._g_epoch.set(self._observed_epoch)

    def _apply_dirty(self, dirty: Optional[np.ndarray]) -> None:
        """Under self._mu: evict dirty ids (surgical) or flush. dirty
        None → flush; oversized dirty set → flush (documented bound)."""
        before = sum(s.entries for s in (*self._dense.values(),
                                         *self._ragged.values()))
        if dirty is not None and dirty.size <= self._dirty_bound:
            ids = np.asarray(dirty, dtype=np.uint64).ravel()
            evicted = 0
            for store in (*self._dense.values(), *self._ragged.values()):
                evicted += store.drop_ids(ids)
            self._ctr_epoch["evicted"].inc(evicted)
            self._ctr_epoch["retained"].inc(before - evicted)
        else:
            self._dense.clear()
            self._ragged.clear()
            self._ctr_epoch["evicted"].inc(before)
            self._ctr_epoch["flushes"].inc()
        self._refresh_bytes()

    # -- internals ---------------------------------------------------------
    def _degraded_count(self) -> int:
        ctr = getattr(self._engine, "_ctr", None)
        if isinstance(ctr, dict) and "degraded" in ctr:
            return int(ctr["degraded"].value)
        return 0

    def _refresh_bytes(self) -> int:
        b = sum(s.nbytes for s in (*self._dense.values(),
                                   *self._ragged.values()))
        self._g_bytes.set(b)
        return b

    def _maybe_evict(self) -> None:
        while self._refresh_bytes() > self._budget:
            stores = [s for s in (*self._dense.values(),
                                  *self._ragged.values()) if s.entries]
            if not stores:
                break
            victim = max(stores, key=lambda s: s.nbytes)
            self._ctr["evicted_rows"].inc(victim.drop_oldest_half())

    def _next_gen(self) -> int:
        self._gen += 1
        return self._gen

    # -- cached reads ------------------------------------------------------
    def get_dense_feature(self, ids, fids, dims=None):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        single = not isinstance(fids, (list, tuple, np.ndarray))
        names = tuple([fids] if single else list(fids))
        dims_t = None if dims is None else tuple(
            [dims] if single else list(dims))
        key = ("dense", names, dims_t)
        n = ids.size
        if n == 0:
            return self._engine.get_dense_feature(ids, fids, dims)
        self.maybe_invalidate()
        with self._mu:
            store = self._dense.setdefault(key, _DenseStore())
            hit, pos = store.lookup(ids)
            n_hit = int(hit.sum())
            gen = self._next_gen()
            if n_hit:
                hit_rows = pos[hit]
                store.touch(hit_rows, gen)
                hit_vals = store.vals[hit_rows]
            splits = store.splits
            width = store.width
            epoch0 = self._observed_epoch
        self._ctr["hits"].inc(n_hit)
        self._ctr["misses"].inc(n - n_hit)
        if n_hit == n:
            out = np.ascontiguousarray(hit_vals)
            return self._split_dense(out, splits, single)
        miss_ids = ids[~hit]
        uniq, inv = np.unique(miss_ids, return_inverse=True)
        d0 = self._degraded_count()
        fetched = self._engine.get_dense_feature(uniq, fids, dims)
        poisoned = self._degraded_count() > d0
        parts = [fetched] if single else list(fetched)
        f_splits = tuple(int(p.shape[1]) for p in parts)
        packed = parts[0] if len(parts) == 1 else np.concatenate(
            parts, axis=1)
        if width >= 0 and packed.shape[1] != width:
            # inferred width changed (graph_partition ragged rows + no
            # explicit dims): the cached rows and this batch disagree on
            # the padded shape — drop the store and answer the whole
            # request fresh so cache-on stays byte-identical to
            # cache-off for THIS call
            with self._mu:
                self._dense.pop(key, None)
                self._refresh_bytes()
            return self._engine.get_dense_feature(ids, fids, dims)
        if not poisoned:
            with self._mu:
                # re-check under the lock: a concurrent caller may have
                # fetched+inserted the same misses while we were on the
                # wire — the stores' insert requires ABSENT keys, and
                # duplicates would bloat bytes/entries for nothing.
                # Epoch guard: a delta observed while we were on the
                # wire means these rows may be PRE-delta — serving them
                # to this caller is fine (the bump was not yet observed
                # at fetch time), caching them would be permanent
                # staleness.
                hit2, _ = store.lookup(uniq)
                fresh = ~hit2
                if fresh.any() and self._observed_epoch == epoch0:
                    store.splits = store.splits or f_splits
                    store.insert(uniq[fresh], packed[fresh], gen)
                    self._ctr["inserts"].inc(int(fresh.sum()))
                    self._maybe_evict()
        else:
            self._ctr["poison_skips"].inc()
        out = np.empty((n, packed.shape[1]), dtype=np.float32)
        if n_hit:
            out[hit] = hit_vals
        out[~hit] = packed[inv]
        return self._split_dense(out, splits or f_splits, single)

    @staticmethod
    def _split_dense(out: np.ndarray, splits, single: bool):
        if single:
            return out
        edges = np.cumsum((0,) + tuple(splits))
        return [np.ascontiguousarray(out[:, a:b])
                for a, b in zip(edges[:-1], edges[1:])]

    def get_full_neighbor(self, ids, edge_types=None,
                          sorted_by_id: bool = False,
                          in_edges: bool = False):
        ids = np.ascontiguousarray(ids, dtype=np.uint64).ravel()
        key = ("nbr", edge_types_str(edge_types), bool(sorted_by_id),
               bool(in_edges))
        n = ids.size
        self.maybe_invalidate()
        with self._mu:
            store = self._ragged.setdefault(key, _RaggedStore())
            hit, pos = store.lookup(ids)
            n_hit = int(hit.sum())
            gen = self._next_gen()
            if n_hit:
                hit_rows = pos[hit]
                store.touch(hit_rows, gen)
                h_cnt, h_nbr, h_w, h_t = store.gather(hit_rows)
            epoch0 = self._observed_epoch
        self._ctr["hits"].inc(n_hit)
        self._ctr["misses"].inc(n - n_hit)
        counts = np.zeros(n, dtype=np.int64)
        if n_hit:
            counts[hit] = h_cnt
        if n_hit < n:
            miss_ids = ids[~hit]
            uniq, inv = np.unique(miss_ids, return_inverse=True)
            d0 = self._degraded_count()
            off_u, nbr_u, w_u, t_u = self._engine.get_full_neighbor(
                uniq, edge_types=edge_types, sorted_by_id=sorted_by_id,
                in_edges=in_edges)
            poisoned = self._degraded_count() > d0
            off_u = off_u.astype(np.int64)
            cnt_u = np.diff(off_u)
            if not poisoned:
                with self._mu:
                    # same still-absent re-check + epoch guard as the
                    # dense path (a mid-fetch delta orphans this batch)
                    hit2, _ = store.lookup(uniq)
                    rows = np.flatnonzero(~hit2)
                    if rows.size and self._observed_epoch != epoch0:
                        rows = rows[:0]
                    if rows.size:
                        cnt_f = cnt_u[rows]
                        src = (np.repeat(off_u[:-1][rows], cnt_f)
                               + _ranges(cnt_f))
                        store.insert(uniq[rows], cnt_f,
                                     (nbr_u[src], w_u[src], t_u[src]),
                                     gen)
                        self._ctr["inserts"].inc(rows.size)
                        self._maybe_evict()
            else:
                self._ctr["poison_skips"].inc()
            m_cnt = cnt_u[inv]
            m_src = np.repeat(off_u[inv], m_cnt) + _ranges(m_cnt)
            counts[~hit] = m_cnt
        out_off = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(counts, dtype=np.int64)])
        total = int(out_off[-1])
        out_nbr = np.empty(total, dtype=np.uint64)
        out_w = np.empty(total, dtype=np.float32)
        out_t = np.empty(total, dtype=np.int32)
        if n_hit:
            dst = np.repeat(out_off[:-1][hit], h_cnt) + _ranges(h_cnt)
            out_nbr[dst], out_w[dst], out_t[dst] = h_nbr, h_w, h_t
        if n_hit < n:
            dst = np.repeat(out_off[:-1][~hit], m_cnt) + _ranges(m_cnt)
            out_nbr[dst] = nbr_u[m_src]
            out_w[dst] = w_u[m_src]
            out_t[dst] = t_u[m_src]
        return out_off.astype(np.uint64), out_nbr, out_w, out_t

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        _obs.unregister_health(self._obs_name)
        close = getattr(self._engine, "close", None)
        if callable(close):
            close()
