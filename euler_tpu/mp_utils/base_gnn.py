"""Conv-stack GNN assembly over edge_index batches.

Parity: tf_euler/python/mp_utils/base_gnn.py:27-95 (BaseGNNNet __call__ =
sampler→blocks→convs loop; JKGNNNet :97). Here the sampling already
happened host-side (WholeDataFlow / FanoutDataFlow); this module runs the
conv stack on the batch's node table and returns root-row embeddings.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu import convolution as C

Array = jax.Array

_CONV_BUILDERS = {
    "gcn": lambda dim, i, n, kw: C.GCNConv(out_dim=dim),
    "sage": lambda dim, i, n, kw: C.SAGEConv(out_dim=dim),
    "gat": lambda dim, i, n, kw: C.GATConv(out_dim=dim,
                                           heads=kw.get("heads", 1)),
    "agnn": lambda dim, i, n, kw: (C.GCNConv(out_dim=dim) if i == 0
                                   else C.AGNNConv()),
    "gin": lambda dim, i, n, kw: C.GINConv(out_dim=dim),
    "graph": lambda dim, i, n, kw: C.GraphConv(out_dim=dim),
    "sgcn": lambda dim, i, n, kw: C.SGCNConv(out_dim=dim,
                                             k_hop=kw.get("k_hop", 2)),
    "tag": lambda dim, i, n, kw: C.TAGConv(out_dim=dim,
                                           k_hop=kw.get("k_hop", 3)),
    "arma": lambda dim, i, n, kw: C.ARMAConv(
        out_dim=dim, num_stacks=kw.get("num_stacks", 2),
        num_layers=kw.get("arma_layers", 1)),
    "appnp": lambda dim, i, n, kw: C.APPNPConv(
        k_hop=kw.get("k_hop", 10), alpha=kw.get("alpha", 0.1)),
    "gated": lambda dim, i, n, kw: C.GatedGraphConv(
        out_dim=dim, num_layers=kw.get("gate_layers", 2)),
    "relation": lambda dim, i, n, kw: C.RelationConv(
        out_dim=dim, num_relations=kw.get("num_relations", 1)),
}


def get_conv(name: str, dim: int, layer_idx: int, num_layers: int,
             kwargs: Dict) -> nn.Module:
    try:
        return _CONV_BUILDERS[name.lower()](dim, layer_idx, num_layers, kwargs)
    except KeyError:
        raise ValueError(
            f"unknown conv {name!r}; options {sorted(_CONV_BUILDERS)}"
        ) from None


class BaseGNNNet(nn.Module):
    """conv_name × num_layers over (x, edge_index); returns root embeddings.

    APPNP-style convs that end with propagation-only layers get a leading
    MLP, matching the reference model structure.
    """

    conv_name: str = "gcn"
    dim: int = 32
    num_layers: int = 2
    out_dim: int = 0            # 0 → dim
    conv_kwargs: Dict = None
    # input dropout before each conv (reference citation models use 0.5);
    # active only when a "dropout" rng is provided (training)
    dropout: float = 0.0

    def _drop(self, h: Array) -> Array:
        if self.dropout <= 0.0:
            return h
        return nn.Dropout(self.dropout)(
            h, deterministic=not self.has_rng("dropout"))

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> Array:
        x = batch["x"]
        edge_index = batch["edge_index"]
        kw = self.conv_kwargs or {}
        n = x.shape[0]
        name = self.conv_name.lower()
        if name == "appnp":
            # predict-then-propagate: MLP then one propagation conv
            h = nn.relu(nn.Dense(self.dim, name="mlp_0")(self._drop(x)))
            h = nn.Dense(self.out_dim or self.dim, name="mlp_1")(h)
            h = C.APPNPConv(k_hop=kw.get("k_hop", 10),
                            alpha=kw.get("alpha", 0.1))(h, edge_index, n)
        elif name in ("sgcn",):
            h = C.SGCNConv(out_dim=self.out_dim or self.dim,
                           k_hop=kw.get("k_hop", self.num_layers))(
                x, edge_index, n)
        else:
            h = x
            for i in range(self.num_layers):
                dim = (self.out_dim or self.dim) if i == self.num_layers - 1 \
                    else self.dim
                conv = get_conv(name, dim, i, self.num_layers, kw)
                h = self._drop(h)
                if name == "relation":
                    h = conv(h, edge_index, batch.get("edge_type"), n)
                else:
                    h = conv(h, edge_index, n)
                if i < self.num_layers - 1:
                    h = nn.relu(h)
        root = batch.get("root_index")
        return h if root is None else jnp.take(h, root, axis=0)


class JKGNNNet(nn.Module):
    """Jumping-knowledge variant (reference base_gnn.py:97): concat of all
    layer outputs feeds the head."""

    conv_name: str = "gcn"
    dim: int = 32
    num_layers: int = 2
    conv_kwargs: Dict = None

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> Array:
        x = batch["x"]
        edge_index = batch["edge_index"]
        kw = self.conv_kwargs or {}
        n = x.shape[0]
        h = x
        outs = []
        for i in range(self.num_layers):
            conv = get_conv(self.conv_name, self.dim, i, self.num_layers, kw)
            h = conv(h, edge_index, n)
            if i < self.num_layers - 1:
                h = nn.relu(h)
            outs.append(h)
        h = jnp.concatenate(outs, axis=-1)
        root = batch.get("root_index")
        return h if root is None else jnp.take(h, root, axis=0)
