"""Graph-level GNN (classification over whole graphs).

Parity: tf_euler/python/mp_utils/graph_gnn.py:28 (GraphGNNNet) — conv
stack + readout pool over batches of graphs. Batch carries x, edge_index,
graph_index (node → graph), num_graphs is static (config).
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.mp_utils.base_gnn import get_conv
from euler_tpu import graph_pool as P
from euler_tpu.utils import metrics as M

Array = jax.Array

_POOLS = {
    "sum": lambda dim: P.SumPool(),
    "mean": lambda dim: P.MeanPool(),
    "max": lambda dim: P.MaxPool(),
    "attention": lambda dim: P.AttentionPool(dim=dim),
    "set2set": lambda dim: P.Set2SetPool(dim=dim),
}


class GraphGNNNet(nn.Module):
    """conv × L → pool → graph embedding."""

    conv_name: str = "gin"
    pool_name: str = "sum"
    dim: int = 32
    num_layers: int = 2
    num_graphs: int = 0  # static graphs per batch
    conv_kwargs: Dict = None

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> Array:
        x, edge_index = batch["x"], batch["edge_index"]
        gi = batch["graph_index"]
        n = x.shape[0]
        kw = self.conv_kwargs or {}
        h = x
        for i in range(self.num_layers):
            h = get_conv(self.conv_name, self.dim, i, self.num_layers, kw)(
                h, edge_index, n)
            if i < self.num_layers - 1:
                h = nn.relu(h)
        pool = _POOLS[self.pool_name.lower()](self.dim)
        return pool(h, gi, self.num_graphs)


class GraphModel(nn.Module):
    """Supervised graph classification on top of GraphGNNNet."""

    conv_name: str = "gin"
    pool_name: str = "sum"
    dim: int = 32
    num_layers: int = 2
    num_graphs: int = 0
    num_classes: int = 2
    conv_kwargs: Dict = None
    dropout: float = 0.0  # readout dropout, active only in training

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = GraphGNNNet(
            self.conv_name, self.pool_name, self.dim, self.num_layers,
            self.num_graphs, self.conv_kwargs, name="gnn")(batch)
        if self.dropout > 0.0:
            emb = nn.Dropout(self.dropout)(
                emb, deterministic=not self.has_rng("dropout"))
        logits = nn.Dense(self.num_classes, name="out")(emb)
        labels = batch["labels"].astype(jnp.int32)
        mask = batch.get("graph_mask")
        per = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        if mask is not None:
            per = per * mask
            loss = per.sum() / jnp.maximum(mask.sum(), 1.0)
            pred = jnp.argmax(logits, -1)
            acc = ((pred == labels) * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        else:
            loss = per.mean()
            acc = M.accuracy(logits, labels)
        return ModelOutput(emb, loss, "acc", acc)
