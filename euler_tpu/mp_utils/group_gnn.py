"""Multi-group (heterogeneous) GNN assembly.

Parity: tf_euler/python/mp_utils/group_gnn.py:29,40 (GroupGNNNet /
SharedGroupGNNNet) — one conv stack per edge-type group, outputs combined
by attention. SharedGroupGNNNet shares conv parameters across groups.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.mp_utils.base_gnn import BaseGNNNet
from euler_tpu.utils.layers import AttLayer


class GroupGNNNet(nn.Module):
    """Per-group conv stacks over group-filtered edge sets.

    batch["group_edge_index"]: list of [2, E_g] per group (host-side
    dataflow filters edges by type into static-size groups).
    """

    conv_name: str = "gcn"
    dim: int = 32
    num_layers: int = 2
    num_groups: int = 2
    shared: bool = False
    conv_kwargs: Dict = None

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> jnp.ndarray:
        outs = []
        shared_net = None
        if self.shared:
            shared_net = BaseGNNNet(self.conv_name, self.dim, self.num_layers,
                                    conv_kwargs=self.conv_kwargs, name="gnn")
        for g in range(self.num_groups):
            sub = dict(batch)
            sub["edge_index"] = batch["group_edge_index"][g]
            net = shared_net or BaseGNNNet(
                self.conv_name, self.dim, self.num_layers,
                conv_kwargs=self.conv_kwargs, name=f"gnn_{g}")
            outs.append(net(sub))
        stacked = jnp.stack(outs, axis=1)            # [B, G, D]
        return AttLayer(self.dim, name="combine")(stacked)


class SharedGroupGNNNet(GroupGNNNet):
    """Parameter-shared variant (reference group_gnn.py:40)."""

    shared: bool = True
