"""Model contract: (embedding, loss, metric_name, metric).

Parity: tf_euler/python/mp_utils/base.py:24-90 (SuperviseModel /
UnsuperviseModel). Models are flax modules taking a batch dict (jnp
arrays, already on device) and returning a ModelOutput; the estimator
differentiates through .loss.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from euler_tpu.utils import metrics as M
from euler_tpu.utils.layers import Embedding

Array = jax.Array


class ModelOutput(NamedTuple):
    embedding: Array
    loss: Array
    metric_name: str
    metric: Array


class SuperviseModel(nn.Module):
    """Supervised node classification: embed → dense logits → xent.

    Subclasses define embed(batch) → [B, D]. multilabel chooses sigmoid
    cross-entropy + micro-F1 (the reference's default for cora-style
    multilabel targets, mp_utils/base.py:24-48), else softmax + accuracy.
    """

    num_classes: int = 0
    multilabel: bool = True
    # regularization (reference models use dropout 0.5 + L2 on citation
    # sets, e.g. examples/gat/gat.py): active only when the estimator
    # provides a "dropout" rng, i.e. during training steps
    dropout: float = 0.0
    # mesh whose 'model' axis row-shards the HBM tables (feature/label/
    # neighbor) — None means replicated tables, plain local gathers
    table_mesh: Any = None

    def embed(self, batch: Dict[str, Any]) -> Array:
        raise NotImplementedError

    def table_gather(self):
        from euler_tpu.parallel.device_sampler import make_table_gather

        return make_table_gather(self.table_mesh)

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = self.embed(batch)
        if self.dropout > 0.0:
            emb = nn.Dropout(self.dropout)(
                emb, deterministic=not self.has_rng("dropout"))
        labels = batch.get("labels")
        if labels is None:
            # device-resident label table (DeviceFeatureStore): gather the
            # root rows in-jit instead of shipping labels from the host
            labels = self.table_gather()(batch["label_table"],
                                         batch["rows"][0])
        logits = nn.Dense(self.num_classes, name="out")(emb)
        # optional [B] 0/1 metric_mask: padded rows (deterministic eval
        # sweeps pad the final chunk to the static batch shape) drop out
        # of both the loss mean and the metric counts
        mask = batch.get("metric_mask")

        def wmean(per_row):
            return M.masked_mean(per_row, mask)

        if self.multilabel:
            loss = wmean(optax.sigmoid_binary_cross_entropy(
                logits, labels.astype(jnp.float32)).sum(-1))
            metric = M.micro_f1(jax.nn.sigmoid(logits), labels, mask=mask)
            name = "f1"
        else:
            # labels arrive either as integer classes [B] or one-hot [B, C]
            # (dense label features are stored one-hot)
            if labels.ndim == logits.ndim:
                loss = wmean(optax.softmax_cross_entropy(
                    logits, labels.astype(jnp.float32)))
                int_labels = jnp.argmax(labels, axis=-1)
            else:
                int_labels = labels.astype(jnp.int32)
                loss = wmean(optax.softmax_cross_entropy_with_integer_labels(
                    logits, int_labels))
            metric = M.micro_f1(logits, int_labels, mask=mask)
            name = "f1"
        return ModelOutput(emb, loss, name, metric)


class UnsuperviseModel(nn.Module):
    """Unsupervised embedding with negative sampling: positive (src, pos)
    pairs + num_negs sampled negatives, sigmoid ranking loss, MRR metric.

    Parity: mp_utils/base.py:49-90. Subclasses define embed(batch) and
    may override context_embed(pos, negs) -> (pos_emb, negs_emb)
    (the default embeds both from ONE shared id-context table — a single
    submodule, created once).
    batch: src_emb inputs + 'pos' ids + 'negs' ids handled by the caller's
    dataflow; this base consumes precomputed embeddings:
      embed(batch) → [B, D]; embed_context on pos [B, 1, D] / negs [B, N, D].
    """

    dim: int = 0
    max_id: int = 0
    num_negs: int = 5

    def embed(self, batch: Dict[str, Any]) -> Array:
        raise NotImplementedError

    def context_embed(self, pos: Array, negs: Array):
        """Context (pos, negs) embeddings from ONE shared table — a
        single submodule construction, since flax forbids creating two
        modules under the same explicit name in one call. Overrides
        needing more of the batch can read it in embed()/__call__."""
        ctx = Embedding(self.max_id + 1, self.dim, name="ctx_emb")
        return ctx(pos), ctx(negs)

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        emb = self.embed(batch)                       # [B, D]
        pos, negs = self.context_embed(batch["pos"], batch["negs"])
        if pos.ndim == 2:
            pos = pos[:, None, :]                     # [B, 1, D]
        pos_logit = jnp.einsum("bd,bkd->bk", emb, pos)    # [B, 1]
        neg_logit = jnp.einsum("bd,bkd->bk", emb, negs)   # [B, N]
        loss = (
            optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit)).mean()
            + optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit)).mean()
        )
        scores = jnp.concatenate([pos_logit, neg_logit], axis=1)
        return ModelOutput(emb, loss, "mrr", M.mrr(scores))
