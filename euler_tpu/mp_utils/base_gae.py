"""Graph auto-encoder models (GAE / VGAE).

Parity: tf_euler/python/mp_utils/base_gae.py (BaseGraphGAE) + the
examples/gae model: GNN encoder → inner-product decoder, reconstruction
loss over positive edges + sampled negatives; VGAE adds the KL term.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from euler_tpu.mp_utils.base import ModelOutput
from euler_tpu.mp_utils.base_gnn import BaseGNNNet
from euler_tpu.utils import metrics as M

Array = jax.Array


class BaseGraphGAE(nn.Module):
    """batch: x/edge_index node table + pos_src/pos_dst/neg_src/neg_dst
    row indices into the table. variational=True → VGAE."""

    conv_name: str = "gcn"
    dim: int = 32
    num_layers: int = 2
    variational: bool = False
    conv_kwargs: Dict = None

    @nn.compact
    def __call__(self, batch: Dict[str, Any]) -> ModelOutput:
        sub = dict(batch)
        sub.pop("root_index", None)  # encode the whole node table
        h = BaseGNNNet(self.conv_name, self.dim, self.num_layers,
                       conv_kwargs=self.conv_kwargs, name="enc")(sub)
        kl = 0.0
        if self.variational:
            mu = nn.Dense(self.dim, name="mu")(h)
            logvar = nn.Dense(self.dim, name="logvar")(h)
            rng = self.make_rng("sample") if self.has_rng("sample") else None
            if rng is not None:
                eps = jax.random.normal(rng, mu.shape)
                h = mu + jnp.exp(0.5 * logvar) * eps
            else:
                h = mu
            kl = -0.5 * jnp.mean(
                jnp.sum(1 + logvar - mu ** 2 - jnp.exp(logvar), axis=-1))
        pos = (h[batch["pos_src"]] * h[batch["pos_dst"]]).sum(-1)
        neg = (h[batch["neg_src"]] * h[batch["neg_dst"]]).sum(-1)
        loss = (
            optax.sigmoid_binary_cross_entropy(pos, jnp.ones_like(pos)).mean()
            + optax.sigmoid_binary_cross_entropy(neg, jnp.zeros_like(neg)).mean()
            + 0.001 * kl
        )
        scores = jnp.concatenate([pos, neg])
        labels = jnp.concatenate([jnp.ones_like(pos), jnp.zeros_like(neg)])
        return ModelOutput(h, loss, "auc", M.auc(scores, labels))
