from euler_tpu.mp_utils.base import (  # noqa: F401
    ModelOutput,
    SuperviseModel,
    UnsuperviseModel,
)
from euler_tpu.mp_utils.base_gae import BaseGraphGAE  # noqa: F401
from euler_tpu.mp_utils.base_gnn import BaseGNNNet, JKGNNNet, get_conv  # noqa: F401
from euler_tpu.mp_utils.graph_gnn import GraphGNNNet, GraphModel  # noqa: F401
from euler_tpu.mp_utils.group_gnn import GroupGNNNet, SharedGroupGNNNet  # noqa: F401
