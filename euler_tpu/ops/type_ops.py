"""Type-name ops over the global graph.

Parity: tf_euler/python/euler_ops/type_ops.py (get_node_type_id /
get_edge_type_id — data prep declares type NAMES; training code refers
to them by name and these translate to the engine's integer ids).
"""

from __future__ import annotations

import numpy as np

from euler_tpu.ops.base import get_graph

ALL_NODE_TYPE = -1


def _ids(type_id_or_names, edge: bool):
    g = get_graph()
    if isinstance(type_id_or_names, (list, tuple, np.ndarray)):
        return np.array([g.type_id(t, edge=edge) for t in type_id_or_names],
                        dtype=np.int32)
    return g.type_id(type_id_or_names, edge=edge)


def get_node_type_id(type_id_or_names):
    """Node type name(s) (or int id passthrough) → int id(s)."""
    return _ids(type_id_or_names, edge=False)


def get_edge_type_id(type_id_or_names):
    """Edge type name(s) (or int id passthrough) → int id(s)."""
    return _ids(type_id_or_names, edge=True)
