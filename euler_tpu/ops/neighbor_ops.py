"""Neighbor ops over the global graph.

Parity: tf_euler/python/euler_ops/neighbor_ops.py (sample_neighbor,
sample_fanout at :122, get_full_neighbor, get_sorted_full_neighbor,
get_top_k_neighbor) — shapes are fixed/padded rather than SparseTensor.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.ops.base import get_graph


def sample_neighbor(nodes, count: int, edge_types=None, default_node: int = 0):
    return get_graph().sample_neighbor(
        nodes, count, edge_types=edge_types, default_id=default_node
    )


def sample_fanout(nodes, counts, edge_types=None, default_node: int = 0):
    """Multi-hop expansion; returns (layers_ids, layers_weights, layers_types)
    where layers_ids[0] is the input nodes and layers_ids[i+1] the hop-i
    samples (matches the reference's convention of including the roots)."""
    g = get_graph()
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    ids, w, t = g.sample_fanout(
        roots, counts, edge_types=edge_types, default_id=default_node
    )
    return [roots] + ids, w, t


def get_full_neighbor(nodes, edge_types=None):
    return get_graph().get_full_neighbor(nodes, edge_types=edge_types)


def get_sorted_full_neighbor(nodes, edge_types=None):
    return get_graph().get_full_neighbor(
        nodes, edge_types=edge_types, sorted_by_id=True
    )


def get_neighbor_edges(nodes, edge_types=None):
    """Edges to each node's out-neighbors (reference API_GET_NB_EDGE /
    GQL outE): (offsets, src, dst, types, weights) CSR arrays whose
    triples chain into feature_ops.get_edge_dense_feature."""
    return get_graph().get_neighbor_edges(nodes, edge_types=edge_types)


def get_top_k_neighbor(nodes, k: int, edge_types=None, default_node: int = 0):
    return get_graph().get_top_k_neighbor(
        nodes, k, edge_types=edge_types, default_id=default_node
    )


def sample_neighbor_layerwise(nodes, layer_sizes, edge_types=None,
                              default_node: int = 0,
                              weight_func: str = ""):
    """LADIES-style layerwise sampling (reference sampleLNB /
    SampleNeighborLayerwiseWithAdj). weight_func '' or 'sqrt' (the
    reference's hub-dampening transform of the accumulated candidate
    weight, local_sample_layer_op.cc:94)."""
    return get_graph().sample_layerwise(
        nodes, layer_sizes, edge_types=edge_types, default_id=default_node,
        weight_func=weight_func
    )


def sparse_get_adj(roots, nbr_ids, edge_types=None):
    """Adjacency between a root batch and a candidate neighbor set.

    Parity: reference SparseGetAdj (API_SPARSE_GET_ADJ,
    ops/euler_ops.cc:22-37; used by layerwise dataflows to connect each
    layer to the next layer's sampled pool).

    Returns (edge_index [2, E] int32, weights [E]) where edge_index[0]
    indexes `roots` rows and edge_index[1] indexes `nbr_ids` rows; only
    edges whose destination is in nbr_ids survive.
    """
    import numpy as np

    roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
    nbr_ids = np.ascontiguousarray(nbr_ids, dtype=np.uint64).ravel()
    pos = {int(v): i for i, v in enumerate(nbr_ids)}
    off, ids, w, _ = get_graph().get_full_neighbor(roots,
                                                   edge_types=edge_types)
    src_rows, dst_rows, ws = [], [], []
    for i in range(len(roots)):
        for j in range(int(off[i]), int(off[i + 1])):
            p = pos.get(int(ids[j]))
            if p is not None:
                src_rows.append(i)
                dst_rows.append(p)
                ws.append(w[j])
    return (np.array([src_rows, dst_rows], dtype=np.int32),
            np.array(ws, dtype=np.float32))
