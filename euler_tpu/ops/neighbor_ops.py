"""Neighbor ops over the global graph.

Parity: tf_euler/python/euler_ops/neighbor_ops.py (sample_neighbor,
sample_fanout at :122, get_full_neighbor, get_sorted_full_neighbor,
get_top_k_neighbor) — shapes are fixed/padded rather than SparseTensor.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.ops.base import get_graph


def sample_neighbor(nodes, count: int, edge_types=None, default_node: int = 0):
    return get_graph().sample_neighbor(
        nodes, count, edge_types=edge_types, default_id=default_node
    )


def sample_fanout(nodes, counts, edge_types=None, default_node: int = 0):
    """Multi-hop expansion; returns (layers_ids, layers_weights, layers_types)
    where layers_ids[0] is the input nodes and layers_ids[i+1] the hop-i
    samples (matches the reference's convention of including the roots)."""
    g = get_graph()
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    ids, w, t = g.sample_fanout(
        roots, counts, edge_types=edge_types, default_id=default_node
    )
    return [roots] + ids, w, t


def get_full_neighbor(nodes, edge_types=None):
    return get_graph().get_full_neighbor(nodes, edge_types=edge_types)


def get_sorted_full_neighbor(nodes, edge_types=None):
    return get_graph().get_full_neighbor(
        nodes, edge_types=edge_types, sorted_by_id=True
    )


def get_top_k_neighbor(nodes, k: int, edge_types=None, default_node: int = 0):
    return get_graph().get_top_k_neighbor(
        nodes, k, edge_types=edge_types, default_id=default_node
    )


def sample_neighbor_layerwise(nodes, layer_sizes, edge_types=None,
                              default_node: int = 0):
    """LADIES-style layerwise sampling (reference sampleLNB /
    SampleNeighborLayerwiseWithAdj)."""
    return get_graph().sample_layerwise(
        nodes, layer_sizes, edge_types=edge_types, default_id=default_node
    )
