"""Neighbor ops over the global graph.

Parity: tf_euler/python/euler_ops/neighbor_ops.py (sample_neighbor,
sample_fanout at :122, get_full_neighbor, get_sorted_full_neighbor,
get_top_k_neighbor) — shapes are fixed/padded rather than SparseTensor.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.gql import edge_types_str as _et_str
from euler_tpu.ops.base import get_graph, get_query


def sample_neighbor(nodes, count: int, edge_types=None,
                    default_node: int = 0, condition: str = ""):
    """condition (index DNF, e.g. "price gt 3") filters the sampled
    neighbors — the reference appends `.has(condition)` to the sampleNB
    gremlin the same way (sample_neighbor_op.cc:40)."""
    if not condition:
        return get_graph().sample_neighbor(
            nodes, count, edge_types=edge_types, default_id=default_node
        )
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    out = get_query().run(
        f"v(r).sampleNB({_et_str(edge_types)}, {int(count)}, "
        f"{int(default_node)}).has({condition}).as(nb)", {"r": roots})
    idx = out["nb:0"].reshape(-1, 2).astype(np.int64)
    n = roots.size
    ids = np.full((n, count), np.uint64(default_node), np.uint64)
    w = np.zeros((n, count), np.float32)
    t = np.zeros((n, count), np.int32)
    for i in range(min(n, idx.shape[0])):
        b, e = int(idx[i, 0]), int(idx[i, 1])
        m = min(e - b, count)
        ids[i, :m] = out["nb:1"][b:b + m]
        w[i, :m] = out["nb:2"][b:b + m]
        t[i, :m] = out["nb:3"][b:b + m]
    return ids, w, t


def sample_fanout(nodes, counts, edge_types=None, default_node: int = 0):
    """Multi-hop expansion; returns (layers_ids, layers_weights, layers_types)
    where layers_ids[0] is the input nodes and layers_ids[i+1] the hop-i
    samples (matches the reference's convention of including the roots)."""
    g = get_graph()
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    ids, w, t = g.sample_fanout(
        roots, counts, edge_types=edge_types, default_id=default_node
    )
    return [roots] + ids, w, t


def _conditioned_full_neighbor(nodes, edge_types, condition, verb):
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    out = get_query().run(
        f"v(r).{verb}({_et_str(edge_types)}).has({condition}).as(nb)",
        {"r": roots})
    idx = out["nb:0"].reshape(-1, 2)
    offsets = np.concatenate([[0], idx[:, 1]]).astype(np.uint64)
    return (offsets, out["nb:1"].astype(np.uint64),
            out["nb:2"].astype(np.float32), out["nb:3"].astype(np.int32))


def get_full_neighbor(nodes, edge_types=None, condition: str = ""):
    if condition:
        return _conditioned_full_neighbor(nodes, edge_types, condition,
                                          "getNB")
    return get_graph().get_full_neighbor(nodes, edge_types=edge_types)


def get_sorted_full_neighbor(nodes, edge_types=None, condition: str = ""):
    if condition:
        return _conditioned_full_neighbor(nodes, edge_types, condition,
                                          "getSortedNB")
    return get_graph().get_full_neighbor(
        nodes, edge_types=edge_types, sorted_by_id=True
    )


def get_neighbor_edges(nodes, edge_types=None):
    """Edges to each node's out-neighbors (reference API_GET_NB_EDGE /
    GQL outE): (offsets, src, dst, types, weights) CSR arrays whose
    triples chain into feature_ops.get_edge_dense_feature."""
    return get_graph().get_neighbor_edges(nodes, edge_types=edge_types)


def get_top_k_neighbor(nodes, k: int, edge_types=None,
                       default_node: int = 0, condition: str = ""):
    """condition filters candidate neighbors before the weight-ordered
    top-k (reference get_top_k_neighbor_op.cc:34: outE.has(cond)
    .order_by(weight, desc).limit(k))."""
    if not condition:
        return get_graph().get_top_k_neighbor(
            nodes, k, edge_types=edge_types, default_id=default_node
        )
    # node-attribute conditions filter the neighbor set (getNB.has,
    # index-backed), then weight-ordered top-k per row
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    off, nbr, w_all, t_all = _conditioned_full_neighbor(
        roots, edge_types, condition, "getNB")
    n = roots.size
    ids = np.full((n, k), np.uint64(default_node), np.uint64)
    w = np.zeros((n, k), np.float32)
    t = np.zeros((n, k), np.int32)
    for i in range(n):
        b, e = int(off[i]), int(off[i + 1])
        if e <= b:
            continue
        order = np.argsort(-w_all[b:e], kind="stable")[:k]
        m = order.size
        ids[i, :m] = nbr[b:e][order]
        w[i, :m] = w_all[b:e][order]
        t[i, :m] = t_all[b:e][order]
    return ids, w, t


def sample_neighbor_layerwise(nodes, layer_sizes, edge_types=None,
                              default_node: int = 0,
                              weight_func: str = ""):
    """LADIES-style layerwise sampling (reference sampleLNB /
    SampleNeighborLayerwiseWithAdj). weight_func '' or 'sqrt' (the
    reference's hub-dampening transform of the accumulated candidate
    weight, local_sample_layer_op.cc:94)."""
    return get_graph().sample_layerwise(
        nodes, layer_sizes, edge_types=edge_types, default_id=default_node,
        weight_func=weight_func
    )


def get_multi_hop_neighbor(nodes, edge_types_per_hop):
    """Full multi-hop expansion with inter-hop adjacency (reference
    neighbor_ops.py:209 get_multi_hop_neighbor).

    edge_types_per_hop: one edge-type filter per hop (None = all).
    Returns (nodes_list, adj_list): nodes_list[h] is the UNIQUE node ids
    of hop h (h=0 is the roots); adj_list[h] is the
    (edge_index [2, E] int32, weights [E]) sparse adjacency from
    nodes_list[h] rows to nodes_list[h+1] rows (the sparse_get_adj
    convention)."""
    g = get_graph()
    cur = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    nodes_list = [cur]
    adj_list = []
    for ets in edge_types_per_hop:
        off, ids, w, _ = g.get_full_neighbor(cur, edge_types=ets)
        nxt = np.unique(ids) if ids.size else np.zeros(0, np.uint64)
        src_rows = np.repeat(np.arange(cur.size),
                             np.diff(off.astype(np.int64)))
        # nxt is sorted-unique → vectorized position lookup (a dict per
        # hop would put O(E) Python work on the host feeder path)
        dst_rows = np.searchsorted(nxt, ids).astype(np.int32)
        adj_list.append((
            np.stack([src_rows.astype(np.int32), dst_rows]),
            np.asarray(w, np.float32)))
        nodes_list.append(nxt)
        cur = nxt
    return nodes_list, adj_list


def sample_fanout_layerwise_each_node(nodes, layer_counts, edge_types=None,
                                      default_node: int = 0):
    """Hop 1 = per-node sample_neighbor; later hops = one shared
    layerwise pool per hop (reference neighbor_ops.py:161). Returns the
    per-hop node arrays [roots, hop1, pool2, ...]."""
    g = get_graph()
    cur = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    out = [cur]
    for h, m in enumerate(layer_counts):
        if h == 0:
            nb, _, _ = g.sample_neighbor(cur, int(m),
                                         edge_types=edge_types,
                                         default_id=default_node)
            cur = nb.reshape(-1)
        else:
            cur = g.sample_layerwise(cur, [int(m)], edge_types=edge_types,
                                     default_id=default_node)[0]
        out.append(cur)
    return out


def sample_fanout_layerwise(nodes, layer_counts, edge_types=None,
                            default_node: int = 0, weight_func: str = ""):
    """Every hop a shared layerwise pool (reference neighbor_ops.py:189).
    Returns [roots, pool1, pool2, ...]."""
    g = get_graph()
    cur = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    out = [cur]
    for m in layer_counts:
        cur = g.sample_layerwise(cur, [int(m)], edge_types=edge_types,
                                 default_id=default_node,
                                 weight_func=weight_func)[0]
        out.append(cur)
    return out


def sample_fanout_with_feature(nodes, counts, edge_types=None,
                               default_node: int = 0,
                               dense_feature_names=(), dense_dimensions=(),
                               sparse_feature_names=()):
    """Fanout + per-hop feature fetch in one call (reference
    neighbor_ops.py:49 SampleFanoutWithFeature). Returns
    (neighbors, weights, types, dense_features, sparse_features):
    neighbors has len(counts)+1 per-hop id arrays (roots first);
    dense_features is hop-major then feature-major ([hop][feat] →
    [n_hop, dim]); sparse_features likewise with (offsets, values)
    CSR pairs."""
    g = get_graph()
    roots = np.ascontiguousarray(nodes, dtype=np.uint64).ravel()
    ids, w, t = g.sample_fanout(roots, list(counts),
                                edge_types=edge_types,
                                default_id=default_node)
    neighbors = [roots] + list(ids)
    # one native call PER FEATURE over the concatenated hops, split back
    # by hop sizes — not hops x features round trips (host feeder path)
    flat = np.concatenate(neighbors)
    splits = np.cumsum([len(h) for h in neighbors])[:-1]
    dense, sparse = [], []
    if dense_feature_names:
        dims = list(dense_dimensions) if dense_dimensions else None
        per_feat = g.get_dense_feature(flat, list(dense_feature_names),
                                       dims)
        by_hop = [np.split(f, splits) for f in per_feat]   # [feat][hop]
        dense = [[by_hop[f][h] for f in range(len(per_feat))]
                 for h in range(len(neighbors))]           # [hop][feat]
    if sparse_feature_names:
        per_feat_sp = []
        for fname in sparse_feature_names:
            offs, vals = g.get_sparse_feature(flat, fname)
            offs = offs.astype(np.int64)
            hop_feats = []
            lo = 0
            for h in neighbors:
                hi = lo + len(h)
                o = offs[lo:hi + 1] - offs[lo]
                hop_feats.append((o.astype(np.uint64),
                                  vals[offs[lo]:offs[hi]]))
                lo = hi
            per_feat_sp.append(hop_feats)                  # [feat][hop]
        sparse = [[per_feat_sp[f][h]
                   for f in range(len(sparse_feature_names))]
                  for h in range(len(neighbors))]          # [hop][feat]
    return neighbors, w, t, dense, sparse


def sparse_get_adj(roots, nbr_ids, edge_types=None):
    """Adjacency between a root batch and a candidate neighbor set.

    Parity: reference SparseGetAdj (API_SPARSE_GET_ADJ,
    ops/euler_ops.cc:22-37; used by layerwise dataflows to connect each
    layer to the next layer's sampled pool).

    Returns (edge_index [2, E] int32, weights [E]) where edge_index[0]
    indexes `roots` rows and edge_index[1] indexes `nbr_ids` rows; only
    edges whose destination is in nbr_ids survive.
    """
    import numpy as np

    roots = np.ascontiguousarray(roots, dtype=np.uint64).ravel()
    nbr_ids = np.ascontiguousarray(nbr_ids, dtype=np.uint64).ravel()
    pos = {int(v): i for i, v in enumerate(nbr_ids)}
    off, ids, w, _ = get_graph().get_full_neighbor(roots,
                                                   edge_types=edge_types)
    src_rows, dst_rows, ws = [], [], []
    for i in range(len(roots)):
        for j in range(int(off[i]), int(off[i + 1])):
            p = pos.get(int(ids[j]))
            if p is not None:
                src_rows.append(i)
                dst_rows.append(p)
                ws.append(w[j])
    return (np.array([src_rows, dst_rows], dtype=np.int32),
            np.array(ws, dtype=np.float32))
