"""Global-graph initialization — parity with the reference's
tf_euler.initialize_graph / initialize_embedded_graph / initialize_shared_graph
(tf_euler/python/euler_ops/base.py:37,63,70).

A process-global GraphEngine backs the functional ops in this package
(sample_ops, neighbor_ops, feature_ops, walk_ops). Models/dataflows may
also carry an explicit engine; the global is a convenience for scripts and
API parity.
"""

from __future__ import annotations

from typing import Optional

from euler_tpu.graph import GraphEngine

_GRAPH: Optional[GraphEngine] = None


def initialize_graph(config) -> GraphEngine:
    """Initialize the process-global graph.

    config: either a GraphEngine (adopted as-is), a directory path
    (embedded load), or a dict with keys {directory, shard_idx, shard_num,
    data_type} mirroring the reference's "k=v;..." config string.
    """
    global _GRAPH
    if isinstance(config, GraphEngine) or hasattr(config, "sample_fanout"):
        # embedded engine OR a RemoteGraphEngine / compatible client —
        # the reference's initialize_graph covers both modes too
        # (tf_euler/python/euler_ops/base.py:37 local vs remote config)
        _GRAPH = config
    elif isinstance(config, str):
        _GRAPH = GraphEngine.load(config)
    elif isinstance(config, dict):
        _GRAPH = GraphEngine.load(
            config["directory"],
            shard_idx=int(config.get("shard_idx", 0)),
            shard_num=int(config.get("shard_num", 1)),
            data_type=int(config.get("data_type", 0)),
        )
    else:
        raise TypeError(f"unsupported graph config: {type(config)}")
    return _GRAPH


def initialize_embedded_graph(directory: str, **kw) -> GraphEngine:
    return initialize_graph({"directory": directory, **kw})


def initialize_shared_graph(graph: GraphEngine) -> GraphEngine:
    return initialize_graph(graph)


def get_graph() -> GraphEngine:
    if _GRAPH is None:
        raise RuntimeError(
            "graph not initialized; call euler_tpu.ops.initialize_graph first"
        )
    return _GRAPH


_QUERY_CACHE: dict = {}
_INDEX_SPEC: str = ""


def set_index_spec(spec: str) -> None:
    """Declare the attribute indexes conditioned ops may use, e.g.
    "price:range_index;category:hash_index" (the reference builds these
    at data-prep time; conditions require a matching index there too).
    Rebuilds the cached query on next use."""
    global _INDEX_SPEC
    _INDEX_SPEC = spec
    _QUERY_CACHE.clear()


def get_query():
    """A Query bound to the global graph — backs the ops' `condition`
    parameters (the reference kernels append `.has(condition)` to their
    gremlin the same way, e.g. sample_neighbor_op.cc:40). Embedded
    engines get a cached Query.local built with set_index_spec's
    indexes (compile cache persists across calls); cluster engines
    reuse their own proxy (their shards' index spec is fixed at
    start_service time)."""
    g = get_graph()
    q = getattr(g, "query", None)
    if q is not None:  # RemoteGraphEngine carries its proxy
        return q
    key = (id(g), _INDEX_SPEC)
    cached = _QUERY_CACHE.get(key)
    if cached is None or cached[0]() is None:
        import weakref

        from euler_tpu.gql import Query

        cached = (weakref.ref(g), Query.local(g, index_spec=_INDEX_SPEC))
        _QUERY_CACHE.clear()  # one live entry: the current global graph
        _QUERY_CACHE[key] = cached
    return cached[1]
