from euler_tpu.ops import mp_ops  # noqa: F401
from euler_tpu.ops.base import (  # noqa: F401
    get_graph,
    initialize_embedded_graph,
    initialize_graph,
    initialize_shared_graph,
)
from euler_tpu.ops.feature_ops import (  # noqa: F401
    get_binary_feature,
    get_dense_feature,
    get_edge_binary_feature,
    get_edge_dense_feature,
    get_edge_sparse_feature,
    get_node_type,
    get_sparse_feature,
)
from euler_tpu.ops.neighbor_ops import (  # noqa: F401
    get_full_neighbor,
    get_multi_hop_neighbor,
    get_neighbor_edges,
    get_sorted_full_neighbor,
    get_top_k_neighbor,
    sample_fanout,
    sample_fanout_layerwise,
    sample_fanout_layerwise_each_node,
    sample_fanout_with_feature,
    sample_neighbor,
    sample_neighbor_layerwise,
    sparse_get_adj,
)
from euler_tpu.ops.sample_ops import (  # noqa: F401
    sample_edge,
    sample_node,
    sample_node_with_src,
    sample_node_with_types,
)
from euler_tpu.ops.type_ops import (  # noqa: F401
    ALL_NODE_TYPE,
    get_edge_type_id,
    get_node_type_id,
)
from euler_tpu.ops.walk_ops import gen_pair, random_walk  # noqa: F401
