from euler_tpu.ops import mp_ops  # noqa: F401
from euler_tpu.ops.base import (  # noqa: F401
    get_graph,
    initialize_embedded_graph,
    initialize_graph,
    initialize_shared_graph,
)
from euler_tpu.ops.feature_ops import (  # noqa: F401
    get_binary_feature,
    get_dense_feature,
    get_edge_binary_feature,
    get_edge_dense_feature,
    get_edge_sparse_feature,
    get_node_type,
    get_sparse_feature,
)
from euler_tpu.ops.neighbor_ops import (  # noqa: F401
    get_full_neighbor,
    get_neighbor_edges,
    get_sorted_full_neighbor,
    get_top_k_neighbor,
    sample_fanout,
    sample_neighbor,
    sample_neighbor_layerwise,
    sparse_get_adj,
)
from euler_tpu.ops.sample_ops import (  # noqa: F401
    sample_edge,
    sample_node,
    sample_node_with_types,
)
from euler_tpu.ops.walk_ops import gen_pair, random_walk  # noqa: F401
