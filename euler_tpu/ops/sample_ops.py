"""Root sampling ops over the global graph.

Parity: tf_euler/python/euler_ops/sample_ops.py:38 (sample_node),
sample_edge, sample_node_with_types, sample_graph_label analog.
Returns numpy uint64 arrays ready for jax.device_put.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.ops.base import get_graph, get_query


def sample_node(count: int, node_type: int = -1,
                condition: str = "") -> np.ndarray:
    """condition (index DNF, e.g. "price gt 3") restricts sampling to
    matching nodes — the reference's sample_node(condition) via
    `sampleN(...).has(...)` (sample_node_op.cc:61)."""
    if not condition:
        return get_graph().sample_node(count, node_type)
    out = get_query().run(
        f"sampleN({int(node_type)}, {int(count)}).has({condition}).as(n)")
    return out["n:0"].astype(np.uint64).ravel()


def sample_edge(count: int, edge_type: int = -1):
    return get_graph().sample_edge(count, edge_type)


def sample_node_with_types(types) -> np.ndarray:
    return get_graph().sample_node_with_types(types)


def sample_node_with_src(src_nodes, count: int) -> np.ndarray:
    """For each src node, sample `count` nodes of the SAME type —
    type-matched negatives (reference sample_ops.py:75
    sample_node_with_src = get_node_type + sample_n_with_types).
    Returns [len(src), count] uint64."""
    g = get_graph()
    src = np.ascontiguousarray(src_nodes, dtype=np.uint64).ravel()
    types = np.repeat(g.get_node_type(src), count)
    return g.sample_node_with_types(types).reshape(src.size, count)
