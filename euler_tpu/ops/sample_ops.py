"""Root sampling ops over the global graph.

Parity: tf_euler/python/euler_ops/sample_ops.py:38 (sample_node),
sample_edge, sample_node_with_types, sample_graph_label analog.
Returns numpy uint64 arrays ready for jax.device_put.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.ops.base import get_graph


def sample_node(count: int, node_type: int = -1) -> np.ndarray:
    return get_graph().sample_node(count, node_type)


def sample_edge(count: int, edge_type: int = -1):
    return get_graph().sample_edge(count, edge_type)


def sample_node_with_types(types) -> np.ndarray:
    return get_graph().sample_node_with_types(types)
