"""Random-walk ops.

Parity: tf_euler/python/euler_ops/walk_ops.py (random_walk :29, gen_pair
:25 — node2vec walks and skip-gram pair generation).
"""

from __future__ import annotations

import numpy as np

from euler_tpu.ops.base import get_graph


def random_walk(nodes, walk_len: int, p: float = 1.0, q: float = 1.0,
                edge_types=None, default_node: int = 0) -> np.ndarray:
    """[n, walk_len+1] uint64 walks, column 0 = the input nodes."""
    return get_graph().random_walk(
        nodes, walk_len, p=p, q=q, edge_types=edge_types,
        default_id=default_node,
    )


def gen_pair(paths: np.ndarray, left_win_size: int,
             right_win_size: int) -> np.ndarray:
    """Skip-gram (center, context) pairs from walk paths.

    paths: [n, L]. Returns [n, num_pairs, 2] where pairs pad with the path's
    own center when the window clips at the boundary (keeps the shape
    static; such self-pairs are harmless for negative-sampling losses).
    """
    paths = np.asarray(paths)
    n, L = paths.shape
    pairs = []
    for i in range(L):
        for off in range(-left_win_size, right_win_size + 1):
            if off == 0:
                continue
            j = i + off
            if j < 0 or j >= L:
                continue
            pairs.append(np.stack([paths[:, i], paths[:, j]], axis=1))
    if not pairs:
        return np.zeros((n, 0, 2), dtype=paths.dtype)
    return np.stack(pairs, axis=1)
