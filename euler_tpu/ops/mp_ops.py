"""Message-passing primitives in JAX.

Parity with the reference's tf_euler mp ops (MPGather / MPScatterAdd /
MPScatterMax + registered gradients, tf_euler/python/euler_ops/mp_ops.py:27-77
and kernels gather_op.cc / scatter_op.cc). TPU-first redesign: these are
thin, jit-able wrappers over XLA segment ops — gradients come from JAX
autodiff instead of hand-registered gradient functions, and everything
fuses into the surrounding computation under jit.

Conventions: `index` maps each message row to its destination segment;
`num_segments` must be static under jit (pass it explicitly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "gather",
    "scatter_add",
    "scatter_mean",
    "scatter_max",
    "scatter_softmax",
    "segment_count",
    "degree_norm",
]


def gather(params: jax.Array, indices: jax.Array) -> jax.Array:
    """params[indices] — row gather (reference MPGather)."""
    return jnp.take(params, indices, axis=0)


def scatter_add(src: jax.Array, index: jax.Array, num_segments: int) -> jax.Array:
    """Sum rows of `src` into `num_segments` buckets (reference MPScatterAdd)."""
    return jax.ops.segment_sum(src, index, num_segments=num_segments)


def scatter_mean(src: jax.Array, index: jax.Array, num_segments: int) -> jax.Array:
    total = jax.ops.segment_sum(src, index, num_segments=num_segments)
    count = segment_count(index, num_segments)
    return total / jnp.maximum(count, 1.0)[:, None] if total.ndim > 1 else (
        total / jnp.maximum(count, 1.0)
    )


def scatter_max(src: jax.Array, index: jax.Array, num_segments: int) -> jax.Array:
    """Max-reduce rows into buckets; empty buckets yield 0 (reference
    MPScatterMax fills with a large negative then relies on later ops —
    here empty segments are clamped to 0 for stability)."""
    out = jax.ops.segment_max(src, index, num_segments=num_segments)
    return jnp.where(jnp.isfinite(out), out, 0.0)


def segment_count(index: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_sum(
        jnp.ones(index.shape[0], dtype=jnp.float32), index,
        num_segments=num_segments,
    )


def scatter_softmax(logits: jax.Array, index: jax.Array,
                    num_segments: int) -> jax.Array:
    """Per-segment softmax over a flat logit vector (GAT attention).

    Numerically stable: subtracts the per-segment max before exp.
    """
    seg_max = jax.ops.segment_max(logits, index, num_segments=num_segments)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    shifted = logits - seg_max[index]
    ex = jnp.exp(shifted)
    denom = jax.ops.segment_sum(ex, index, num_segments=num_segments)
    return ex / jnp.maximum(denom[index], 1e-16)


def degree_norm(edge_index: jax.Array, num_nodes: int,
                add_self_loops: bool = True) -> jax.Array:
    """Symmetric GCN normalization coefficients per edge:
    1/sqrt(deg(src) * deg(dst)). edge_index is [2, E] (src, dst)."""
    src, dst = edge_index[0], edge_index[1]
    ones = jnp.ones(src.shape[0], dtype=jnp.float32)
    deg = jax.ops.segment_sum(ones, dst, num_segments=num_nodes)
    if add_self_loops:
        deg = deg + 1.0
    dinv = jax.lax.rsqrt(jnp.maximum(deg, 1.0))
    return dinv[src] * dinv[dst]
