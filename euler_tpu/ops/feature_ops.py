"""Feature ops over the global graph.

Parity: tf_euler/python/euler_ops/feature_ops.py:111 (get_dense_feature and
the sparse/binary + edge variants backed by Get*Feature TF kernels).
"""

from __future__ import annotations

from euler_tpu.ops.base import get_graph


def get_dense_feature(nodes, feature_ids, dims=None):
    return get_graph().get_dense_feature(nodes, feature_ids, dims)


def get_sparse_feature(nodes, feature_id):
    return get_graph().get_sparse_feature(nodes, feature_id)


def get_binary_feature(nodes, feature_id):
    return get_graph().get_binary_feature(nodes, feature_id)


def get_edge_dense_feature(src, dst, types, feature_ids, dims=None):
    return get_graph().get_edge_dense_feature(src, dst, types, feature_ids, dims)


def get_edge_sparse_feature(src, dst, types, feature_id):
    return get_graph().get_edge_sparse_feature(src, dst, types, feature_id)


def get_edge_binary_feature(src, dst, types, feature_id):
    """(offsets, bytes) CSR of per-edge raw byte strings (parity:
    tf_euler GetEdgeBinaryFeature, kernels/get_edge_binary_feature_op.cc)."""
    return get_graph().get_edge_binary_feature(src, dst, types, feature_id)


def get_node_type(nodes):
    return get_graph().get_node_type(nodes)
