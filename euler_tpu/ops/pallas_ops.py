"""Pallas TPU kernels for the hot host-feeder ops.

The fanout training path gathers each output row's k neighbor feature
rows from the HBM-resident table and mean-reduces them:
    out[i] = mean_j table[rows[i, j]]      # rows: [n, k] int32
XLA expresses this as gather → reshape → mean, materializing the
[n·k, D] intermediate in HBM (written then re-read: 2·n·k·D·4 bytes of
traffic). The fused kernel streams each neighbor row HBM→VMEM once and
accumulates in VMEM, cutting HBM traffic to n·k·D·4 + n·D·4.

CLOSED NEGATIVE RESULT (round 5 — PERF.md "Pallas gather: closed").
gather_mean() defaults to the XLA formulation and that is the final
verdict, not an interim one:
- Small-scale (200k x 128 table): the fused kernel was within 2x of
  XLA's gather in either direction, no reproducible win.
- Per-row DMA cost analysis (round 4): at d=100 bf16 a row is ~200B,
  so each async copy moves less than one 512B HBM burst and the
  issue/semaphore overhead dominates — the per-row design loses
  regardless of tile_n.
- The last credible configuration — 128B-aligned int8 rows
  (int8 + pad128, one aligned burst per row) — could not even be
  compiled: all four products-scale probes (t8 / pad128 / onesem /
  onesem+pad128) crash this environment's remote Mosaic compiler with
  HTTP 500 (round-5 window, .bench_cache/profile_tpu.json), and the
  meaningful XLA-side A/Bs (pad128 59.6ms vs plain 59.8ms vs
  promise_in_bounds 58.6ms on the 4.9M-row hop-2 gather) show the
  gather is HBM-random-access-bound, not layout-bound.
The hop-2 gather was instead removed structurally (the in-jit
historical-activation cache, parallel/encoders — 4.2x step-time win).
The kernel below stays as the validated template for neighbor-indexed
fusions XLA can't express (interpret-mode tests pin numerics), not as
a performance path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

Array = jax.Array

# default output rows per grid step: amortizes control overhead while
# keeping k·D scratch well under VMEM
_TILE_N = 8


def _xla_gather_mean(table: Array, rows: Array) -> Array:
    n, k = rows.shape
    return jnp.take(table, rows.reshape(-1), axis=0) \
        .reshape(n, k, table.shape[-1]).mean(axis=1)


def _kernel(rows_ref, table_ref, out_ref, scratch, sems, *,
            one_sem: bool):
    """One grid step: gather k rows for each of tile_n outputs, reduce.
    rows_ref is this step's (tile_n, k) index block in SMEM. All
    tile_n·k row fetches are in flight at once (start all, then wait) —
    serializing them makes the kernel DMA-latency-bound.

    one_sem selects the semaphore layout: a per-copy semaphore array
    (sems.at[idx]) vs ONE shared DMA semaphore every copy signals and
    each wait consumes once — the dynamically-indexed array is a
    suspect for the remote Mosaic compiler crash seen on TPU, so the
    profiler A/Bs both layouts over the same body."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tile_n, k = rows_ref.shape

    def dma_for(idx):
        row = rows_ref[idx // k, idx % k]
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(row, 1), :],
            scratch.at[pl.ds(idx, 1), :],
            sems if one_sem else sems.at[idx],
        )

    def start(idx, _):
        dma_for(idx).start()
        return 0

    def wait(idx, _):
        dma_for(idx).wait()
        return 0

    jax.lax.fori_loop(0, tile_n * k, start, 0)
    jax.lax.fori_loop(0, tile_n * k, wait, 0)
    d = scratch.shape[-1]
    out_ref[:, :] = jnp.mean(scratch[:, :].reshape(tile_n, k, d), axis=1)


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "interpret", "one_sem"))
def _pallas_gather_mean(table: Array, rows: Array, tile_n: int = _TILE_N,
                        interpret: bool = False,
                        one_sem: bool = False) -> Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, k = rows.shape
    d = table.shape[-1]
    assert n % tile_n == 0
    return pl.pallas_call(
        functools.partial(_kernel, one_sem=one_sem),
        grid=(n // tile_n,),
        in_specs=[
            # this step's index block rides SMEM (DMA addresses are
            # scalar reads); the table stays wherever it lives (HBM)
            pl.BlockSpec((tile_n, k), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((tile_n, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((tile_n * k, d), table.dtype),
            pltpu.SemaphoreType.DMA if one_sem
            else pltpu.SemaphoreType.DMA((tile_n * k,)),
        ],
        out_shape=jax.ShapeDtypeStruct((n, d), table.dtype),
        interpret=interpret,
    )(rows, table)


def gather_mean(table: Array, rows: Array,
                use_pallas: bool = False, tile_n: int = _TILE_N) -> Array:
    """out[i] = mean over k of table[rows[i]]; rows [n, k] int32.

    use_pallas=True runs the fused Pallas kernel on TPU when shapes allow
    (n divisible by the row tile); default is the XLA gather+mean (see
    module docstring for the measured tradeoff).
    """
    n, k = rows.shape
    on_tpu = jax.default_backend() == "tpu"
    if not use_pallas or not on_tpu or n % tile_n != 0:
        return _xla_gather_mean(table, rows)
    return _pallas_gather_mean(table, rows, tile_n=tile_n)
