"""GraphSAGE layer. Parity: tf_euler/python/convolution/sage_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class SAGEConv(nn.Module):
    """x' = W · concat(x_tgt, mean_{j∈N(i)} x_j), optional L2 normalize."""

    out_dim: int
    normalize: bool = False
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        nbr = mp.scatter_mean(mp.gather(x_src, edge_index[0]), edge_index[1], n)
        out = nn.Dense(self.out_dim, use_bias=self.use_bias, name="lin")(
            jnp.concatenate([x_tgt[:n], nbr], axis=-1)
        )
        if self.normalize:
            out = out / jnp.maximum(
                jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-12
            )
        return out
