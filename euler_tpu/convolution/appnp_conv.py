"""APPNP layer (personalized-PageRank propagation).
Parity: tf_euler/python/convolution/appnp_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class APPNPConv(nn.Module):
    """z^{k+1} = (1-α) Â z^k + α h, K iterations; h is the input prediction.

    The K-step loop runs as a compile-time-unrolled scan over the shared
    normalized adjacency (K is static).
    """

    k_hop: int = 10
    alpha: float = 0.1

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        if x_src is not x_tgt:
            raise ValueError("APPNPConv requires a shared node set (non-bipartite)")
        n = num_nodes if num_nodes is not None else x_src.shape[0]
        src, dst = edge_index[0], edge_index[1]
        ones = jnp.ones(src.shape[0], dtype=jnp.float32)
        deg = jax.ops.segment_sum(ones, dst, num_segments=n) + 1.0
        deg_s = jax.ops.segment_sum(ones, src, num_segments=n) + 1.0
        norm = jax.lax.rsqrt(deg_s)[src] * jax.lax.rsqrt(deg)[dst]
        self_norm = (1.0 / deg)

        def propagate(z):
            agg = mp.scatter_add(mp.gather(z, src) * norm[:, None], dst, n)
            return agg + z * self_norm[:, None]

        h = x_src
        z = h

        def body(z, _):
            return (1.0 - self.alpha) * propagate(z) + self.alpha * h, None

        z, _ = jax.lax.scan(body, z, None, length=self.k_hop)
        return z
