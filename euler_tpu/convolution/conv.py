"""Base machinery for message-passing convolution layers.

Parity: the reference's conv base (tf_euler/python/convolution/conv.py —
gather by edge_index, message, scatter-aggregate, update), redesigned as
flax.linen modules over XLA segment ops: under jit the gather/segment ops
fuse with the surrounding matmuls, and autodiff supplies gradients (the
reference registers TF gradients by hand in mp_ops.py:39-57).

Conventions:
  x           [N, D] node features, or (x_src, x_tgt) for bipartite blocks
  edge_index  [2, E] int32; row 0 = message source, row 1 = destination
  num_nodes   static destination count (required under jit)
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp

Array = jax.Array
XInput = Union[Array, Tuple[Array, Array]]


def split_x(x: XInput) -> Tuple[Array, Array]:
    """Returns (x_src, x_tgt); a single array serves as both."""
    if isinstance(x, tuple):
        return x
    return x, x


def aggregate(msgs: Array, dst: Array, num_nodes: int, aggr: str) -> Array:
    if aggr == "add" or aggr == "sum":
        return mp.scatter_add(msgs, dst, num_nodes)
    if aggr == "mean":
        return mp.scatter_mean(msgs, dst, num_nodes)
    if aggr == "max":
        return mp.scatter_max(msgs, dst, num_nodes)
    raise ValueError(f"unknown aggregation: {aggr}")


class Conv(nn.Module):
    """Generic message-passing layer: linear → propagate → update.

    Subclasses override message()/update() semantics inline in __call__;
    this base exists for user-defined layers and mirrors the reference's
    Conv contract.
    """

    out_dim: int
    aggr: str = "add"

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        h = nn.Dense(self.out_dim, name="lin")(x_src)
        msgs = mp.gather(h, edge_index[0])
        return aggregate(msgs, edge_index[1], n, self.aggr)
