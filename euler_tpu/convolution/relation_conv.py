"""Relational conv (R-GCN style per-edge-type transforms).
Parity: tf_euler/python/convolution/relation_conv.py + RelationDataFlow."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class RelationConv(nn.Module):
    """x' = σ(W_0 x + Σ_r Σ_{j∈N_r(i)} (1/c_{i,r}) W_r x_j).

    edge_type: [E] int32 relation per edge. One einsum over a stacked
    [R, D_in, D_out] weight tensor instead of R separate matmuls — the
    one-hot relation mixing keeps the MXU busy and shapes static (no
    per-relation boolean masking).
    """

    out_dim: int
    num_relations: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 edge_type: Optional[Array] = None,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        d_in = x_src.shape[-1]
        if edge_type is None:
            edge_type = jnp.zeros(edge_index.shape[1], dtype=jnp.int32)
        w_rel = self.param(
            "w_rel", nn.initializers.glorot_uniform(),
            (self.num_relations, d_in, self.out_dim),
        )
        src, dst = edge_index[0], edge_index[1]
        msgs = mp.gather(x_src, src)                       # [E, D_in]
        w_e = w_rel[edge_type]                             # [E, D_in, D_out]
        msgs = jnp.einsum("ed,edo->eo", msgs, w_e)         # per-edge transform
        # mean within (dst, relation): normalize by count of same-relation
        # in-edges c_{i,r}
        seg = dst * self.num_relations + edge_type
        cnt = mp.segment_count(seg, n * self.num_relations)
        msgs = msgs / jnp.maximum(cnt[seg], 1.0)[:, None]
        agg = mp.scatter_add(msgs, dst, n)
        out = agg + nn.Dense(self.out_dim, use_bias=self.use_bias,
                             name="lin_root")(x_tgt[:n])
        return out
