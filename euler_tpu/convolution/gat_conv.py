"""GAT layer (Veličković et al.). Parity: tf_euler/python/convolution/gat_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class GATConv(nn.Module):
    """Multi-head additive attention over edges + implicit self-loops.

    heads are concatenated (concat=True) or averaged; per-edge softmax uses
    the numerically-stable segment softmax from mp_ops.
    """

    out_dim: int
    heads: int = 1
    concat: bool = True
    negative_slope: float = 0.2
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        H, D = self.heads, self.out_dim
        w = nn.Dense(H * D, use_bias=False, name="lin")
        h_src = w(x_src).reshape(-1, H, D)
        h_tgt = h_src if x_src is x_tgt else w(x_tgt).reshape(-1, H, D)
        a_src = self.param("att_src", nn.initializers.glorot_uniform(), (1, H, D))
        a_dst = self.param("att_dst", nn.initializers.glorot_uniform(), (1, H, D))
        alpha_src = (h_src * a_src).sum(-1)  # [N_src, H]
        alpha_dst = (h_tgt * a_dst).sum(-1)  # [N_tgt, H]
        src, dst = edge_index[0], edge_index[1]
        # self-loop edges appended virtually: compute edge logits for real
        # edges and for each node's self edge, softmax over both.
        e_alpha = alpha_src[src] + alpha_dst[dst]          # [E, H]
        s_alpha = alpha_src[:n] + alpha_dst[:n] if x_src is x_tgt else (
            alpha_dst[:n] * 2.0
        )
        e_alpha = nn.leaky_relu(e_alpha, self.negative_slope)
        s_alpha = nn.leaky_relu(s_alpha, self.negative_slope)
        # All heads at once: segment ops reduce along axis 0 of [E+n, H(,D)].
        logits = jnp.concatenate([e_alpha, s_alpha], axis=0)       # [E+n, H]
        index = jnp.concatenate([dst, jnp.arange(n, dtype=dst.dtype)])
        att = mp.scatter_softmax(logits, index, n)                  # [E+n, H]
        msgs = jnp.concatenate([h_src[src], h_tgt[:n]], axis=0)     # [E+n, H, D]
        out = mp.scatter_add(msgs * att[:, :, None], index, n)      # [n, H, D]
        out = out.reshape(n, H * D) if self.concat else out.mean(axis=1)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (H * D if self.concat else D,))
            out = out + bias
        return out
