"""GCN layer (Kipf & Welling). Parity: tf_euler/python/convolution/gcn_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class GCNConv(nn.Module):
    """x' = D̂^{-1/2} Â D̂^{-1/2} x W with self-loops folded in.

    Self-loops are applied implicitly (the node's own transformed feature
    joins the sum with the proper norm) so edge_index never needs mutation —
    shapes stay static under jit. On bipartite blocks (sampled fanouts) the
    symmetric norm degenerates to 1/d̂_dst (row normalization), matching the
    reference's sampled-subgraph behavior.
    """

    out_dim: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        bipartite = x_src is not x_tgt
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        w = nn.Dense(self.out_dim, use_bias=False, name="lin")
        h_src = w(x_src)
        h_tgt = h_src if not bipartite else w(x_tgt)
        src, dst = edge_index[0], edge_index[1]
        ones = jnp.ones(src.shape[0], dtype=jnp.float32)
        deg_dst = jax.ops.segment_sum(ones, dst, num_segments=n) + 1.0
        inv_sqrt_dst = jax.lax.rsqrt(deg_dst)
        if bipartite:
            # row-normalized: 1/d̂_dst per incoming edge + self at 1/d̂_dst
            norm = (1.0 / deg_dst)[dst]
            self_norm = 1.0 / deg_dst
        else:
            deg_src = jax.ops.segment_sum(ones, src,
                                          num_segments=x_src.shape[0]) + 1.0
            norm = jax.lax.rsqrt(deg_src)[src] * inv_sqrt_dst[dst]
            self_norm = 1.0 / deg_dst
        msgs = mp.gather(h_src, src) * norm[:, None]
        out = mp.scatter_add(msgs, dst, n)
        out = out + h_tgt[:n] * self_norm[:, None]
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.out_dim,))
            out = out + bias
        return out
