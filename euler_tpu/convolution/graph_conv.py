"""GraphConv layer (Morris et al. weighted-sum variant).
Parity: tf_euler/python/convolution/graph_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, aggregate, split_x


class GraphConv(nn.Module):
    """x' = W1 x + W2 · aggr_{j∈N(i)} w_ij x_j."""

    out_dim: int
    aggr: str = "add"
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None,
                 edge_weight: Optional[Array] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        msgs = mp.gather(x_src, edge_index[0])
        if edge_weight is not None:
            msgs = msgs * edge_weight[:, None]
        agg = aggregate(msgs, edge_index[1], n, self.aggr)
        return (
            nn.Dense(self.out_dim, use_bias=self.use_bias, name="lin_root")(x_tgt[:n])
            + nn.Dense(self.out_dim, use_bias=False, name="lin_nbr")(agg)
        )
