"""ARMA layer (auto-regressive moving-average graph filter).
Parity: tf_euler/python/convolution/arma_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class ARMAConv(nn.Module):
    """K parallel ARMA_1 stacks of depth T, averaged:
    z^{t+1} = σ(L̂ z^t W + x V).
    """

    out_dim: int
    num_stacks: int = 1
    num_layers: int = 1
    dropout: float = 0.0
    deterministic: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        if x_src is not x_tgt:
            raise ValueError("ARMAConv requires a shared node set")
        n = num_nodes if num_nodes is not None else x_src.shape[0]
        src, dst = edge_index[0], edge_index[1]
        ones = jnp.ones(src.shape[0], dtype=jnp.float32)
        deg = jax.ops.segment_sum(ones, dst, num_segments=n) + 1.0
        deg_s = jax.ops.segment_sum(ones, src, num_segments=n) + 1.0
        norm = jax.lax.rsqrt(deg_s)[src] * jax.lax.rsqrt(deg)[dst]

        def lap(z):
            return mp.scatter_add(mp.gather(z, src) * norm[:, None], dst, n)

        stacks = []
        for s in range(self.num_stacks):
            z = x_src
            for t in range(self.num_layers):
                root = nn.Dense(self.out_dim, use_bias=False,
                                name=f"v_{s}_{t}")(x_src)
                z = nn.Dense(self.out_dim, use_bias=True,
                             name=f"w_{s}_{t}")(z)
                z = nn.relu(lap(z) + root)
            stacks.append(z)
        return jnp.mean(jnp.stack(stacks, axis=0), axis=0)
