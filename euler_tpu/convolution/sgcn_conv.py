"""Simplified GCN (SGC) layer. Parity: tf_euler/python/convolution/sgcn_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class SGCNConv(nn.Module):
    """x' = Â^K x W — K propagation steps, a single linear layer."""

    out_dim: int
    k_hop: int = 2
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        if x_src is not x_tgt:
            raise ValueError("SGCNConv requires a shared node set")
        n = num_nodes if num_nodes is not None else x_src.shape[0]
        src, dst = edge_index[0], edge_index[1]
        ones = jnp.ones(src.shape[0], dtype=jnp.float32)
        deg = jax.ops.segment_sum(ones, dst, num_segments=n) + 1.0
        deg_s = jax.ops.segment_sum(ones, src, num_segments=n) + 1.0
        norm = jax.lax.rsqrt(deg_s)[src] * jax.lax.rsqrt(deg)[dst]
        self_norm = 1.0 / deg

        def body(z, _):
            agg = mp.scatter_add(mp.gather(z, src) * norm[:, None], dst, n)
            return agg + z * self_norm[:, None], None

        z, _ = jax.lax.scan(body, x_src, None, length=self.k_hop)
        return nn.Dense(self.out_dim, use_bias=self.use_bias, name="lin")(z)
