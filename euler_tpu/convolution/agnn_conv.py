"""AGNN layer (Attention-based GNN). Parity: tf_euler/python/convolution/agnn_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class AGNNConv(nn.Module):
    """Propagation P where P_ij = softmax_j(β · cos(x_i, x_j)); β learned."""

    requires_grad: bool = True

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        if self.requires_grad:
            beta = self.param("beta", nn.initializers.ones, (1,))
        else:
            beta = jnp.ones((1,))
        norm_src = x_src / jnp.maximum(
            jnp.linalg.norm(x_src, axis=-1, keepdims=True), 1e-12)
        norm_tgt = x_tgt / jnp.maximum(
            jnp.linalg.norm(x_tgt, axis=-1, keepdims=True), 1e-12)
        src, dst = edge_index[0], edge_index[1]
        # self-loops appended virtually (node attends to itself too)
        cos = (norm_src[src] * norm_tgt[dst]).sum(-1)
        self_cos = jnp.ones(n, dtype=cos.dtype)
        logits = beta[0] * jnp.concatenate([cos, self_cos])
        index = jnp.concatenate([dst, jnp.arange(n, dtype=dst.dtype)])
        att = mp.scatter_softmax(logits, index, n)
        msgs = jnp.concatenate([x_src[src], x_tgt[:n]], axis=0)
        return mp.scatter_add(msgs * att[:, None], index, n)
