"""Gated Graph Conv (GGNN, Li et al.).
Parity: tf_euler/python/convolution/gated_graph_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class GatedGraphConv(nn.Module):
    """h^{t+1} = GRU(Σ_j W_t h_j, h^t) for num_layers steps.

    Input features are zero-padded to out_dim (reference pads likewise).
    """

    out_dim: int
    num_layers: int = 1

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        if x_src is not x_tgt:
            raise ValueError("GatedGraphConv requires a shared node set")
        n = num_nodes if num_nodes is not None else x_src.shape[0]
        d_in = x_src.shape[-1]
        if d_in > self.out_dim:
            raise ValueError("input dim must be <= out_dim")
        h = jnp.pad(x_src, ((0, 0), (0, self.out_dim - d_in)))
        gru = nn.GRUCell(features=self.out_dim, name="gru")
        src, dst = edge_index[0], edge_index[1]
        for t in range(self.num_layers):
            m = nn.Dense(self.out_dim, use_bias=False, name=f"w_{t}")(h)
            agg = mp.scatter_add(mp.gather(m, src), dst, n)
            h, _ = gru(h, agg)
        return h
