"""GIN layer (graph isomorphism network). Parity: tf_euler/python/convolution/gin_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array, XInput, split_x


class GINConv(nn.Module):
    """x' = MLP((1+ε) x + Σ_{j∈N(i)} x_j); ε learnable when train_eps."""

    out_dim: int
    hidden_dim: int = 0  # 0 → out_dim
    eps: float = 0.0
    train_eps: bool = False

    @nn.compact
    def __call__(self, x: XInput, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        x_src, x_tgt = split_x(x)
        n = num_nodes if num_nodes is not None else x_tgt.shape[0]
        agg = mp.scatter_add(mp.gather(x_src, edge_index[0]), edge_index[1], n)
        if self.train_eps:
            eps = self.param("eps", nn.initializers.constant(self.eps), (1,))[0]
        else:
            eps = self.eps
        h = (1.0 + eps) * x_tgt[:n] + agg
        hidden = self.hidden_dim or self.out_dim
        h = nn.relu(nn.Dense(hidden, name="mlp_0")(h))
        return nn.Dense(self.out_dim, name="mlp_1")(h)
