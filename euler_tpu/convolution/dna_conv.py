"""DNA layer (dynamic neighborhood aggregation, Fey 2019).
Parity: tf_euler/python/convolution/dna_conv.py."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import mp_ops as mp
from euler_tpu.convolution.conv import Array


class DNAConv(nn.Module):
    """Attention over the layer history of each neighbor:
    h_i^{t+1} = Σ_j softmax_j(q(h_i^{≤t}) · k(h_j^{≤t})) v(h_j^{≤t}).

    x here is the stacked history [N, T, D] (grows by one layer per call in
    the model loop). Query is the node's latest layer; keys/values attend
    over each neighbor's whole history via scaled dot-product.
    """

    out_dim: int
    heads: int = 1

    @nn.compact
    def __call__(self, x: Array, edge_index: Array,
                 num_nodes: Optional[int] = None) -> Array:
        if x.ndim != 3:
            raise ValueError("DNAConv expects stacked history [N, T, D]")
        n = num_nodes if num_nodes is not None else x.shape[0]
        N, T, D = x.shape
        H = self.heads
        dh = self.out_dim // H
        q_w = nn.Dense(self.out_dim, use_bias=False, name="q")
        k_w = nn.Dense(self.out_dim, use_bias=False, name="k")
        v_w = nn.Dense(self.out_dim, use_bias=False, name="v")
        # attention runs over N(i) ∪ {i}: append virtual self-loop edges
        # (the paper's formulation; without them a node's own history only
        # enters through the query and the update loses its skip path)
        loop = jnp.arange(n, dtype=edge_index.dtype)
        src = jnp.concatenate([edge_index[0], loop])
        dst = jnp.concatenate([edge_index[1], loop])
        # per-edge: query = dst's latest layer; key/value = src's history
        q = q_w(x[:, -1, :]).reshape(N, H, dh)[dst]          # [E, H, dh]
        k = k_w(x).reshape(N, T, H, dh)[src]                 # [E, T, H, dh]
        v = v_w(x).reshape(N, T, H, dh)[src]
        logits = (k * q[:, None]).sum(-1) / jnp.sqrt(float(dh))  # [E, T, H]
        att = nn.softmax(logits, axis=1)
        per_edge = (att[..., None] * v).sum(axis=1)          # [E, H, dh]
        per_edge = per_edge.reshape(-1, self.out_dim)
        return mp.scatter_mean(per_edge, dst, n)
