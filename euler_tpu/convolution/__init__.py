"""Message-passing convolution zoo (parity: tf_euler/python/convolution/,
14 layers — SURVEY.md §2.3)."""

from euler_tpu.convolution.conv import Conv, aggregate, split_x  # noqa: F401
from euler_tpu.convolution.agnn_conv import AGNNConv  # noqa: F401
from euler_tpu.convolution.appnp_conv import APPNPConv  # noqa: F401
from euler_tpu.convolution.arma_conv import ARMAConv  # noqa: F401
from euler_tpu.convolution.dna_conv import DNAConv  # noqa: F401
from euler_tpu.convolution.gat_conv import GATConv  # noqa: F401
from euler_tpu.convolution.gated_graph_conv import GatedGraphConv  # noqa: F401
from euler_tpu.convolution.gcn_conv import GCNConv  # noqa: F401
from euler_tpu.convolution.gin_conv import GINConv  # noqa: F401
from euler_tpu.convolution.graph_conv import GraphConv  # noqa: F401
from euler_tpu.convolution.relation_conv import RelationConv  # noqa: F401
from euler_tpu.convolution.sage_conv import SAGEConv  # noqa: F401
from euler_tpu.convolution.sgcn_conv import SGCNConv  # noqa: F401
from euler_tpu.convolution.tag_conv import TAGConv  # noqa: F401
