"""ctypes loader for the native graph engine (libeuler_core.so).

Parity: the reference loads libeuler_core.so / libtf_euler.so via ctypes
(euler/python/start_service.py:27-30, tf_euler/python/euler_ops/base.py).
Here there is a single library exposing the batch C API defined in
euler_tpu/core/cc/capi.cc; this module declares argtypes once and exposes
the raw handle-based functions. Use euler_tpu.graph.GraphEngine for the
numpy-facing wrapper.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libeuler_core.so")

_lib = None


def _build() -> None:
    proc = subprocess.run(
        ["make", "-C", os.path.join(_HERE, "cc"), "-j", "4"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            "native engine build failed:\n" + proc.stdout + proc.stderr
        )


def _stale() -> bool:
    """True when the .so must be (re)built before loading.

    A missing library always triggers a build. The mtime-vs-source check
    is a developer convenience only, gated behind EULER_TPU_DEV_REBUILD:
    a fresh checkout or container copy can legitimately carry sources
    newer than a prebuilt .so, and surprise-compiling at import (or hard-
    failing where no compiler exists) is worse than using the prebuilt.
    """
    if not os.path.exists(_LIB_PATH):
        return True
    if not os.environ.get("EULER_TPU_DEV_REBUILD"):
        return False
    so_mtime = os.path.getmtime(_LIB_PATH)
    cc = os.path.join(_HERE, "cc")
    for name in os.listdir(cc):
        if name.endswith((".cc", ".h")) or name == "Makefile":
            if os.path.getmtime(os.path.join(cc, name)) > so_mtime:
                return True
    return False


def load() -> ctypes.CDLL:
    """Load (building if necessary) the native engine library."""
    global _lib
    if _lib is not None:
        return _lib
    if _stale():
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    _declare(lib)
    _lib = lib
    return lib


c_u64p = ctypes.POINTER(ctypes.c_uint64)
c_i64p = ctypes.POINTER(ctypes.c_int64)
c_i32p = ctypes.POINTER(ctypes.c_int32)
c_f32p = ctypes.POINTER(ctypes.c_float)
c_voidp = ctypes.c_void_p


def _declare(lib: ctypes.CDLL) -> None:
    i64, i32, u64, f32 = (
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_uint64,
        ctypes.c_float,
    )
    sigs = {
        "etg_last_error": (ctypes.c_char_p, []),
        "etg_seed": (None, [u64]),
        "etg_set_log_level": (None, [i32]),
        "etg_builder_new": (i64, []),
        "etg_builder_set_feature": (i32, [i64, i32, i32, i32, i64, ctypes.c_char_p]),
        "etg_builder_set_num_types": (i32, [i64, i32, i32]),
        "etg_builder_set_type_name": (i32, [i64, i32, i32, ctypes.c_char_p]),
        "etg_type_id": (i32, [i64, i32, ctypes.c_char_p]),
        "etg_type_name": (i32, [i64, i32, i32, ctypes.c_char_p, i64]),
        "etg_builder_add_nodes": (i32, [i64, i64, c_u64p, c_i32p, c_f32p]),
        "etg_builder_add_edges": (i32, [i64, i64, c_u64p, c_u64p, c_i32p, c_f32p]),
        "etg_builder_set_node_dense": (i32, [i64, c_u64p, i64, i32, i64, c_f32p]),
        "etg_builder_set_node_sparse": (i32, [i64, c_u64p, i64, i32, c_u64p, c_u64p]),
        "etg_builder_set_node_binary": (i32, [i64, u64, i32, ctypes.c_char_p, i64]),
        "etg_builder_set_edge_dense": (i32, [i64, c_u64p, c_u64p, c_i32p, i64, i32, i64, c_f32p]),
        "etg_builder_set_edge_sparse": (i32, [i64, u64, u64, i32, i32, c_u64p, i64]),
        "etg_builder_set_edge_binary": (i32, [i64, u64, u64, i32, i32, ctypes.c_char_p, i64]),
        "etg_builder_finalize": (i64, [i64, i32]),
        "etg_load": (i64, [ctypes.c_char_p, i32, i32, i32, i32]),
        "etg_dump": (i32, [i64, ctypes.c_char_p, i32, i32]),
        "etg_free": (i32, [i64]),
        "etg_node_count": (i64, [i64]),
        "etg_edge_count": (i64, [i64]),
        "etg_num_node_types": (i32, [i64]),
        "etg_num_edge_types": (i32, [i64]),
        "etg_num_node_features": (i32, [i64]),
        "etg_num_edge_features": (i32, [i64]),
        "etg_feature_info": (i32, [i64, i32, i32, c_i32p, c_i64p, ctypes.c_char_p, i64]),
        "etg_all_node_ids": (i32, [i64, c_u64p]),
        "etg_node_rows": (i32, [i64, c_u64p, i64, i32, c_i32p]),
        "etg_builder_set_graph_labels": (i32, [i64, c_u64p, c_u64p, i64]),
        "etg_graph_label_count": (i64, [i64]),
        "etg_sample_graph_label": (i32, [i64, i64, c_u64p]),
        "etg_get_graph_by_label": (i32, [i64, c_u64p, i64, c_voidp]),
        "etg_all_node_weights": (i32, [i64, c_f32p]),
        "etg_node_weight_sums": (i32, [i64, c_f32p]),
        "etg_edge_weight_sums": (i32, [i64, c_f32p]),
        "etg_sample_node": (i32, [i64, i32, i64, c_u64p]),
        "etg_sample_node_with_types": (i32, [i64, c_i32p, i64, c_u64p]),
        "etg_sample_edge": (i32, [i64, i32, i64, c_u64p, c_u64p, c_i32p]),
        "etg_get_node_type": (i32, [i64, c_u64p, i64, c_i32p]),
        "etg_sample_neighbor": (i32, [i64, c_u64p, i64, c_i32p, i64, i64, u64, c_u64p, c_f32p, c_i32p]),
        "etg_sample_in_neighbor": (i32, [i64, c_u64p, i64, c_i32p, i64, i64, u64, c_u64p, c_f32p, c_i32p]),
        "etg_get_top_k_neighbor": (i32, [i64, c_u64p, i64, c_i32p, i64, i64, u64, c_u64p, c_f32p, c_i32p]),
        "etg_sample_fanout": (i32, [i64, c_u64p, i64, c_i32p, i64, c_i32p, c_i64p, u64, ctypes.POINTER(c_u64p), ctypes.POINTER(c_f32p), ctypes.POINTER(c_i32p)]),
        "etg_random_walk": (i32, [i64, c_u64p, i64, i64, f32, f32, u64, c_i32p, i64, c_u64p]),
        "etg_sample_layerwise": (i32, [i64, c_u64p, i64, c_i32p, i64, c_i32p, i64, u64, i32, ctypes.POINTER(c_u64p)]),
        "etg_get_dense_feature": (i32, [i64, c_u64p, i64, i32, i64, c_f32p]),
        "etg_get_edge_dense_feature": (i32, [i64, c_u64p, c_u64p, c_i32p, i64, i32, i64, c_f32p]),
        "etres_new": (c_voidp, []),
        "etres_free": (None, [c_voidp]),
        "etres_offsets_len": (i64, [c_voidp]),
        "etres_offsets": (c_u64p, [c_voidp]),
        "etres_u64_len": (i64, [c_voidp]),
        "etres_u64": (c_u64p, [c_voidp]),
        "etres_f32_len": (i64, [c_voidp]),
        "etres_f32": (c_f32p, [c_voidp]),
        "etres_i32_len": (i64, [c_voidp]),
        "etres_i32": (c_i32p, [c_voidp]),
        "etres_bytes_len": (i64, [c_voidp]),
        "etres_bytes": (ctypes.POINTER(ctypes.c_char), [c_voidp]),
        "etg_get_full_neighbor": (i32, [i64, c_u64p, i64, c_i32p, i64, i32, i32, c_voidp]),
        "etg_get_sparse_feature": (i32, [i64, c_u64p, i64, i32, c_voidp]),
        "etg_get_binary_feature": (i32, [i64, c_u64p, i64, i32, c_voidp]),
        "etg_get_edge_sparse_feature": (i32, [i64, c_u64p, c_u64p, c_i32p, i64, i32, c_voidp]),
        "etg_get_edge_binary_feature": (i32, [i64, c_u64p, c_u64p, c_i32p, i64, i32, c_voidp]),
        # query layer (gremlin → DAG → executor; local or distributed)
        "etq_new_local": (i64, [i64, ctypes.c_char_p, u64]),
        "etq_new_remote": (i64, [ctypes.c_char_p, u64, ctypes.c_char_p]),
        "etq_free": (i32, [i64]),
        "etq_stats": (i32, [i64, c_u64p]),
        "etq_index_dump": (i32, [i64, ctypes.c_char_p]),
        "etg_register_udf": (None, [ctypes.c_char_p, c_voidp]),
        "etg_udf_cache_stats": (None, [ctypes.POINTER(u64), ctypes.POINTER(u64), ctypes.POINTER(u64), ctypes.POINTER(u64)]),
        "etg_udf_cache_clear": (None, []),
        "etg_udf_cache_set_capacity": (None, [u64]),
        "etg_hash64": (u64, [ctypes.c_char_p, u64]),
        # RPC transport (protocol v2 mux / adaptive compression): global
        # config + client-edge counters — see euler_tpu.graph.remote
        # configure_rpc() / rpc_transport_stats() for the friendly wrapper
        # (+ prepared plans / plan-cache size / deflate reuse — the
        # wire-path knobs — and the plan-optimizer block: plan_optimize,
        # coalesce_window_us, reuse_window; stats out buffer is 37 u64s)
        "etg_rpc_config": (None, [i32, i32, i64, i32, i64, i32, i32,
                                  i32, i32, i32, i32, i64, i32]),
        "etg_rpc_stats": (None, [c_u64p]),
        # elastic fleet: epoch-versioned ownership maps — install on a
        # distribute-mode proxy / in-process server, push to a remote
        # server over the kSetOwnership admin verb, read epochs and
        # per-shard request counts (hot-shard detection)
        "etg_push_ownership": (i32, [ctypes.c_char_p, i32, ctypes.c_char_p, c_i64p]),
        "etq_set_ownership": (i32, [i64, ctypes.c_char_p]),
        "etq_ownership_epoch": (i64, [i64]),
        "etq_shard_num": (i32, [i64]),
        "etq_shard_stats": (i32, [i64, c_u64p, c_u64p, i32]),
        "ets_set_ownership": (i32, [i64, ctypes.c_char_p]),
        "ets_map_epoch": (i64, [i64]),
        # tail latency: per-thread deadline handoff for the next query
        # run (remaining ms; <= 0 clears) — REMOTE sub-calls stamp the
        # remaining budget into their v2 request frames
        "etg_set_call_deadline_ms": (None, [ctypes.c_double]),
        # cross-process tracing: per-thread (trace_id, parent_span)
        # handoff for the next query run (trace_id 0 clears); server-
        # side per-verb/phase timing histograms (out[27] = n, sum_us,
        # counts[25]) and the traced-span ring dump (stride-10 u64
        # records into an EtResult)
        "etg_set_call_trace": (None, [u64, u64]),
        "etg_server_trace_hist": (i32, [i32, i32, c_u64p]),
        "etg_server_trace_dump": (i32, [c_voidp]),
        # streaming deltas: graph epoch + batched O(delta) apply +
        # dirty-set retrieval, on embedded handles (etg_*) and query
        # proxies (etq_* — local swaps the handle's graph, distribute
        # broadcasts kApplyDelta to every shard)
        "etg_graph_epoch": (i64, [i64]),
        "etg_apply_delta": (i32, [i64, i64, c_u64p, c_i32p, c_f32p, i64, c_u64p, c_u64p, c_i32p, c_f32p, c_i64p]),
        "etg_delta_since": (i32, [i64, i64, c_voidp, c_i64p, c_i32p]),
        "etg_udf_cache_epoch_evictions": (u64, []),
        "etq_epoch": (i64, [i64]),
        "etq_apply_delta": (i32, [i64, i64, c_u64p, c_i32p, c_f32p, i64, c_u64p, c_u64p, c_i32p, c_f32p, c_i64p]),
        "etq_delta_since": (i32, [i64, i64, c_voidp, c_i64p, c_i32p]),
        "et_udf_emit": (None, [c_voidp, c_u64p, i64, c_f32p, i64]),
        "etq_exec_new": (i64, [i64]),
        "etq_exec_add_input": (i32, [i64, ctypes.c_char_p, i32, i32, c_i64p, c_voidp]),
        "etq_exec_run": (i32, [i64, ctypes.c_char_p]),
        "etq_exec_output_count": (i64, [i64]),
        "etq_exec_output_name": (ctypes.c_char_p, [i64, i64]),
        "etq_exec_output_info": (i32, [i64, i64, c_i32p, c_i32p, c_i64p]),
        "etq_exec_output_dims": (i32, [i64, i64, c_i64p]),
        "etq_exec_output_data": (c_voidp, [i64, i64]),
        "etq_exec_free": (i32, [i64]),
        "ets_start": (i64, [ctypes.c_char_p, i32, i32, i32, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]),
        # durable form: + wal_dir, fsync_policy (0=never 1=always),
        # compact_bytes, catchup (registry anti-entropy on restart)
        "ets_start2": (i64, [ctypes.c_char_p, i32, i32, i32, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, i32, i64, i32]),
        # out-of-core form: + storage (0=ram 1=mmap), hot_bytes (hub
        # hot-set budget for the mmap tier)
        "ets_start3": (i64, [ctypes.c_char_p, i32, i32, i32, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, i32, i64, i32, i32, i64]),
        "ets_epoch": (i64, [i64]),
        "ets_port": (i32, [i64]),
        "ets_stop": (i32, [i64]),
        # durability counters: appends, fsyncs, replayed_records,
        # compactions, catchup_deltas, refused, torn_records, degraded
        "etg_wal_stats": (None, [c_u64p]),
        # out-of-core columnar store: write a handle's snapshot to a
        # store file / mmap-attach one as a new handle / process-global
        # tier counters (35 slots, store.h slot order)
        "etg_store_write": (i32, [i64, ctypes.c_char_p]),
        "etg_store_open": (i64, [ctypes.c_char_p, i64]),
        "etg_store_stats": (None, [c_u64p]),
        "etr_start": (i64, [i32]),
        "etr_port": (i32, [i64]),
        "etr_stop": (i32, [i64]),
        "etr_scan": (i64, [ctypes.c_char_p, ctypes.c_char_p, i64]),
        "etq_compile_debug": (i64, [ctypes.c_char_p, i32, i32, ctypes.c_char_p, ctypes.c_char_p, i64]),
        # explain(): stage 0 = as-registered plan, stage 1 = what the
        # server's prepare-time optimizer executes (+ rewrite counts,
        # determinism verdict); ets_plan_debug dumps a live server's
        # shared prepared-plan store
        "etq_compile_debug2": (i64, [ctypes.c_char_p, i32, i32, ctypes.c_char_p, i32, ctypes.c_char_p, i64]),
        "ets_plan_debug": (i64, [i64, ctypes.c_char_p, i64]),
    }
    for name, (restype, argtypes) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes


class EngineError(RuntimeError):
    pass


def check(lib: ctypes.CDLL, rc: int) -> None:
    if rc != 0:
        raise EngineError(lib.etg_last_error().decode())
