// Immutable columnar property-graph store.
//
// Capability parity with the reference's euler/core/graph/ (Graph, Node,
// Edge, GraphBuilder, GraphMeta — SURVEY.md §2.1), redesigned for a TPU
// host feeder: instead of per-node heap objects in hash maps
// (reference graph.h:189-192, node.h:35-43), the store is struct-of-arrays —
// one global CSR adjacency partitioned into (node, edge_type) groups with a
// shared cumulative-weight array, flat zero-filled dense-feature matrices,
// and CSR sparse/binary features. Batch sampling walks contiguous arrays and
// emits fixed-shape, default-padded outputs that map 1:1 onto static-shape
// jax.Arrays (no ragged post-processing on the device path).
//
// Thread-safety: Graph is immutable after Finalize(); all Sample*/Get*
// methods are const and take an explicit RNG → safe for concurrent readers.
// Streaming mutations never break this: ApplyGraphDelta builds a NEW
// snapshot off-path and GraphRef swaps it in (RCU) — readers holding the
// old snapshot finish safely while new requests see the new epoch.
#ifndef EULER_TPU_GRAPH_H_
#define EULER_TPU_GRAPH_H_

#include <algorithm>
#include <cstdint>
#include <deque>
#include <limits>
#include <memory>
#include <mutex>
#include <string>
#include <tuple>
#include <unordered_map>
#include <vector>

#include "col.h"
#include "common.h"
#include "sampling.h"

namespace et {

using NodeId = uint64_t;
constexpr uint32_t kInvalidIndex = std::numeric_limits<uint32_t>::max();

// ---------------------------------------------------------------------------
// OwnershipMap — epoch-versioned partition → owner-shard routing (the
// elastic-fleet replacement for the implicit (id % P) % shard_num hash
// convention). Partition p lists one or more owner shards (primary
// first; extra owners are replicas holding the same rows — hot-partition
// rebalancing spreads reads over them). The map is registry-published
// and client-cached; every change bumps map_epoch, and servers refuse
// kExecute requests stamped with an OLDER epoch ("stale ownership map")
// so a client routing on a superseded map can never silently read a
// shard that stopped receiving that partition's deltas. map_epoch == 0
// means "no map": every consumer falls back to the hash convention,
// byte-identical to pre-elastic builds.
// ---------------------------------------------------------------------------
struct OwnershipMap {
  uint64_t map_epoch = 0;
  int partition_num = 1;
  // owners[p] = owning shard indices, primary first, each sorted-unique
  // after the primary. Never empty for a valid map.
  std::vector<std::vector<int>> owners;
  int shard_num = 0;  // 1 + max shard index listed (the fleet width)

  // The hash convention as an explicit map: owners[p] = {p % shard_num}.
  static OwnershipMap Default(int partition_num, int shard_num,
                              uint64_t epoch = 1);

  // Compact registry-entry-safe spec: "e<epoch>-P<pn>-<o0>.<o1>..."
  // with multi-owner partitions joined by '+', e.g. "e3-P4-0.1.2.2+3".
  std::string Encode() const;
  static Status Decode(const std::string& spec, OwnershipMap* out);

  int partition_of(NodeId id) const {
    return static_cast<int>(id % static_cast<uint64_t>(
                                     std::max(partition_num, 1)));
  }
  const std::vector<int>& owners_of(NodeId id) const {
    return owners[partition_of(id)];
  }
  bool owns(int shard_idx, NodeId id) const {
    for (int s : owners_of(id))
      if (s == shard_idx) return true;
    return false;
  }
  int primary(NodeId id) const { return owners_of(id)[0]; }
  // True when shard `sup`'s owned partition set covers every partition
  // `shard` owns — `sup` can then serve any request routed to `shard`
  // (the replica-hedging eligibility test).
  bool Covers(int sup, int shard) const;
};

enum class FeatureKind : int { kDense = 0, kSparse = 1, kBinary = 2 };

struct FeatureInfo {
  std::string name;
  FeatureKind kind = FeatureKind::kDense;
  int64_t dim = 0;  // dense: vector length; sparse/binary: max/advisory
};

struct GraphMeta {
  std::string name = "euler_tpu_graph";
  int num_node_types = 1;
  int num_edge_types = 1;
  int partition_num = 1;
  uint64_t node_count = 0;  // global (all partitions)
  uint64_t edge_count = 0;
  std::vector<FeatureInfo> node_features;  // indexed by feature id
  std::vector<FeatureInfo> edge_features;
  std::vector<std::string> node_type_names;
  std::vector<std::string> edge_type_names;
};

// CSR store for one variable-length feature over all rows. Columns are
// Col<T> so the whole feature can live in an mmap'd store (store.h).
struct VarFeature {
  Col<uint64_t> offsets;  // size rows+1
  Col<uint64_t> values_u64;  // sparse kind
  Col<char> values_bytes;    // binary kind
};

class GraphBuilder;
class ColumnarStore;  // store.h — mmap'd columnar file backing a Graph
class StorageTier;    // store.h — hot-set accounting over attached columns

class Graph {
 public:
  // ---- topology ----
  uint32_t NodeIndex(NodeId id) const {
    // dense-id fast path: id→row is one bounds check + array load when
    // the id space is compact (ogbn-style 0..N ids) — the hash lookup
    // otherwise dominates the per-edge sampling cost
    if (!dense_idx_.empty()) {
      uint64_t off = id - dense_base_;
      return off < dense_idx_.size() ? dense_idx_[off] : kInvalidIndex;
    }
    auto it = id2idx_.find(id);
    return it == id2idx_.end() ? kInvalidIndex : it->second;
  }
  size_t node_count() const { return node_ids_.size(); }
  size_t edge_count() const { return adj_nbr_.size(); }
  int num_node_types() const { return meta_.num_node_types; }
  int num_edge_types() const { return meta_.num_edge_types; }
  const GraphMeta& meta() const { return meta_; }
  GraphMeta* mutable_meta() { return &meta_; }
  NodeId node_id(uint32_t idx) const { return node_ids_[idx]; }
  int32_t node_type(uint32_t idx) const { return node_types_[idx]; }
  float node_weight(uint32_t idx) const { return node_weights_[idx]; }

  // Sum of node/edge weights, per type — powers weight-proportional
  // cross-shard sampling (reference query_proxy.cc:77-105).
  const std::vector<float>& node_type_weight_sums() const {
    return node_type_wsum_;
  }
  const std::vector<float>& edge_type_weight_sums() const {
    return edge_type_wsum_;
  }

  // ---- global sampling ----
  // type < 0 samples across all types ∝ weight. Appends `count` node ids.
  void SampleNode(int type, size_t count, Pcg32* rng,
                  NodeId* out_ids) const;
  // Per-row type array variant (reference sampleNWithTypes).
  void SampleNodeWithTypes(const int32_t* types, size_t count, Pcg32* rng,
                           NodeId* out_ids) const;
  // Samples edges ∝ weight; writes parallel (src, dst, type) triples.
  void SampleEdge(int type, size_t count, Pcg32* rng, NodeId* out_src,
                  NodeId* out_dst, int32_t* out_type) const;

  // ---- neighbor access ----
  // Group range for (node idx, edge type) in the adjacency arrays.
  inline void GroupRange(uint32_t idx, int et, size_t* begin,
                         size_t* end) const {
    size_t g = static_cast<size_t>(idx) * meta_.num_edge_types + et;
    *begin = adj_offsets_[g];
    *end = adj_offsets_[g + 1];
  }

  // Sample `count` neighbors of node `id` restricted to `edge_types`
  // (nullptr → all), ∝ edge weight across the selected groups. Missing node
  // or empty neighborhood pads with `default_id` / weight 0 / type -1.
  void SampleNeighbor(NodeId id, const int32_t* edge_types, size_t n_types,
                      size_t count, NodeId default_id, Pcg32* rng,
                      NodeId* out_ids, float* out_w, int32_t* out_t) const;

  // Batch SampleNeighbor over n nodes with a software-pipelined layout:
  // staged passes (id→idx, group ranges, group totals, draws) each
  // prefetch a fixed distance ahead, so the DRAM misses of giant-graph
  // adjacency arrays overlap instead of serializing — the giant-store
  // fanout path collapsed ~25x without this (cache locality, r2 weak #4).
  // Same sampling semantics and per-node draw order as SampleNeighbor;
  // the rng consumption differs (one stream across the batch).
  void SampleNeighborBatch(const NodeId* ids, size_t n,
                           const int32_t* edge_types, size_t n_types,
                           size_t count, NodeId default_id, Pcg32* rng,
                           NodeId* out_ids, float* out_w,
                           int32_t* out_t) const;

  // Appends all neighbors (ids, weights, types) for the selected edge types.
  void GetFullNeighbor(NodeId id, const int32_t* edge_types, size_t n_types,
                       std::vector<NodeId>* ids, std::vector<float>* ws,
                       std::vector<int32_t>* ts, bool sorted_by_id = false) const;

  // Top-k by weight (descending). Pads to k with default_id.
  void GetTopKNeighbor(NodeId id, const int32_t* edge_types, size_t n_types,
                       size_t k, NodeId default_id, NodeId* out_ids,
                       float* out_w, int32_t* out_t) const;

  // In-edge variants operate on the reverse adjacency (built at Finalize).
  void GetFullInNeighbor(NodeId id, const int32_t* edge_types, size_t n_types,
                         std::vector<NodeId>* ids, std::vector<float>* ws,
                         std::vector<int32_t>* ts) const;
  void SampleInNeighbor(NodeId id, const int32_t* edge_types, size_t n_types,
                        size_t count, NodeId default_id, Pcg32* rng,
                        NodeId* out_ids, float* out_w, int32_t* out_t) const;

  size_t OutDegree(NodeId id, const int32_t* edge_types, size_t n_types) const;

  // ---- features ----
  // Dense: writes count*dim floats, zero-filled for missing nodes/features.
  void GetDenseFeature(const NodeId* ids, size_t count, int fid,
                       int64_t dim, float* out) const;
  // Sparse/binary return CSR appended into the out vectors.
  void GetSparseFeature(const NodeId* ids, size_t count, int fid,
                        std::vector<uint64_t>* offsets,
                        std::vector<uint64_t>* values) const;
  void GetBinaryFeature(const NodeId* ids, size_t count, int fid,
                        std::vector<uint64_t>* offsets,
                        std::vector<char>* values) const;

  // Edge features are keyed by (src, dst, type).
  uint64_t EdgeSlot(NodeId src, NodeId dst, int32_t type) const;  // kNoSlot if absent
  static constexpr uint64_t kNoSlot = std::numeric_limits<uint64_t>::max();
  void GetEdgeDenseFeature(const NodeId* src, const NodeId* dst,
                           const int32_t* type, size_t count, int fid,
                           int64_t dim, float* out) const;
  void GetEdgeSparseFeature(const NodeId* src, const NodeId* dst,
                            const int32_t* type, size_t count, int fid,
                            std::vector<uint64_t>* offsets,
                            std::vector<uint64_t>* values) const;
  void GetEdgeBinaryFeature(const NodeId* src, const NodeId* dst,
                            const int32_t* type, size_t count, int fid,
                            std::vector<uint64_t>* offsets,
                            std::vector<char>* values) const;
  float GetEdgeWeight(NodeId src, NodeId dst, int32_t type) const;

  // ---- whole-graph (graph classification) support ----
  // Each node may belong to one "graph label" (reference graph_label /
  // API_SAMPLE_GRAPH_LABEL / API_GET_GRAPH_BY_LABEL, sample_graph_label_op
  // + get_graph_by_label_op): small graphs packed into one store, sampled
  // and fetched by label for whole-graph batching.
  size_t graph_label_count() const { return label_ids_.size(); }
  const std::vector<uint64_t>& graph_label_ids() const { return label_ids_; }
  uint64_t node_graph_label(uint32_t idx) const {
    return idx < graph_labels_.size() ? graph_labels_[idx] : 0;
  }
  // Uniform over distinct labels; writes `count` labels (0 when none).
  void SampleGraphLabel(size_t count, Pcg32* rng, uint64_t* out) const;
  // Hash-distribute mode only: shard s "owns" labels with
  // label % shard_num == s; sampling each label from exactly one shard
  // keeps the global draw uniform even when a label's nodes span shards
  // (labels whose owner shard holds none of their nodes are invisible —
  // negligible for labels with more members than shards).
  size_t OwnedGraphLabelCount(int shard_idx, int shard_num) const;
  void SampleGraphLabelOwned(size_t count, int shard_idx, int shard_num,
                             Pcg32* rng, uint64_t* out) const;
  std::shared_ptr<const std::vector<uint64_t>> OwnedLabels(
      int shard_idx, int shard_num) const;
  // Node rows of one label; nullptr when unknown.
  const std::vector<uint32_t>* GraphNodes(uint64_t label) const;

  // ---- serialization ----
  Status Dump(const std::string& path) const;  // single-partition binary dump

  // Process-unique id, assigned at construction. Finalized graphs are
  // immutable, so (uid, query) fully identifies a result — the UDF
  // result cache keys on it (udf.h UdfResultCache). A delta-applied
  // snapshot is a NEW Graph with a new uid, so cached results for the
  // pre-delta snapshot can never be served after a swap.
  uint64_t uid() const { return uid_; }

  // Graph epoch: monotonic version stamp. 0 for a freshly finalized
  // graph; ApplyGraphDelta stamps base.epoch() + 1 on the snapshot it
  // produces. Carried on v2 RPC reply frames and exposed through capi
  // so clients invalidate derived state (caches, alias tables) on the
  // bump instead of assuming immutability.
  uint64_t epoch() const { return epoch_; }
  void set_epoch(uint64_t e) { epoch_ = e; }

  // Whether Finalize built the reverse adjacency (ApplyGraphDelta must
  // rebuild with the same setting for byte parity).
  bool has_in_adjacency() const { return !in_adj_offsets_.empty(); }

  // ---- out-of-core storage tier ----
  // True when the big columns are mmap-attached to a ColumnarStore file
  // instead of heap-resident (store.h LoadGraphFromStore).
  bool attached() const { return store_ != nullptr; }
  const std::shared_ptr<ColumnarStore>& store() const { return store_; }
  StorageTier* tier() const { return tier_raw_; }

 private:
  friend class GraphBuilder;
  friend std::unique_ptr<GraphBuilder> BuilderFromGraph(const Graph&);
  friend struct StoreAccess;  // store.cc serializer/attacher
  Graph();

  // Hot/cold accounting hook: every row-addressed accessor calls this
  // once per resolved row. One predictable branch on the RAM path;
  // TierTouchRow (graph.cc) does the bitmask check + cold latency
  // timing only when a tier is attached.
  inline void TouchRow(uint32_t idx) const {
    if (tier_raw_ != nullptr) TierTouchRow(idx);
  }
  void TierTouchRow(uint32_t idx) const;

  // Weighted choice among the (begin,end) cumw groups selected by edge_types;
  // returns adjacency slot or kNoSlot when all groups are empty/zero.
  uint64_t SampleAdjSlot(uint32_t idx, const int32_t* edge_types,
                         size_t n_types, Pcg32* rng) const;

  uint64_t uid_ = 0;
  uint64_t epoch_ = 0;
  GraphMeta meta_;
  // Out-of-core backing: when non-null, the Col members below are
  // attached to this mmap'd store (which must outlive them) and tier_
  // does hot/cold accounting. Null for ordinary heap-resident graphs.
  std::shared_ptr<ColumnarStore> store_;
  std::shared_ptr<StorageTier> tier_;
  StorageTier* tier_raw_ = nullptr;  // branch-cheap hook (TouchRow)
  // nodes
  Col<NodeId> node_ids_;
  Col<int32_t> node_types_;
  Col<float> node_weights_;
  std::unordered_map<NodeId, uint32_t> id2idx_;
  // direct id→row table when the id range is ≤ 4× node count (built at
  // Finalize); empty → fall back to the hash map
  Col<uint32_t> dense_idx_;
  NodeId dense_base_ = 0;
  // out-adjacency: group g = idx*num_edge_types + et
  Col<uint64_t> adj_offsets_;  // size N*ET + 1
  Col<NodeId> adj_nbr_;
  Col<float> adj_w_;
  Col<float> adj_cumw_;  // per-group inclusive prefix sums
  // in-adjacency (same layout; slot order independent of out slots)
  Col<uint64_t> in_adj_offsets_;
  Col<NodeId> in_adj_nbr_;
  Col<float> in_adj_w_;
  Col<float> in_adj_cumw_;
  // Edge slot lookup needs no map: each (src row, type) group's slots
  // are sorted by dst, so EdgeSlot binary-searches the group — O(log d)
  // with zero build/memory cost (a 100M+-entry hash map here once
  // dominated finalize time and RSS).
  struct EdgeKeyHash {
    size_t operator()(const std::tuple<uint32_t, NodeId, int32_t>& k) const {
      uint64_t h = std::get<0>(k) * 0x9e3779b97f4a7c15ULL;
      h ^= std::get<1>(k) + 0x9e3779b97f4a7c15ULL + (h << 6) + (h >> 2);
      h ^= static_cast<uint64_t>(std::get<2>(k)) + (h << 6) + (h >> 2);
      return static_cast<size_t>(h);
    }
  };
  // global samplers
  // whole-graph labels
  Col<uint64_t> graph_labels_;  // per node row; empty → unlabeled
  std::vector<uint64_t> label_ids_;     // distinct labels, sorted
  std::unordered_map<uint64_t, std::vector<uint32_t>> label_rows_;
  // OwnedLabels single-entry cache (see graph.cc)
  mutable std::mutex owned_mu_;
  mutable int owned_sidx_ = -1, owned_snum_ = -1;
  mutable std::shared_ptr<const std::vector<uint64_t>> owned_ids_;
  std::vector<Col<uint32_t>> nodes_by_type_;  // type → node indices
  std::vector<AliasSampler> node_sampler_by_type_;
  AliasSampler node_sampler_all_;  // over node indices 0..N-1
  std::vector<Col<uint64_t>> edges_by_type_;  // type → adj slots
  std::vector<AliasSampler> edge_sampler_by_type_;
  AliasSampler edge_sampler_all_;  // over adjacency slots 0..E-1
  std::vector<float> node_type_wsum_;
  std::vector<float> edge_type_wsum_;
  // features: [fid] → flat matrix (dense) or CSR (sparse/binary)
  std::vector<Col<float>> node_dense_;   // size N*dim, zero-filled
  std::vector<VarFeature> node_var_;
  std::vector<Col<float>> edge_dense_;   // size E*dim (adj slot order)
  std::vector<VarFeature> edge_var_;

  void FindAdjSlots(NodeId src, NodeId dst, int32_t type, uint64_t* slot) const;
};

// Accumulates rows, then Finalize() produces the immutable SoA Graph.
// Parity: reference graph_builder.h:47 (multi-threaded partition loading is
// in loader.cc; the builder itself is single-threaded row accumulation).
class GraphBuilder {
 public:
  GraphBuilder() { meta_.node_type_names = {"0"}; meta_.edge_type_names = {"0"}; }

  GraphMeta* mutable_meta() { return &meta_; }

  void AddNode(NodeId id, int32_t type, float weight);
  // src is auto-created (type 0, weight 1) if missing; dst is NOT — in a
  // sharded graph the destination may live on another shard, and creating a
  // ghost local node would pollute the global samplers. Negative edge types
  // are rejected with a warning.
  void AddEdge(NodeId src, NodeId dst, int32_t type, float weight);

  void SetNodeDense(NodeId id, int fid, const float* v, int64_t dim);
  void SetNodeSparse(NodeId id, int fid, const uint64_t* v, int64_t len);
  void SetNodeBinary(NodeId id, int fid, const char* v, int64_t len);
  void SetEdgeDense(NodeId src, NodeId dst, int32_t type, int fid,
                    const float* v, int64_t dim);
  void SetEdgeSparse(NodeId src, NodeId dst, int32_t type, int fid,
                     const uint64_t* v, int64_t len);
  void SetEdgeBinary(NodeId src, NodeId dst, int32_t type, int fid,
                     const char* v, int64_t len);

  // Bulk columnar entry points (zero-copy friendly; used by the ctypes
  // bridge for dataset ingestion without per-row Python calls).
  void AddNodes(const NodeId* ids, const int32_t* types, const float* weights,
                size_t n);
  void AddEdges(const NodeId* src, const NodeId* dst, const int32_t* types,
                const float* weights, size_t n);
  // Column of dense features for n nodes (values is n*dim row-major).
  void SetNodeDenseBulk(const NodeId* ids, size_t n, int fid, int64_t dim,
                        const float* values);
  void SetEdgeDenseBulk(const NodeId* src, const NodeId* dst,
                        const int32_t* types, size_t n, int fid, int64_t dim,
                        const float* values);
  void SetNodeSparseBulk(const NodeId* ids, size_t n, int fid,
                         const uint64_t* offsets, const uint64_t* values);
  // Assign nodes to whole-graph labels (graph classification batching).
  void SetGraphLabels(const NodeId* ids, const uint64_t* labels, size_t n);

  std::unique_ptr<Graph> Finalize(bool build_in_adjacency = true);

 private:
  struct NodeRow {
    NodeId id;
    int32_t type;
    float weight;
  };
  struct EdgeRow {
    NodeId src, dst;
    int32_t type;
    float weight;
  };
  struct FeatCell {
    uint64_t row;  // node row idx or edge row idx
    std::vector<float> f32;
    std::vector<uint64_t> u64;
    std::vector<char> bytes;
  };

  uint32_t EnsureNode(NodeId id, int32_t type, float weight, bool overwrite);
  int64_t FindEdgeRow(NodeId src, NodeId dst, int32_t type) const;

  GraphMeta meta_;
  std::vector<NodeRow> nodes_;
  std::unordered_map<NodeId, uint32_t> node_row_;
  std::vector<EdgeRow> edges_;
  // Lazy (src_row, dst, type) → builder row index, extended
  // incrementally on feature-setter lookups (edge_indexed_upto_ marks
  // how far edges_ has been indexed). Plain ingest never touches it:
  // maintaining a 100M+-entry map per AddEdge made bulk loads minutes
  // slower for graphs that set no edge features at all, while the
  // incremental cursor keeps interleaved AddEdge/SetEdge* loading
  // (io.cc per-record pattern) linear.
  mutable std::unordered_map<std::tuple<uint32_t, NodeId, int32_t>,
                             uint64_t, Graph::EdgeKeyHash>
      edge_row_;
  mutable size_t edge_indexed_upto_ = 0;
  // feature cells per fid, sorted at finalize
  std::vector<std::vector<FeatCell>> node_feat_cells_;
  std::vector<std::vector<FeatCell>> edge_feat_cells_;
  std::unordered_map<NodeId, uint64_t> graph_label_of_;

  std::vector<FeatCell>* NodeCells(int fid);
  std::vector<FeatCell>* EdgeCells(int fid);
};

// ---------------------------------------------------------------------------
// Streaming deltas: swappable snapshot holder + O(delta) bookkeeping.
// ---------------------------------------------------------------------------

// Shared, swappable holder for an immutable Graph snapshot (RCU shape):
// readers snapshot get() and keep sampling the old graph while a delta
// finalizes off-path; Swap atomically publishes the new snapshot plus
// the per-epoch dirty-node set that produced it. This is what turns
// "the graph is immutable" from a load-bearing assumption into a
// checked, versioned invariant — every Graph stays immutable, only the
// ref moves.
class GraphRef {
 public:
  explicit GraphRef(std::shared_ptr<const Graph> g) : g_(std::move(g)) {}

  std::shared_ptr<const Graph> get() const {
    std::lock_guard<std::mutex> lk(mu_);
    return g_;
  }
  uint64_t epoch() const {
    std::lock_guard<std::mutex> lk(mu_);
    return g_->epoch();
  }

  // Publish `next` (epoch already stamped) with the dirty-node set of
  // the delta that produced it. History is bounded (kMaxEpochs entries
  // / kMaxDirtyIds total ids); once it overflows, DirtySince reports
  // uncovered and clients fall back to a full flush — the documented
  // escape hatch, never silent staleness.
  void Swap(std::shared_ptr<const Graph> next, std::vector<NodeId> dirty) {
    std::lock_guard<std::mutex> lk(mu_);
    SwapLocked(std::move(next), std::move(dirty));
  }

  // Compare-and-swap publish: fails (false, no change) when the held
  // snapshot is no longer `expected` — a concurrent apply through a
  // DIFFERENT surface (capi handle vs a proxy bound to it) rebuilt
  // from the same base first, and silently dropping either delta would
  // lose writes. Callers surface "concurrent delta apply; retry".
  bool SwapFrom(const std::shared_ptr<const Graph>& expected,
                std::shared_ptr<const Graph> next,
                std::vector<NodeId> dirty) {
    std::lock_guard<std::mutex> lk(mu_);
    if (g_ != expected) return false;
    SwapLocked(std::move(next), std::move(dirty));
    return true;
  }

  // Union of dirty sets for epochs in (from, epoch()], sorted unique.
  // Returns false (and clears out) when the history no longer covers
  // `from` — caller must treat everything as dirty. epoch_out (when
  // non-null) receives the epoch the result covers UP TO, read under
  // the same lock — a concurrent Swap can never make the caller think
  // ids reach an epoch they don't.
  bool DirtySince(uint64_t from, std::vector<NodeId>* out,
                  uint64_t* epoch_out = nullptr) const {
    std::lock_guard<std::mutex> lk(mu_);
    out->clear();
    uint64_t cur = g_->epoch();
    if (epoch_out != nullptr) *epoch_out = cur;
    // from > cur: the caller observed an epoch this graph never reached
    // — an EPOCH REGRESSION (a restarted shard reloaded pre-delta data
    // at epoch 0). History cannot prove anything about it; report
    // uncovered so the caller full-flushes instead of silently serving
    // rows from a future the graph lost.
    if (from > cur) return false;
    if (from == cur) return true;  // nothing newer — empty dirty set
    // coverage: every epoch in (from, cur] must be present in history
    uint64_t oldest = hist_.empty() ? cur + 1 : hist_.front().first;
    if (from + 1 < oldest) return false;
    for (const auto& kv : hist_) {
      if (kv.first > from)
        out->insert(out->end(), kv.second.begin(), kv.second.end());
    }
    std::sort(out->begin(), out->end());
    out->erase(std::unique(out->begin(), out->end()), out->end());
    return true;
  }

  static constexpr size_t kMaxEpochs = 64;
  static constexpr size_t kMaxDirtyIds = 4u << 20;  // ~32MB of u64 ids

  // Serializes delta APPLIES across every surface sharing this ref
  // (capi handle, local proxies, a serving GraphServer): two racing
  // rebuilds from one base would each win SwapFrom's CAS for different
  // bases and one would error "retry" — queueing here turns that into
  // plain back-to-back applies. Per-ref, so independent graphs in one
  // process apply concurrently. The CAS stays the correctness backstop.
  std::mutex& apply_mutex() const { return apply_mu_; }

 private:
  void SwapLocked(std::shared_ptr<const Graph> next,
                  std::vector<NodeId> dirty) {
    hist_ids_ += dirty.size();
    hist_.emplace_back(next->epoch(), std::move(dirty));
    while (hist_.size() > kMaxEpochs || hist_ids_ > kMaxDirtyIds) {
      hist_ids_ -= hist_.front().second.size();
      hist_.pop_front();
    }
    g_ = std::move(next);
  }

  mutable std::mutex mu_;
  mutable std::mutex apply_mu_;
  std::shared_ptr<const Graph> g_;
  // (epoch, sorted-unique dirty node ids), oldest first
  std::deque<std::pair<uint64_t, std::vector<NodeId>>> hist_;
  size_t hist_ids_ = 0;
};

// Reconstruct a GraphBuilder whose Finalize() reproduces `g` byte-for-
// byte: node rows in engine-row order (EnsureNode appends, so existing
// rows keep their indices across deltas — the invariant device tables
// patch against), edges from the adjacency slots, features bulk-copied.
std::unique_ptr<GraphBuilder> BuilderFromGraph(const Graph& g);

// base + delta → a NEW immutable snapshot with epoch = base.epoch()+1.
// Delta semantics ride the existing builder machinery: AddNodes
// (last-write-wins type/weight update), AddEdges (duplicate
// (src,dst,type) dedupes last-added-wins, i.e. an existing edge's
// weight updates in place). When shard_num > 1 the delta is filtered to
// this shard's hash ownership ((id % partition_num) % shard_num ==
// shard_idx, the LoadShard/DumpOnePartition convention; edges are
// source-owned) so a broadcast delta lands each row on exactly one
// shard. dirty_out gets the FULL delta's node ids (nodes ∪ edge
// endpoints, unfiltered, sorted unique) — over-invalidation across
// shards is safe, staleness is not.
//
// omap (optional): an installed OwnershipMap replaces the hash filter —
// this shard applies exactly the rows whose partition lists shard_idx
// as an owner (a replicated hot partition lands on EVERY owner), which
// is what routes graph_partition-mode deltas too: ownership is the
// map's say, not the modulus convention.
Status ApplyGraphDelta(const Graph& base, const NodeId* node_ids,
                       const int32_t* node_types, const float* node_weights,
                       size_t n_nodes, const NodeId* edge_src,
                       const NodeId* edge_dst, const int32_t* edge_types,
                       const float* edge_weights, size_t n_edges,
                       int shard_idx, int shard_num,
                       std::unique_ptr<Graph>* out,
                       std::vector<NodeId>* dirty_out,
                       const OwnershipMap* omap = nullptr);

}  // namespace et

#endif  // EULER_TPU_GRAPH_H_
